"""tpulint network/liveness rule (NET501) for the request path.

The serving and control planes are built from threads that call each
other over HTTP and park on events. A single missing timeout in that
web is how a replica brownout (slow, not dead) wedges the whole plane:
the PR 14 resilience layer (deadlines, hedges, breakers) only works if
no hop can block forever underneath it. NET501 makes "every wait is
bounded" a static property of ``serving/`` and ``control/``:

- ``urlopen(...)`` must pass an explicit ``timeout`` (kwarg or the
  third positional) — the stdlib default is the global socket timeout,
  which is None unless someone set it process-wide;
- bare ``.wait()`` on an event/condition must pass a timeout. The few
  parks that are provably bounded by protocol (a loop that fires the
  event on every exit path) carry a per-line suppression with the
  justification, so the invariant is auditable instead of implicit.
"""

from __future__ import annotations

import ast
from typing import Iterator

from kubeflow_tpu.analysis.core import (
    Finding, Module, Rule, call_name, register,
)


@register
class UnboundedNetworkWait(Rule):
    """NET501: unbounded block on the request path. A browned-out peer
    (slow, not dead) turns every missing timeout into a stuck thread —
    and stuck threads are what deadlines/hedges exist to prevent."""

    id = "NET501"
    name = "unbounded-network-wait"
    short = "blocking wait / urlopen without an explicit timeout"

    # the planes where a wedged thread takes requests down with it;
    # non-file paths ("<corpus>", "<string>") are always in scope so the
    # corpus pins exercise the rule directly
    _SCOPES = ("serving/", "control/")

    def _in_scope(self, module: Module) -> bool:
        p = module.path.replace("\\", "/")
        if not p.endswith(".py"):
            return True
        return any(s in p for s in self._SCOPES)

    def check(self, module: Module) -> Iterator[Finding]:
        if not self._in_scope(module):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name and (name == "urlopen" or name.endswith(".urlopen")):
                has_timeout = (
                    any(kw.arg == "timeout" for kw in node.keywords)
                    # urlopen(url, data, timeout): third positional
                    or len(node.args) >= 3)
                if not has_timeout:
                    yield self.finding(
                        module, node,
                        f"{name}() without an explicit timeout: a "
                        "browned-out replica blocks this thread forever "
                        "— pass timeout= so the deadline layer can act")
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "wait"
                    and not node.args and not node.keywords):
                yield self.finding(
                    module, node,
                    "bare .wait() with no timeout on the request path; "
                    "pass a timeout (or suppress with the protocol that "
                    "guarantees the event fires)")
