"""Shared container entrypoint for controller managers.

Each operator image runs `python -m kubeflow_tpu.control.<name>`; the
__main__ stubs call into here. Mirrors the kubebuilder main.go shape:
build the client (in-cluster), build the controller, run forever with
/metrics + /healthz served (manager wiring of e.g.
notebook-controller/main.go).
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading


def run_controller(name: str, build, *, extra_args=None) -> None:  # pragma: no cover
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    import os

    p = argparse.ArgumentParser(f"kubeflow-tpu-{name}")
    p.add_argument("--metrics-port", type=int, default=8080)
    p.add_argument("--apiserver", default="", help="override in-cluster config")
    p.add_argument(
        "--enable-leader-election", action="store_true",
        default=os.environ.get("ENABLE_LEADER_ELECTION", "false").lower() == "true",
        help="Enable leader election for controller manager. Enabling this "
             "will ensure there is only one active controller manager.")
    if extra_args:
        extra_args(p)
    args = p.parse_args()

    from kubeflow_tpu.control.k8s.rest import RestClient

    client = RestClient(base_url=args.apiserver or None)

    # staging chaos drills: TPU_CHAOS_RATE>0 wraps the client in the
    # seeded fault injector (TPU_CHAOS_SEED picks the schedule) so a
    # whole controller deployment can be soak-tested against apiserver
    # faults without touching the cluster. 0/unset: no wrapper at all.
    from kubeflow_tpu.control.k8s import chaos

    if float(os.environ.get(chaos.ENV_RATE, "0") or 0) > 0:
        client = chaos.ChaosClient(client)
        logging.getLogger("kubeflow_tpu.chaos").warning(
            "chaos fault injection ENABLED for %s (TPU_CHAOS_RATE=%s, "
            "TPU_CHAOS_SEED=%s)", name,
            os.environ.get(chaos.ENV_RATE),
            os.environ.get(chaos.ENV_SEED, "0"))

    ctl = build(client, args)

    # --enable-leader-election parity (notebook-controller main.go:51-62):
    # HA replicas elect one active manager through a coordination Lease
    elector = None
    if args.enable_leader_election:
        from kubeflow_tpu.control.leases import LeaderElector

        elector = LeaderElector(
            client, f"{name}-controller",
            namespace=os.environ.get("POD_NAMESPACE", "kubeflow"))
        ctl.with_leader_election(elector)

    import prometheus_client as prom

    prom.start_http_server(args.metrics_port)

    # goodput_* export (the PR 10 ledger finally leaves the process):
    # every controller manager publishes its span-stream accounting
    # into the registry its /metrics endpoint serves, so the fleet
    # scrape plane aggregates goodput like any other series.
    # TPU_GOODPUT_CHIPS sizes chip-seconds-lost; 0 disables the loop.
    from kubeflow_tpu.obs.goodput import ENV_GOODPUT_CHIPS, GoodputExporter

    goodput_chips = int(os.environ.get(ENV_GOODPUT_CHIPS, "1") or 0)
    goodput_exporter = None
    if goodput_chips > 0:
        goodput_exporter = GoodputExporter(chips=goodput_chips).start()

    ctl.run(workers=2)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    # process lifetime park, released only by SIGTERM/SIGINT — not a
    # request-path wait, nothing upstream is blocked on this thread
    stop.wait()  # tpulint: disable=NET501  signal-released process park
    ctl.stop()
    if goodput_exporter is not None:
        goodput_exporter.stop()
    if elector is not None:
        elector.release()  # immediate hand-off on clean shutdown
