"""Vision Transformer for image classification.

The modern image-classification member of the zoo, next to ResNet (the
reference's benchmark CNN, tf-controller-examples/tf-cnn/
create_job_specs.py:101-121 `--model=resnet50`). ViT is the TPU-native
shape for vision: ONE big matmul turns the image into patch tokens
(MXU-friendly, no conv lowering), then the same pre-norm encoder
pattern as the rest of the framework — bf16 compute, mesh-axis
annotations on every weight, so dp/fsdp/tp shardings apply unchanged.

Classification uses mean-pooled patch features (GAP head — simpler than
a class token and equally accurate at this scale; Beyer et al.,
"Better plain ViT baselines for ImageNet-1k", 2022).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.models.registry import register_model
from kubeflow_tpu.models.transformer import RMSNorm
from kubeflow_tpu.ops.attention import reference_attention
from kubeflow_tpu.parallel.mesh import AXIS_FSDP, AXIS_MODEL

Dtype = Any


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    d_model: int = 384
    n_layers: int = 12
    n_heads: int = 6
    d_ff: int = 1536
    num_classes: int = 1000
    dtype: Dtype = jnp.bfloat16

    @property
    def n_patches(self) -> int:
        side = self.image_size // self.patch_size
        return side * side


class ViTBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = cfg.n_heads
        d_head = cfg.d_model // h
        init = nn.initializers.normal(0.02)
        part = nn.with_partitioning

        y = RMSNorm(dtype=cfg.dtype, name="ln_attn")(x)
        qkv = nn.DenseGeneral(
            (3, h, d_head), use_bias=False, dtype=cfg.dtype,
            kernel_init=part(init, (AXIS_FSDP, None, AXIS_MODEL, None)),
            name="qkv",
        )(y)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        # 196 patch tokens: the O(L^2) reference path is the right call
        # (a 196x196 f32 score block is VMEM-trivial; flash's block
        # machinery would only add overhead)
        att = reference_attention(q, k, v, causal=False)
        att = nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), use_bias=False, dtype=cfg.dtype,
            kernel_init=part(init, (AXIS_MODEL, None, AXIS_FSDP)), name="o",
        )(att)
        x = x + att

        y = RMSNorm(dtype=cfg.dtype, name="ln_mlp")(x)
        y = nn.DenseGeneral(
            cfg.d_ff, use_bias=True, dtype=cfg.dtype,
            kernel_init=part(init, (AXIS_FSDP, AXIS_MODEL)), name="fc1",
        )(y)
        y = nn.gelu(y)
        y = nn.DenseGeneral(
            cfg.d_model, use_bias=True, dtype=cfg.dtype,
            kernel_init=part(init, (AXIS_MODEL, AXIS_FSDP)), name="fc2",
        )(y)
        return x + y


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, train: bool = True):
        cfg = self.cfg
        del train  # no dropout in the speed-run configuration
        b = images.shape[0]
        p, side = cfg.patch_size, cfg.image_size // cfg.patch_size
        if images.shape[1:] != (cfg.image_size, cfg.image_size, 3):
            raise ValueError(
                f"ViT configured for {cfg.image_size}px RGB, got "
                f"{images.shape}")
        # [B, H, W, C] -> [B, n_patches, p*p*C]: pure reshape/transpose,
        # then ONE [p*p*C -> d_model] matmul embeds every patch (the
        # space-to-depth trick the ResNet stem uses, taken to term).
        x = images.astype(cfg.dtype).reshape(b, side, p, side, p, 3)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, side * side, p * p * 3)
        x = nn.DenseGeneral(
            cfg.d_model, use_bias=True, dtype=cfg.dtype,
            kernel_init=nn.with_partitioning(
                nn.initializers.normal(0.02), (None, AXIS_MODEL)),
            name="patch_embed",
        )(x)
        pos = self.param(
            "pos_embed",
            nn.with_partitioning(nn.initializers.normal(0.02),
                                 (None, AXIS_MODEL)),
            (cfg.n_patches, cfg.d_model), jnp.float32,
        )
        x = x + jnp.asarray(pos, cfg.dtype)[None]
        for i in range(cfg.n_layers):
            x = ViTBlock(cfg, name=f"layer_{i}")(x)
        x = RMSNorm(dtype=cfg.dtype, name="ln_f")(x)
        x = x.mean(axis=1)  # GAP over patches
        # f32 logits out of a bf16 matmul (same rationale as LMHead)
        head = self.param(
            "head_kernel",
            nn.with_partitioning(nn.initializers.zeros_init(),
                                 (AXIS_FSDP, AXIS_MODEL)),
            (cfg.d_model, cfg.num_classes), jnp.float32,
        )
        return jnp.einsum("bd,dv->bv", x, head.astype(cfg.dtype),
                          preferred_element_type=jnp.float32)

    def fwd_flops_per_image(self) -> float:
        """2*MAC forward FLOPs (the MFU-meter convention)."""
        cfg = self.cfg
        n, d = cfg.n_patches, cfg.d_model
        per_block = (
            2 * n * d * (3 * d)            # qkv
            + 2 * n * n * d * 2            # scores + values
            + 2 * n * d * d                # out proj
            + 2 * n * d * cfg.d_ff * 2     # fc1 + fc2
        )
        embed = 2 * n * (cfg.patch_size ** 2 * 3) * d
        head = 2 * d * cfg.num_classes
        return float(cfg.n_layers * per_block + embed + head)


def _build(**overrides):
    fields = {f.name for f in dataclasses.fields(ViTConfig)}
    kw = {k: overrides.pop(k) for k in list(overrides) if k in fields}
    if overrides:
        raise ValueError(f"unknown vit kwargs {sorted(overrides)}")
    return ViT(ViTConfig(**kw))


@register_model("vit-test")
def vit_test(**kw):
    base = dict(image_size=32, patch_size=8, d_model=32, n_layers=2,
                n_heads=2, d_ff=64, num_classes=10)
    base.update(kw)
    return _build(**base)


@register_model("vit-s16")
def vit_s16(**kw):
    """ViT-S/16: the classic small config (22M params)."""
    return _build(**kw)


@register_model("vit-b16")
def vit_b16(**kw):
    base = dict(d_model=768, n_layers=12, n_heads=12, d_ff=3072)
    base.update(kw)
    return _build(**base)
