"""Webhook server entry: python -m kubeflow_tpu.control.poddefault.

Serves HTTPS when --certs-dir (or WEBHOOK_CERTS_DIR) is set — the kube
apiserver refuses plain-HTTP webhook callees, so production manifests
always set it (tpctl/manifests.py wires the matching caBundle into the
MutatingWebhookConfiguration). Plain HTTP remains available for local
debugging only. Reference flags: admission-webhook/main.go:541-542.
"""
import argparse
import os

from kubeflow_tpu.control.k8s.rest import RestClient
from kubeflow_tpu.control.poddefault import PodDefaultMutator

p = argparse.ArgumentParser("poddefault-webhook")
p.add_argument("--port", type=int, default=4443)
p.add_argument("--apiserver", default="")
p.add_argument("--certs-dir", default=os.environ.get("WEBHOOK_CERTS_DIR", ""),
               help="serve HTTPS with a bootstrapped CA + cert from this dir")
args = p.parse_args()
mutator = PodDefaultMutator(RestClient(base_url=args.apiserver or None))
svc = mutator.serve(port=args.port, certs_dir=args.certs_dir or None)
print(f"poddefault webhook on :{svc.port} ({'https' if svc.tls else 'http'})")
if svc.tls:
    # announce our CA to the apiserver (background: the registration may
    # be applied after this pod starts; serving must not wait on it)
    import threading

    threading.Thread(target=mutator.publish_ca_bundle, daemon=True,
                     name="ca-bundle-publish").start()
svc.serve_forever()
