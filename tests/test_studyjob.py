"""StudyJob sweep semantics — preserves the condition contract the
reference's E2E polls (testing/katib_studyjob_test.py:128-194)."""

import pytest

from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.jaxjob.controller import build_controller as build_jaxjob
from kubeflow_tpu.control.jaxjob.controller import worker_name
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.k8s.kubelet import FakeKubelet
from kubeflow_tpu.control.runtime import seed_controller
from kubeflow_tpu.tune import studyjob as SJ


@pytest.fixture()
def world():
    cluster = FakeCluster()
    study_ctl = seed_controller(SJ.build_controller(cluster))
    jaxjob_ctl = seed_controller(build_jaxjob(cluster, record_events=False))
    kubelet = FakeKubelet(cluster)
    return cluster, study_ctl, jaxjob_ctl, kubelet


def drain(*ctls):
    for _ in range(8):
        for c in ctls:
            c.run_until_idle(advance_delayed=True)


PARAMS = [
    {"name": "lr", "parameterType": "double",
     "feasible": {"min": 0.01, "max": 0.03, "steps": 3}},
    {"name": "opt", "parameterType": "categorical",
     "feasible": {"list": ["sgd", "adamw"]}},
]

TRIAL_TEMPLATE = {
    "spec": {
        "replicas": 1,
        "template": {"spec": {"containers": [{
            "name": "jax", "image": "kubeflow-tpu/jaxrt:latest",
            "command": ["python", "-m", "kubeflow_tpu.runtime.launcher",
                        "--learning-rate=${lr}", "--optimizer=${opt}"],
        }]}},
    }
}


class TestSuggestions:
    def test_grid(self):
        out = SJ.grid_suggestions(PARAMS, max_trials=6)
        assert len(out) == 6
        assert {s["opt"] for s in out} == {"sgd", "adamw"}
        assert all(0.01 <= s["lr"] <= 0.03 for s in out)

    def test_grid_truncates_to_max(self):
        assert len(SJ.grid_suggestions(PARAMS, max_trials=2)) == 2

    def test_random_deterministic_by_seed(self):
        a = SJ.random_suggestions(PARAMS, 4, seed=7)
        b = SJ.random_suggestions(PARAMS, 4, seed=7)
        assert a == b

    def test_template_substitution(self):
        trial = SJ.StudyJobReconciler().generate_trial(
            SJ.new_studyjob("s", parameters=PARAMS, trial_template=TRIAL_TEMPLATE),
            0, {"lr": 0.02, "opt": "adamw"},
        )
        cmd = trial["spec"]["template"]["spec"]["containers"][0]["command"]
        assert "--learning-rate=0.02" in cmd and "--optimizer=adamw" in cmd
        # full-token substitution keeps native types (usable for replicas etc.)
        sub = SJ._substitute({"replicas": "${n}"}, {"n": 4})
        assert sub["replicas"] == 4


class TestSweepLifecycle:
    def run_all_trials(self, cluster, study_ctl, jaxjob_ctl, kubelet, objective):
        """Drive trials to completion, reporting `objective(params)`."""
        import json

        for _ in range(30):
            drain(study_ctl, jaxjob_ctl)
            kubelet.step()
            drain(study_ctl, jaxjob_ctl)
            jobs = cluster.list(JT.API_VERSION, JT.KIND, namespace="default")
            progressed = False
            for job in jobs:
                if ob.cond_is_true(job, JT.COND_SUCCEEDED):
                    continue
                if not ob.cond_is_true(job, JT.COND_RUNNING):
                    continue
                params = json.loads(ob.annotations_of(job)[
                    "studyjob.kubeflow.org/parameters"])
                fresh = cluster.get(JT.API_VERSION, JT.KIND,
                                    ob.meta(job)["name"], "default")
                ob.set_annotation(fresh, SJ.ANNO_OBJECTIVE,
                                  str(objective(params)))
                cluster.update(fresh)
                kubelet.succeed(worker_name(ob.meta(job)["name"], 0))
                progressed = True
            drain(study_ctl, jaxjob_ctl)
            study = cluster.get(SJ.API_VERSION, SJ.KIND, "sweep", "default")
            if ob.cond_is_true(study, SJ.COND_SUCCEEDED):
                return study
            if not progressed and not jobs:
                continue
        return cluster.get(SJ.API_VERSION, SJ.KIND, "sweep", "default")

    def test_full_sweep_finds_best(self, world):
        cluster, study_ctl, jaxjob_ctl, kubelet = world
        cluster.create(SJ.new_studyjob(
            "sweep", parameters=PARAMS, trial_template=TRIAL_TEMPLATE,
            max_trials=4, parallel_trials=2))
        drain(study_ctl, jaxjob_ctl)
        # katib contract: Running condition while trials execute
        study = cluster.get(SJ.API_VERSION, SJ.KIND, "sweep", "default")
        assert ob.cond_is_true(study, SJ.COND_RUNNING)
        # parallelism cap respected
        jobs = cluster.list(JT.API_VERSION, JT.KIND, namespace="default")
        assert len(jobs) == 2

        study = self.run_all_trials(cluster, study_ctl, jaxjob_ctl, kubelet,
                                    objective=lambda p: p["lr"])
        assert ob.cond_is_true(study, SJ.COND_SUCCEEDED)
        assert not ob.cond_is_true(study, SJ.COND_RUNNING)
        assert study["status"]["trials"]["completed"] == 4
        best = study["status"]["bestTrial"]
        # minimize lr -> best has the smallest lr among the 4 grid points
        assert best["objective"] == min(
            s["lr"] for s in SJ.grid_suggestions(PARAMS, 4))

    def test_maximize_direction(self, world):
        cluster, study_ctl, jaxjob_ctl, kubelet = world
        sj = SJ.new_studyjob("sweep", parameters=PARAMS,
                             trial_template=TRIAL_TEMPLATE,
                             max_trials=3, parallel_trials=3, goal="maximize")
        cluster.create(sj)
        study = self.run_all_trials(cluster, study_ctl, jaxjob_ctl, kubelet,
                                    objective=lambda p: p["lr"])
        best = study["status"]["bestTrial"]
        assert best["objective"] == max(
            s["lr"] for s in SJ.grid_suggestions(PARAMS, 3))

    def test_bad_algorithm_fails(self, world):
        cluster, study_ctl, _, _ = world
        sj = SJ.new_studyjob("sweep", algorithm="simulated-annealing",
                             parameters=PARAMS)
        cluster.create(sj)
        drain(study_ctl)
        study = cluster.get(SJ.API_VERSION, SJ.KIND, "sweep", "default")
        assert ob.cond_is_true(study, SJ.COND_FAILED)

    def test_study_delete_cascades_to_trials(self, world):
        cluster, study_ctl, jaxjob_ctl, _ = world
        cluster.create(SJ.new_studyjob(
            "sweep", parameters=PARAMS, trial_template=TRIAL_TEMPLATE,
            max_trials=4, parallel_trials=2))
        drain(study_ctl, jaxjob_ctl)
        assert cluster.list(JT.API_VERSION, JT.KIND, namespace="default")
        cluster.delete(SJ.API_VERSION, SJ.KIND, "sweep", "default")
        assert cluster.list(JT.API_VERSION, JT.KIND, namespace="default") == []


class TestBayes:
    def test_explores_then_exploits_near_best(self):
        """With observations strongly favoring lr~=0.012, the refined
        tail clusters nearer that anchor than uniform sampling."""
        params = [{"name": "lr", "parameterType": "double",
                   "feasible": {"min": 0.0, "max": 1.0}}]
        obs = [{"parameters": {"lr": x}, "objective": (x - 0.012) ** 2}
               for x in (0.012, 0.3, 0.6, 0.9)]
        out = SJ.bayes_suggestions(params, 16, seed=3,
                                   observations=obs, goal="minimize")
        uniform = SJ.random_suggestions(params, 16, seed=3)
        tail = [s["lr"] for s in out[8:]]
        utail = [s["lr"] for s in uniform[8:]]
        assert all(0.0 <= v <= 1.0 for v in tail)
        mean = lambda vs: sum(vs) / len(vs)  # noqa: E731
        assert mean([abs(v - 0.012) for v in tail]) < \
            mean([abs(v - 0.012) for v in utail])

    def test_without_observations_falls_back_to_random(self):
        params = [{"name": "lr", "parameterType": "double",
                   "feasible": {"min": 0.0, "max": 1.0}}]
        assert SJ.bayes_suggestions(params, 5, seed=1) == \
            SJ.random_suggestions(params, 5, seed=1)

    def test_full_bayes_sweep_succeeds(self, world):
        cluster, study_ctl, jaxjob_ctl, kubelet = world
        cluster.create(SJ.new_studyjob(
            "sweep", algorithm="bayesianoptimization", parameters=PARAMS,
            trial_template=TRIAL_TEMPLATE, max_trials=5, parallel_trials=1))
        study = TestSweepLifecycle().run_all_trials(
            cluster, study_ctl, jaxjob_ctl, kubelet,
            objective=lambda p: (p["lr"] - 0.02) ** 2)
        assert ob.cond_is_true(study, SJ.COND_SUCCEEDED)
        assert study["status"]["trials"]["completed"] == 5
        assert study["status"]["bestTrial"]["objective"] is not None


class TestSuccessiveHalving:
    ALGO_PARAMS = [{"name": "lr", "parameterType": "double",
                    "feasible": {"min": 0.01, "max": 0.03, "steps": 3}}]
    BUDGET_TEMPLATE = {
        "spec": {
            "replicas": 1,
            "template": {"spec": {"containers": [{
                "name": "jax", "image": "kubeflow-tpu/jaxrt:latest",
                "command": ["python", "-m", "kubeflow_tpu.runtime.launcher",
                            "--learning-rate=${lr}",
                            "--total-steps=${budget}"],
            }]}},
        }
    }

    def test_rung_ladder(self):
        rungs, eta = SJ.sha_rungs({"minBudget": 10, "maxBudget": 90,
                                   "reduction": 3})
        assert rungs == [10, 30, 90] and eta == 3

    def test_bracket_respects_max_trial_cap(self):
        # rungs [5, 10]: n0=4 -> 4+2=6 total; maxTrialCount is the cap
        assert SJ.sha_bracket(6, [5, 10], 2) == 4
        assert SJ.sha_bracket(4, [5, 10], 2) == 3  # 3+1=4
        assert SJ.sha_bracket(1, [5, 10, 20], 2) == 1

    def test_promotions_appear_only_when_rung_drains(self):
        algo = {"minBudget": 5, "maxBudget": 10}
        first = SJ.sha_suggestions(self.ALGO_PARAMS, 6, seed=0,
                                   observations=[], algo=algo)
        assert len(first) == 4 and all(s["budget"] == 5 for s in first)
        # half the rung done -> still no promotions
        obs = [{"parameters": dict(s), "objective": s["lr"]}
               for s in first[:2]]
        assert len(SJ.sha_suggestions(
            self.ALGO_PARAMS, 6, seed=0, observations=obs, algo=algo)) == 4
        # full rung done -> top half promoted to budget 10
        obs = [{"parameters": dict(s), "objective": s["lr"]} for s in first]
        out = SJ.sha_suggestions(self.ALGO_PARAMS, 6, seed=0,
                                 observations=obs, algo=algo)
        assert len(out) == 6  # never exceeds maxTrialCount
        promoted = [s for s in out if s["budget"] == 10]
        assert len(promoted) == 2
        best_lrs = sorted(s["lr"] for s in first)[:2]
        assert sorted(s["lr"] for s in promoted) == best_lrs

    def test_failed_trials_drain_rung_without_promotion(self):
        """A rung containing failed (objective-None) trials still drains;
        promotions come from the survivors only — the bracket must not
        stall forever nor promote a failed config."""
        algo = {"minBudget": 5, "maxBudget": 10}
        first = SJ.sha_suggestions(self.ALGO_PARAMS, 6, seed=0,
                                   observations=[], algo=algo)
        obs = [{"parameters": dict(s),
                "objective": None if i < 3 else s["lr"]}
               for i, s in enumerate(first)]
        out = SJ.sha_suggestions(self.ALGO_PARAMS, 6, seed=0,
                                 observations=obs, algo=algo)
        promoted = [s for s in out if s["budget"] == 10]
        # expected//eta = 2 but only 1 survivor -> exactly it is promoted
        assert [s["lr"] for s in promoted] == [first[3]["lr"]]
        # all trials failed: rung drains, nothing promoted, no stall
        obs_all_failed = [{"parameters": dict(s), "objective": None}
                          for s in first]
        out = SJ.sha_suggestions(self.ALGO_PARAMS, 6, seed=0,
                                 observations=obs_all_failed, algo=algo)
        assert [s for s in out if s["budget"] == 10] == []

    def test_full_sha_sweep_promotes_and_substitutes_budget(self, world):
        cluster, study_ctl, jaxjob_ctl, kubelet = world
        sj = SJ.new_studyjob(
            "sweep", algorithm="hyperband", parameters=self.ALGO_PARAMS,
            trial_template=self.BUDGET_TEMPLATE,
            max_trials=4, parallel_trials=4)
        sj["spec"]["algorithm"].update({"minBudget": 5, "maxBudget": 20,
                                        "reduction": 2})
        cluster.create(sj)
        study = TestSweepLifecycle().run_all_trials(
            cluster, study_ctl, jaxjob_ctl, kubelet,
            objective=lambda p: p["lr"] / p["budget"])
        assert ob.cond_is_true(study, SJ.COND_SUCCEEDED)
        # maxTrialCount=4 caps the bracket: 2 at budget 5, 1 promoted to
        # 10, 1 promoted to 20
        assert study["status"]["trials"]["completed"] == 4
        best = study["status"]["bestTrial"]
        assert best["parameters"]["budget"] == 20
        # ${budget} reached the trial command line
        import json as _json
        jobs = cluster.list(JT.API_VERSION, JT.KIND, namespace="default")
        budgets = set()
        for j in jobs:
            cmd = j["spec"]["template"]["spec"]["containers"][0]["command"]
            flag = [c for c in cmd if c.startswith("--total-steps=")][0]
            budgets.add(int(flag.split("=")[1]))
            p = _json.loads(ob.annotations_of(j)[
                "studyjob.kubeflow.org/parameters"])
            assert int(flag.split("=")[1]) == p["budget"]
        assert budgets == {5, 10, 20}


def test_sha_cap_holds_with_ladder_longer_than_budget():
    """maxTrialCount=2 with a 3-rung ladder: the top rung is dropped so
    the total never exceeds the cap (1 trial at each remaining rung)."""
    params = [{"name": "lr", "parameterType": "double",
               "feasible": {"min": 0.0, "max": 1.0}}]
    algo = {"minBudget": 1, "maxBudget": 4, "reduction": 2}
    out = SJ.sha_suggestions(params, 2, seed=0, observations=[], algo=algo)
    assert len(out) == 1 and out[0]["budget"] == 1
    obs = [{"parameters": dict(out[0]), "objective": 0.5}]
    out2 = SJ.sha_suggestions(params, 2, seed=0, observations=obs, algo=algo)
    assert len(out2) == 2 and out2[1]["budget"] == 2
    obs.append({"parameters": dict(out2[1]), "objective": 0.4})
    out3 = SJ.sha_suggestions(params, 2, seed=0, observations=obs, algo=algo)
    assert len(out3) == 2  # budget-4 rung dropped: cap respected
