"""kubeflow_tpu — a TPU-native ML platform framework.

A ground-up re-design of the capability surface of the Kubeflow mono-repo
(reference: MartinForReal/kubeflow) for Cloud TPU:

- ``kubeflow_tpu.parallel``  — device meshes, shardings, distributed init
  (the TPU-native replacement for TF_CONFIG gRPC parameter-server and
  OpenMPI/NCCL ring-allreduce; reference: tf-controller-examples/tf-cnn/
  launcher.py:68-80, components/openmpi-controller/controller/controller.py).
- ``kubeflow_tpu.ops``       — Pallas TPU kernels (flash attention, ring
  attention) and XLA-collective building blocks.
- ``kubeflow_tpu.models``    — flax model zoo (ResNet, decoder LM, BERT, MoE);
  the tf-cnn / tf-serving payload analogues.
- ``kubeflow_tpu.runtime``   — jaxrt: in-pod launcher, trainer loop, MFU
  meter, orbax checkpointing, Prometheus metrics.
Planned (build order per SURVEY.md §7; not yet in tree):
``control`` (JAXJob/Notebook/Profile/Tensorboard controllers, PodDefault
webhook, KFAM, gatekeeper over an in-memory fake API server), ``tpctl``
(bootstrap/kfctl-analogue deployment engine), ``serving`` (TF-Serving REST
contract), ``tune`` (StudyJob-style sweeps).
"""

__version__ = "0.1.0"
