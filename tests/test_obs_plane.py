"""Fleet observability plane tier (ISSUE 10): the shared exposition
parser, the ring TSDB + ScrapeLoop, PromQL-lite + recording/alerting
rules, goodput accounting, the dashboard surface, and the obs bench
contract.

The acceptance drill lives here too: a scripted kill drill over a REAL
TokenRouter on a virtual clock — healthy traffic, then a fault window
(slow completions + a killed replica + reconcile errors) — must fire
the RouterLatencySLOBurn and ReconcileErrorRate alerts DURING the
window, emit AlertFiring Events through the EventRecorder, and resolve
both after heal. Goodput-ledger conservation is additionally asserted
inside the chaos soak and the elastic resize drill (tests/test_chaos.py).
"""

import json
import math
import urllib.request

import pytest

from kubeflow_tpu.obs import expofmt
from kubeflow_tpu.obs import goodput as gp
from kubeflow_tpu.obs import rules as R
from kubeflow_tpu.obs import trace as tr
from kubeflow_tpu.obs.events import EventRecorder
from kubeflow_tpu.obs.plane import FleetPlane
from kubeflow_tpu.obs.tsdb import (
    HttpTarget, RegistryTarget, ScrapeLoop, TimeSeriesStore,
    jaxservice_targets, series_key,
)
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.runtime.metrics import (
    DEFAULT_BUCKETS, MetricsRegistry, serve_metrics,
)
from kubeflow_tpu.serving.router import (
    REQUEST_BUCKETS, Member, RegistrySignals, TokenRouter,
)

pytestmark = pytest.mark.obs


class ManualClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- the ONE exposition parser (satellite 1) ---------------------------------


class TestExpofmt:
    def test_parse_round_trips_registry_render(self):
        """Parsing render() must reproduce the registry's own
        structured samples — the scraped path and the fast path agree
        byte-for-byte (fast-vs-scraped parity)."""
        reg = MetricsRegistry()
        reg.gauge("g_metric", 1.5, service="a", zone="x")
        reg.gauge("g_metric", 2.5, service="b", zone="x")
        reg.counter_inc("c_total", by=3.0, job="j")
        parsed = {}
        for s in expofmt.parse(reg.render()):
            parsed.setdefault(s.name, []).append(
                (tuple(sorted(s.labels_dict().items())), s.value))
        for name in ("g_metric", "c_total"):
            fast = sorted((tuple(sorted(ls.items())), v)
                          for ls, v in reg.series(name))
            assert sorted(parsed[name]) == fast

    def test_escaped_label_values_round_trip(self):
        """The naive split-on-comma parser this replaces corrupted
        quoted commas and escapes; the shared parser must not."""
        reg = MetricsRegistry()
        nasty = 'a,b="c"\\d\ne'
        reg.gauge("esc_metric", 7.0, path=nasty, other="plain")
        samples = expofmt.samples(reg.render(), "esc_metric")
        assert samples == [({"other": "plain", "path": nasty}, 7.0)]

    def test_histograms_parse_as_component_series(self):
        reg = MetricsRegistry()
        reg.histogram("h_seconds", 0.3, buckets=(0.1, 0.5), svc="s")
        names = {s.name for s in expofmt.parse(reg.render())}
        assert names == {"h_seconds_bucket", "h_seconds_sum",
                         "h_seconds_count"}
        buckets = expofmt.samples(reg.render(), "h_seconds_bucket")
        by_le = {ls["le"]: v for ls, v in buckets}
        assert by_le == {"0.1": 0.0, "0.5": 1.0, "+Inf": 1.0}

    def test_garbage_lines_are_skipped_not_raised(self):
        text = ("# HELP x y\n# TYPE x gauge\nx 1\n"
                "!!!garbage\nname{borked 2\nx{a=\"b\"} nope\n"
                "ok_metric{a=\"b\"} 3\n")
        got = [(s.name, s.value) for s in expofmt.parse(text)]
        assert got == [("x", 1.0), ("ok_metric", 3.0)]

    def test_registry_signals_scraped_equals_fast(self):
        """RegistrySignals over a scraped body (callable source) must
        agree with the in-process fast path — now THROUGH the shared
        parser."""
        reg = MetricsRegistry()
        reg.gauge("router_queue_depth", 4, namespace="ns", service="s")
        reg.counter_inc("router_tokens_total", by=123.0,
                        namespace="ns", service="s")
        fast = RegistrySignals(reg)
        scraped = RegistrySignals(lambda: reg.render())
        assert fast.queue_depth("ns", "s") == scraped.queue_depth("ns", "s")
        assert fast.tokens_total("ns", "s") == \
            scraped.tokens_total("ns", "s")

    def test_router_has_no_second_parser_spelling(self):
        """The hoist pin: serving/router.py must consume obs/expofmt
        and may not retain (or regrow) an inline exposition parser."""
        import inspect

        from kubeflow_tpu.serving.router import RegistrySignals

        src = inspect.getsource(RegistrySignals)
        assert "expofmt" in src
        for fragment in ('rpartition(" ")', "partition(\"{\")",
                         "rstrip(\"}\")", 'split(",")'):
            assert fragment not in src, (
                f"RegistrySignals regrew inline parsing: {fragment}")


# -- the TSDB ----------------------------------------------------------------


class TestTimeSeriesStore:
    def test_instant_latest_within_lookback(self):
        st = TimeSeriesStore()
        st.append("m", {"a": "1"}, 10.0, t=100.0)
        st.append("m", {"a": "1"}, 20.0, t=200.0)
        st.append("m", {"a": "2"}, 5.0, t=50.0)
        assert st.instant("m", None, at=210.0, lookback=60.0) == \
            [({"a": "1"}, 20.0)]
        # a=2's point aged out of the lookback; at=49 sees nothing
        assert st.instant("m", {"a": "2"}, at=49.0, lookback=60.0) == []
        assert st.instant("m", {"a": "2"}, at=60.0, lookback=60.0) == \
            [({"a": "2"}, 5.0)]

    def test_ring_bounds_points(self):
        st = TimeSeriesStore(max_points=4)
        for i in range(10):
            st.append("m", None, float(i), t=float(i))
        pts = st.window("m", None, -1.0, 99.0)[0][1]
        assert [v for _, v in pts] == [6.0, 7.0, 8.0, 9.0]

    def test_series_cap_drops_and_counts(self):
        st = TimeSeriesStore(max_series=2)
        assert st.append("a", None, 1.0, 0.0)
        assert st.append("b", None, 1.0, 0.0)
        assert not st.append("c", None, 1.0, 0.0)  # over cap: dropped
        assert st.append("a", None, 2.0, 1.0)      # existing: fine
        assert st.stats()["dropped"] == 1
        assert st.series_count() == 2

    def test_real_nan_data_is_not_staleness(self):
        """A worker legitimately exporting NaN (diverged loss) must
        stay visible as data — only the TSDB's own marker bit pattern
        hides a series (the Prometheus staleness convention)."""
        st = TimeSeriesStore()
        st.append("jaxrt_loss", {"i": "w0"}, float("nan"), t=10.0)
        got = st.instant("jaxrt_loss", None, at=11.0)
        assert len(got) == 1 and math.isnan(got[0][1])
        assert not expofmt.is_stale(float("nan"))
        assert expofmt.is_stale(expofmt.STALE_NAN)

    def test_staleness_marker_hides_from_instant(self):
        st = TimeSeriesStore()
        st.append("m", {"i": "x"}, 3.0, t=10.0)
        st.mark_stale(series_key("m", {"i": "x"}), t=20.0)
        assert st.instant("m", None, at=25.0) == []
        # range reads skip the NaN marker but keep real samples
        assert st.window("m", None, 0.0, 30.0) == \
            [({"i": "x"}, [(10.0, 3.0)])]
        # fresh data after the marker revives the series
        st.append("m", {"i": "x"}, 4.0, t=30.0)
        assert st.instant("m", None, at=31.0) == [({"i": "x"}, 4.0)]


# -- the scrape loop ---------------------------------------------------------


class TestScrapeLoop:
    def _world(self):
        clock = ManualClock()
        reg = MetricsRegistry()
        reg.gauge("fleet_gauge", 1.0, shard="s0")
        store = TimeSeriesStore()
        loop = ScrapeLoop(store, targets=[
            RegistryTarget("w0", reg, labels={"job": "worker"})],
            clock=clock)
        return clock, reg, store, loop

    def test_ingest_attaches_instance_and_job_labels(self):
        clock, reg, store, loop = self._world()
        stats = loop.scrape_once()
        assert stats["ok"] == 1 and stats["failed"] == 0
        got = store.instant("fleet_gauge", None, at=0.0)
        assert got == [({"instance": "w0", "job": "worker",
                         "shard": "s0"}, 1.0)]
        assert store.instant("up", None, at=0.0) == \
            [({"instance": "w0", "job": "worker"}, 1.0)]

    def test_scrape_op_counts_replay_exactly(self):
        """The zero-rescan pin: identical registries scrape to
        IDENTICAL op counts — no hidden re-reads, machine-independent
        (the obs_bench --check gate compares these numbers)."""
        runs = []
        for _ in range(2):
            clock, reg, store, loop = self._world()
            for _ in range(3):
                loop.scrape_once()
                clock.advance(15.0)
            runs.append((store.stats(), loop.stats()))
        assert runs[0] == runs[1]
        # exact pin: 1 gauge sample + 1 up per cycle x 3 cycles
        assert runs[0][0]["appends"] == 6
        assert runs[0][1] == {"scrapes": 3, "failures": 0, "samples": 3}

    def test_target_loss_marks_stale_and_up_zero(self):
        clock, reg, store, loop = self._world()
        loop.scrape_once()
        clock.advance(15.0)
        loop.targets[0].fetch = lambda: (_ for _ in ()).throw(
            ConnectionError("down"))
        loop.scrape_once()
        assert not loop.up("w0")
        # the gauge is stale-marked (hidden), up reads 0
        assert store.instant("fleet_gauge", None, at=15.0) == []
        assert store.instant("up", None, at=15.0) == \
            [({"instance": "w0", "job": "worker"}, 0.0)]
        # markers land once; a second failed cycle appends only up=0
        before = store.stats()["appends"]
        clock.advance(15.0)
        loop.scrape_once()
        assert store.stats()["appends"] == before + 1

    def test_never_up_target_writes_labeled_up_zero(self):
        """A target unreachable from its FIRST scrape still produces
        `up` with its full label set — `up{job="..."} == 0` alerting
        must match it."""
        clock = ManualClock()
        store = TimeSeriesStore()
        bad = RegistryTarget("r9", MetricsRegistry(),
                             labels={"job": "serving"})
        bad.fetch = lambda: (_ for _ in ()).throw(OSError("refused"))
        loop = ScrapeLoop(store, targets=[bad], clock=clock)
        loop.scrape_once()
        assert store.instant("up", {"job": "serving"}, at=0.0) == \
            [({"instance": "r9", "job": "serving"}, 0.0)]

    def test_vanished_series_within_live_target_goes_stale(self):
        """A label set the target STOPS exposing (a replica leaving a
        gauge family) must not linger as last-known-value."""
        clock = ManualClock()
        reg = MetricsRegistry()
        reg.gauge("inflight", 5.0, replica="r0")
        reg.gauge("inflight", 7.0, replica="r1")
        store = TimeSeriesStore()
        loop = ScrapeLoop(store, targets=[RegistryTarget("x", reg)],
                          clock=clock)
        loop.scrape_once()
        assert len(store.instant("inflight", None, at=0.0)) == 2
        # registry drops r1 (fresh registry without it)
        reg2 = MetricsRegistry()
        reg2.gauge("inflight", 6.0, replica="r0")
        loop.targets[0].registry = reg2
        clock.advance(15.0)
        loop.scrape_once()
        got = store.instant("inflight", None, at=15.0)
        assert [(ls["replica"], v) for ls, v in got] == [("r0", 6.0)]

    def test_vanished_target_is_forgotten_and_alerts_resolve(self):
        """A replica REMOVED from discovery (drained + deleted, gone
        from the endpoints annotation) must stale-mark everything it
        exposed — up included — and stop counting as a tracked target,
        so alerts over it resolve instead of riding last-known values
        to lookback expiry."""
        clock = ManualClock()
        reg = MetricsRegistry()
        reg.gauge("serving_kv_pages_free", 0.0, model="m")
        store = TimeSeriesStore()
        fleet = [RegistryTarget("r0", reg)]
        loop = ScrapeLoop(store, discover=lambda: list(fleet),
                          clock=clock)
        eng = R.RuleEngine(store, rules=[
            R.AlertRule("KVPagesExhausted",
                        "serving_kv_pages_free == 0", for_s=0.0)],
            clock=clock, lookback_s=600.0)
        loop.scrape_once()
        assert [t["to"] for t in eng.evaluate_once()] == \
            ["pending", "firing"]
        fleet.clear()  # the replica leaves discovery entirely
        clock.advance(15.0)
        loop.scrape_once()
        assert [t["to"] for t in eng.evaluate_once()] == ["resolved"]
        # up is stale-marked too, and the target is no longer tracked
        assert store.instant("up", None, at=15.0) == []
        assert not loop.up("r0")

    def test_discovery_blip_does_not_mass_forget(self):
        """One failed discovery cycle (apiserver hiccup) must not
        forget the fleet — that would falsely resolve live alerts and
        reset their for-duration."""
        clock = ManualClock()
        reg = MetricsRegistry()
        reg.gauge("serving_kv_pages_free", 0.0, model="m")
        store = TimeSeriesStore()
        state = {"fail": False}

        def discover():
            if state["fail"]:
                raise ConnectionError("apiserver blip")
            return [RegistryTarget("r0", reg)]

        loop = ScrapeLoop(store, discover=discover, clock=clock)
        loop.scrape_once()
        assert loop.up("r0")
        state["fail"] = True
        clock.advance(15.0)
        loop.scrape_once()
        # still tracked, series still live (not stale-marked)
        assert loop.up("r0")
        assert store.instant("serving_kv_pages_free", None, at=15.0)

    def test_never_up_target_forgotten_resolves_up_alert(self):
        """A replica that crashlooped from provisioning onward (never
        one good scrape) and then leaves discovery must have its
        synthesized up=0 series stale-marked on the removal cycle."""
        clock = ManualClock()
        store = TimeSeriesStore()
        bad = RegistryTarget("r9", MetricsRegistry(),
                             labels={"job": "serving"})
        bad.fetch = lambda: (_ for _ in ()).throw(OSError("refused"))
        fleet = [bad]
        loop = ScrapeLoop(store, discover=lambda: list(fleet),
                          clock=clock)
        loop.scrape_once()
        assert store.instant("up", None, at=0.0) == \
            [({"instance": "r9", "job": "serving"}, 0.0)]
        fleet.clear()
        clock.advance(15.0)
        loop.scrape_once()
        assert store.instant("up", None, at=15.0) == []

    def test_http_target_over_real_metrics_endpoint(self):
        reg = MetricsRegistry()
        reg.gauge("served_gauge", 42.0)
        srv = serve_metrics(port=0, registry=reg)
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/metrics"
            store = TimeSeriesStore()
            loop = ScrapeLoop(store, targets=[HttpTarget("h0", url)],
                              clock=ManualClock())
            stats = loop.scrape_once()
            assert stats["ok"] == 1
            assert store.instant("served_gauge", None, at=0.0) == \
                [({"instance": "h0"}, 42.0)]
        finally:
            srv.shutdown()

    def test_jaxservice_target_discovery_from_endpoints_annotation(self):
        from kubeflow_tpu.control.jaxservice import types as ST
        from kubeflow_tpu.serving.router import render_endpoints

        cluster = FakeCluster()
        svc = ST.new_jaxservice("chat", model="m")
        svc["metadata"].setdefault("annotations", {})[
            ST.ANNOTATION_ENDPOINTS] = render_endpoints([
                {"name": "chat-replica-0", "addr": "10.0.0.1:9000",
                 "state": "active"},
                {"name": "chat-replica-1", "addr": "10.0.0.2:9000",
                 "state": "cordoned"},   # cordoned stays scraped
                {"name": "half", "state": "active"},  # no addr: skipped
            ])
        cluster.create(svc)
        # a SECOND namespace with the same service + replica names must
        # not collide in the instance keyspace (scrape dedups on it)
        svc_b = ST.new_jaxservice("chat", model="m", namespace="team-b")
        svc_b["metadata"].setdefault("annotations", {})[
            ST.ANNOTATION_ENDPOINTS] = render_endpoints(
                [{"name": "chat-replica-0", "addr": "10.1.0.1:9000",
                  "state": "active"}])
        cluster.create(svc_b)
        targets = jaxservice_targets(cluster)
        assert [(t.instance, t.url) for t in targets] == [
            ("default/chat-replica-0", "http://10.0.0.1:9000/metrics"),
            ("default/chat-replica-1", "http://10.0.0.2:9000/metrics"),
            ("team-b/chat-replica-0", "http://10.1.0.1:9000/metrics"),
        ]
        assert targets[0].labels["service"] == "chat"
        assert targets[0].labels["replica"] == "chat-replica-0"

    def test_discovery_through_cluster_cache_zero_list_calls(self):
        """Steady-state discovery must read the cache's indexed
        objects, never relist — the PR 7 op-count discipline."""
        from kubeflow_tpu.control.cache import ClusterCache
        from kubeflow_tpu.control.jaxservice import types as ST
        from kubeflow_tpu.serving.router import render_endpoints

        cluster = FakeCluster()
        svc = ST.new_jaxservice("chat", model="m")
        svc["metadata"].setdefault("annotations", {})[
            ST.ANNOTATION_ENDPOINTS] = render_endpoints(
                [{"name": "chat-replica-0", "addr": "10.0.0.1:9000",
                  "state": "active"}])
        cluster.create(svc)
        cache = ClusterCache(cluster,
                             kinds=((ST.API_VERSION, ST.KIND),)).connect()
        cluster.reset_stats()
        for _ in range(5):
            targets = jaxservice_targets(cache)
            assert len(targets) == 1
        assert cluster.stats.get("list_calls", 0) == 0


# -- PromQL-lite + rules -----------------------------------------------------


class TestEvaluator:
    def _store(self):
        st = TimeSeriesStore()
        for t in range(0, 120, 15):
            st.append("c_total", {"svc": "a"}, float(t), t=float(t))
            st.append("c_total", {"svc": "b"}, float(2 * t), t=float(t))
        return st

    def test_instant_and_matchers(self):
        st = self._store()
        ev = R.Evaluator(st)
        assert ev.query('c_total{svc="a"}', 105.0) == [({"svc": "a"},
                                                        105.0)]

    def test_rate_and_sum_by(self):
        st = self._store()
        ev = R.Evaluator(st)
        rates = dict((ls["svc"], v)
                     for ls, v in ev.query("rate(c_total[1m])", 105.0))
        assert rates["a"] == pytest.approx(1.0)
        assert rates["b"] == pytest.approx(2.0)
        total = ev.query("sum (rate(c_total[1m]))", 105.0)
        assert total == [({}, pytest.approx(3.0))]

    def test_rate_handles_counter_reset(self):
        st = TimeSeriesStore()
        for t, v in [(0, 0), (15, 30), (30, 5), (45, 35)]:
            st.append("c_total", None, float(v), t=float(t))
        ev = R.Evaluator(st)
        # increases: 30, reset->5, +30 => 65 over 45s
        got = ev.query("increase(c_total[1m])", 45.0)
        assert got == [({}, pytest.approx(65.0))]

    def test_arithmetic_division_by_zero_drops(self):
        st = TimeSeriesStore()
        st.append("num", {"k": "x"}, 4.0, t=0.0)
        st.append("den", {"k": "x"}, 2.0, t=0.0)
        st.append("num", {"k": "y"}, 4.0, t=0.0)
        st.append("den", {"k": "y"}, 0.0, t=0.0)
        ev = R.Evaluator(st)
        assert ev.query("num / den", 0.0) == [({"k": "x"}, 2.0)]

    def test_scientific_notation_thresholds_parse(self):
        """A five-nines SLO budget interpolates as 1.0000...e-05; the
        tokenizer must accept exponents or the strictest deployments'
        burn rules silently never evaluate."""
        st = TimeSeriesStore()
        st.append("x", None, 1.0, t=0.0)
        ev = R.Evaluator(st)
        assert ev.query("x > 1e-05", 0.0) == [({}, 1.0)]
        assert ev.query("x * 2E3", 0.0) == [({}, 2000.0)]
        # the full five-nines pack must parse end-to-end
        eng = R.RuleEngine(st, rules=R.default_rule_pack(
            objective=0.99999), clock=lambda: 0.0)
        eng.evaluate_once(at=0.0)
        assert eng._failures == 0

    def test_comparison_filters_and_multiwindow_and(self):
        st = TimeSeriesStore()
        st.append("short_burn", {"svc": "a"}, 5.0, t=0.0)
        st.append("long_burn", {"svc": "a"}, 0.2, t=0.0)
        st.append("short_burn", {"svc": "b"}, 5.0, t=0.0)
        st.append("long_burn", {"svc": "b"}, 3.0, t=0.0)
        ev = R.Evaluator(st)
        got = ev.query("short_burn > 1 and long_burn > 1", 0.0)
        # only b exceeds BOTH windows — the blip (a) is damped
        assert got == [({"svc": "b"}, 5.0)]


class TestHistogramQuantile:
    """Satellite 4: histogram_quantile against MetricsRegistry native
    histograms — exact-bucket-boundary, empty-histogram, and
    counter-reset cases."""

    def _scrape(self, reg, store, clock):
        loop = ScrapeLoop(store, targets=[RegistryTarget("m", reg)],
                          clock=clock)
        loop.scrape_once()
        return loop

    def test_exact_bucket_boundary(self):
        """A rank landing exactly on a cumulative bucket count returns
        the bucket's upper bound, no interpolation overshoot."""
        reg = MetricsRegistry()
        for _ in range(5):
            reg.histogram("h", 0.05, buckets=(0.1, 1.0), m="x")
        for _ in range(5):
            reg.histogram("h", 0.5, buckets=(0.1, 1.0), m="x")
        store, clock = TimeSeriesStore(), ManualClock()
        self._scrape(reg, store, clock)
        ev = R.Evaluator(store)
        got = ev.query("histogram_quantile(0.5, h_bucket)", 0.0)
        assert got[0][1] == pytest.approx(0.1)
        # interpolation inside the second bucket
        got = ev.query("histogram_quantile(0.75, h_bucket)", 0.0)
        assert 0.1 < got[0][1] <= 1.0

    def test_empty_histogram_yields_nan_and_no_alert(self):
        reg = MetricsRegistry()
        reg.histogram("h", 0.2, buckets=(0.1, 1.0), m="x")
        store, clock = TimeSeriesStore(), ManualClock()
        self._scrape(reg, store, clock)
        # an all-zero bucket family: synthesize via rate over ONE point
        # (no increase -> total 0)
        ev = R.Evaluator(store)
        got = ev.query("histogram_quantile(0.9, rate(h_bucket[1m]))", 0.0)
        assert len(got) == 1 and math.isnan(got[0][1])
        eng = R.RuleEngine(store, rules=[R.AlertRule(
            "Q", "histogram_quantile(0.9, rate(h_bucket[1m])) >= 0")],
            clock=lambda: 0.0)
        assert eng.evaluate_once(at=0.0) == []
        assert eng.active_alerts() == []

    def test_quantile_in_inf_bucket_reports_highest_finite_bound(self):
        reg = MetricsRegistry()
        reg.histogram("h", 99.0, buckets=(0.1, 1.0), m="x")  # +Inf only
        store, clock = TimeSeriesStore(), ManualClock()
        self._scrape(reg, store, clock)
        got = R.Evaluator(store).query(
            "histogram_quantile(0.5, h_bucket)", 0.0)
        assert got[0][1] == pytest.approx(1.0)

    def test_quantile_over_rate_survives_counter_reset(self):
        """A replica restart zeroes its histogram counters mid-window;
        rate()'s reset handling must keep the quantile sane instead of
        producing a negative increase."""
        store = TimeSeriesStore()
        # cumulative bucket counts, reset between t=30 and t=45
        series = {
            "0.1": [(0, 10), (15, 20), (30, 30), (45, 5), (60, 15)],
            "1.0": [(0, 20), (15, 40), (30, 60), (45, 10), (60, 30)],
            "+Inf": [(0, 20), (15, 40), (30, 60), (45, 10), (60, 30)],
        }
        for le, pts in series.items():
            for t, v in pts:
                store.append("h_bucket", {"le": le}, float(v),
                             t=float(t))
        got = R.Evaluator(store).query(
            "histogram_quantile(0.5, rate(h_bucket[1m]))", 60.0)
        assert len(got) == 1
        v = got[0][1]
        assert not math.isnan(v) and 0.0 < v <= 1.0


class TestRuleEngine:
    def test_recording_rule_materializes_selectable_series(self):
        store = TimeSeriesStore()
        clock = ManualClock()
        eng = R.RuleEngine(store, rules=[
            R.RecordingRule("job:c:rate1m", "rate(c_total[1m])")],
            clock=clock)
        for t in range(0, 90, 15):
            store.append("c_total", {"svc": "a"}, float(t * 2),
                         t=float(t))
            clock.t = float(t)
            eng.evaluate_once()
        got = eng.query("job:c:rate1m", at=75.0)
        assert got == [({"svc": "a"}, pytest.approx(2.0))]

    def test_alert_machine_pending_firing_resolved_with_events(self):
        cluster = FakeCluster()
        store = TimeSeriesStore()
        clock = ManualClock()
        eng = R.RuleEngine(
            store,
            rules=[R.AlertRule("HotZone", "temp > 10", for_s=30.0,
                               summary="too hot")],
            recorder=EventRecorder(cluster), clock=clock)
        log = []
        for t in range(0, 300, 15):
            clock.t = float(t)
            store.append("temp", {"namespace": "default", "zone": "z"},
                         50.0 if 30 <= t <= 120 else 1.0, t=float(t))
            for tr_ in eng.evaluate_once():
                log.append((t, tr_["to"]))
        assert log == [(30, "pending"), (60, "firing"),
                       (135, "resolved")]
        events = cluster.list("v1", "Event", namespace="default")
        reasons = {e["reason"]: e for e in events}
        assert reasons["AlertFiring"]["type"] == "Warning"
        assert "HotZone" in reasons["AlertFiring"]["message"]
        assert reasons["AlertResolved"]["type"] == "Normal"

    def test_refiring_bumps_event_count_dedup(self):
        cluster = FakeCluster()
        store = TimeSeriesStore()
        clock = ManualClock()
        eng = R.RuleEngine(
            store, rules=[R.AlertRule("Flappy", "temp > 10",
                                      for_s=0.0)],
            recorder=EventRecorder(cluster), clock=clock)
        for t in range(0, 150, 15):
            clock.t = float(t)
            hot = (t // 30) % 2 == 0  # flaps every other pair of cycles
            store.append("temp", {"namespace": "default"},
                         50.0 if hot else 0.0, t=float(t))
            eng.evaluate_once()
        events = [e for e in cluster.list("v1", "Event",
                                          namespace="default")
                  if e["reason"] == "AlertFiring"]
        # dedup: ONE Event object whose count climbed, not one per flap
        assert len(events) == 1
        assert events[0]["count"] >= 2

    def test_pending_blip_never_fires_no_event(self):
        cluster = FakeCluster()
        store = TimeSeriesStore()
        eng = R.RuleEngine(
            store, rules=[R.AlertRule("Slow", "lat > 1", for_s=60.0)],
            recorder=EventRecorder(cluster), clock=lambda: 0.0)
        # hot for one cycle only — shorter than for_s
        store.append("lat", {"namespace": "default"}, 5.0, t=0.0)
        eng.evaluate_once(at=0.0)
        store.append("lat", {"namespace": "default"}, 0.1, t=15.0)
        eng.evaluate_once(at=15.0)
        assert cluster.list("v1", "Event", namespace="default") == []
        assert eng.active_alerts() == []

    def test_alerts_series_and_registry_gauges_publish(self):
        store = TimeSeriesStore()
        reg = MetricsRegistry()
        eng = R.RuleEngine(store, rules=[
            R.AlertRule("A", "temp > 0", for_s=0.0)],
            registry=reg, clock=lambda: 0.0)
        store.append("temp", None, 1.0, t=0.0)
        eng.evaluate_once(at=0.0)
        assert store.instant("ALERTS", {"alertname": "A"}, at=0.0)
        rendered = reg.render()
        assert 'obs_alerts{alertname="A",state="firing"} 1' in rendered
        assert "obs_alert_transitions_total" in rendered

    def test_staleness_resolves_alert_when_target_dies(self):
        """Satellite 4: ScrapeLoop target loss -> staleness marker ->
        the alert over that series RESOLVES instead of firing forever
        on the last-known-bad value."""
        clock = ManualClock()
        reg = MetricsRegistry()
        reg.gauge("serving_kv_pages_free", 0.0, model="m")  # exhausted!
        store = TimeSeriesStore()
        loop = ScrapeLoop(store, targets=[
            RegistryTarget("r0", reg)], clock=clock)
        eng = R.RuleEngine(store, rules=[
            R.AlertRule("KVPagesExhausted",
                        "serving_kv_pages_free == 0", for_s=0.0)],
            clock=clock, lookback_s=60.0)
        loop.scrape_once()
        trs = eng.evaluate_once()
        assert [t["to"] for t in trs] == ["pending", "firing"]
        # the replica dies; its gauge goes stale
        clock.advance(15.0)
        loop.targets[0].fetch = lambda: (_ for _ in ()).throw(
            OSError("gone"))
        loop.scrape_once()
        trs = eng.evaluate_once()
        assert [t["to"] for t in trs] == ["resolved"]
        assert eng.active_alerts() == []


# -- goodput (tentpole layer 3) ----------------------------------------------


def mkspan(name, start, end, **attrs):
    s = tr.Span(name=name, trace_id="t" * 32, span_id=tr.new_span_id(),
                start=start, attrs=attrs)
    s.end = end
    return s


class TestGoodput:
    def test_buckets_sum_to_wall_clock(self):
        spans = [
            mkspan("jaxjob.provision", 1.0, 2.0),
            mkspan("train.step", 3.0, 4.0, compile=True, step=0),
            mkspan("train.step", 4.0, 5.0, step=1),
            mkspan("train.checkpoint", 5.0, 5.5, step=2),
            mkspan("train.step", 5.5, 6.5, step=2),
        ]
        rep = gp.account(spans, 0.0, 10.0, chips=8).check()
        b = rep.buckets
        assert b[gp.ADMISSION] == pytest.approx(3.0)  # 0..3 incl prov
        assert b[gp.COMPILE] == pytest.approx(1.0)
        assert b[gp.PRODUCTIVE] == pytest.approx(2.0)
        assert b[gp.CHECKPOINT] == pytest.approx(0.5)
        assert b[gp.OTHER] == pytest.approx(3.5)
        assert rep.goodput == pytest.approx(0.2)
        assert rep.chip_seconds_lost()[gp.ADMISSION] == pytest.approx(24.0)

    def test_overlap_never_double_counts(self):
        """A checkpoint inside a step window and nested fit/step spans
        must resolve by priority, conserving total time."""
        spans = [
            mkspan("train.step", 1.0, 5.0, step=7),
            mkspan("train.checkpoint", 2.0, 3.0, step=7),  # inside step
            mkspan("train.step", 1.0, 5.0, step=7),        # duplicate
        ]
        rep = gp.account(spans, 0.0, 6.0).check()
        assert rep.buckets[gp.PRODUCTIVE] == pytest.approx(4.0)
        assert rep.buckets[gp.CHECKPOINT] == pytest.approx(0.0)

    def test_second_provision_is_restart_rebuild(self):
        spans = [
            mkspan("jaxjob.provision", 0.5, 1.0),
            mkspan("train.step", 1.0, 2.0, step=0),
            mkspan("jaxjob.provision", 3.0, 4.0),  # the gang restart
            mkspan("train.step", 4.0, 5.0, step=1),
        ]
        rep = gp.account(spans, 0.0, 5.0).check()
        assert rep.buckets[gp.RESTART] == pytest.approx(1.0)
        assert rep.buckets[gp.ADMISSION] == pytest.approx(1.0)

    def test_resize_rebuild_classified(self):
        spans = [
            mkspan("train.step", 1.0, 2.0, step=0),
            mkspan("elastic.rebuild", 2.0, 3.5, gen=2, size=2),
            mkspan("train.step", 3.5, 4.5, step=1),
        ]
        rep = gp.account(spans, 1.0, 4.5).check()
        assert rep.buckets[gp.RESIZE] == pytest.approx(1.5)
        assert rep.buckets[gp.ADMISSION] == pytest.approx(0.0)

    def test_window_clipping_and_open_spans_skipped(self):
        open_span = tr.Span(name="train.step", trace_id="t" * 32,
                            span_id="s" * 16, start=2.0)  # end=None
        spans = [mkspan("train.step", 0.0, 4.0, step=0), open_span]
        rep = gp.account(spans, 1.0, 3.0).check()
        assert rep.wall_s == pytest.approx(2.0)
        assert rep.buckets[gp.PRODUCTIVE] == pytest.approx(2.0)

    def test_conservation_violation_raises(self):
        rep = gp.GoodputReport(wall_s=10.0, chips=1,
                               buckets={gp.PRODUCTIVE: 3.0,
                                        gp.OTHER: 3.0})
        with pytest.raises(AssertionError, match="buckets sum"):
            rep.check()

    def test_serving_slo_from_registry(self):
        reg = MetricsRegistry()
        for lat in [0.1] * 98 + [3.0, 4.0]:  # 98% under 0.5s
            reg.histogram("router_request_seconds", lat,
                          buckets=REQUEST_BUCKETS,
                          namespace="default", service="chat")
        slo = gp.ServingSLO(latency_target_s=0.5, objective=0.99)
        st = slo.from_registry(reg, "default", "chat")
        assert st["requests"] == 100
        assert st["attainment"] == pytest.approx(0.98)
        assert st["budget_burn"] == pytest.approx(2.0)
        assert not st["met"]

    def test_serving_slo_int_target_matches_rendered_buckets(self):
        """The registry renders le bounds as str(float) ("1.0"); an
        int-valued target must still count its fast samples instead of
        reporting a false 100x burn."""
        reg = MetricsRegistry()
        for lat in [0.2] * 10:
            reg.histogram("router_request_seconds", lat,
                          buckets=REQUEST_BUCKETS,
                          namespace="default", service="chat")
        slo = gp.ServingSLO(latency_target_s=1, objective=0.99)  # int!
        st = slo.from_registry(reg, "default", "chat")
        assert st["attainment"] == pytest.approx(1.0)
        assert st["met"]
        # the burn expression embeds the same normalized spelling
        assert 'le="1.0"' in R.burn_rate_expr(1, 0.99, "1m")

    def test_job_report_pinned_start_with_only_open_spans(self):
        open_span = tr.Span(name="train.step", trace_id="t" * 32,
                            span_id="s" * 16, start=5.0)  # still open
        rep = gp.job_report([open_span], window_start=2.0)
        rep.check()
        assert rep.wall_s == 0.0  # all-admission zero window, no crash

    def test_serving_slo_from_store_windowed(self):
        store = TimeSeriesStore()
        # 10 fast then 10 slow requests across two windows
        for t, fast, total in [(0, 0, 0), (60, 10, 10), (120, 10, 20)]:
            store.append("router_request_seconds_bucket",
                         {"le": "0.5", "service": "chat"}, float(fast),
                         t=float(t))
            store.append("router_request_seconds_count",
                         {"service": "chat"}, float(total), t=float(t))
        # fractional windows round instead of truncating to "[0s]" (an
        # empty window read a burning service as trivially met)
        empty = gp.ServingSLO().from_store(store, at=120.0,
                                           window_s=0.4, service="chat")
        assert empty["requests"] == 0.0
        slo = gp.ServingSLO(latency_target_s=0.5, objective=0.9)
        st = slo.from_store(store, at=120.0, window_s=70.0,
                            service="chat")
        # window (50,120]: fast 0->10... increase(fast)=10, total=20-?: (60->120): 10
        assert st["requests"] == pytest.approx(10.0)
        assert st["attainment"] == pytest.approx(0.0)
        assert st["budget_burn"] == pytest.approx(10.0)


# -- the acceptance kill drill -----------------------------------------------


class TestKillDrill:
    """Scripted chaos kill drill on a virtual clock: a REAL TokenRouter
    serves healthy traffic, then a fault window (replica killed, slow
    completions, reconcile errors) — the router-SLO and reconcile
    alerts must FIRE during the window and RESOLVE after heal, with
    Events through the EventRecorder. Pinned per the ISSUE acceptance
    criteria."""

    HEALTHY_LAT = 0.06
    FAULT_LAT = 2.0
    FAULT = range(8, 14)  # fault-window cycles (15s each)

    def _drive_cycle(self, router, clock, cycle):
        latency = self.FAULT_LAT if cycle in self.FAULT \
            else self.HEALTHY_LAT
        tickets = [router.submit(40) for _ in range(12)]
        if cycle == self.FAULT.start:
            # the kill: one replica vanishes; its in-flight work sheds
            router.set_members([Member("r0")])
        if cycle == self.FAULT.stop:
            router.set_members([Member("r0"), Member("r1")])  # heal
        clock.advance(latency)
        for t in tickets:
            router.complete(t)
        clock.advance(15.0 - latency)

    def test_router_slo_and_reconcile_alerts_fire_then_resolve(self):
        clock = ManualClock()
        cluster = FakeCluster()
        reg = MetricsRegistry()
        router = TokenRouter(service="chat", namespace="default",
                             clock=clock, registry=reg, prom_sink=False,
                             tracer=tr.Tracer())
        router.set_members([Member("r0"), Member("r1")])
        plane = FleetPlane(
            registry=MetricsRegistry(),
            recorder=EventRecorder(cluster),
            targets=[RegistryTarget("router", reg)],
            rules=R.default_rule_pack(latency_target_s=0.5,
                                      short_window="30s",
                                      long_window="2m"),
            interval_s=15.0, clock=clock)
        by_cycle: dict[int, list] = {}
        for cycle in range(40):
            self._drive_cycle(router, clock, cycle)
            # reconcile traffic: errors only inside the fault window
            reg.counter_inc("controller_reconcile_total", by=20.0,
                            controller="jaxjob", result="success")
            if cycle in self.FAULT:
                reg.counter_inc("controller_reconcile_total", by=10.0,
                                controller="jaxjob", result="error")
            out = plane.tick(at=clock.t)
            for trans in out["transitions"]:
                by_cycle.setdefault(cycle, []).append(
                    (trans["alert"], trans["to"]))
        flat = [(c, a, to) for c, moves in sorted(by_cycle.items())
                for a, to in moves]

        def cycle_of(alert, to):
            return next((c for c, a, t_ in flat
                         if a == alert and t_ == to), None)

        # both alerts FIRE inside the fault window...
        for alert in ("RouterLatencySLOBurn", "ReconcileErrorRate"):
            fired_at = cycle_of(alert, "firing")
            assert fired_at is not None, (alert, flat)
            assert self.FAULT.start <= fired_at <= self.FAULT.stop, \
                (alert, fired_at, flat)
            # ...and RESOLVE at/after the heal cycle (the short burn
            # window clears fast — that speed is the point of
            # multi-window burn alerts)
            resolved_at = cycle_of(alert, "resolved")
            assert resolved_at is not None, (alert, flat)
            assert resolved_at >= self.FAULT.stop, (alert, resolved_at)
        assert plane.engine.active_alerts() == []
        # the Events made it through the recorder, dedup'd per alert
        events = cluster.list("v1", "Event", namespace="default")
        reasons = [(e["reason"],
                    e["involvedObject"]["name"]) for e in events]
        assert ("AlertFiring", "routerlatencysloburn") in reasons
        assert ("AlertResolved", "routerlatencysloburn") in reasons
        assert ("AlertFiring", "reconcileerrorrate") in reasons
        assert ("AlertResolved", "reconcileerrorrate") in reasons
        # zero drops through the kill: the shed tickets completed
        completed = reg.series("router_requests_total")
        outcomes = {ls["outcome"]: v for ls, v in completed}
        assert outcomes.get("failed", 0) == 0
        assert outcomes["completed"] == 12 * 40


# -- dashboard surface -------------------------------------------------------


class TestDashboardRoutes:
    def _dash(self):
        from kubeflow_tpu.utils.httpd import HttpReq
        from kubeflow_tpu.webapps.dashboard import Dashboard

        clock = ManualClock()
        reg = MetricsRegistry()
        reg.gauge("router_queue_depth", 3.0, namespace="default",
                  service="chat")
        plane = FleetPlane(registry=MetricsRegistry(), recorder=None,
                           targets=[RegistryTarget("router", reg)],
                           interval_s=15.0, clock=clock,
                           collector=tr.TraceCollector())
        plane.tick(at=0.0)
        router = Dashboard(FakeCluster(), plane=plane).router()

        def get(path, query=None):
            resp = router.dispatch(HttpReq(
                method="GET", path=path, params={},
                query=query or {},
                headers={"kubeflow-userid": "alice@example.com"}))
            return resp.status, json.loads(resp.body)

        return get, plane

    def test_api_query_evaluates_promql_lite(self):
        get, _ = self._dash()
        status, doc = get("/api/query",
                          {"q": ['router_queue_depth{service="chat"}']})
        assert status == 200
        assert doc["result"] == [{
            "labels": {"instance": "router", "namespace": "default",
                       "service": "chat"}, "value": 3.0}]

    def test_api_query_bad_expression_is_400(self):
        get, _ = self._dash()
        status, doc = get("/api/query", {"q": ["sum by ("]})
        assert status == 400

    def test_bad_numeric_params_are_400_not_500(self):
        get, _ = self._dash()
        assert get("/api/query", {"q": ["up"], "at": ["abc"]})[0] == 400
        assert get("/api/goodput", {"chips": ["abc"]})[0] == 400
        assert get("/api/goodput", {"window_s": ["x"]})[0] == 400

    def test_api_alerts_and_goodput_shapes(self):
        get, plane = self._dash()
        status, doc = get("/api/alerts")
        assert status == 200 and doc == {"alerts": []}
        plane.collector.add(mkspan("train.step", 1.0, 2.0, step=0))
        status, doc = get("/api/goodput")
        assert status == 200
        assert doc["training"]["goodput_pct"] == pytest.approx(100.0)
        assert "serving" in doc


# -- bench contract (CI ratchet) ---------------------------------------------


@pytest.mark.usefixtures("virtual_time_guard")
class TestObsBenchContract:
    def test_smoke_is_deterministic_and_fires_the_pack(self):
        from tools.obs_bench import SMOKE_CONFIG, run_bench

        r1 = run_bench(**SMOKE_CONFIG)
        r2 = run_bench(**SMOKE_CONFIG)
        # byte-stable decisions + exact scrape op counts per seed
        assert r1["decision_fingerprint"] == r2["decision_fingerprint"]
        assert r1["appends"] == r2["appends"]
        assert r1["samples_total"] == r2["samples_total"]
        assert r1["series"] == r2["series"]
        assert r1["dropped"] == 0
        assert r1["alerts_fired"] == [
            "CheckpointFailures", "KVPagesExhausted",
            "ReconcileErrorRate", "RouterLatencySLOBurn",
            "SchedulerPassSlow"]
        assert set(r1["alerts_resolved"]) >= {
            "KVPagesExhausted", "ReconcileErrorRate",
            "RouterLatencySLOBurn"}

    def test_check_green_against_committed_bank(self):
        from tools.obs_bench import DEFAULT_OUT, check_against

        assert check_against(DEFAULT_OUT) == 0

    def test_check_fails_on_poisoned_bank(self, tmp_path):
        from tools.obs_bench import DEFAULT_OUT, check_against

        with open(DEFAULT_OUT) as fh:
            bank = json.load(fh)
        bank["smoke"]["decision_fingerprint"] = "0" * 64
        poisoned = tmp_path / "bank.json"
        poisoned.write_text(json.dumps(bank))
        assert check_against(str(poisoned)) == 1

    def test_banked_full_run_meets_acceptance(self):
        """The committed bank must show >=10k series with rule eval
        inside a sane budget — the ISSUE acceptance row."""
        from tools.obs_bench import DEFAULT_OUT

        with open(DEFAULT_OUT) as fh:
            bank = json.load(fh)
        full = bank["full"]
        assert full["series"] >= 10000
        assert full["eval_p99_ms"] > 0
        assert full["eval_p99_ms"] < 1000.0  # budget: well under 1s
        assert full["alerts_fired"] == [
            "CheckpointFailures", "KVPagesExhausted",
            "ReconcileErrorRate", "RouterLatencySLOBurn",
            "SchedulerPassSlow"]


# -- silences + routing (ISSUE 13 satellite a) --------------------------------


class TestSilenceStore:
    def _store(self):
        from kubeflow_tpu.obs.plane import SilenceStore

        clock = ManualClock()
        return SilenceStore(clock=clock), clock

    def test_alertname_matcher_matches_rule_name(self):
        store, _ = self._store()
        store.add({"alertname": "KVPagesExhausted"}, until=100.0)
        assert store.silenced("KVPagesExhausted",
                              {"service": "chat"}, at=0.0)
        assert not store.silenced("NodeSLOBurn", {}, at=0.0)

    def test_label_matchers_must_all_match(self):
        store, _ = self._store()
        store.add({"alertname": "A", "namespace": "prod"}, until=100.0)
        assert store.silenced("A", {"namespace": "prod"}, at=0.0)
        assert not store.silenced("A", {"namespace": "dev"}, at=0.0)
        assert not store.silenced("A", {}, at=0.0)

    def test_expiry_prunes_and_unmutes(self):
        store, clock = self._store()
        store.add({"alertname": "A"}, until=50.0)
        assert store.silenced("A", {}, at=49.0)
        assert not store.silenced("A", {}, at=50.0)  # until <= now
        clock.t = 60.0
        assert store.list() == []  # pruned on read

    def test_add_validates_and_delete_round_trips(self):
        store, _ = self._store()
        with pytest.raises(ValueError):
            store.add({}, until=100.0)
        entry = store.add({"alertname": "A"}, until=100.0,
                          comment="maint", created_by="alice")
        assert entry["id"] == "s1"
        assert [s["id"] for s in store.list(at=0.0)] == ["s1"]
        assert store.delete("s1") is True
        assert store.delete("s1") is False

    def test_store_capacity_is_bounded(self):
        from kubeflow_tpu.obs.plane import SilenceStore

        store = SilenceStore(clock=ManualClock(), limit=2)
        store.add({"a": "1"}, until=100.0)
        store.add({"a": "2"}, until=100.0)
        with pytest.raises(ValueError):
            store.add({"a": "3"}, until=100.0)


class TestSilencedRuleEngine:
    def test_silence_mutes_events_but_not_the_state_machine(self):
        """Alertmanager semantics: a silenced alert still walks
        pending/firing/resolved and still publishes gauges — only the
        notification Events (and remediation) are muted."""
        clock = ManualClock()
        store = TimeSeriesStore()
        cluster = FakeCluster()
        muted = {"on": True}
        eng = R.RuleEngine(
            store,
            rules=[R.AlertRule(name="Hot", expr="temp > 10",
                               for_s=0.0)],
            recorder=EventRecorder(cluster),
            registry=MetricsRegistry(), clock=clock,
            silenced=lambda alert, labels, at: muted["on"])
        store.append("temp", {"zone": "a"}, 99.0, 10.0)
        trs = eng.evaluate_once(at=10.0)
        assert [t["to"] for t in trs] == ["pending", "firing"]
        assert cluster.list("v1", "Event", namespace="default") == []
        # silence lifts -> the next transition notifies again
        muted["on"] = False
        store.append("temp", {"zone": "a"}, 1.0, 20.0)
        (t2,) = eng.evaluate_once(at=20.0)
        assert t2["to"] == "resolved"
        reasons = [e["reason"] for e in
                   cluster.list("v1", "Event", namespace="default")]
        assert reasons == ["AlertResolved"]


class TestRouting:
    def test_first_match_routing_by_severity_and_matchers(self):
        from kubeflow_tpu.obs.plane import Route

        plane = FleetPlane(
            registry=MetricsRegistry(), targets=[],
            clock=ManualClock(), collector=tr.TraceCollector(),
            routes=(
                Route(receiver="prod-page", severity="critical",
                      matchers={"namespace": "prod"}),
                Route(receiver="page", severity="critical"),
                Route(receiver="ticket", severity="warning"),
                Route(receiver="log"),
            ))
        assert plane.route_for("A", "critical",
                               {"namespace": "prod"}) == "prod-page"
        assert plane.route_for("A", "critical",
                               {"namespace": "dev"}) == "page"
        assert plane.route_for("A", "warning", {}) == "ticket"
        assert plane.route_for("A", "info", {}) == "log"

    def test_alerts_read_enriched_with_severity_receiver_silenced(self):
        clock = ManualClock()
        reg = MetricsRegistry()
        reg.gauge("temp", 99.0, zone="a")
        plane = FleetPlane(
            registry=MetricsRegistry(),
            targets=[RegistryTarget("t", reg)],
            clock=clock, collector=tr.TraceCollector(),
            rules=[R.AlertRule(name="Hot", expr="temp > 10",
                               for_s=0.0, severity="critical")])
        plane.tick(at=0.0)
        (alert,) = plane.alerts()["alerts"]
        assert alert["severity"] == "critical"
        assert alert["receiver"] == "page"
        assert alert["silenced"] is False
        plane.silences.add({"alertname": "Hot"}, until=1000.0)
        (alert,) = plane.alerts()["alerts"]
        assert alert["silenced"] is True


class TestPlaneRemediation:
    def test_tick_runs_remediation_and_audit_is_readable(self):
        from kubeflow_tpu.obs.remediate import (
            Remediation, RemediationEngine,
        )

        clock = ManualClock()
        reg = MetricsRegistry()
        reg.gauge("temp", 99.0, zone="a")
        ran = []
        engine = RemediationEngine(
            [Remediation("cool", "Hot",
                         lambda trn: ran.append(trn) or "cooled")],
            registry=MetricsRegistry(), clock=clock)
        plane = FleetPlane(
            registry=MetricsRegistry(),
            targets=[RegistryTarget("t", reg)],
            clock=clock, collector=tr.TraceCollector(),
            rules=[R.AlertRule(name="Hot", expr="temp > 10",
                               for_s=0.0)],
            remediator=engine)
        out = plane.tick(at=0.0)
        assert [d["result"] for d in out["remediations"]] == ["executed"]
        assert len(ran) == 1
        (entry,) = plane.remediation_audit()["audit"]
        assert entry["action"] == "cool" and entry["alert"] == "Hot"

    def test_plane_silence_mutes_remediation_too(self):
        """The plane owns the hookup: one POST /api/silences mutes
        notification AND action."""
        from kubeflow_tpu.obs.remediate import (
            Remediation, RemediationEngine,
        )

        clock = ManualClock()
        reg = MetricsRegistry()
        reg.gauge("temp", 99.0, zone="a")
        ran = []
        engine = RemediationEngine(
            [Remediation("cool", "Hot",
                         lambda trn: ran.append(trn) or "")],
            registry=MetricsRegistry(), clock=clock)
        plane = FleetPlane(
            registry=MetricsRegistry(),
            targets=[RegistryTarget("t", reg)],
            clock=clock, collector=tr.TraceCollector(),
            rules=[R.AlertRule(name="Hot", expr="temp > 10",
                               for_s=0.0)],
            remediator=engine)
        plane.silences.add({"alertname": "Hot"}, until=1000.0)
        out = plane.tick(at=0.0)
        assert [d["result"] for d in out["remediations"]] \
            == ["silenced"]
        assert ran == []


class TestSilencesApi:
    def _dash(self):
        from kubeflow_tpu.utils.httpd import HttpReq
        from kubeflow_tpu.webapps.dashboard import Dashboard

        clock = ManualClock()
        plane = FleetPlane(registry=MetricsRegistry(), targets=[],
                           clock=clock, collector=tr.TraceCollector())
        router = Dashboard(FakeCluster(), plane=plane).router()

        def call(method, path, body=None, params=None):
            resp = router.dispatch(HttpReq(
                method=method, path=path, params=params or {},
                query={},
                headers={"kubeflow-userid": "alice@example.com"},
                body=json.dumps(body).encode() if body is not None
                else b""))
            return resp.status, json.loads(resp.body)

        return call, plane, clock

    def test_post_list_delete_lifecycle(self):
        call, plane, _ = self._dash()
        status, entry = call(
            "POST", "/api/silences",
            {"matchers": {"alertname": "KVPagesExhausted"},
             "until": 500.0, "comment": "maint window"})
        assert status == 201
        assert entry["createdBy"] == "alice@example.com"
        assert plane.silences.silenced("KVPagesExhausted", {}, at=0.0)
        status, doc = call("GET", "/api/silences")
        assert status == 200
        assert [s["id"] for s in doc["silences"]] == [entry["id"]]
        status, doc = call("DELETE", f"/api/silences/{entry['id']}",
                           params={"id": entry["id"]})
        assert status == 200 and doc == {"deleted": entry["id"]}
        assert call("GET", "/api/silences")[1] == {"silences": []}

    def test_post_duration_s_relative_expiry(self):
        call, plane, clock = self._dash()
        clock.t = 100.0
        status, entry = call(
            "POST", "/api/silences",
            {"matchers": {"alertname": "A"}, "duration_s": 60})
        assert status == 201 and entry["until"] == 160.0

    def test_post_validation_is_400(self):
        call, _, _ = self._dash()
        assert call("POST", "/api/silences", {"until": 5.0})[0] == 400
        assert call("POST", "/api/silences",
                    {"matchers": {"a": "b"}})[0] == 400
        assert call("POST", "/api/silences",
                    {"matchers": {}, "until": 5.0})[0] == 400

    def test_delete_unknown_is_404(self):
        call, _, _ = self._dash()
        assert call("DELETE", "/api/silences/s99",
                    params={"id": "s99"})[0] == 404


# -- goodput exporter (ISSUE 13 satellite b) ----------------------------------


class TestGoodputExporter:
    def test_export_once_publishes_the_ledger_as_series(self):
        reg = MetricsRegistry()
        collector = tr.TraceCollector()
        collector.add(mkspan("jaxjob.provision", 0.0, 10.0))
        collector.add(mkspan("train.step", 10.0, 90.0, step=0))
        collector.add(mkspan("train.checkpoint", 90.0, 100.0))
        exp = gp.GoodputExporter(registry=reg, collector=collector,
                                 chips=8)
        report = exp.export_once(at=100.0)
        assert report.goodput == pytest.approx(0.8)
        assert reg.series("goodput_ratio")[0][1] == pytest.approx(0.8)
        assert reg.series("goodput_wall_seconds")[0][1] \
            == pytest.approx(100.0)
        buckets = {ls["bucket"]: v
                   for ls, v in reg.series("goodput_bucket_seconds")}
        assert buckets["productive_step"] == pytest.approx(80.0)
        assert buckets["checkpoint"] == pytest.approx(10.0)
        lost = {ls["cause"]: v
                for ls, v in reg.series("goodput_chip_seconds_lost")}
        # chips scale the cost: 20 non-productive seconds * 8 chips
        assert sum(lost.values()) == pytest.approx(160.0)

    def test_scrape_plane_picks_the_series_up(self):
        reg = MetricsRegistry()
        collector = tr.TraceCollector()
        collector.add(mkspan("train.step", 0.0, 10.0, step=0))
        gp.GoodputExporter(registry=reg,
                           collector=collector).export_once(at=10.0)
        clock = ManualClock()
        plane = FleetPlane(registry=MetricsRegistry(),
                           targets=[RegistryTarget("ctl", reg)],
                           clock=clock, collector=collector)
        plane.tick(at=0.0)
        out = plane.query("goodput_ratio")
        assert out["result"][0]["value"] == pytest.approx(1.0)


# -- heal bench contract (ISSUE 13 satellite f) -------------------------------


@pytest.mark.usefixtures("virtual_time_guard")
class TestHealBenchContract:
    def test_smoke_is_deterministic_and_heals(self):
        from tools.heal_bench import SMOKE_CONFIG, run_bench

        r1 = run_bench(**SMOKE_CONFIG)
        r2 = run_bench(**SMOKE_CONFIG)
        assert r1["decision_fingerprint"] == r2["decision_fingerprint"]
        assert r1["appends"] == r2["appends"]
        assert r1["heals"] == r2["heals"]
        assert r1["remediation_results"] == r2["remediation_results"]
        # the smoke window heals the KV incident and the node burn
        # end-to-end (cluster-state clear conditions, zero reconciles)
        assert r1["heals"]["KVPagesExhausted"]["healed"] is True
        assert r1["heals"]["NodeSLOBurn"]["healed"] is True
        assert r1["cordoned"] == ["tpu-0"]
        assert r1["remediation_results"] == {"executed": 3}

    def test_check_green_against_committed_bank(self):
        from tools.heal_bench import DEFAULT_OUT, check_against

        assert check_against(DEFAULT_OUT) == 0

    def test_check_fails_on_poisoned_bank(self, tmp_path):
        from tools.heal_bench import DEFAULT_OUT, check_against

        with open(DEFAULT_OUT) as fh:
            bank = json.load(fh)
        bank["smoke"]["decision_fingerprint"] = "0" * 64
        poisoned = tmp_path / "bank.json"
        poisoned.write_text(json.dumps(bank))
        assert check_against(str(poisoned)) == 1

    def test_banked_full_run_meets_acceptance(self):
        """The ISSUE acceptance row: every staged incident heals
        end-to-end with zero human reconciles — remediation fired, the
        breached signal cleared, and the topology moves happened."""
        from tools.heal_bench import DEFAULT_OUT

        with open(DEFAULT_OUT) as fh:
            bank = json.load(fh)
        full = bank["full"]
        for incident in ("KVPagesExhausted", "SchedulerPassSlow",
                         "NodeSLOBurn"):
            heal = full["heals"][incident]
            assert heal["healed"] is True, incident
            assert heal["remediated"] is not None
            assert heal["resolved"] > heal["fired"]
        assert full["remediation_results"] == {"executed": 3}
        assert full["cordoned"] == ["tpu-0"]
        # the drained gang shrank elastically and grew back
        assert full["train_status"]["resizes"] >= 2
        assert full["train_status"]["activeReplicas"] == 2
