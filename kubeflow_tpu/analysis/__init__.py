"""tpulint — JAX/TPU-aware static analysis for this tree.

Two rule families, both distilled from bugs this repo actually shipped
(VERDICT.md):

- ``TPU1xx`` (rules_jax): closure-captured arrays in jitted programs,
  host syncs inside traced functions, import-time device work, missing
  buffer donation on train steps.
- ``LOCK2xx`` (rules_lockset): a lockset checker for the hand-rolled
  mutex idiom of the control plane, plus blocking-call detection in
  reconcile bodies.

CLI: ``python -m kubeflow_tpu.analysis [paths...]`` — exits nonzero on
findings. Suppress a finding in-line with
``# tpulint: disable=RULE  <justification>``. docs/static-analysis.md
documents every rule.
"""

from kubeflow_tpu.analysis.core import (  # noqa: F401
    Finding, Module, Rule, all_rules, register, scan_paths, scan_source,
)
from kubeflow_tpu.analysis.report import render_json, render_text  # noqa: F401

__all__ = ["Finding", "Module", "Rule", "all_rules", "register",
           "scan_paths", "scan_source", "render_json", "render_text"]
