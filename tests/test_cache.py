"""ClusterCache + capacity index + FakeCluster list-index (ISSUE 7).

The fleet-scale contract: the informer-style cache must be
indistinguishable from a fresh relist — after any sequence of cluster
mutations, watch drops (ChaosWatchStream), 410-expired resumes, and
out-of-order deliveries — while serving every hot-path read from its
incremental indexes; and the bisect best-fit over sorted free-capacity
buckets must place exactly like the old full scan.
"""

import random

import pytest

from kubeflow_tpu.control.cache import NODE, POD, ClusterCache
from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.chaos import ChaosClient, ChaosPolicy
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.scheduler import capacity as CP
from kubeflow_tpu.control.scheduler import nodes as N
from kubeflow_tpu.control.scheduler import (
    GATE_GANG, SCHEDULER_NAME,
)

# -- helpers -----------------------------------------------------------------


def mk_pod(name, namespace="default", job=None, node=None, chips=2,
           phase=None, selector=None, gates=False):
    pod = ob.new_object(
        "v1", "Pod", name, namespace,
        labels={JT.LABEL_JOB_NAME: job} if job else None)
    pod["spec"] = {
        "schedulerName": SCHEDULER_NAME,
        "containers": [{"name": "jax", "resources": {
            "limits": {JT.RESOURCE_TPU: chips}}}],
    }
    if selector:
        pod["spec"]["nodeSelector"] = selector
    if node:
        pod["spec"]["nodeName"] = node
    if gates:
        pod["spec"]["schedulingGates"] = [{"name": GATE_GANG}]
    if phase:
        pod["status"] = {"phase": phase}
    return pod


def recomputed_free(cluster) -> dict:
    free = {}
    for n in cluster.list("v1", "Node"):
        v = N.node_view(n)
        free[v.name] = v.allocatable_chips
    for p in cluster.list("v1", "Pod"):
        node = (p.get("spec") or {}).get("nodeName")
        if not node or node not in free:
            continue
        if (p.get("status") or {}).get("phase") in N.TERMINAL_PHASES:
            continue
        free[node] -= N.pod_tpu_request(p)
    return free


def assert_cache_equals_relist(cache: ClusterCache, cluster: FakeCluster):
    """THE property: the cache's snapshot — raw objects, free-chip
    accounting, and the sorted buckets — must equal a fresh relist."""
    for api, kind in (NODE, POD):
        want = {
            (ob.meta(o).get("namespace") or "", ob.meta(o)["name"]):
                ob.meta(o)["resourceVersion"]
            for o in cluster.list(api, kind)}
        got = {k: ob.meta(o)["resourceVersion"]
               for k, o in cache.objects(api, kind).items()}
        assert got == want, f"{kind} snapshot diverged from relist"
        # the per-namespace buckets partition the same snapshot exactly
        by_ns: dict = {}
        for ns in {k[0] for k in want}:
            for o in cache.objects_ns(api, kind, ns):
                m = ob.meta(o)
                by_ns[(m.get("namespace") or "", m["name"])] = \
                    m["resourceVersion"]
        assert by_ns == want, f"{kind} namespace buckets diverged"
    cap = cache.capacity()
    want_free = recomputed_free(cluster)
    assert cap.free == want_free, "free-chip accounting diverged"
    # bucket integrity: the catch-all bucket is exactly {(free, name)}
    flat = dict((name, free) for free, name in cap.buckets[CP.ALL_NODES].items)
    assert flat == want_free, "sorted bucket diverged from free map"
    assert cap.buckets[CP.ALL_NODES].items == \
        sorted(cap.buckets[CP.ALL_NODES].items), "bucket lost sort order"
    spot = {name for name, v in cap.views.items() if v.spot}
    assert {n for _f, n in cap.buckets[CP.ALL_NODES].spot} == spot


# -- FakeCluster list index (satellite) --------------------------------------


class TestFakeClusterListIndex:
    def _mixed_store(self):
        c = FakeCluster()
        for i in range(40):
            c.create(ob.new_object("v1", "ConfigMap", f"cm-{i}", "ns"))
        for i in range(10):
            c.create(mk_pod(f"p-{i}", "ns", job="g1"))
        for i in range(5):
            c.create(mk_pod(f"q-{i}", "other", job="g2"))
        c.create(N.new_tpu_node("n0"))
        return c

    def test_list_scans_only_the_matching_kind_bucket(self):
        c = self._mixed_store()
        c.reset_stats()
        pods = c.list("v1", "Pod")
        assert len(pods) == 15
        # op-count pin: 56 objects live, only the 15 pods were scanned
        assert c.stats["list_scanned"] == 15
        assert c.stats["list_copied"] == 15

    def test_namespaced_list_scans_only_that_namespace(self):
        c = self._mixed_store()
        c.reset_stats()
        pods = c.list("v1", "Pod", namespace="other")
        assert len(pods) == 5
        assert c.stats["list_scanned"] == 5

    def test_label_selector_scans_bucket_copies_matches_only(self):
        c = self._mixed_store()
        c.reset_stats()
        pods = c.list("v1", "Pod", namespace="ns",
                      label_selector={"matchLabels": {
                          JT.LABEL_JOB_NAME: "g1"}})
        assert len(pods) == 10
        assert c.stats["list_scanned"] == 10
        assert c.stats["list_copied"] == 10

    def test_list_snapshot_copies_nothing(self):
        c = self._mixed_store()
        c.reset_stats()
        items, rv = c.list_snapshot("v1", "Pod")
        assert len(items) == 15
        assert rv == c.current_rv
        assert c.stats["list_copied"] == 0
        # same content as the copying path, same order
        assert [ob.meta(o)["name"] for o in items] == \
            [ob.meta(o)["name"] for o in c.list("v1", "Pod")]

    def test_index_tracks_update_and_delete(self):
        c = self._mixed_store()
        got = c.get("v1", "Pod", "p-0", "ns")
        got["spec"]["nodeName"] = "n0"
        c.update(got)
        assert any(p["spec"].get("nodeName") == "n0"
                   for p in c.list("v1", "Pod", namespace="ns"))
        c.delete("v1", "Pod", "p-0", "ns")
        assert len(c.list("v1", "Pod", namespace="ns")) == 9
        c.reset_stats()
        c.list("v1", "Pod", namespace="ns")
        assert c.stats["list_scanned"] == 9

    def test_stats_paused_suspends_counting(self):
        c = self._mixed_store()
        c.reset_stats()
        with c.stats_paused():
            c.list("v1", "Pod")
        assert c.stats["list_scanned"] == 0


# -- ClusterCache incremental maintenance ------------------------------------


class TestClusterCacheIncremental:
    def test_initial_sync_equals_relist(self):
        cluster = FakeCluster()
        cluster.create(N.new_tpu_node("n0"))
        cluster.create(mk_pod("p0", job="g", node="n0"))
        cache = ClusterCache(cluster).connect()
        assert_cache_equals_relist(cache, cluster)

    def test_incremental_bind_terminal_delete(self):
        cluster = FakeCluster()
        cache = ClusterCache(cluster).connect()
        cluster.create(N.new_tpu_node("n0"))           # 4 chips
        cluster.create(N.new_tpu_node("n1", spot=True))
        cluster.create(mk_pod("p0", job="g", chips=2, gates=True))
        cache.refresh()
        assert_cache_equals_relist(cache, cluster)
        assert cache.capacity().free == {"n0": 4, "n1": 4}
        # bind
        cluster.patch("v1", "Pod", "p0", {"spec": {"nodeName": "n0"}},
                      "default")
        cache.refresh()
        assert cache.capacity().free == {"n0": 2, "n1": 4}
        assert [ob.meta(p)["name"] for p in cache.pods_on_node("n0")] == \
            ["p0"]
        # terminal phase releases the chips
        cur = cluster.get("v1", "Pod", "p0", "default")
        cur.setdefault("status", {})["phase"] = "Succeeded"
        cluster.update_status(cur)
        cache.refresh()
        assert cache.capacity().free == {"n0": 4, "n1": 4}
        assert cache.pods_on_node("n0") == []
        # delete drops the object entirely
        cluster.delete("v1", "Pod", "p0", "default")
        cache.refresh()
        assert_cache_equals_relist(cache, cluster)
        assert cache.gang_pods("default", "g") == []

    def test_gang_index_and_ordering(self):
        cluster = FakeCluster()
        cache = ClusterCache(cluster).connect()
        for i in (2, 0, 1):
            cluster.create(mk_pod(f"w-{i}", job="train", gates=True))
        cluster.create(mk_pod("other", job="noise", gates=True))
        cache.refresh()
        assert [ob.meta(p)["name"]
                for p in cache.gang_pods("default", "train")] == \
            ["w-0", "w-1", "w-2"]
        assert cache.gang_pods("default", "missing") == []

    def test_unhealthy_bound_nodes_short_circuit_surface(self):
        cluster = FakeCluster()
        cache = ClusterCache(cluster).connect()
        cluster.create(N.new_tpu_node("n0"))
        cluster.create(N.new_tpu_node("n1"))
        cluster.create(mk_pod("p0", job="g", node="n0"))
        cluster.create(mk_pod("p1", job="g", node="n1"))
        cache.refresh()
        assert cache.unhealthy_bound_nodes() == {}   # all Ready: O(1)-ish
        # NotReady under a bound pod
        node = cluster.get("v1", "Node", "n0")
        node["status"]["conditions"] = [{"type": "Ready", "status": "False"}]
        cluster.update_status(node)
        # deleted under a bound pod
        cluster.delete("v1", "Node", "n1")
        cache.refresh()
        assert cache.unhealthy_bound_nodes() == \
            {"n0": "NotReady", "n1": "deleted"}
        assert_cache_equals_relist(cache, cluster)

    def test_note_write_gives_read_your_writes(self):
        """The assume-cache path: a bind response folded in via
        note_write is visible BEFORE any watch event is drained (the
        real-apiserver case where the watch is asynchronous)."""
        cluster = FakeCluster()
        cache = ClusterCache(cluster).connect()
        cluster.create(N.new_tpu_node("n0"))
        cluster.create(mk_pod("p0", job="g", gates=True))
        cache.refresh()
        resp = cluster.patch("v1", "Pod", "p0",
                             {"spec": {"nodeName": "n0"}}, "default")
        cache.note_write(resp)  # NO refresh
        assert cache.capacity().free == {"n0": 2}
        # the watch's later delivery of the same rv is a no-op
        before = cache.stats()["stale_events"]
        cache.refresh()
        assert cache.capacity().free == {"n0": 2}
        assert cache.stats()["stale_events"] > before
        assert_cache_equals_relist(cache, cluster)

    def test_graceful_delete_under_aliasing_snapshot_applies(self):
        """list_snapshot hands the cache STORE references; the fake must
        therefore replace-not-mutate on every rv bump (graceful delete,
        GC ref pruning), or the aliased object's rv advances in place
        and the follow-up MODIFIED event is dropped as a replay."""
        cluster = FakeCluster()
        pod = mk_pod("p0", job="g", node=None)
        ob.meta(pod)["finalizers"] = ["example.com/hold"]
        cluster.create(pod)
        cache = ClusterCache(cluster).connect()  # aliases the stored pod
        stale_before = cache.stats()["stale_events"]
        cluster.delete("v1", "Pod", "p0", "default")  # graceful: marks only
        cache.refresh()
        # the deletionTimestamp MODIFIED was a REAL change, not a replay
        assert cache.stats()["stale_events"] == stale_before
        cached = cache.objects("v1", "Pod")[("default", "p0")]
        assert ob.meta(cached).get("deletionTimestamp")
        assert_cache_equals_relist(cache, cluster)
        cluster.remove_finalizer(  # updates AND reaps (no finalizers left)
            cluster.get("v1", "Pod", "p0", "default"), "example.com/hold")
        cache.refresh()
        assert cache.objects("v1", "Pod") == {}
        assert_cache_equals_relist(cache, cluster)

    def test_out_of_order_delivery_is_rv_guarded(self):
        cluster = FakeCluster()
        cache = ClusterCache(cluster).connect()
        cluster.create(mk_pod("p0", job="g"))
        v1 = cluster.patch("v1", "Pod", "p0",
                           {"metadata": {"annotations": {"step": "1"}}},
                           "default")
        v2 = cluster.patch("v1", "Pod", "p0",
                           {"metadata": {"annotations": {"step": "2"}}},
                           "default")
        cache.note_write(v2)
        cache.note_write(v1)  # stale: must NOT roll back
        pods = cache.gang_pods("default", "g")
        assert ob.annotations_of(pods[0])["step"] == "2"
        cache.refresh()
        assert_cache_equals_relist(cache, cluster)


# -- chaos: watch drops, 410 relists, random churn ---------------------------


class TestClusterCacheUnderChaos:
    def _churn(self, rng, cluster, chaos, live_pods, live_nodes, step):
        """One seeded mutation against the cluster."""
        roll = rng.random()
        if roll < 0.18 or not live_nodes:
            name = f"cn-{step}"
            cluster.create(N.new_tpu_node(
                name, topology=rng.choice(["2x4", "4x4"]),
                spot=rng.random() < 0.3))
            live_nodes.append(name)
        elif roll < 0.30:
            name = f"cp-{step}"
            cluster.create(mk_pod(name, job=f"g{step % 5}",
                                  chips=rng.choice([1, 2, 4]), gates=True))
            live_pods.append(name)
        elif roll < 0.50 and live_pods:
            name = rng.choice(live_pods)
            cluster.patch("v1", "Pod", name,
                          {"spec": {"nodeName": rng.choice(live_nodes)}},
                          "default")
        elif roll < 0.65 and live_pods:
            name = rng.choice(live_pods)
            cur = cluster.get("v1", "Pod", name, "default")
            cur.setdefault("status", {})["phase"] = \
                rng.choice(["Running", "Succeeded", "Failed"])
            cluster.update_status(cur)
        elif roll < 0.75 and live_pods:
            name = live_pods.pop(rng.randrange(len(live_pods)))
            cluster.delete("v1", "Pod", name, "default")
        elif roll < 0.85 and live_nodes:
            chaos.fail_node(rng.choice(live_nodes))
        elif roll < 0.92 and live_nodes:
            chaos.heal_node(rng.choice(live_nodes))
        elif len(live_nodes) > 1:
            name = live_nodes.pop(rng.randrange(len(live_nodes)))
            chaos.delete_node(name)

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_snapshot_equals_relist_across_watch_drops_and_410(self, seed):
        """Property-style: random churn against a TINY watch history
        (every resume overflows it -> 410 Expired -> relist) behind a
        ChaosWatchStream that tears the stream down every few events;
        at every checkpoint the cache must equal a fresh relist."""
        cluster = FakeCluster(history_limit=8)
        chaos = ChaosClient(cluster, ChaosPolicy(seed=seed, rate=0.0,
                                                 watch_drop_every=4))
        cache = ClusterCache(chaos).connect()
        rng = random.Random(seed)
        live_pods, live_nodes = [], []
        for step in range(120):
            self._churn(rng, cluster, chaos, live_pods, live_nodes, step)
            if step % 10 == 9:
                cache.refresh()
                assert_cache_equals_relist(cache, cluster)
        cache.refresh()
        assert_cache_equals_relist(cache, cluster)
        # the chaos stream really did drop (the test is non-vacuous)
        assert cache.stats()["events"] > 0

    def test_own_resubscribe_handles_410_with_truncated_history(self):
        """The cache's OWN resume path (a stream that died, not a
        ChaosWatchStream drop): with the resume rv fallen out of the
        watch cache, resubscribe must 410 -> relist -> consistent."""
        cluster = FakeCluster(history_limit=4)
        cache = ClusterCache(cluster).connect()
        cluster.create(N.new_tpu_node("n0"))
        cache.refresh()
        sub = next(s for s in cache._subs if s.kind == "Pod")
        sub.stream.stop()  # the stream dies silently
        for i in range(12):  # history (4) overflows: resume must 410
            cluster.create(mk_pod(f"p-{i}", job="g", gates=True))
        relists_before = cache.stats()["relists"]
        cache._resubscribe(sub)
        cache.refresh()
        assert cache.stats()["relists"] > relists_before
        assert_cache_equals_relist(cache, cluster)

    def test_relist_failure_keeps_serving_and_retries(self):
        """A chaotic apiserver failing the relist must not break the
        cache: it serves the last snapshot, marks the kind dirty, and
        the next refresh retries to consistency."""
        cluster = FakeCluster()
        cluster.create(N.new_tpu_node("n0"))
        cache = ClusterCache(cluster).connect()

        calls = {"n": 0}
        orig = cluster.list_snapshot

        def failing(api, kind, *a, **kw):
            calls["n"] += 1
            raise ob.ApiError("chaos: relist refused")

        cluster.list_snapshot = failing
        try:
            sub = next(s for s in cache._subs if s.kind == "Node")
            assert cache._try_relist(sub) is False
            assert (("v1", "Node") in cache._dirty)
            # still serving the pre-failure snapshot
            assert "n0" in cache.node_views()
        finally:
            cluster.list_snapshot = orig
        cluster.create(N.new_tpu_node("n1"))
        cache.refresh()  # retries the dirty kind
        assert_cache_equals_relist(cache, cluster)


# -- capacity: bisect best-fit equivalence -----------------------------------


def brute_force_assign(pods, views, free, prefer_spot=False):
    """The pre-ISSUE-7 linear-scan best-fit, verbatim semantics."""
    remaining = dict(free)
    out = {}
    for pod in pods:
        need = N.pod_tpu_request(pod)
        candidates = [name for name in sorted(views)
                      if remaining[name] >= need
                      and N.feasible(pod, views[name])]
        if prefer_spot:
            spot = [n for n in candidates if views[n].spot]
            candidates = spot or candidates
        best = None
        for name in candidates:
            if best is None or remaining[name] < remaining[best]:
                best = name
        if best is None:
            return None
        remaining[best] -= need
        out[ob.meta(pod)["name"]] = best
    return out


class TestCapacityBestFit:
    def _world(self, rng, n_nodes):
        views, free = {}, {}
        for i in range(n_nodes):
            topo = rng.choice(["2x4", "4x4", "2x2"])
            node = N.new_tpu_node(
                f"n{i:03d}", topology=topo,
                chips_per_node=rng.choice([2, 4]),
                ready=rng.random() > 0.1,
                spot=rng.random() < 0.3)
            v = N.node_view(node)
            views[v.name] = v
            free[v.name] = rng.randint(0, v.allocatable_chips)
        return views, free

    @pytest.mark.parametrize("seed", list(range(8)))
    def test_bisect_matches_linear_scan(self, seed):
        from kubeflow_tpu.control.scheduler.scheduler import GangScheduler

        rng = random.Random(seed)
        views, free = self._world(rng, rng.randint(3, 30))
        topo = rng.choice(["2x4", "4x4", "2x2"])
        sel = {JT.NODESELECTOR_ACCEL: "tpu-v5-lite-podslice",
               JT.NODESELECTOR_TOPOLOGY: topo}
        if rng.random() < 0.3:
            sel = None  # un-pooled pod: the catch-all bucket path
        pods = [mk_pod(f"w-{i}", chips=rng.choice([1, 2, 4]),
                       selector=sel, gates=True)
                for i in range(rng.randint(1, 6))]
        for pod in pods:
            if views and rng.random() < 0.5:
                pod["spec"]["tolerations"] = [dict(N.spot_taint())]
        prefer_spot = rng.random() < 0.5
        want = brute_force_assign(pods, views, free, prefer_spot)
        cap = CP.Capacity.from_views(views, free)
        got = GangScheduler._assign(pods, cap, prefer_spot=prefer_spot)
        assert got == want, f"seed {seed}: bisect diverged from scan"

    def test_txn_fork_isolation_and_credit(self):
        views = {v.name: v for v in
                 (N.node_view(N.new_tpu_node(n)) for n in ("a", "b"))}
        free = {"a": 2, "b": 4}
        cap = CP.Capacity.from_views(views, free)
        base = cap.txn()
        base.credit("a", 2)           # a preemption what-if credit
        trial = base.fork()
        trial.take("a", 4)
        assert trial.free_of("a") == 0
        assert base.free_of("a") == 4     # fork never leaks into base
        assert cap.free["a"] == 2         # snapshot untouched
        trial2 = base.fork()
        assert trial2.free_of("a") == 4

    def test_scanned_counter_counts_walked_nodes(self):
        views = {v.name: v for v in
                 (N.node_view(N.new_tpu_node(n, ready=(n != "a")))
                  for n in ("a", "b"))}
        cap = CP.Capacity.from_views(views, {"a": 4, "b": 4})
        txn = cap.txn()
        pod = mk_pod("w", gates=True)
        assert txn.best_fit(pod, 4) == "b"   # walks over unready "a"
        assert cap.scanned == 2


# -- hot-path metrics render in BOTH sinks -----------------------------------


class TestHotPathMetrics:
    def test_pass_metrics_in_both_sinks(self):
        """ISSUE 7 satellite: scheduler_pass_seconds (native histogram)
        + scheduler_nodes_scanned_total + the cache hit-rate counters
        render in the MetricsRegistry sink AND the Prometheus sink."""
        import prometheus_client as prom

        from kubeflow_tpu.control.runtime import seed_controller
        from kubeflow_tpu.control.scheduler.scheduler import build_scheduler
        from kubeflow_tpu.runtime.metrics import MetricsRegistry

        class Clock:
            t = 0.0

            def __call__(self):
                return self.t

        registry = MetricsRegistry()
        cluster = FakeCluster()
        ctl = seed_controller(build_scheduler(
            cluster, registry=registry, record_events=False, clock=Clock()))
        cluster.create(N.new_tpu_node("n0"))
        for i in range(2):
            pod = mk_pod(f"g-worker-{i}", job="g", chips=2, gates=True)
            ob.set_annotation(pod, "scheduler.kubeflow.org/gang-size", "2")
            cluster.create(pod)
        before_pass = prom.REGISTRY.get_sample_value(
            "scheduler_pass_seconds_count") or 0.0
        ctl.run_until_idle(advance_delayed=True)
        text = registry.render()
        assert "# TYPE scheduler_pass_seconds histogram" in text
        assert "scheduler_pass_seconds_count" in text
        assert "scheduler_nodes_scanned_total" in text
        assert 'scheduler_cache_reads_total{source="cache"}' in text
        assert "cluster_cache_events_total" in text
        # and the Prometheus sink saw the same pass
        after_pass = prom.REGISTRY.get_sample_value(
            "scheduler_pass_seconds_count")
        assert after_pass > before_pass
        assert (prom.REGISTRY.get_sample_value(
            "scheduler_nodes_scanned_total") or 0.0) > 0
        assert (prom.REGISTRY.get_sample_value(
            "scheduler_cache_reads_total",
            {"source": "cache"}) or 0.0) > 0
        # the gang really bound (the pass did the work being measured)
        assert all(p["spec"].get("nodeName") == "n0"
                   for p in cluster.list("v1", "Pod"))

    def test_legacy_mode_reports_list_source(self):
        from kubeflow_tpu.control.runtime import seed_controller
        from kubeflow_tpu.control.scheduler.scheduler import build_scheduler
        from kubeflow_tpu.runtime.metrics import MetricsRegistry

        registry = MetricsRegistry()
        cluster = FakeCluster()
        ctl = seed_controller(build_scheduler(
            cluster, registry=registry, record_events=False, cache=False))
        cluster.create(N.new_tpu_node("n0"))
        pod = mk_pod("solo-worker-0", job="solo", chips=2, gates=True)
        ob.set_annotation(pod, "scheduler.kubeflow.org/gang-size", "1")
        cluster.create(pod)
        ctl.run_until_idle(advance_delayed=True)
        text = registry.render()
        assert 'scheduler_cache_reads_total{source="list"}' in text
        assert "cluster_cache_" not in text  # no cache, no cache stats


# -- pumped-mode races: the snapshot may trail the triggering event ----------


class TestPumpedModeRaces:
    def test_pumped_stale_sync_keeps_gang_queued(self):
        """In production (pumped) mode refresh() cannot drain the
        pump-owned streams, so a reconcile can read a snapshot that
        predates the pod event that triggered it. 'No pending pods'
        must then be CONFIRMED against the apiserver before the gang is
        dropped from the queue — gated Pending pods emit no further
        events, so a wrong drop is a permanent stall."""
        from kubeflow_tpu.control.runtime import Request
        from kubeflow_tpu.control.scheduler.scheduler import build_scheduler

        cluster = FakeCluster()
        ctl = build_scheduler(cluster, record_events=False)
        rec = ctl.reconciler
        rec.cache._threads = ["pump"]  # production mode: no poll-drain
        cluster.create(N.new_tpu_node("n0"))
        for i in range(2):
            pod = mk_pod(f"g-worker-{i}", job="g", chips=2, gates=True)
            ob.set_annotation(
                pod, "scheduler.kubeflow.org/gang-size", "2")
            cluster.create(pod)
        rec.reconcile(cluster, Request("default", "g"))
        assert rec.queue.get("default", "g") is not None, \
            "stale snapshot dropped the gang from the queue"
        # the pump catches up: the still-queued gang admits normally
        rec.cache._threads = []
        rec.cache.refresh()
        rec.queue.kick()
        rec.reconcile(cluster, Request("default", "g"))
        assert all(p["spec"].get("nodeName") == "n0"
                   for p in cluster.list("v1", "Pod", namespace="default"))

    def test_pumped_node_snapshot_miss_confirms_live(self):
        """A pumped snapshot can lag a Node ADDED riding its own stream
        while the pod got in via the reconciler's note_write — a cache
        miss must be CONFIRMED against the apiserver before the node is
        condemned (the legacy per-node GET was authoritative; a false
        'node gone' restarts a healthy gang)."""
        from kubeflow_tpu.control.cache import ClusterCache
        from kubeflow_tpu.control.jaxjob.controller import JAXJobReconciler

        cluster = FakeCluster()
        cache = ClusterCache(cluster).connect()
        rec = JAXJobReconciler(record_events=False, cache=cache)
        pod = ob.new_object("v1", "Pod", "j-worker-0", "default")
        pod["spec"] = {"nodeName": "n-new"}
        pod = cluster.create(pod)
        cache.refresh()
        # the node joins AFTER the last drain; its ADDED is still in
        # the pump's stream when the reconcile reads the snapshot
        cluster.create(ob.new_object("v1", "Node", "n-new"))
        cache._threads = ["pump"]  # production mode: no poll-drain
        try:
            assert rec._unhealthy_nodes(cluster, [pod]) == []
            # and folded back in: the next snapshot read hits
            assert cache.node("n-new") is not None
        finally:
            cache._threads = []

    def test_legacy_health_pass_survives_api_error(self):
        """The legacy short-circuit must not commit its node-set memory
        until the eviction loop lands: an ApiError mid-pass would
        otherwise consume the vanished-node signal and the dead node's
        gang pods would never be evicted."""
        from kubeflow_tpu.control.scheduler.scheduler import (
            RETRY_ALL, build_scheduler,
        )

        cluster = FakeCluster()
        ctl = build_scheduler(cluster, record_events=False, cache=False)
        rec = ctl.reconciler
        cluster.create(N.new_tpu_node("n0"))
        cluster.create(N.new_tpu_node("n1"))
        cluster.create(mk_pod("w-0", job="g", node="n0", chips=2))
        rec.reconcile(cluster, RETRY_ALL)      # seeds _known_nodes
        cluster.delete("v1", "Node", "n0")
        real_list = cluster.list
        calls = {"pod_lists": 0}

        def flaky_list(api, kind, **kw):
            if kind == "Pod" and calls["pod_lists"] == 0:
                calls["pod_lists"] += 1
                raise ob.ApiError("transient 500 mid health pass")
            return real_list(api, kind, **kw)

        cluster.list = flaky_list
        with pytest.raises(ob.ApiError):
            rec.reconcile(cluster, RETRY_ALL)  # blows up after node list
        cluster.list = real_list
        rec.reconcile(cluster, RETRY_ALL)      # retry must still see it
        p = cluster.get("v1", "Pod", "w-0", "default")
        assert (p.get("status") or {}).get("phase") == "Failed"
        assert (p.get("status") or {}).get("reason") == "Evicted"

    def test_note_write_cannot_resurrect_deleted_pod(self):
        """A write response noted AFTER the watch applied the object's
        DELETED (reconcile thread vs pump thread) must not re-insert
        the dead pod — the tombstone catches what the cached-old rv
        guard cannot. A genuine recreation (higher rv) passes."""
        cluster = FakeCluster()
        cache = ClusterCache(cluster).connect()
        cluster.create(N.new_tpu_node("n0"))
        cluster.create(mk_pod("p0", job="g", gates=True))
        cache.refresh()
        resp = cluster.patch("v1", "Pod", "p0",
                             {"spec": {"nodeName": "n0"}}, "default")
        cluster.delete("v1", "Pod", "p0", "default")
        cache.refresh()          # the DELETED is applied first...
        stale_before = cache.stats()["stale_events"]
        cache.note_write(resp)   # ...then the older write response lands
        assert cache.stats()["stale_events"] > stale_before
        assert cache.objects("v1", "Pod") == {}
        assert cache.gang_pods("default", "g") == []
        assert cache.pods_on_node("n0") == []
        assert cache.capacity().free == {"n0": 4}
        assert_cache_equals_relist(cache, cluster)
        # recreation under the same name: globally monotonic rvs beat
        # the tombstone, the assume-note works again
        cluster.create(mk_pod("p0", job="g", gates=True))
        cache.note_write(cluster.get("v1", "Pod", "p0", "default"))
        assert ("default", "p0") in cache.objects("v1", "Pod")
        cache.refresh()
        assert_cache_equals_relist(cache, cluster)


class TestSameNameRecreation:
    def test_noted_recreation_survives_old_incarnations_deleted(self):
        """The elastic-shrink shape: a reconciler deletes a pod and
        recreates its replacement under the SAME NAME, folding both in
        via note_delete/note_write before the watch delivers. The old
        incarnation's later watch DELETED must NOT evict the live
        replacement — and must NOT tombstone at the replacement's rv,
        which would drop the replacement's own ADDED as stale and lose
        the pod forever (the WorkerDisappeared regression this guard
        pins)."""
        cluster = FakeCluster()
        old = cluster.create(mk_pod("w-0", job="g"))
        cache = ClusterCache(cluster).connect()
        cache.refresh()
        # out-of-band mutations (the controller's own writes)
        cluster.delete("v1", "Pod", "w-0", "default")
        replacement = cluster.create(mk_pod("w-0", job="g"))
        cache.note_delete(old)
        cache.note_write(replacement)
        assert ("default", "w-0") in cache.objects("v1", "Pod")
        # the watch now replays history: DELETED(old rv) then ADDED(new)
        cache.refresh()
        got = cache.objects("v1", "Pod").get(("default", "w-0"))
        assert got is not None, "stale DELETED evicted the recreation"
        assert ob.meta(got)["resourceVersion"] == \
            ob.meta(replacement)["resourceVersion"]
        assert_cache_equals_relist(cache, cluster)


# -- controller wiring: reconcile paths off per-reconcile lists -------------


class TestControllerCacheWiring:
    """ROADMAP #3's remaining item: the jaxjob and notebook controllers
    ride the indexed cache via ``Controller.uses()``. The pin is the
    FakeCluster op counters — once a controller's caches are synced,
    steady-state reconciles issue ZERO list calls (every pod/node/event
    read is an index lookup); the legacy ``cache=False`` arms still
    list, proving the counter actually measures the path."""

    def _drain(self, ctl, kubelet=None, rounds=6):
        for _ in range(rounds):
            ctl.run_until_idle(advance_delayed=True)
            if kubelet is not None:
                # the kubelet is test harness, not the controller under
                # measurement — its full-store list must not pollute the
                # zero-list pins (the sched_bench stats_paused pattern)
                with ctl.client.stats_paused():
                    kubelet.step()

    def test_jaxjob_reconcile_zero_list_calls(self):
        from kubeflow_tpu.control.jaxjob.controller import build_controller
        from kubeflow_tpu.control.k8s.kubelet import FakeKubelet
        from kubeflow_tpu.control.runtime import Request, seed_controller

        cluster = FakeCluster()
        ctl = seed_controller(build_controller(cluster, record_events=False))
        kubelet = FakeKubelet(cluster)
        cluster.create(JT.new_jaxjob(
            "train", replicas=2, accelerator="tpu-v5-lite-podslice",
            topology="2x4", chips_per_worker=4))
        self._drain(ctl, kubelet)
        job = cluster.get(JT.API_VERSION, JT.KIND, "train", "default")
        assert ob.cond_is_true(job, JT.COND_RUNNING)

        cluster.reset_stats()
        ctl.enqueue(Request("default", "train"))
        self._drain(ctl, kubelet)
        assert cluster.stats["list_calls"] == 0, dict(cluster.stats)

        # the legacy arm DOES list — the counter measures the real path
        legacy = seed_controller(build_controller(
            cluster, record_events=False, cache=False))
        cluster.reset_stats()
        legacy.enqueue(Request("default", "train"))
        self._drain(legacy, kubelet)
        assert cluster.stats["list_calls"] > 0

    def test_jaxjob_node_mapper_zero_list_calls(self):
        from kubeflow_tpu.control.jaxjob.controller import build_controller
        from kubeflow_tpu.control.k8s.kubelet import FakeKubelet
        from kubeflow_tpu.control.runtime import seed_controller

        cluster = FakeCluster()
        ctl = seed_controller(build_controller(cluster, record_events=False))
        kubelet = FakeKubelet(cluster)
        cluster.create(JT.new_jaxjob("train", replicas=1,
                                     accelerator="tpu-v5-lite-podslice",
                                     topology="2x2", chips_per_worker=4))
        self._drain(ctl, kubelet)
        cluster.reset_stats()
        node = cluster.get("v1", "Node", "fake-node")
        node["status"]["conditions"] = [{"type": "Ready", "status": "False"}]
        cluster.update_status(node)
        self._drain(ctl, kubelet)
        assert cluster.stats["list_calls"] == 0, dict(cluster.stats)
        # and the slice-health path actually fired off the cached node
        job = cluster.get(JT.API_VERSION, JT.KIND, "train", "default")
        assert (job.get("status") or {}).get("preemptions", 0) >= 1

    def test_notebook_reconcile_zero_list_calls(self):
        from kubeflow_tpu.control.notebook import types as NT
        from kubeflow_tpu.control.notebook.controller import build_controller
        from kubeflow_tpu.control.runtime import Request, seed_controller

        cluster = FakeCluster()
        ctl = seed_controller(build_controller(cluster))
        nb = ob.new_object(NT.API_VERSION, NT.KIND, "nb", "default")
        nb["spec"] = {"template": {"spec": {"containers": [
            {"name": "nb", "image": "jupyter"}]}}}
        cluster.create(nb)
        self._drain(ctl)
        # a pod with the notebook label, plus a pod Event to forward
        pod = ob.new_object("v1", "Pod", "nb-0", "default",
                            labels={NT.LABEL_NOTEBOOK_NAME: "nb"})
        pod["status"] = {"phase": "Running",
                         "containerStatuses": [{"name": "nb", "ready": True,
                                                "state": {"running": {}}}]}
        cluster.create(pod)
        cluster.record_event(pod, "Pulled", "image pulled")
        self._drain(ctl)

        cluster.reset_stats()
        ctl.enqueue(Request("default", "nb"))
        self._drain(ctl)
        assert cluster.stats["list_calls"] == 0, dict(cluster.stats)
        nb = cluster.get(NT.API_VERSION, NT.KIND, "nb", "default")
        assert (nb.get("status") or {}).get("readyReplicas") == 1
        # the Event-forwarding path ran off the cache too
        fwd = [e for e in cluster.list("v1", "Event", namespace="default")
               if (e.get("involvedObject") or {}).get("name") == "nb"
               and e.get("reason") == "Pulled"]
        assert fwd, "pod event should forward onto the Notebook"

        legacy = seed_controller(build_controller(cluster, cache=False))
        cluster.reset_stats()
        legacy.enqueue(Request("default", "nb"))
        self._drain(legacy)
        assert cluster.stats["list_calls"] > 0

    def test_notebook_event_forward_notes_own_marker(self):
        # read-your-own-writes for the forwarded-marker events: the
        # marker must be folded into the cache AT RECORD TIME — under
        # production pumped watches the next reconcile can run before
        # the pump delivers it, and a snapshot without the marker would
        # re-forward the same pod event (count-dedup inflating the
        # Notebook event's count past the real occurrence count).
        from kubeflow_tpu.control.cache import ClusterCache
        from kubeflow_tpu.control.notebook import types as NT
        from kubeflow_tpu.control.notebook.controller import (
            NotebookReconciler,
        )

        cluster = FakeCluster()
        cache = ClusterCache(
            cluster, kinds=(("v1", "Pod"), ("v1", "Event")),
            pod_labels=(NT.LABEL_NOTEBOOK_NAME,)).connect()
        rec = NotebookReconciler(cache=cache)
        nb = cluster.create(
            ob.new_object(NT.API_VERSION, NT.KIND, "nb", "default"))
        pod = ob.new_object("v1", "Pod", "nb-0", "default",
                            labels={NT.LABEL_NOTEBOOK_NAME: "nb"})
        pod = cluster.create(pod)
        cluster.record_event(pod, "Pulled", "image pulled")
        cache.refresh()

        rec._forward_pod_events(cluster, nb, [pod])
        # noted without a refresh: the snapshot already has the marker
        markers = [e for e in cache.objects("v1", "Event").values()
                   if (e.get("source") or {}).get(
                       "component", "").startswith("nb-fwd-")]
        assert markers, "recorded marker must be note_write'n"
        # a second pass over the SAME (stale) snapshot forwards nothing
        rec._forward_pod_events(cluster, nb, [pod])
        fwd = [e for e in cluster.list("v1", "Event", namespace="default")
               if (e.get("involvedObject") or {}).get("name") == "nb"
               and e.get("reason") == "Pulled"]
        assert len(fwd) == 1 and fwd[0].get("count", 1) == 1, fwd

    def test_jaxservice_reconcile_zero_list_calls(self):
        from kubeflow_tpu.control.jaxservice import types as ST
        from kubeflow_tpu.control.jaxservice.controller import (
            build_controller,
        )
        from kubeflow_tpu.control.k8s.kubelet import FakeKubelet
        from kubeflow_tpu.control.runtime import Request, seed_controller

        cluster = FakeCluster()
        ctl = seed_controller(build_controller(cluster, record_events=False))
        kubelet = FakeKubelet(cluster)
        cluster.create(ST.new_jaxservice("chat", model="gpt-125m",
                                         min_replicas=2, max_replicas=2))
        self._drain(ctl, kubelet)
        svc = cluster.get(ST.API_VERSION, ST.KIND, "chat", "default")
        assert ob.cond_is_true(svc, ST.COND_READY)

        cluster.reset_stats()
        ctl.enqueue(Request("default", "chat"))
        self._drain(ctl, kubelet)
        assert cluster.stats["list_calls"] == 0, dict(cluster.stats)


class TestMarkDirty:
    """The remediation engine's watch-gap repair path (ISSUE 13): force
    a wholesale relist of cached kinds without restarting the cache."""

    def test_mark_dirty_all_kinds_relists_on_refresh(self):
        cluster = FakeCluster()
        cluster.create(N.new_tpu_node("n0"))
        cache = ClusterCache(cluster).connect()
        base = cache.stats()["relists"]
        marked = cache.mark_dirty()
        assert marked == len(cache._subs)
        cache.refresh()
        assert cache.stats()["relists"] == base + marked
        assert_cache_equals_relist(cache, cluster)

    def test_mark_dirty_scoped_to_named_kinds(self):
        cluster = FakeCluster()
        cache = ClusterCache(cluster).connect()
        base = cache.stats()["relists"]
        assert cache.mark_dirty([NODE]) == 1
        cache.refresh()
        assert cache.stats()["relists"] == base + 1

    def test_mark_dirty_repairs_a_silently_desynced_index(self):
        """The incident the action exists for: a watch gap leaves the
        snapshot stale; mark_dirty + refresh restores relist parity."""
        cluster = FakeCluster()
        cluster.create(N.new_tpu_node("n0"))
        cache = ClusterCache(cluster).connect()
        cache.refresh()
        # simulate a dropped watch event: mutate the cluster while the
        # cache's streams are silently broken
        for sub in cache._subs:
            sub.stream = None
        cluster.create(N.new_tpu_node("n1"))
        assert "n1" not in cache.node_views()
        cache.mark_dirty([NODE])
        cache.refresh()
        assert "n1" in cache.node_views()
