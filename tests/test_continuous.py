"""Continuous batching (serving/continuous.py): slot-based lockstep
decode must produce exactly what generate() produces, while requests
join and leave independently."""

import threading

import numpy as np
import pytest


@pytest.fixture(scope="module")
def lm():
    import jax

    from kubeflow_tpu.models.registry import get_model

    model = get_model("transformer-test", vocab_size=64, max_seq_len=16)
    tok = np.zeros((1, 1), np.int32)
    variables = model.init(jax.random.PRNGKey(0), tok, train=False)
    return model, variables


def reference_generate(model, variables, tokens, prompt_len=8, max_new=4):
    import jax.numpy as jnp

    from kubeflow_tpu.runtime.generate import generate

    row = [int(t) for t in tokens][-prompt_len:]
    pad = prompt_len - len(row)
    prompt = jnp.asarray([[0] * pad + row], jnp.int32)
    out = generate(model, variables, prompt, max_new_tokens=max_new,
                   pad_len=jnp.asarray([pad], jnp.int32))
    return [int(t) for t in np.asarray(out)[0, prompt_len:]]


class TestSlotDecoder:
    def test_matches_generate_exactly_greedy(self, lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        dec = SlotDecoder(model, variables, slots=4, prompt_len=8,
                          max_new_tokens=4)
        try:
            prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9], [10, 11]]
            want = [reference_generate(model, variables, p) for p in prompts]
            got = [dec.submit(p) for p in prompts]  # sequential joins
            assert got == want
        finally:
            dec.close()

    def test_concurrent_staggered_requests_stay_exact(self, lm):
        """Requests arriving WHILE others decode (the continuous-batching
        point) must not perturb each other's tokens."""
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        dec = SlotDecoder(model, variables, slots=3, prompt_len=8,
                          max_new_tokens=6)
        try:
            prompts = [[i + 1, i + 2, i + 3] for i in range(7)]  # > slots
            want = {tuple(p): reference_generate(
                model, variables, p, max_new=6) for p in prompts}
            results: dict = {}
            errs: list = []

            def go(p):
                try:
                    results[tuple(p)] = dec.submit(p)
                except Exception as e:  # pragma: no cover
                    errs.append(e)

            threads = [threading.Thread(target=go, args=(p,))
                       for p in prompts]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errs, errs
            assert results == want  # slot reuse + lockstep never leak
        finally:
            dec.close()

    def test_slot_reuse_after_drain(self, lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        dec = SlotDecoder(model, variables, slots=2, prompt_len=8,
                          max_new_tokens=3)
        try:
            for round_ in range(3):  # 3 waves through 2 slots
                p = [round_ + 1, round_ + 2]
                assert dec.submit(p) == reference_generate(
                    model, variables, p, max_new=3)
            assert dec.active_slots == 0
        finally:
            dec.close()

    def test_close_fails_pending_cleanly(self, lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        dec = SlotDecoder(model, variables, slots=1, prompt_len=8,
                          max_new_tokens=2)
        dec.close()
        with pytest.raises(RuntimeError, match="shut down"):
            dec.submit([1, 2, 3])


class TestContinuousServing:
    """The TF-Serving REST contract answered from the slot decoder."""

    def test_http_predict_matches_generate(self, lm):
        import requests

        from kubeflow_tpu.serving.server import (
            ModelServer, serve_lm_generator)

        model, variables = lm
        srv = ModelServer()
        srv.register(serve_lm_generator(
            "cb-lm", "transformer-test", prompt_len=8, max_new_tokens=4,
            vocab_size=64,  # max_seq_len derives from prompt+new
            continuous_batching=True, decode_slots=4))
        svc = srv.serve(host="127.0.0.1", port=0)
        svc.serve_background()
        try:
            base = f"http://127.0.0.1:{svc.port}"
            r = requests.post(
                f"{base}/v1/models/cb-lm:predict",
                json={"instances": [{"tokens": [1, 2, 3]},
                                    {"tokens": [4, 5]}]},
                timeout=300)
            assert r.status_code == 200, r.text
            preds = r.json()["predictions"]
            assert preds[0] == reference_generate(model, variables, [1, 2, 3])
            assert preds[1] == reference_generate(model, variables, [4, 5])
            meta = requests.get(
                f"{base}/v1/models/cb-lm/metadata", timeout=30).json()
            sig = meta["metadata"]["signature_def"]
            assert sig["continuous_batching"] is True
        finally:
            svc.shutdown()
            srv.close()

    def test_mesh_sharded_continuous_batching(self, lm):
        """--mesh and --continuous-batching compose: the slot decoder's
        prefill/step programs run over sharded variables."""
        import requests

        from kubeflow_tpu.serving.server import (
            ModelServer, serve_lm_generator)

        model, variables = lm
        srv = ModelServer()
        srv.register(serve_lm_generator(
            "cb-mesh", "transformer-test", prompt_len=8, max_new_tokens=4,
            vocab_size=64, mesh={"fsdp": 2, "model": 4},
            continuous_batching=True, decode_slots=2))
        svc = srv.serve(host="127.0.0.1", port=0)
        svc.serve_background()
        try:
            r = requests.post(
                f"http://127.0.0.1:{svc.port}/v1/models/cb-mesh:predict",
                json={"instances": [{"tokens": [1, 2, 3]}]}, timeout=300)
            assert r.status_code == 200, r.text
            preds = r.json()["predictions"]
            # sharding is placement, not numerics: unsharded-exact
            assert preds[0] == reference_generate(
                model, variables, [1, 2, 3])
        finally:
            svc.shutdown()
            srv.close()


class TestSchedulingFairness:
    def test_idle_burst_prefills_as_one_batch(self, lm):
        """An IDLE decoder takes the whole waiting burst through one
        batched prefill instead of burst_size serial scans."""
        import time as _time

        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        dec = SlotDecoder(model, variables, slots=4, prompt_len=8,
                          max_new_tokens=3)
        try:
            calls: list = []
            real_prefill = dec._prefill

            def spy(params, prompts, pads):
                calls.append(int(prompts.shape[0]))
                return real_prefill(params, prompts, pads)

            # hold the loop while the burst queues up: pause via a fake
            # empty free list, then restore
            dec._prefill = spy
            held, dec._free = dec._free, []
            prompts = [[i + 1, i + 2] for i in range(4)]
            want = [reference_generate(model, variables, p, max_new=3)
                    for p in prompts]
            results: dict = {}
            threads = [threading.Thread(
                target=lambda i=i: results.__setitem__(
                    i, dec.submit(prompts[i]))) for i in range(4)]
            for t in threads:
                t.start()
            _time.sleep(0.3)  # burst fully queued while no slots "free"
            dec._free = held
            for t in threads:
                t.join(timeout=120)
            assert [results[i] for i in range(4)] == want
            assert calls and calls[0] == 4, calls  # ONE batch-4 prefill
        finally:
            dec.close()


    def test_at_most_one_prefill_between_decode_ticks(self, lm):
        """A burst must not stall generations: once anything is active,
        the loop alternates admit-one / step (never two prefills
        back-to-back)."""
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        dec = SlotDecoder(model, variables, slots=4, prompt_len=8,
                          max_new_tokens=4)
        try:
            trace: list = []
            real_prefill, real_step = dec._prefill, dec._step

            def spy_prefill(*a, **k):
                trace.append("P")
                return real_prefill(*a, **k)

            def spy_step(*a, **k):
                trace.append("S")
                return real_step(*a, **k)

            dec._prefill, dec._step = spy_prefill, spy_step
            prompts = [[i + 1, i + 2] for i in range(4)]
            want = [reference_generate(model, variables, p) for p in prompts]
            results: dict = {}

            def go(i):
                results[i] = dec.submit(prompts[i])

            # make it deterministic: get one generation ACTIVE first,
            # then burst the rest — those must admit one per tick
            t0 = threading.Thread(target=go, args=(0,))
            t0.start()
            import time as _time

            for _ in range(200):
                if dec.active_slots >= 1:
                    break
                _time.sleep(0.01)
            threads = [threading.Thread(target=go, args=(i,))
                       for i in range(1, 4)]
            for t in threads:
                t.start()
            for t in [t0] + threads:
                t.join(timeout=120)
            assert [results[i] for i in range(4)] == want
            for a, b in zip(trace, trace[1:]):
                assert not (a == "P" and b == "P"), trace
        finally:
            dec.close()


def test_serve_bench_tool_runs_both_modes():
    """tools/serve_bench.py: the serving-side ledger must emit one valid
    JSON line per mode (plumbing check; numbers come from TPU runs)."""
    import json
    import os
    import subprocess
    import sys

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        "import sys, jax, importlib.util\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "sys.argv = ['sb', '--model', 'transformer-test', '--vocab-size',"
        " '64', '--prompt-len', '8', '--max-new-tokens', '3',"
        " '--requests', '6', '--concurrency', '2', '--slots', '2',"
        " '--param-dtype', '']\n"
        "spec = importlib.util.spec_from_file_location("
        "'sb', 'tools/serve_bench.py')\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "sys.exit(m.main())\n")
    r = subprocess.run([sys.executable, "-c", code], cwd=here,
                       capture_output=True, text=True, timeout=400)
    assert r.returncode == 0, r.stderr[-500:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines()
             if ln.startswith("{")]
    assert {d["mode"] for d in lines} == {"micro", "continuous"}
    for d in lines:
        assert d["tokens_per_sec"] > 0 and d["p50_ms"] > 0


class TestFailureContainment:
    """The high-effort decode review's findings, pinned."""

    def test_malformed_row_in_burst_fails_only_its_caller(self, lm):
        """A wrong-length submit_padded row must fail THAT caller; valid
        co-batched requests get THEIR OWN continuations (row/prefill
        alignment survives the drop)."""
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        dec = SlotDecoder(model, variables, slots=4, prompt_len=8,
                          max_new_tokens=3)
        try:
            held, dec._free = dec._free, []  # queue the burst together
            results: dict = {}

            def good(i):
                results[i] = dec.submit([i + 1, i + 2])

            def bad():
                try:
                    dec.submit_padded([1, 2, 3], 0)  # wrong length
                    results["bad"] = "no error"
                except ValueError:
                    results["bad"] = "valueerror"

            threads = [threading.Thread(target=bad)] + [
                threading.Thread(target=good, args=(i,)) for i in range(3)]
            for t in threads:
                t.start()
            import time as _time

            _time.sleep(0.3)
            dec._free = held
            for t in threads:
                t.join(timeout=120)
            assert results["bad"] == "valueerror"
            for i in range(3):
                assert results[i] == reference_generate(
                    model, variables, [i + 1, i + 2], max_new=3), i
        finally:
            dec.close()

    def test_step_failure_recovers_instead_of_zombie(self, lm):
        """A runtime failure in the donated step poisons in-flight
        requests ONCE and the decoder rebuilds: later submits succeed
        (no permanent zombie serving errors forever)."""
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        dec = SlotDecoder(model, variables, slots=2, prompt_len=8,
                          max_new_tokens=3)
        try:
            real_step = dec._step
            blew = []

            def exploding_step(params, state):
                if not blew:
                    blew.append(1)
                    # simulate the donation: the failed call consumed
                    # the input buffers before dying
                    import jax

                    jax.tree.map(lambda a: a.delete(), state)
                    raise RuntimeError("RESOURCE_EXHAUSTED (simulated)")
                return real_step(params, state)

            dec._step = exploding_step
            with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
                dec.submit([1, 2, 3])
            # rebuilt: the very next request decodes correctly
            assert dec.submit([1, 2, 3]) == reference_generate(
                model, variables, [1, 2, 3], max_new=3)
        finally:
            dec.close()

    def test_geometry_past_max_seq_len_is_refused(self, lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm  # max_seq_len = 16
        with pytest.raises(ValueError, match="max_seq_len"):
            SlotDecoder(model, variables, slots=2, prompt_len=12,
                        max_new_tokens=8)
        import jax.numpy as jnp

        from kubeflow_tpu.runtime.generate import generate

        with pytest.raises(ValueError, match="max_seq_len"):
            generate(model, variables, jnp.ones((1, 12), jnp.int32),
                     max_new_tokens=8)


class TestPerRequestBudgets:
    """Per-instance max_new_tokens caps (ISSUE 9): honored on EVERY
    decode path, not just the slot decoder, and validated hard."""

    def test_continuous_budget_is_ragged_and_exact(self, lm):
        from kubeflow_tpu.serving.server import serve_lm_generator

        model, variables = lm
        served = serve_lm_generator(
            "cb-budget", "transformer-test", prompt_len=8,
            max_new_tokens=4, vocab_size=64,
            continuous_batching=True, decode_slots=2)
        try:
            full = reference_generate(model, variables, [1, 2, 3])
            out = served.predict([
                {"tokens": [1, 2, 3], "max_new_tokens": 2},
                {"tokens": [1, 2, 3], "max_new_tokens": 4}])
            assert out[0] == full[:2] and out[1] == full
        finally:
            served.close()

    def test_plain_generate_budget_applies_too(self):
        from kubeflow_tpu.serving.server import serve_lm_generator

        served = serve_lm_generator(
            "plain-budget", "transformer-test", prompt_len=8,
            max_new_tokens=4, vocab_size=64)
        try:
            full = served.predict([{"tokens": [1, 2, 3]}])[0]
            capped = served.predict(
                [{"tokens": [1, 2, 3], "max_new_tokens": 2}])[0]
            assert capped == full[:2]
        finally:
            served.close()

    def test_out_of_range_budget_is_400(self):
        from kubeflow_tpu.serving.server import serve_lm_generator
        from kubeflow_tpu.utils.httpd import ApiHttpError

        served = serve_lm_generator(
            "bad-budget", "transformer-test", prompt_len=8,
            max_new_tokens=4, vocab_size=64)
        try:
            with pytest.raises(ApiHttpError):
                served.predict(
                    [{"tokens": [1, 2, 3], "max_new_tokens": 9}])
        finally:
            served.close()
