"""Subprocess driver for the elastic shrink/grow loss-continuity e2e.

Run by test_elastic.py in a FRESH interpreter (the gang_worker.py /
sched_worker.py pattern): this image's jaxlib corrupts its heap when a
long-lived process mixes many prior compilations with meshes over
device SUBSETS — the same pre-existing crash family that kills
tests/test_checkpoint.py in full-suite runs. Elastic resizes are
exactly subset meshes, so the e2e gets its own process (and no
persistent compilation cache) and reports its verdict as one JSON line:

    ELASTIC_E2E {"worlds": [4, 2, 4], "losses": [...], ...}

Scenario (deterministic under the fake scheduler clock): a 4-worker
elastic JAXJob on 2 spot + 2 on-demand hosts; both spot hosts are
reclaimed mid-training (the world shrinks to the 2 survivors and
resumes from the checkpointed step), then healed (the scheduler
readmits the replacements and the world grows back to 4). A reference
run trains the same config uninterrupted for the loss-curve comparison.
"""

from __future__ import annotations

import json
import os
import sys


def main(ckpt_root: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import test_elastic as TE

    from kubeflow_tpu.control.jaxjob import types as T
    from kubeflow_tpu.control.jaxjob.controller import (
        job_world, worker_name,
    )
    from kubeflow_tpu.control.k8s import objects as ob
    from kubeflow_tpu.control.scheduler.nodes import new_tpu_node
    from kubeflow_tpu.runtime import elastic
    from kubeflow_tpu.runtime.trainer import Trainer

    fc = TE.S.FakeClock()
    cluster, jax_ctl, sched_ctl, kubelet, _reg = TE.sched_world(fc)
    for i in range(2):
        cluster.create(new_tpu_node(f"spot{i}", topology="4x4", spot=True))
    for i in range(2):
        cluster.create(new_tpu_node(f"ond{i}", topology="4x4"))
    cluster.create(TE.gang_elastic_job())
    TE.pump([jax_ctl, sched_ctl], fc, kubelet)
    bind0 = TE.bindings(cluster)

    def set_ready(ready: bool) -> None:
        for name in ("spot0", "spot1"):
            node = cluster.get("v1", "Node", name)
            node["status"]["conditions"] = [
                {"type": "Ready", "status": "True" if ready else "False"}]
            cluster.update_status(node)
        TE.pump([sched_ctl, jax_ctl], fc, kubelet, rounds=8)

    losses: list[float] = []

    def callback(i, m):
        losses.append(float(m["loss"]))
        if len(losses) == 5:
            set_ready(False)   # spot reclaim lands mid-step-6
        if len(losses) == 8:
            set_ready(True)    # capacity readmitted mid-step-9

    def source():
        return job_world(
            cluster.get(T.API_VERSION, T.KIND, "train", "default"))

    coord = elastic.ElasticCoordinator(
        source, my_name=worker_name("train", 2),
        form_world=lambda w: None, mesh_fn=TE._device_mesh_fn())
    state, summary = coord.run(
        TE._train_cfg(os.path.join(ckpt_root, "elastic")),
        full_world=4, callback=callback)

    ref_losses: list[float] = []
    ref = Trainer(TE._train_cfg(os.path.join(ckpt_root, "ref")),
                  mesh=TE._device_mesh_fn()(None, 4))
    ref.fit(callback=lambda i, m: ref_losses.append(float(m["loss"])))

    job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
    st = job.get("status") or {}
    print("ELASTIC_E2E " + json.dumps({
        "elastic": summary["elastic"],
        "step": int(state.step),
        "losses": losses,
        "ref_losses": ref_losses,
        "initial_spot_bindings": sorted(
            bind0[worker_name("train", i)] for i in (0, 1)),
        "restarts": st.get("restarts", 0),
        "preemptions": st.get("preemptions", 0),
        "resizes": st.get("resizes", 0),
        "active_replicas": st.get("activeReplicas", 0),
        "resizing": (ob.cond_get(job, T.COND_RESIZING) or {}).get("status"),
        "running": ob.cond_is_true(job, T.COND_RUNNING),
    }), flush=True)


if __name__ == "__main__":
    main(sys.argv[1])
