"""Profile operator — multi-tenancy: one namespace per user/team.

Reference: components/profile-controller (SURVEY.md §2.2): Profile CR ->
Namespace (istio-injection labeled) + default-editor/default-viewer
ServiceAccounts + owner RoleBinding + ResourceQuota + cloud-credential
plugins, with a finalizer for cleanup. TPU twist: quota is expressed in
`google.com/tpu` chips alongside cpu/memory.
"""

from kubeflow_tpu.control.profile.types import API_VERSION, KIND, new_profile  # noqa: F401
from kubeflow_tpu.control.profile.controller import (  # noqa: F401
    ProfileReconciler,
    build_controller,
)
