"""Ring attention == reference attention, on a real seq-sharded mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.attention import reference_attention
from kubeflow_tpu.ops.ring_attention import ring_attention
from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh


def make_qkv(b=2, l=32, h=4, hk=4, d=16, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, l, h, d), dtype)
    k = jax.random.normal(ks[1], (b, l, hk, d), dtype)
    v = jax.random.normal(ks[2], (b, l, hk, d), dtype)
    return q, k, v


@pytest.mark.parametrize("ring", [2, 4, 8])
def test_ring_matches_reference(devices8, ring):
    mesh = build_mesh(MeshSpec(data=1, seq=ring), devices=jax.devices()[:ring])
    q, k, v = make_qkv()
    want = reference_attention(q, k, v, causal=True)
    with mesh:
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_ring_with_gqa(devices8):
    mesh = build_mesh(MeshSpec(data=1, seq=4), devices=jax.devices()[:4])
    q, k, v = make_qkv(h=8, hk=2)
    want = reference_attention(q, k, v, causal=True)
    with mesh:
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_ring_with_data_parallel_too(devices8):
    mesh = build_mesh(MeshSpec(data=2, seq=4))
    q, k, v = make_qkv(b=4)
    want = reference_attention(q, k, v, causal=True)
    with mesh:
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_ring_falls_back_without_seq_axis(devices8):
    mesh = build_mesh(MeshSpec(data=8))
    q, k, v = make_qkv()
    want = reference_attention(q, k, v, causal=True)
    with mesh:
        got = ring_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_ring_gradients_flow(devices8):
    mesh = build_mesh(MeshSpec(data=1, seq=4), devices=jax.devices()[:4])
    q, k, v = make_qkv()

    def loss_ring(q, k, v):
        with mesh:
            return ring_attention(q, k, v, mesh=mesh).sum()

    def loss_ref(q, k, v):
        return reference_attention(q, k, v, causal=True).sum()

    # jit the grads: un-jitted execution compiles op-by-op and is the
    # dominant cost of this test on the virtual mesh
    g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref))(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref), atol=1e-4, rtol=1e-4)


def test_lm_with_ring_attention_end_to_end(devices8):
    """Flagship model trains with seq parallelism enabled."""
    from kubeflow_tpu.parallel.mesh import MeshSpec
    from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

    cfg = TrainConfig.from_dict(dict(
        model="transformer-test",
        model_kwargs={"attention_impl": "ring"},
        task="lm", global_batch=4, seq_len=64, vocab_size=256,
        mesh=MeshSpec(data=2, seq=4), optimizer="adamw",
        learning_rate=1e-3, total_steps=2, warmup_steps=1,
    ))
    trainer = Trainer(cfg)
    state, summary = trainer.fit(steps=2)
    assert np.isfinite(summary["final"]["loss"])


def test_ring_gqa_with_model_axis_not_dividing_kv_heads(devices8):
    """n_kv_heads (2) < model axis (4): KV heads are repeated to Q heads
    before sharding instead of crashing shard_map."""
    mesh = build_mesh(MeshSpec(data=1, model=4, seq=2), devices=jax.devices()[:8])
    q, k, v = make_qkv(h=8, hk=2)
    want = reference_attention(q, k, v, causal=True)
    with mesh:
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("ring", [2, 4])
def test_noncausal_ring_matches_reference(devices8, ring):
    """Bidirectional (BERT-style) long-context SP path."""
    mesh = build_mesh(MeshSpec(data=1, seq=ring), devices=jax.devices()[:ring])
    q, k, v = make_qkv()
    want = reference_attention(q, k, v, causal=False)
    with mesh:
        got = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, causal=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_noncausal_ring_gradients(devices8):
    mesh = build_mesh(MeshSpec(data=1, seq=4), devices=jax.devices()[:4])
    q, k, v = make_qkv()

    def loss_ring(q, k, v):
        with mesh:
            return ring_attention(q, k, v, mesh=mesh, causal=False).sum()

    def loss_ref(q, k, v):
        return reference_attention(q, k, v, causal=False).sum()

    g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref))(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               atol=1e-4, rtol=1e-4)


def test_bert_with_ring_attention(devices8):
    """BERT routes bidirectional attention through the ring SP path and
    matches the local reference implementation on unpadded input."""
    from kubeflow_tpu.models.registry import get_model

    mesh = build_mesh(MeshSpec(data=2, seq=4))
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 1, 500)

    ref_model = get_model("bert-test")
    ring_model = get_model("bert-test", attention_impl="ring")
    variables = ref_model.init(jax.random.PRNGKey(1), tokens, train=False)
    want = ref_model.apply(variables, tokens, train=False)
    with mesh:
        got = jax.jit(lambda v, t: ring_model.apply(v, t, train=False))(
            variables, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=3e-2, rtol=3e-2)


from conftest import make_segments as _segments  # noqa: E402


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("ring", [2, 4])
def test_ring_with_segments_matches_reference(devices8, ring, causal):
    """Packed sequences under sequence parallelism: the K-side ids
    rotate with K/V, so cross-document masking survives every ring hop."""
    mesh = build_mesh(MeshSpec(data=1, seq=ring), devices=jax.devices()[:ring])
    q, k, v = make_qkv()
    seg = _segments(2, 32, 3)
    want = reference_attention(q, k, v, causal=causal, segment_ids=seg)
    with mesh:
        got = jax.jit(lambda q, k, v, s: ring_attention(
            q, k, v, mesh=mesh, causal=causal, segment_ids=s))(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_segments_gradients(devices8):
    mesh = build_mesh(MeshSpec(data=1, seq=4), devices=jax.devices()[:4])
    q, k, v = make_qkv(b=1)
    seg = _segments(1, 32, 2)

    def f_ring(q, k, v):
        with mesh:
            return (ring_attention(q, k, v, mesh=mesh, segment_ids=seg)
                    .astype(jnp.float32) ** 2).sum()

    def f_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True, segment_ids=seg)
                .astype(jnp.float32) ** 2).sum()

    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ring_with_window_matches_reference(devices8):
    """Sliding window under sequence parallelism: the global-index bound
    must hold across ring hops."""
    mesh = build_mesh(MeshSpec(data=1, seq=4), devices=jax.devices()[:4])
    q, k, v = make_qkv()
    want = reference_attention(q, k, v, causal=True, window=10)
    with mesh:
        got = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, window=10))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_window_gradients_and_segments(devices8):
    """Window gradients under the ring's streaming-softmax backward, and
    window x packing composition — both against the reference oracle."""
    mesh = build_mesh(MeshSpec(data=1, seq=4), devices=jax.devices()[:4])
    q, k, v = make_qkv(b=1)
    seg = _segments(1, 32, 2)

    def f_ring(q, k, v):
        with mesh:
            return (ring_attention(q, k, v, mesh=mesh, window=12,
                                   segment_ids=seg)
                    .astype(jnp.float32) ** 2).sum()

    def f_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True, window=12,
                                    segment_ids=seg)
                .astype(jnp.float32) ** 2).sum()

    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ring_window_small_window_skips_hops(devices8):
    """window <= l_block: only the self block + one predecessor are
    needed; correctness must hold with the hop cap engaged."""
    mesh = build_mesh(MeshSpec(data=1, seq=8), devices=jax.devices()[:8])
    q, k, v = make_qkv()  # l=32, l_block=4
    want = reference_attention(q, k, v, causal=True, window=3)
    with mesh:
        got = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, window=3))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
