"""Worker payload for the multi-process gang e2e test.

What a real JAXJob training container does (the launcher contract,
reference tf-cnn/launcher.py:59-93): join the jax.distributed world from
JAXJOB_* env, build a process-spanning mesh, train with checkpointing,
exit 0. Run by LocalPodExecutor as an actual subprocess.

Env knobs (set by the test through the pod spec / env_hook):
  GANG_CKPT_DIR     shared orbax checkpoint dir
  GANG_TOTAL_STEPS  global step target
  GANG_STEP_DELAY_S per-step sleep so the test can kill a worker mid-run
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

# sitecustomize may have pre-registered a TPU backend; force cpu the same
# way tests/conftest.py does.
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.parallel.dist import initialize_from_env  # noqa: E402


def main() -> int:
    dist = initialize_from_env()
    assert jax.device_count() == dist.num_processes, \
        (jax.device_count(), dist.num_processes)

    import time

    from kubeflow_tpu.parallel.mesh import MeshSpec
    from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

    delay = float(os.environ.get("GANG_STEP_DELAY_S", "0"))
    cfg = TrainConfig.from_dict(dict(
        model="transformer-test",
        task="lm",
        global_batch=2 * dist.num_processes,
        seq_len=16,
        vocab_size=64,
        mesh=MeshSpec(data=dist.num_processes),
        optimizer="adamw",
        learning_rate=1e-3,
        total_steps=int(os.environ["GANG_TOTAL_STEPS"]),
        warmup_steps=1,
        checkpoint_dir=os.environ["GANG_CKPT_DIR"],
        checkpoint_every=1,
        log_every=10**9,
    ))
    trainer = Trainer(cfg)
    cb = (lambda i, m: time.sleep(delay)) if delay else None
    # Same SIGTERM contract as the launcher: checkpoint + EX_TEMPFAIL.
    # The trainer turns the per-worker notice into a gang-agreed stop
    # (all ranks break at the same step) when num_processes > 1.
    from kubeflow_tpu.runtime.preemption import EX_TEMPFAIL, PreemptionNotice

    notice = PreemptionNotice().install()
    state, summary = trainer.fit(callback=cb, stop=notice)
    line = json.dumps({"rank": dist.process_id,
                       "start_step": summary["start_step"],
                       "final_step": int(state.step),
                       "preempted": bool(summary.get("preempted", False)),
                       "loss": summary["final"].get("loss")})
    print(line, flush=True)
    # Also append to a shared log so the test can assert per-run
    # start_steps (stdout is swallowed by the executor on success).
    log_path = os.environ.get("GANG_LOG")
    if log_path:
        with open(log_path, "a") as f:
            f.write(line + "\n")
    return EX_TEMPFAIL if summary.get("preempted") else 0


if __name__ == "__main__":
    sys.exit(main())
