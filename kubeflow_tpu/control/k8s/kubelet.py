"""Fake kubelets: drive Pod phases against the FakeCluster.

Two levels, matching the test tiers SURVEY.md §4 prescribes:

- ``FakeKubelet`` — phase simulation for controller unit tests
  (Pending -> Running via step(); tests flip terminal phases explicitly).
- ``LocalPodExecutor`` — actually EXECUTES pod container commands as
  local subprocesses with the pod's env (plus overrides), mapping exit
  codes to Succeeded/Failed. This is what lets a JAXJob e2e test run a
  real multi-process `jax.distributed` training gang on the dev machine —
  the hermetic stand-in for the reference's per-CI-run GKE clusters.
"""

from __future__ import annotations

import logging
import os
import subprocess
import threading
import time

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster

log = logging.getLogger("kubeflow_tpu.kubelet")


def _set_phase(cluster: FakeCluster, pod: dict, phase: str, **status_extra) -> dict | None:
    m = ob.meta(pod)
    try:
        cur = cluster.get("v1", "Pod", m["name"], m.get("namespace"))
    except ob.NotFound:
        return None
    cur.setdefault("status", {})
    cur["status"]["phase"] = phase
    cur["status"].update(status_extra)
    return cluster.update_status(cur)


class FakeKubelet:
    """Pending -> Running on step(); terminal phases are test-driven.

    This fake stands in for the whole node fleet, not one kubelet: it
    runs any pod BOUND to any node (spec.nodeName set) and never runs a
    pod still carrying a scheduling gate. ``auto_bind`` (default, the
    pre-gang-scheduler behavior) additionally stands in for
    kube-scheduler: unbound ungated pods are bound to ``node_name``
    (creating that Node, Ready, if absent — slice-health checks treat a
    missing node as failed) and then run. Gang-scheduler tests pass
    ``auto_bind=False`` so only scheduler-bound pods execute.
    """

    def __init__(self, cluster: FakeCluster, auto_bind: bool = True,
                 node_name: str = "fake-node"):
        self.cluster = cluster
        self.auto_bind = auto_bind
        self.node_name = node_name

    def _ensure_node(self) -> None:
        if self.cluster.get_or_none("v1", "Node", self.node_name) is None:
            node = ob.new_object("v1", "Node", self.node_name)
            node["status"] = {
                "conditions": [{"type": "Ready", "status": "True"}]}
            try:
                self.cluster.create(node)
            except ob.Conflict:
                pass

    def step(self) -> int:
        moved = 0
        for pod in self.cluster.list("v1", "Pod"):
            if (pod.get("status") or {}).get("phase", "Pending") != "Pending":
                continue
            spec = pod.get("spec") or {}
            if spec.get("schedulingGates"):
                continue  # not admitted by the gang scheduler yet
            if not spec.get("nodeName"):
                if not self.auto_bind:
                    continue  # kubelets run only bound pods
                self._ensure_node()
                m = ob.meta(pod)
                try:
                    pod = self.cluster.patch(
                        "v1", "Pod", m["name"],
                        {"spec": {"nodeName": self.node_name}},
                        m.get("namespace"))
                except ob.NotFound:
                    continue
            _set_phase(
                self.cluster, pod, "Running",
                startTime=ob.now_iso(),
                containerStatuses=[
                    {"name": c.get("name", "main"),
                     "state": {"running": {"startedAt": ob.now_iso()}},
                     "ready": True}
                    for c in pod["spec"].get("containers", [])
                ],
            )
            moved += 1
        return moved

    def succeed(self, name: str, namespace: str = "default") -> None:
        pod = self.cluster.get("v1", "Pod", name, namespace)
        _set_phase(self.cluster, pod, "Succeeded")

    def fail(self, name: str, namespace: str = "default", message: str = "boom",
             exit_code: int = 1) -> None:
        pod = self.cluster.get("v1", "Pod", name, namespace)
        containers = (pod.get("spec") or {}).get("containers") or []
        main = containers[0].get("name", "main") if containers else "main"
        _set_phase(
            self.cluster, pod, "Failed",
            containerStatuses=[{
                "name": main,
                "state": {"terminated": {"exitCode": exit_code,
                                         "message": message}},
                "ready": False,
            }],
        )


class LocalPodExecutor:
    """Run pod containers as local subprocesses.

    Watches the cluster for pods (optionally label-filtered), launches
    `spec.containers[0].command + args` with the container env exported,
    and reflects process state back into pod.status.phase. DNS-style
    coordinator addresses can't resolve locally, so callers provide
    ``env_overrides`` per pod (e.g. rewrite JAXJOB_COORDINATOR_ADDRESS to
    127.0.0.1) via a hook.
    """

    def __init__(
        self,
        cluster: FakeCluster,
        label_selector: dict | None = None,
        env_hook=None,  # fn(pod, env: dict) -> dict
        cwd: str | None = None,
        node_name: str | None = None,
    ):
        self.cluster = cluster
        self.label_selector = label_selector
        self.env_hook = env_hook
        self.cwd = cwd
        # scheduler-binding simulation: launched pods get spec.nodeName
        # so slice-health (node NotReady/taint) paths see real bindings.
        # Mutable: tests re-point it to model rescheduling onto a healthy
        # node after a drain.
        self.node_name = node_name
        # key -> (pod uid, process). The uid is the pod's identity: a
        # gang restart recreates a pod under the same name, and the old
        # incarnation's process must be reaped before the new one runs
        # (kubelet semantics — otherwise a relaunched jax.distributed
        # worker can reach the previous incarnation's coordinator and
        # die with "connected with a different incarnation").
        self._procs: dict[tuple[str, str], tuple[str, subprocess.Popen]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()

    def _pod_env(self, pod: dict) -> dict[str, str]:
        env = dict(os.environ)
        for c in pod["spec"].get("containers", [])[:1]:
            for e in c.get("env", []):
                if "value" in e:
                    env[e["name"]] = str(e["value"])
        if self.env_hook:
            env = self.env_hook(pod, env)
        return env

    def poll_once(self) -> None:
        """Reap stale/finished processes, then launch new pods.

        Reaping runs first so that a gang-restarted pod set (delete +
        recreate under the same names) has its previous incarnation's
        processes killed — and the coordinator port released — before
        the new gang launches in the same pass.
        """
        pods = self.cluster.list("v1", "Pod", label_selector=self.label_selector)
        with self._lock:
            # -- harvest / reap ------------------------------------------
            for key, (uid, proc) in list(self._procs.items()):
                ns, name = key
                rc = proc.poll()
                pod = self.cluster.get_or_none("v1", "Pod", name, ns)
                if pod is None or ob.meta(pod).get("uid") != uid:
                    # pod deleted or replaced by a new incarnation (gang
                    # restart): kill + reap; never touch the new pod's
                    # status from the old process's exit code.
                    if rc is None:
                        proc.kill()
                    proc.wait(timeout=10)
                    if proc.stdout:
                        proc.stdout.close()
                    del self._procs[key]
                    continue
                if rc is None:
                    continue
                out = (proc.stdout.read() or b"").decode(errors="replace")
                del self._procs[key]
                if rc == 0:
                    _set_phase(self.cluster, pod, "Succeeded")
                else:
                    log.warning("pod %s failed rc=%d\n%s", name, rc, out[-2000:])
                    _set_phase(
                        self.cluster, pod, "Failed",
                        containerStatuses=[{
                            "name": pod["spec"]["containers"][0].get(
                                "name", "main"),
                            "state": {"terminated": {"exitCode": rc,
                                                     "message": out[-500:]}},
                        }],
                    )
            # -- launch --------------------------------------------------
            for pod in pods:
                m = ob.meta(pod)
                key = (m.get("namespace") or "default", m["name"])
                phase = (pod.get("status") or {}).get("phase", "Pending")
                if pod["spec"].get("schedulingGates"):
                    continue  # gated: the gang scheduler has not admitted it
                if phase == "Pending" and key not in self._procs:
                    c = pod["spec"]["containers"][0]
                    cmd = list(c.get("command") or []) + list(c.get("args") or [])
                    log.info("exec pod %s: %s", m["name"], " ".join(cmd))
                    if self.node_name and not pod["spec"].get("nodeName"):
                        # bind-once: re-read and only self-bind if still
                        # unbound — the gang scheduler may have placed
                        # this pod between our list() and now, and its
                        # binding must win (never rebind a bound pod)
                        fresh = self.cluster.get_or_none("v1", "Pod",
                                                         m["name"], key[0])
                        if fresh is not None and fresh["spec"].get("nodeName"):
                            pod = fresh
                        elif fresh is not None:
                            fresh["spec"]["nodeName"] = self.node_name
                            pod = self.cluster.update(fresh)
                    proc = subprocess.Popen(
                        cmd,
                        env=self._pod_env(pod),
                        cwd=self.cwd,
                        stdout=subprocess.PIPE,
                        stderr=subprocess.STDOUT,
                    )
                    self._procs[key] = (m.get("uid", ""), proc)
                    _set_phase(self.cluster, pod, "Running", startTime=ob.now_iso())

    def alive_count(self) -> int:
        """Number of tracked worker processes still running."""
        with self._lock:
            return sum(1 for _uid, p in self._procs.values() if p.poll() is None)

    def kill_pod(self, name: str, namespace: str = "default",
                 sig: int | None = None) -> bool:
        """Signal the process backing a pod (fault injection for e2e
        tests — the hermetic stand-in for a preempted TPU worker).
        Default SIGKILL = hard node loss; sig=SIGTERM = the kubelet's
        graceful-eviction notice ahead of TPU maintenance.
        Returns False when no live process backs that pod."""
        with self._lock:
            entry = self._procs.get((namespace, name))
            if entry is None or entry[1].poll() is not None:
                return False
            if sig is None:
                entry[1].kill()
            else:
                entry[1].send_signal(sig)
            return True

    def run_until_settled(self, timeout: float = 120.0, poll: float = 0.2) -> None:
        """Poll until no tracked process is alive and no Pending pods remain."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.poll_once()
            pods = self.cluster.list("v1", "Pod", label_selector=self.label_selector)
            pending = any(
                (p.get("status") or {}).get("phase", "Pending") in ("Pending", "Running")
                for p in pods
            )
            if not pending and not self._procs:
                return
            time.sleep(poll)
        raise TimeoutError("pods did not settle in time")

    def shutdown(self) -> None:
        self._stop.set()
        with self._lock:
            for _uid, proc in self._procs.values():
                if proc.poll() is None:
                    proc.kill()
            self._procs.clear()
