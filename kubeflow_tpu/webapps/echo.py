"""echo-server: request-reflection demo/test service.

Mirrors components/echo-server/main.py (Flask one-file app used by the
platform's smoke tests): replies with the request's method, path, query,
headers and body so E2E tests can assert what reached the backend
through the gateway/auth chain.
"""

from __future__ import annotations

from kubeflow_tpu.utils import httpd
from kubeflow_tpu.utils.httpd import HttpReq, Router


def _echo(req: HttpReq):
    return {
        "method": req.method,
        "path": req.path,
        "query": req.query,
        "headers": dict(req.headers),
        "body": req.body.decode(errors="replace"),
        "user": req.user or req.header("kubeflow-userid") or None,
    }


def router() -> Router:
    r = Router("echo")
    httpd.add_health_routes(r)  # before the catch-all: first match wins
    for method in ("GET", "POST", "PUT", "DELETE"):
        r.route(method, "/", _echo)
        r.route(method, "/{path*}", _echo)
    return r


def serve(host: str = "0.0.0.0", port: int = 8080) -> httpd.HttpService:
    return httpd.HttpService(router(), host, port)
