"""Pallas TPU flash attention (forward kernel + blockwise backward).

The hot op of the transformer path, built for the MXU:

- Forward is a Pallas kernel: grid (batch*heads, q_blocks, kv_blocks),
  streaming-softmax accumulators (running max / sum / output) in VMEM
  scratch that persist across the sequential kv-block grid dimension, so
  attention memory is O(BLOCK_Q x BLOCK_K) instead of O(L^2). Logits and
  accumulation in f32 on the MXU (`preferred_element_type`), inputs bf16.
- Causal blocks above the diagonal are predicated off with `@pl.when`
  (skipped entirely, ~2x speedup), diagonal blocks masked with
  `broadcasted_iota` (TPU needs >=2D iota).
- Backward is fused Pallas too: a dq kernel (accumulates over kv blocks)
  and a dk/dv kernel (accumulates over q blocks), both recomputing
  probabilities from the saved logsumexp (the flash trick) so memory is
  O(BLOCK_Q x BLOCK_K); all matmuls on the MXU in f32. A blockwise XLA
  backward (`_flash_bwd_xla`) remains as the differential-test oracle.

On non-TPU platforms the kernel runs in Pallas interpret mode (tests on
the virtual CPU mesh exercise the same code path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

# Hardware-swept defaults (BASELINE.md round 3): on a v5e, 512x512
# blocks more than double train MFU vs 128x128 (llama-1b bs16 seq2048:
# 0.227 -> 0.467) — bigger blocks amortize the per-block HBM re-reads of
# K/V across 4x more MXU work and still fit VMEM comfortably. Blocks
# clamp to the sequence length, so short-seq callers are unaffected;
# override per-run with KFTPU_FLASH_BLOCK_Q/K.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() not in ("tpu",)


def _vmem_spec(shape, imap) -> "pl.BlockSpec":
    return pl.BlockSpec(shape, imap, memory_space=pltpu.VMEM)


def _recompute_p_ds(q, k, v, g, lse_row, delta_row, *, scale, causal,
                    block_q, block_k, qi, ki, offset):
    """Shared backward block math: recompute probabilities from the saved
    lse and form ds = p * (dp - delta) * scale. Used by BOTH backward
    kernels so the masking/scaling convention can never diverge between
    dq and dk/dv."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                          # [BQ, BK]
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = (qi * block_q + rows + offset) >= (ki * block_k + cols)
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse_row[:, None])                  # [BQ, BK]
    dp = jax.lax.dot_general(
        g, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_row[:, None]) * scale
    return p, ds


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s, *,
                scale: float, causal: bool, block_q: int, block_k: int,
                offset: int):
    # offset = lk - lq: causality is end-aligned (query row i may attend
    # keys <= i + offset), matching reference_attention's tril(k=lk-lq) —
    # the KV-cache decode / chunked-prefill convention.
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    # causal: kv block strictly above the diagonal contributes nothing
    run = True
    if causal:
        run = ki * block_k <= qi * block_q + (block_q - 1) + offset

    @pl.when(run)
    def _compute():
        q = q_ref[0]                                   # [BQ, D]
        k = k_ref[0]                                   # [BK, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                      # [BQ, BK]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (qi * block_q + rows + offset) >= (ki * block_k + cols)
            s = jnp.where(mask, s, NEG_INF)
        m_prev = m_s[:]                                # [BQ, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                         # [BQ, BK]
        l_new = l_s[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_s[:] = m_new
        l_s[:] = l_new

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_s[:], 1e-20)
        o_ref[0] = (acc_s[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_s[:] + jnp.log(l))[:, 0]


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    """q,k,v: [BH, L, D] (kv already repeated to q heads)."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    nq = pl.cdiv(lq, block_q)
    nk = pl.cdiv(lk, block_k)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, offset=lk - lq,
    )
    if not _HAS_PLTPU:
        raise ImportError(
            "jax.experimental.pallas.tpu unavailable in this JAX build; "
            "use attention(impl='reference') instead of the flash kernel"
        )
    scratch = [
        pltpu.VMEM((block_q, 1), jnp.float32),   # running max
        pltpu.VMEM((block_q, 1), jnp.float32),   # running sum
        pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
    ]
    bs = _vmem_spec

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            bs((1, block_q, d), lambda b, i, j: (b, i, 0)),
            bs((1, block_k, d), lambda b, i, j: (b, j, 0)),
            bs((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            bs((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # lse rides as [BH, 1, L] so the block's trailing dims are
            # (1, block_q) — legal under Mosaic's (8, 128) tiling rule
            # (1 == the full middle dim; block_q % 128 == 0).
            bs((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, lq), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)
    return out, lse.reshape(bh, lq)


# --------------------------------------------------------------------------
# backward: fused Pallas kernels (dq; dk/dv), with the saved-lse flash
# trick — probabilities are recomputed blockwise, memory stays
# O(BLOCK_Q x BLOCK_K). Two kernels because the two gradients accumulate
# over different grid axes (dq over kv blocks, dk/dv over q blocks);
# each keeps its accumulator in VMEM scratch across the sequential inner
# grid dimension, exactly like the forward.
# --------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref,
                   acc_s, *, scale, causal, block_q, block_k, offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_s[:] = jnp.zeros_like(acc_s)

    run = True
    if causal:
        run = ki * block_k <= qi * block_q + (block_q - 1) + offset

    @pl.when(run)
    def _compute():
        k = k_ref[0]                                   # [BK, D]
        _, ds = _recompute_p_ds(
            q_ref[0], k, v_ref[0], g_ref[0], lse_ref[0, 0], delta_ref[0, 0],
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            qi=qi, ki=ki, offset=offset)
        acc_s[:] = acc_s[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = acc_s[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_s, dv_s, *,
                    scale, causal, block_q, block_k, offset):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    run = True
    if causal:
        # any row of this q block may attend into this kv block
        run = ki * block_k <= qi * block_q + (block_q - 1) + offset

    @pl.when(run)
    def _compute():
        q = q_ref[0]                                   # [BQ, D]
        g = g_ref[0]
        p, ds = _recompute_p_ds(
            q, k_ref[0], v_ref[0], g, lse_ref[0, 0], delta_ref[0, 0],
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            qi=qi, ki=ki, offset=offset)
        dv_s[:] = dv_s[:] + jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [BK, D]
        dk_s[:] = dk_s[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, g, scale, causal, block_q, block_k,
                      interpret):
    """Fused backward: q,k,v,out,g [BH, L, D]; lse [BH, L]."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    nq = pl.cdiv(lq, block_q)
    nk = pl.cdiv(lk, block_k)
    offset = lk - lq
    # delta_i = sum_d(do_i * o_i): one cheap rowwise reduction in XLA.
    # lse/delta ride as [BH, 1, L] for Mosaic's (8, 128) tiling rule.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = delta.reshape(bh, 1, lq)
    lse = lse.reshape(bh, 1, lq)

    bs = _vmem_spec

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, offset=offset),
        grid=(bh, nq, nk),
        in_specs=[
            bs((1, block_q, d), lambda b, i, j: (b, i, 0)),   # q
            bs((1, block_k, d), lambda b, i, j: (b, j, 0)),   # k
            bs((1, block_k, d), lambda b, i, j: (b, j, 0)),   # v
            bs((1, block_q, d), lambda b, i, j: (b, i, 0)),   # g
            bs((1, 1, block_q), lambda b, i, j: (b, 0, i)),   # lse
            bs((1, 1, block_q), lambda b, i, j: (b, 0, i)),   # delta
        ],
        out_specs=bs((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, offset=offset),
        grid=(bh, nk, nq),
        in_specs=[
            bs((1, block_q, d), lambda b, j, i: (b, i, 0)),   # q
            bs((1, block_k, d), lambda b, j, i: (b, j, 0)),   # k
            bs((1, block_k, d), lambda b, j, i: (b, j, 0)),   # v
            bs((1, block_q, d), lambda b, j, i: (b, i, 0)),   # g
            bs((1, 1, block_q), lambda b, j, i: (b, 0, i)),   # lse
            bs((1, 1, block_q), lambda b, j, i: (b, 0, i)),   # delta
        ],
        out_specs=[
            bs((1, block_k, d), lambda b, j, i: (b, j, 0)),
            bs((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, lk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------
# backward (blockwise XLA fallback / differential-test oracle)
# --------------------------------------------------------------------------

def _flash_bwd_xla(q, k, v, out, lse, g, scale, causal, block_k):
    """Recompute-p backward. All [BH, L, D]; lse [BH, L]."""
    f32 = jnp.float32
    qf, kf, vf, gf = (x.astype(f32) for x in (q, k, v, g))
    # delta_i = sum_d(do_i * o_i) (rowwise), the standard flash-bwd term
    delta = jnp.sum(gf * out.astype(f32), axis=-1)           # [BH, L]
    lk = k.shape[1]
    nk = pl.cdiv(lk, block_k)
    positions_q = jnp.arange(q.shape[1])

    def kv_block(carry, jb):
        dq_acc = carry
        ks = jax.lax.dynamic_slice_in_dim(kf, jb * block_k, block_k, axis=1)
        vs = jax.lax.dynamic_slice_in_dim(vf, jb * block_k, block_k, axis=1)
        s = jnp.einsum("bqd,bkd->bqk", qf, ks) * scale
        if causal:
            cols = jb * block_k + jnp.arange(block_k)
            mask = (positions_q[:, None] + (lk - q.shape[1])) >= cols[None, :]
            s = jnp.where(mask[None], s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                      # [BH, Lq, BK]
        dv = jnp.einsum("bqk,bqd->bkd", p, gf)
        dp = jnp.einsum("bqd,bkd->bqk", gf, vs)
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, ks)
        dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
        return dq_acc, (dk, dv)

    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        kv_block, jnp.zeros_like(qf), jnp.arange(nk)
    )
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(k.shape[0], nk * block_k, k.shape[2])
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(*dk.shape)
    dk = dk[:, :lk]
    dv = dv[:, :lk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    interpret = _interpret_default()
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k):
    interpret = _interpret_default()
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_pallas(q, k, v, out, lse, g, scale, causal,
                             block_q, block_k, _interpret_default())


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jax.Array:
    """Fused attention. [B, L, H, D] in / out; GQA via fewer KV heads."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = scale if scale is not None else d ** -0.5
    if k.shape[2] != h:
        assert h % k.shape[2] == 0, (h, k.shape[2])
        rep = h // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # Clamp to the sequence, then halve until the block divides it (not
    # below the 128-lane tile): a 640-token sequence runs at block 128
    # instead of erroring against the swept 512 default.
    block_q = min(block_q, lq)
    while block_q > 128 and lq % block_q:
        block_q //= 2
    block_k = min(block_k, lk)
    while block_k > 128 and lk % block_k:
        block_k //= 2
    if lq % block_q or lk % block_k:
        raise ValueError(
            f"sequence lengths ({lq}, {lk}) must be multiples of the block "
            f"sizes ({block_q}, {block_k}); pad inputs or pass block sizes"
        )
    # [B, L, H, D] -> [B*H, L, D]
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    out = _flash(qt, kt, vt, scale, causal, block_q, block_k)
    return out.reshape(b, h, lq, d).transpose(0, 2, 1, 3)
