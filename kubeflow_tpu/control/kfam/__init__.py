"""KFAM — Kubeflow Access Management REST service.

Reference: components/access-management (SURVEY.md §2.2): profile +
contributor (RoleBinding) management consumed by the central dashboard.
"""

from kubeflow_tpu.control.kfam.service import KfamService  # noqa: F401
