"""TPU gang scheduler (control/scheduler): queueing, all-or-nothing
admission, priority preemption — plus the node/topology model and the
kubelet binding contract it relies on.

The e2e tests run the JAXJob controller AND the gang scheduler against
one FakeCluster with a non-auto-binding kubelet, so the full production
loop is exercised: JAXJob renders a gated gang -> scheduler admits
all-or-nothing -> kubelet runs only bound pods -> preemption flows back
through the JAXJob controller's existing gang-restart path.
"""

import ast
import pathlib
import sys

import pytest

from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.jaxjob.controller import build_controller, worker_name
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.k8s.kubelet import FakeKubelet, LocalPodExecutor
from kubeflow_tpu.control.runtime import seed_controller
from kubeflow_tpu.control.scheduler import (
    ANNOTATION_GANG_SIZE, ANNOTATION_PRIORITY, GATE_GANG, SCHEDULER_NAME,
)
from kubeflow_tpu.control.scheduler.nodes import (
    feasible, new_tpu_node, node_view, pod_tpu_request,
)
from kubeflow_tpu.control.scheduler.queue import GangQueue
from kubeflow_tpu.control.scheduler.scheduler import build_scheduler
from kubeflow_tpu.control.scheduler.topology import (
    TOPOLOGY_SEPARATOR, chip_count, parse_topology,
)
from kubeflow_tpu.runtime.metrics import MetricsRegistry

PACKAGE = pathlib.Path(__file__).resolve().parent.parent / "kubeflow_tpu"


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- topology ----------------------------------------------------------------


class TestTopology:
    def test_parse_shapes(self):
        assert parse_topology("2x4").dims == (2, 4)
        assert parse_topology("4x4x4").dims == (4, 4, 4)
        assert parse_topology("8").dims == (8,)
        assert parse_topology(" 2X4 ").dims == (2, 4)  # case/space tolerant

    def test_chip_count(self):
        assert chip_count("2x4") == 8
        assert chip_count("4x4x4") == 64
        assert chip_count("1") == 1

    def test_str_roundtrip(self):
        assert str(parse_topology("2x4")) == "2x4"

    @pytest.mark.parametrize("bad", ["", "2xbad", "0x4", "2x-1", "x", "2x"])
    def test_malformed_raises(self, bad):
        with pytest.raises(ValueError):
            parse_topology(bad)

    def test_single_spelling_ast_pin(self):
        """The satellite contract: exactly ONE topology parser. No other
        module in the package may split on the separator (the way
        parallel/mesh.py's AXIS_NAMES is pinned for tpulint), and every
        former parsing site imports the shared module."""
        offenders = []
        for path in PACKAGE.rglob("*.py"):
            if path.parent.name == "scheduler" and path.name == "topology.py":
                continue
            tree = ast.parse(path.read_text())
            for node in ast.walk(tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "split"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and node.args[0].value == TOPOLOGY_SEPARATOR):
                    offenders.append(
                        f"{path.relative_to(PACKAGE)}:{node.lineno}")
        assert offenders == [], (
            f"topology parsing duplicated outside scheduler/topology.py: "
            f"{offenders}")
        for rel in ("control/jaxjob/types.py", "tpctl/tpudef.py",
                    "tpctl/apply.py"):
            src = (PACKAGE / rel).read_text()
            assert "kubeflow_tpu.control.scheduler.topology" in src, rel

    def test_tpudef_shares_parser(self):
        from kubeflow_tpu.tpctl.tpudef import TpuDef

        assert TpuDef(topology="4x4").slice_chips() == 16


# -- node model --------------------------------------------------------------


class TestNodeModel:
    def test_new_tpu_node_surface(self):
        node = new_tpu_node("n0", accelerator="tpu-v5-lite-podslice",
                            topology="2x4")
        v = node_view(node)
        assert v.allocatable_chips == 4  # per-host share of the slice
        assert v.ready
        assert v.labels[JT.NODESELECTOR_ACCEL] == "tpu-v5-lite-podslice"
        assert v.labels[JT.NODESELECTOR_TOPOLOGY] == "2x4"

    def _pod(self, chips=4, selector=None, tolerations=None):
        pod = ob.new_object("v1", "Pod", "p", "default")
        pod["spec"] = {"containers": [{"name": "jax", "resources": {
            "limits": {JT.RESOURCE_TPU: chips}}}]}
        if selector:
            pod["spec"]["nodeSelector"] = selector
        if tolerations:
            pod["spec"]["tolerations"] = tolerations
        return pod

    def test_pod_tpu_request(self):
        assert pod_tpu_request(self._pod(chips=4)) == 4
        cpu_pod = ob.new_object("v1", "Pod", "c", "default")
        cpu_pod["spec"] = {"containers": [{"name": "main"}]}
        assert pod_tpu_request(cpu_pod) == 0

    def test_feasibility_selector_and_readiness(self):
        v = node_view(new_tpu_node("n0", topology="2x4"))
        assert feasible(self._pod(selector={
            JT.NODESELECTOR_TOPOLOGY: "2x4"}), v)
        assert not feasible(self._pod(selector={
            JT.NODESELECTOR_TOPOLOGY: "4x4"}), v)
        assert not feasible(
            self._pod(), node_view(new_tpu_node("n1", ready=False)))

    def test_taints_block_unless_tolerated(self):
        taint = {"key": JT.TAINT_IMPENDING_TERMINATION, "effect": "NoSchedule"}
        v = node_view(new_tpu_node("n0", taints=(taint,)))
        assert not feasible(self._pod(), v)
        assert feasible(self._pod(tolerations=[
            {"key": JT.TAINT_IMPENDING_TERMINATION}]), v)

    def test_toleration_operator_equal_requires_value_and_effect(self):
        """kube semantics: Equal (the default operator) must match the
        taint's VALUE, and a toleration naming an effect only covers
        that effect — a key-only match must not defeat a taint."""
        taint = {"key": "maintenance", "value": "tpu-repair",
                 "effect": "NoExecute"}
        v = node_view(new_tpu_node("n0", taints=(taint,)))
        # wrong value: real kube-scheduler rejects this node
        assert not feasible(self._pod(tolerations=[
            {"key": "maintenance", "operator": "Equal",
             "value": "upgrade-ok"}]), v)
        assert feasible(self._pod(tolerations=[
            {"key": "maintenance", "operator": "Equal",
             "value": "tpu-repair"}]), v)
        # wrong effect never tolerates; Exists-on-key ignores the value
        assert not feasible(self._pod(tolerations=[
            {"key": "maintenance", "operator": "Exists",
             "effect": "NoSchedule"}]), v)
        assert feasible(self._pod(tolerations=[
            {"key": "maintenance", "operator": "Exists"}]), v)


# -- gang queue --------------------------------------------------------------


class TestGangQueue:
    def test_priority_then_fifo_order(self):
        fc = FakeClock()
        q = GangQueue(clock=fc)
        q.offer("ns", "low-a", priority=0)
        q.offer("ns", "high", priority=5)
        q.offer("ns", "low-b", priority=0)
        assert [e.name for e in q.ready()] == ["high", "low-a", "low-b"]

    def test_exponential_backoff_with_fake_clock(self):
        fc = FakeClock()
        q = GangQueue(clock=fc, base_backoff=1.0, max_backoff=8.0)
        q.offer("ns", "g")
        assert q.requeue("ns", "g") == 1.0
        assert q.ready() == []                 # backed off
        assert q.next_wakeup() == 1.0
        fc.advance(1.0)
        assert [e.name for e in q.ready()] == ["g"]
        assert q.requeue("ns", "g") == 2.0     # doubles
        fc.advance(2.0)
        assert q.requeue("ns", "g") == 4.0
        fc.advance(4.0)
        assert q.requeue("ns", "g") == 8.0
        fc.advance(8.0)
        assert q.requeue("ns", "g") == 8.0     # capped
        fc.advance(8.0)
        assert [e.attempts for e in q.ready()] == [5]

    def test_remove_resets_backoff_state(self):
        fc = FakeClock()
        q = GangQueue(clock=fc, base_backoff=1.0)
        q.offer("ns", "g")
        q.requeue("ns", "g")
        q.remove("ns", "g")
        e = q.offer("ns", "g")                 # re-queued fresh
        assert e.attempts == 0 and e.not_before == 0.0

    def test_offer_idempotent_tracks_priority(self):
        q = GangQueue(clock=FakeClock())
        e1 = q.offer("ns", "g", priority=0)
        e2 = q.offer("ns", "g", priority=7)
        assert e2.seq == e1.seq and e2.priority == 7
        assert q.depth() == 1

    def test_depths_report_zero_after_drain_then_prune(self):
        q = GangQueue(clock=FakeClock())
        q.offer("a", "g1")
        q.offer("b", "g2")
        q.remove("a", "g1")
        assert q.depths() == {"a": 0, "b": 1}
        # one zero-fill per drain, then the namespace is pruned so
        # ephemeral-tenant churn cannot grow the map forever
        assert q.depths() == {"b": 1}

    def test_kick_expires_backoff(self):
        fc = FakeClock()
        q = GangQueue(clock=fc, base_backoff=10.0)
        q.offer("ns", "g1")
        q.offer("ns", "g2")
        q.requeue("ns", "g1")
        q.requeue("ns", "g2")
        assert q.ready() == []
        q.kick_one("ns", "g1")
        assert [e.name for e in q.ready()] == ["g1"]
        q.kick()
        assert {e.name for e in q.ready()} == {"g1", "g2"}
        # attempts survive a kick: the NEXT failure still backs off far
        assert all(e.attempts == 1 for e in q.ready())


# -- e2e worlds --------------------------------------------------------------


def gang_job(name, replicas=2, priority=0, topology="2x4", chips=4,
             slice_count=1, **kw):
    return JT.new_jaxjob(
        name, replicas=replicas, slice_count=slice_count,
        accelerator="tpu-v5-lite-podslice", topology=topology,
        chips_per_worker=chips, priority=priority, gang_schedule=True, **kw)


def sched_world(clock):
    cluster = FakeCluster()
    registry = MetricsRegistry()
    jax_ctl = seed_controller(build_controller(cluster, record_events=False))
    sched_ctl = seed_controller(build_scheduler(
        cluster, registry=registry, record_events=False, clock=clock))
    kubelet = FakeKubelet(cluster, auto_bind=False)
    return cluster, jax_ctl, sched_ctl, kubelet, registry


def pump(ctls, clock, kubelet=None, rounds=10):
    for _ in range(rounds):
        for c in ctls:
            c.run_until_idle(advance_delayed=True)
        if kubelet is not None:
            kubelet.step()
        clock.advance(1.0)


def bindings(cluster, namespace="default"):
    return {ob.meta(p)["name"]: (p["spec"].get("nodeName"))
            for p in cluster.list("v1", "Pod", namespace=namespace)}


class TestAllOrNothingAdmission:
    def test_capacity_for_n_minus_one_binds_zero(self):
        """THE gang property: 2 workers, room for 1 => NOTHING binds."""
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0"))     # one 4-chip host
        cluster.create(gang_job("gang", replicas=2))  # needs 2 hosts
        pump([jax_ctl, sched_ctl], fc, kubelet)
        b = bindings(cluster)
        assert len(b) == 2
        assert all(node is None for node in b.values()), b
        for p in cluster.list("v1", "Pod", namespace="default"):
            assert p["spec"]["schedulingGates"] == [{"name": GATE_GANG}]
            phase = (p.get("status") or {}).get("phase", "Pending")
            assert phase == "Pending"
        # queued + backing off, visible in metrics
        text = reg.render()
        assert 'scheduler_queue_depth{namespace="default",tenant="default"} 1' \
            in text
        assert "scheduler_requeues_total" in text

    def test_admits_when_capacity_appears(self):
        import prometheus_client as prom

        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0"))
        cluster.create(gang_job("gang", replicas=2))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        assert all(n is None for n in bindings(cluster).values())
        before = prom.REGISTRY.get_sample_value(
            "jaxjob_gang_schedule_seconds_count") or 0.0

        cluster.create(new_tpu_node("n1"))     # capacity arrives
        pump([jax_ctl, sched_ctl], fc, kubelet)
        b = bindings(cluster)
        assert sorted(b) == ["gang-worker-0", "gang-worker-1"]
        assert sorted(b.values()) == ["n0", "n1"]  # one worker per host
        for p in cluster.list("v1", "Pod", namespace="default"):
            assert not p["spec"].get("schedulingGates")  # gate lifted
        pump([jax_ctl, sched_ctl], fc, kubelet)
        job = cluster.get(JT.API_VERSION, JT.KIND, "gang", "default")
        assert ob.cond_is_true(job, JT.COND_RUNNING)
        # bind latency reached BOTH sinks: the prom histogram and the
        # MetricsRegistry native histogram (ISSUE 4: migrated off the
        # hand-rolled _sum/_count counter pair)
        after = prom.REGISTRY.get_sample_value(
            "jaxjob_gang_schedule_seconds_count")
        assert after == before + 1
        text = reg.render()
        assert "# TYPE scheduler_bind_latency_seconds histogram" in text
        assert ('scheduler_bind_latency_seconds_bucket{namespace="default",'
                'tenant="default",le="+Inf"} 1') in text
        assert ('scheduler_bind_latency_seconds_count{namespace="default",'
                'tenant="default"} 1') in text
        assert ('scheduler_gangs_admitted_total{namespace="default",'
                'tenant="default"} 1') in text
        assert 'scheduler_queue_depth{namespace="default",tenant="default"} 0' \
            in text

    def test_node_event_bypasses_backoff(self):
        """New capacity must not wait out an exponential backoff: a
        Node event kicks every backed-off entry and retries at once."""
        from kubeflow_tpu.control.scheduler.scheduler import GangScheduler

        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0"))
        cluster.create(gang_job("gang", replicas=2))
        for _ in range(4):  # pump WITHOUT advancing the clock
            jax_ctl.run_until_idle(advance_delayed=True)
            sched_ctl.run_until_idle(advance_delayed=True)
        assert all(n is None for n in bindings(cluster).values())
        rec = sched_ctl.reconciler
        assert isinstance(rec, GangScheduler)
        assert rec.queue.get("default", "gang").not_before > 0  # backing off
        cluster.create(new_tpu_node("n1"))  # capacity arrives NOW
        for _ in range(4):
            sched_ctl.run_until_idle(advance_delayed=True)
            jax_ctl.run_until_idle(advance_delayed=True)
        assert fc.t == 0.0  # only the kick can explain admission
        assert sorted(bindings(cluster).values()) == ["n0", "n1"]

    def test_mid_creation_wait_does_not_burn_backoff(self):
        """A gang observed mid-creation (_WAIT) polls at the base rate:
        no attempts escalation, no failed-admission counter — its first
        REAL capacity failure must start the schedule at base_backoff."""
        from kubeflow_tpu.control.scheduler.scheduler import GangScheduler

        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0"))
        cluster.create(new_tpu_node("n1"))
        # half a gang, as a watch could observe it mid-creation
        pod = ob.new_object(
            "v1", "Pod", "gang-worker-0", "default",
            labels={JT.LABEL_JOB_NAME: "gang"},
            annotations={ANNOTATION_GANG_SIZE: "2",
                         ANNOTATION_PRIORITY: "0"})
        pod["spec"] = {"schedulerName": SCHEDULER_NAME,
                       "schedulingGates": [{"name": GATE_GANG}],
                       "containers": [{"name": "jax"}]}
        cluster.create(pod)
        for _ in range(4):
            sched_ctl.run_until_idle(advance_delayed=True)
        rec = sched_ctl.reconciler
        assert isinstance(rec, GangScheduler)
        e = rec.queue.get("default", "gang")
        assert e is not None and e.attempts == 0
        assert "scheduler_requeues_total" not in reg.render()

    def test_deleting_running_gang_kicks_backoff(self):
        """Chips freed by DELETING a Running gang (not just a terminal
        phase) must not wait out a queued gang's backoff."""
        from kubeflow_tpu.control.scheduler.scheduler import GangScheduler

        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0"))
        cluster.create(new_tpu_node("n1"))
        cluster.create(gang_job("a", replicas=2))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        assert ob.cond_is_true(
            cluster.get(JT.API_VERSION, JT.KIND, "a", "default"),
            JT.COND_RUNNING)
        cluster.create(gang_job("b", replicas=2))  # equal priority: queues
        pump([jax_ctl, sched_ctl], fc, kubelet, rounds=3)
        rec = sched_ctl.reconciler
        assert isinstance(rec, GangScheduler)
        for _ in range(3):  # push b deep into backoff
            rec.queue.requeue("default", "b")
        assert rec.queue.get("default", "b").not_before > fc.t
        # delete the RUNNING gang a: its pods cascade-delete at phase
        # Running — capacity frees with no terminal phase ever seen
        cluster.delete(JT.API_VERSION, JT.KIND, "a", "default")
        for _ in range(4):  # drain WITHOUT advancing the clock
            sched_ctl.run_until_idle(advance_delayed=True)
            jax_ctl.run_until_idle(advance_delayed=True)
        b = bindings(cluster)
        assert {b["b-worker-0"], b["b-worker-1"]} == {"n0", "n1"}

    def test_strict_fifo_head_blocks_lower_priority(self):
        """Kueue-StrictFIFO semantics: a blocked high-priority gang
        holds the queue — a smaller low-priority gang that WOULD fit
        must not jump it (no starvation of big jobs)."""
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0", topology="2x2"))  # 4 chips
        # big: 2 slices x 2 workers x 2 chips = 8 chips (needs 2 hosts)
        cluster.create(gang_job("big", replicas=2, chips=2, topology="2x2",
                                slice_count=2, priority=5))
        # small: 2 workers x 2 chips = 4 chips (fits n0 alone)
        cluster.create(gang_job("small", replicas=2, chips=2,
                                topology="2x2", priority=0))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        assert all(n is None for n in bindings(cluster).values())

    def test_failed_bind_releases_whole_gang_and_no_pod_was_runnable(self):
        """All-or-nothing under a mid-bind failure: nodeName lands for
        every pod BEFORE any gate lifts, so a kubelet polling between
        patches never sees a runnable partial gang; after the failure
        everything is unbound and re-gated."""
        from kubeflow_tpu.control.scheduler.scheduler import GangScheduler

        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0"))
        cluster.create(new_tpu_node("n1"))
        cluster.create(gang_job("gang", replicas=2))
        jax_ctl.run_until_idle()

        runnable_seen = []
        orig_patch = cluster.patch
        calls = {"n": 0}

        def failing_patch(api, kind, name, patch, ns=None):
            if kind == "Pod" and "spec" in (patch or {}):
                calls["n"] += 1
                # the invariant: a kubelet polling between scheduler
                # patches must never find a runnable (ungated+bound)
                # pod while any of its gang-mates is still unbound —
                # that would be a startable partial gang
                pods = cluster.list("v1", "Pod", namespace="default")
                if any(not p["spec"].get("nodeName") for p in pods):
                    for p in pods:
                        if p["spec"].get("nodeName") and \
                                not p["spec"].get("schedulingGates"):
                            runnable_seen.append(ob.meta(p)["name"])
                if calls["n"] == 3:  # first gate-lift attempt
                    raise ob.Conflict("injected mid-bind failure")
            return orig_patch(api, kind, name, patch, ns)

        cluster.patch = failing_patch
        try:
            sched_ctl.run_until_idle(advance_delayed=True)
        finally:
            cluster.patch = orig_patch
        assert runnable_seen == []  # the invariant under test
        # the failed attempt was fully rolled back and (backoff kicked
        # by the release events) retried to a clean full admission
        pump([jax_ctl, sched_ctl], fc, kubelet)
        assert sorted(bindings(cluster).values()) == ["n0", "n1"]
        for p in cluster.list("v1", "Pod", namespace="default"):
            assert not p["spec"].get("schedulingGates")

    def test_head_blocking_is_per_namespace(self):
        """Multi-tenancy: an unplaceable gang at the head of namespace
        A's queue must not stop namespace B's gang from admitting."""
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0"))                  # 2x4 pool, 1 host
        cluster.create(new_tpu_node("nb", topology="2x2"))  # tenant B's pool
        # tenant A: needs two 2x4 hosts, only one exists -> blocked head
        cluster.create(gang_job("big-a", replicas=2, priority=10))
        # tenant B: fits its own pool
        cluster.create(gang_job("fit-b", replicas=1, topology="2x2",
                                namespace="tenant-b"))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        b = bindings(cluster, "tenant-b")
        assert b == {"fit-b-worker-0": "nb"}, b
        assert all(n is None for n in bindings(cluster).values())

    def test_topology_spelling_is_normalized_for_placement(self):
        """parse_topology tolerates '2X4'; the pod selector must carry
        the canonical spelling or it can never match a node label."""
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0", topology="2x4"))
        cluster.create(new_tpu_node("n1", topology="2x4"))
        cluster.create(gang_job("gang", replicas=2, topology="2X4"))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        assert sorted(bindings(cluster).values()) == ["n0", "n1"]

    def test_non_gang_jobs_ignore_the_scheduler(self):
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        job = JT.new_jaxjob("plain", replicas=1)   # no gang_schedule
        cluster.create(job)
        pump([jax_ctl, sched_ctl], fc)
        pod = cluster.get("v1", "Pod", worker_name("plain", 0), "default")
        assert "schedulerName" not in pod["spec"]
        assert "schedulingGates" not in pod["spec"]


class TestPriorityPreemption:
    def test_high_priority_gang_preempts_low(self):
        """End to end through the existing JAXJob gang-restart path:
        the evicted low-priority gang restarts (preemption budget, not
        the crash budget) and requeues behind the preemptor."""
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0"))
        cluster.create(new_tpu_node("n1"))
        cluster.create(gang_job("low", replicas=2, priority=0))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        job = cluster.get(JT.API_VERSION, JT.KIND, "low", "default")
        assert ob.cond_is_true(job, JT.COND_RUNNING)

        cluster.create(gang_job("high", replicas=2, priority=10))
        pump([jax_ctl, sched_ctl], fc, kubelet, rounds=14)

        high = cluster.get(JT.API_VERSION, JT.KIND, "high", "default")
        assert ob.cond_is_true(high, JT.COND_RUNNING)
        b = bindings(cluster)
        assert {b["high-worker-0"], b["high-worker-1"]} == {"n0", "n1"}
        # the low gang went through the preemption path, not a crash
        low = cluster.get(JT.API_VERSION, JT.KIND, "low", "default")
        assert low["status"].get("preemptions", 0) >= 1
        assert low["status"].get("restarts", 0) == 0
        assert not ob.cond_is_true(low, JT.COND_FAILED)
        # its recreated pods wait unbound in the queue (no capacity)
        assert b["low-worker-0"] is None and b["low-worker-1"] is None
        text = reg.render()
        assert ('scheduler_preemptions_total{namespace="default",'
                'tenant="default"} 1') in text

    def test_preempted_capacity_goes_to_the_preemptor_not_a_thief(self):
        """No priority inversion across namespaces: chips freed by an
        eviction must land on the high-priority preemptor, never on a
        lower-priority gang queued in another namespace — otherwise a
        priority-5 gang dies so a priority-1 gang can run, and the
        evictions cascade."""
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0"))
        cluster.create(new_tpu_node("n1"))
        cluster.create(gang_job("victim", replicas=2, priority=5))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        # "aaa" sorts before "bbb": the naive alphabetical walk would
        # visit the priority-1 thief right after the eviction
        cluster.create(gang_job("high", replicas=2, priority=10,
                                namespace="bbb"))
        cluster.create(gang_job("thief", replicas=2, priority=1,
                                namespace="aaa"))
        pump([jax_ctl, sched_ctl], fc, kubelet, rounds=14)
        high = cluster.get(JT.API_VERSION, JT.KIND, "high", "bbb")
        assert ob.cond_is_true(high, JT.COND_RUNNING)
        hb = bindings(cluster, "bbb")
        assert {hb["high-worker-0"], hb["high-worker-1"]} == {"n0", "n1"}
        assert all(n is None for n in bindings(cluster, "aaa").values())
        # exactly ONE eviction (the victim), never a cascade via thief
        text = reg.render()
        assert ('scheduler_preemptions_total{namespace="default",'
                'tenant="default"} 1') in text
        assert 'scheduler_preemptions_total{namespace="aaa"}' not in text
        thief = cluster.get(JT.API_VERSION, JT.KIND, "thief", "aaa")
        assert not ob.cond_is_true(thief, JT.COND_RUNNING)
        assert thief["status"].get("preemptions", 0) == 0

    def test_victims_in_other_pools_are_never_evicted(self):
        """A gang blocked on the v5e pool must not evict a lower-priority
        gang running on a different-topology pool — freeing those nodes
        gains it nothing."""
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("small-0", topology="2x2"))  # 2x2 pool
        cluster.create(gang_job("low", replicas=2, chips=2,
                                topology="2x2", priority=0))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        job = cluster.get(JT.API_VERSION, JT.KIND, "low", "default")
        assert ob.cond_is_true(job, JT.COND_RUNNING)
        # high wants the (empty) 2x4 pool — nothing to preempt there
        cluster.create(gang_job("high", replicas=2, priority=10))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        low = cluster.get(JT.API_VERSION, JT.KIND, "low", "default")
        assert low["status"].get("preemptions", 0) == 0
        assert ob.cond_is_true(low, JT.COND_RUNNING)
        assert "scheduler_preemptions_total" not in reg.render()

    def test_equal_priority_never_preempts(self):
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0"))
        cluster.create(new_tpu_node("n1"))
        cluster.create(gang_job("first", replicas=2, priority=3))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        cluster.create(gang_job("second", replicas=2, priority=3))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        b = bindings(cluster)
        assert {b["first-worker-0"], b["first-worker-1"]} == {"n0", "n1"}
        assert b["second-worker-0"] is None
        assert "scheduler_preemptions_total" not in reg.render()


class TestGangPodRendering:
    def test_gated_pods_carry_the_gang_contract(self):
        fc = FakeClock()
        cluster, jax_ctl, _sched, _k, _r = sched_world(fc)
        cluster.create(gang_job("gang", replicas=2, priority=4))
        jax_ctl.run_until_idle()
        pod = cluster.get("v1", "Pod", worker_name("gang", 0), "default")
        assert pod["spec"]["schedulerName"] == SCHEDULER_NAME
        assert pod["spec"]["schedulingGates"] == [{"name": GATE_GANG}]
        anns = ob.annotations_of(pod)
        assert anns[ANNOTATION_GANG_SIZE] == "2"
        assert anns[ANNOTATION_PRIORITY] == "4"

    def test_template_annotations_cannot_override_the_gang_contract(self):
        """The controller owns gang-size/priority: a stale template
        annotation must not shrink the gang (which would re-enable
        partial placement) or skew preemption ordering."""
        fc = FakeClock()
        cluster, jax_ctl, _sched, _k, _r = sched_world(fc)
        job = gang_job("gang", replicas=2, priority=7)
        job["spec"]["template"].setdefault("metadata", {})["annotations"] = {
            ANNOTATION_GANG_SIZE: "1", ANNOTATION_PRIORITY: "99"}
        cluster.create(job)
        jax_ctl.run_until_idle()
        pod = cluster.get("v1", "Pod", worker_name("gang", 0), "default")
        anns = ob.annotations_of(pod)
        assert anns[ANNOTATION_GANG_SIZE] == "2"
        assert anns[ANNOTATION_PRIORITY] == "7"

    def test_foreign_scheduler_name_passes_through_ungated(self):
        """Only the scheduler that will lift a gate may add one: a job
        naming some OTHER scheduler must not get our gate (nothing
        would ever lift it — the pods would hang Pending forever)."""
        fc = FakeClock()
        cluster, jax_ctl, _sched, _k, _r = sched_world(fc)
        job = JT.new_jaxjob("other", replicas=1)
        job["spec"]["schedulerName"] = "my-custom-scheduler"
        cluster.create(job)
        jax_ctl.run_until_idle()
        pod = cluster.get("v1", "Pod", worker_name("other", 0), "default")
        assert pod["spec"]["schedulerName"] == "my-custom-scheduler"
        assert "schedulingGates" not in pod["spec"]
        anns = ob.annotations_of(pod)
        assert ANNOTATION_GANG_SIZE not in anns

    def test_foreign_gate_defers_admission_until_lifted(self):
        """Kube gate semantics end to end: a pod with ANY foreign gate
        is unschedulable, so its gang must not reserve chips (or
        preempt anyone) — admission waits until the foreign controller
        lifts its gate, then binds and removes only OUR gate."""
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0"))
        cluster.create(new_tpu_node("n1"))
        job = gang_job("gang", replicas=2)
        # template names ONLY the foreign gate — the controller must
        # APPEND ours (a setdefault would silently drop it)
        job["spec"]["template"]["spec"]["schedulingGates"] = [
            {"name": "quota.example.com/hold"}]
        cluster.create(job)
        jax_ctl.run_until_idle()
        pod = cluster.get("v1", "Pod", worker_name("gang", 0), "default")
        assert {g["name"] for g in pod["spec"]["schedulingGates"]} == {
            "quota.example.com/hold", GATE_GANG}
        pump([jax_ctl, sched_ctl], fc, kubelet)
        for p in cluster.list("v1", "Pod", namespace="default"):
            assert p["spec"].get("nodeName") is None  # capacity untouched
        # the quota controller lifts its hold
        for p in cluster.list("v1", "Pod", namespace="default"):
            p["spec"]["schedulingGates"] = [
                g for g in p["spec"]["schedulingGates"]
                if g["name"] == GATE_GANG]
            cluster.update(p)
        pump([jax_ctl, sched_ctl], fc, kubelet)
        for p in cluster.list("v1", "Pod", namespace="default"):
            assert p["spec"]["nodeName"] in ("n0", "n1")
            assert not p["spec"].get("schedulingGates")
            assert (p.get("status") or {}).get("phase") == "Running"

    def test_priority_must_be_int(self):
        job = JT.new_jaxjob("j", replicas=1)
        job["spec"]["priority"] = "urgent"
        assert any("spec.priority" in e for e in JT.validate(job))


# -- kubelet binding contract ------------------------------------------------


class TestFakeKubeletBinding:
    def _pod(self, cluster, name="p0", gates=None, node=None):
        pod = ob.new_object("v1", "Pod", name, "default")
        pod["spec"] = {"containers": [{"name": "main"}]}
        if gates:
            pod["spec"]["schedulingGates"] = gates
        if node:
            pod["spec"]["nodeName"] = node
        return cluster.create(pod)

    def test_auto_bind_compat_binds_and_runs(self):
        cluster = FakeCluster()
        kubelet = FakeKubelet(cluster)           # compat default
        self._pod(cluster)
        assert kubelet.step() == 1
        pod = cluster.get("v1", "Pod", "p0", "default")
        assert pod["status"]["phase"] == "Running"
        assert pod["spec"]["nodeName"] == "fake-node"
        # the backing node exists and is Ready (slice-health checks
        # treat a missing node as unhealthy)
        node = cluster.get("v1", "Node", "fake-node")
        assert node["status"]["conditions"][0]["status"] == "True"

    def test_without_auto_bind_only_bound_pods_run(self):
        cluster = FakeCluster()
        cluster.create(new_tpu_node("n0"))
        kubelet = FakeKubelet(cluster, auto_bind=False)
        self._pod(cluster, "unbound")
        self._pod(cluster, "bound", node="n0")
        assert kubelet.step() == 1
        assert (cluster.get("v1", "Pod", "unbound", "default")
                .get("status") or {}).get("phase") is None
        assert cluster.get("v1", "Pod", "bound", "default")[
            "status"]["phase"] == "Running"

    def test_gated_pods_never_run_even_with_auto_bind(self):
        cluster = FakeCluster()
        kubelet = FakeKubelet(cluster)
        self._pod(cluster, gates=[{"name": GATE_GANG}])
        assert kubelet.step() == 0
        pod = cluster.get("v1", "Pod", "p0", "default")
        assert pod["spec"].get("nodeName") is None


class TestExecutorBindOnce:
    def _exec_pod(self, name="p0", node=None, gates=None):
        pod = ob.new_object("v1", "Pod", name, "default")
        pod["spec"] = {"containers": [
            {"name": "main", "command": [sys.executable, "-c", "pass"]}]}
        if node:
            pod["spec"]["nodeName"] = node
        if gates:
            pod["spec"]["schedulingGates"] = gates
        return pod

    def test_respects_scheduler_binding(self):
        """Bind-once: a pod the gang scheduler already placed keeps its
        node — the executor must not race it with its own node_name."""
        cluster = FakeCluster()
        ex = LocalPodExecutor(cluster, node_name="exec-node")
        cluster.create(self._exec_pod(node="tpu-node-7"))
        try:
            ex.run_until_settled(timeout=30)
        finally:
            ex.shutdown()
        pod = cluster.get("v1", "Pod", "p0", "default")
        assert pod["spec"]["nodeName"] == "tpu-node-7"
        assert pod["status"]["phase"] == "Succeeded"

    def test_self_binds_when_unbound(self):
        cluster = FakeCluster()
        ex = LocalPodExecutor(cluster, node_name="exec-node")
        cluster.create(self._exec_pod())
        try:
            ex.run_until_settled(timeout=30)
        finally:
            ex.shutdown()
        pod = cluster.get("v1", "Pod", "p0", "default")
        assert pod["spec"]["nodeName"] == "exec-node"

    def test_skips_gated_pods(self):
        cluster = FakeCluster()
        ex = LocalPodExecutor(cluster)
        cluster.create(self._exec_pod(gates=[{"name": GATE_GANG}]))
        try:
            ex.poll_once()
            assert ex.alive_count() == 0
        finally:
            ex.shutdown()
        pod = cluster.get("v1", "Pod", "p0", "default")
        assert (pod.get("status") or {}).get("phase") is None


# -- slice-aware admission ---------------------------------------------------


class TestSliceAwareAdmission:
    """Multi-slice gangs: each slice lands entirely inside ONE
    (accelerator, topology) pool, different slices may use different
    pools, and admission stays all-or-nothing ACROSS slices."""

    def _pool(self, cluster, prefix, topology, n):
        for i in range(n):
            cluster.create(new_tpu_node(f"{prefix}{i}", topology=topology))

    def test_multislice_gang_admits_across_two_pools(self):
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        self._pool(cluster, "a", "2x4", 2)   # pool A: 2 hosts x 4 chips
        self._pool(cluster, "b", "4x4", 2)   # pool B: 2 hosts x 4 chips
        cluster.create(gang_job("ms", replicas=2, chips=4, topology="2x4",
                                slice_count=2))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        b = bindings(cluster)
        assert all(b.values()), b
        # slice 0 (workers 0-1) in one pool, slice 1 (workers 2-3) in
        # the other — never a slice straddling pools
        slice0 = {b["ms-worker-0"], b["ms-worker-1"]}
        slice1 = {b["ms-worker-2"], b["ms-worker-3"]}
        assert slice0 == {"a0", "a1"} and slice1 == {"b0", "b1"}, b
        # gang-scheduled multislice pods carry NO topology pin — the
        # pool choice is admission's, not the template's
        for p in cluster.list("v1", "Pod", namespace="default"):
            sel = p["spec"].get("nodeSelector") or {}
            assert JT.NODESELECTOR_TOPOLOGY not in sel
            assert sel[JT.NODESELECTOR_ACCEL] == "tpu-v5-lite-podslice"
        assert 'scheduler_slice_admissions_total{namespace="default"} 1.0' \
            in reg.render()
        job = cluster.get(JT.API_VERSION, JT.KIND, "ms", "default")
        assert ob.cond_is_true(job, JT.COND_RUNNING)

    def test_slice_split_across_pools_never_binds(self):
        """Capacity for every WORKER exists, but slice 1 would have to
        straddle two pools — the gang must not bind at all (a split
        slice could never form its ICI mesh)."""
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        self._pool(cluster, "a", "2x4", 1)   # 4 chips: half a slice
        self._pool(cluster, "b", "4x4", 3)   # 12 chips: 1.5 slices
        cluster.create(gang_job("ms", replicas=2, chips=4, topology="2x4",
                                slice_count=2))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        b = bindings(cluster)
        assert len(b) == 4 and all(v is None for v in b.values()), b
        for p in cluster.list("v1", "Pod", namespace="default"):
            assert p["spec"]["schedulingGates"] == [{"name": GATE_GANG}]
        assert ('scheduler_queue_depth{namespace="default",'
                'tenant="default"} 1') in reg.render()

    def test_slice_aligned_partial_admission_and_grow_back(self):
        """Slice-elastic gang, room for one slice: exactly slice 0
        binds (whole slices only — never a sub-slice prefix), the world
        starts at one slice, and the second slice grows back into a
        DIFFERENT pool when capacity appears."""
        fc = FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        self._pool(cluster, "a", "2x4", 2)
        cluster.create(gang_job(
            "ms", replicas=2, chips=4, topology="2x4", slice_count=2,
            elastic_min=4, slice_policy=JT.SLICE_SHRINK, min_slices=1))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        b = bindings(cluster)
        bound = {k for k, v in b.items() if v}
        assert bound == {"ms-worker-0", "ms-worker-1"}, b
        st = (cluster.get(JT.API_VERSION, JT.KIND, "ms", "default")
              .get("status") or {})
        assert st["activeReplicas"] == 2
        assert st["activeSlices"] == 1
        assert st["world"]["members"] == ["ms-worker-0", "ms-worker-1"]
        assert st["world"]["slices"] == [0, 0]
        # grow-back: slice 1 admits into a different pool, whole-slice
        self._pool(cluster, "b", "4x4", 2)
        pump([jax_ctl, sched_ctl], fc, kubelet)
        st = (cluster.get(JT.API_VERSION, JT.KIND, "ms", "default")
              .get("status") or {})
        assert st["activeReplicas"] == 4
        assert st["activeSlices"] == 2
        assert st["world"]["slices"] == [0, 0, 1, 1]
        assert st.get("restarts", 0) == 0 and st.get("preemptions", 0) == 0
        b = bindings(cluster)
        assert {b["ms-worker-2"], b["ms-worker-3"]} == {"b0", "b1"}, b


class TestCordonFeasibility:
    """spec.unschedulable (kubectl cordon / the ISSUE 13 remediation
    engine's cordon-and-drain) must exclude a node from placement."""

    def test_cordoned_node_is_infeasible(self):
        node = new_tpu_node("n0", topology="2x4")
        pod = ob.new_object("v1", "Pod", "p", "default")
        pod["spec"] = {"containers": [{"name": "jax", "resources": {
            "limits": {JT.RESOURCE_TPU: 4}}}]}
        assert feasible(pod, node_view(node))
        node.setdefault("spec", {})["unschedulable"] = True
        v = node_view(node)
        assert v.unschedulable
        assert not feasible(pod, v)
