from kubeflow_tpu.control.mains import run_controller
from kubeflow_tpu.control.scheduler.scheduler import build_scheduler

# 10% requeue-backoff jitter in production: after a node comes back,
# same-shaped gangs must not retry admission in lockstep
run_controller("gang-scheduler",
               lambda client, args: build_scheduler(client, jitter=0.1))
