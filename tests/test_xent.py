"""Chunked LM-head cross-entropy (ops/xent.py) vs the full-logits oracle.

The op must be a pure memory optimization: identical loss, accuracy, and
gradients (hidden AND head kernel) to projecting full [B, L, V] logits
through optax's integer-label cross entropy. Tests run the chunked path
in f32 so equality is exact-tolerance, not bf16-noise-tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeflow_tpu.ops.xent import chunked_lm_xent

B, L, D, V = 2, 16, 8, 29  # V deliberately not a multiple of anything


def _inputs(seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    hidden = jax.random.normal(k1, (B, L, D), jnp.float32)
    kernel = jax.random.normal(k2, (D, V), jnp.float32) * 0.2
    labels = jax.random.randint(k3, (B, L), 0, V)
    return hidden, kernel, labels


def _oracle(hidden, kernel, labels):
    logits = jnp.einsum("bld,dv->blv", hidden, kernel)
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()
    acc = (logits.argmax(-1) == labels).mean()
    return loss, acc


@pytest.mark.parametrize("n_chunks", [1, 2, 4, 16])
def test_matches_full_logits(n_chunks):
    hidden, kernel, labels = _inputs()
    loss, acc = chunked_lm_xent(hidden, kernel, labels, n_chunks,
                                compute_dtype=jnp.float32)
    ref_loss, ref_acc = _oracle(hidden, kernel, labels)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-6)
    np.testing.assert_allclose(acc, ref_acc, rtol=1e-6)


def test_gradients_match_oracle():
    hidden, kernel, labels = _inputs(seed=3)

    def chunked(h, w):
        return chunked_lm_xent(h, w, labels, 4,
                               compute_dtype=jnp.float32)[0]

    def full(h, w):
        return _oracle(h, w, labels)[0]

    gh, gw = jax.grad(chunked, argnums=(0, 1))(hidden, kernel)
    rh, rw = jax.grad(full, argnums=(0, 1))(hidden, kernel)
    np.testing.assert_allclose(gh, rh, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(gw, rw, rtol=1e-5, atol=1e-7)


def test_rejects_indivisible_chunks():
    hidden, kernel, labels = _inputs()
    with pytest.raises(ValueError, match="not divisible"):
        chunked_lm_xent(hidden, kernel, labels, 3)


def test_trainer_chunked_loss_matches_classic():
    """End-to-end through the Trainer: same seed, same batch, the
    xent_chunks step must produce the same loss/accuracy metrics and the
    same updated params as the full-logits step (f32-model tolerance)."""
    from kubeflow_tpu.parallel.mesh import MeshSpec
    from kubeflow_tpu.runtime.data import shard_batch
    from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

    base = dict(
        model="transformer-test",
        model_kwargs={"dtype": jnp.float32},
        task="lm",
        global_batch=8,
        seq_len=32,
        vocab_size=256,
        mesh=MeshSpec(data=8),
        optimizer="adafactor",
        learning_rate=1e-3,
        total_steps=3,
        warmup_steps=1,
        log_every=10**9,
    )
    out = {}
    for name, chunks in [("classic", 0), ("chunked", 4)]:
        trainer = Trainer(TrainConfig.from_dict(dict(base, xent_chunks=chunks)))
        state = trainer.init_state()
        batch = shard_batch(
            next(trainer.data_iter()),
            next(iter(jax.tree.leaves(trainer.batch_shardings))))
        state, m = trainer.train_step(state, batch)
        # eval must follow the same chunked path (a config that only fits
        # chunked must not OOM at its first eval)
        ev = trainer.eval_step(state, batch)
        out[name] = (float(m["loss"]), float(m["accuracy"]), state.params,
                     float(ev["loss"]), float(ev["accuracy"]))
    np.testing.assert_allclose(out["chunked"][0], out["classic"][0],
                               rtol=1e-5)
    np.testing.assert_allclose(out["chunked"][1], out["classic"][1],
                               rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        out["chunked"][2], out["classic"][2])
    np.testing.assert_allclose(out["chunked"][3], out["classic"][3],
                               rtol=1e-5)
    np.testing.assert_allclose(out["chunked"][4], out["classic"][4],
                               rtol=1e-6)


def test_trainer_packed_batch_segment_ids_flow_to_attention():
    """A batch carrying segment_ids must change the loss vs the same
    batch without them (cross-document attention masked), for both the
    classic and chunked head paths — pinning the batch->model->kernel
    wiring end to end."""
    from kubeflow_tpu.parallel.mesh import MeshSpec
    from kubeflow_tpu.runtime.data import shard_batch
    from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

    base = dict(
        model="transformer-test",
        model_kwargs={"dtype": jnp.float32, "attention_impl": "flash"},
        task="lm",
        global_batch=8,
        seq_len=32,
        vocab_size=256,
        mesh=MeshSpec(data=8),
        optimizer="adafactor",
        learning_rate=1e-3,
        total_steps=2,
        warmup_steps=1,
        log_every=10**9,
    )
    seg = jnp.concatenate([jnp.zeros((8, 16), jnp.int32),
                           jnp.ones((8, 16), jnp.int32)], axis=1)
    for chunks in (0, 4):
        trainer = Trainer(TrainConfig.from_dict(dict(base, xent_chunks=chunks)))
        sharding = next(iter(jax.tree.leaves(trainer.batch_shardings)))
        batch = shard_batch(next(trainer.data_iter()), sharding)
        packed = dict(batch, segment_ids=shard_batch(
            {"segment_ids": seg}, sharding)["segment_ids"])
        # train_step donates its state: one fresh state per call
        _, m_plain = trainer.train_step(trainer.init_state(), batch)
        _, m_packed = trainer.train_step(trainer.init_state(), packed)
        assert float(m_plain["loss"]) != float(m_packed["loss"]), (
            f"chunks={chunks}: segment_ids had no effect on the loss")


def test_ignored_labels_match_masked_oracle():
    """Labels of -1 (packing pad / document boundary) must not
    contribute to loss, accuracy, or gradients."""
    hidden, kernel, labels = _inputs(seed=5)
    labels = labels.at[:, ::3].set(-1)

    def masked_oracle(h, w):
        logits = jnp.einsum("bld,dv->blv", h, w)
        valid = labels >= 0
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.maximum(labels, 0))
        return jnp.sum(ce * valid) / jnp.sum(valid)

    loss, acc = chunked_lm_xent(hidden, kernel, labels, 4,
                                compute_dtype=jnp.float32)
    np.testing.assert_allclose(loss, masked_oracle(hidden, kernel),
                               rtol=1e-6)
    gh, gw = jax.grad(
        lambda h, w: chunked_lm_xent(h, w, labels, 4,
                                     compute_dtype=jnp.float32)[0],
        argnums=(0, 1))(hidden, kernel)
    rh, rw = jax.grad(masked_oracle, argnums=(0, 1))(hidden, kernel)
    np.testing.assert_allclose(gh, rh, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(gw, rw, rtol=1e-5, atol=1e-7)
    # rows whose label is -1 must have zero hidden-gradient
    np.testing.assert_array_equal(np.asarray(gh[:, ::3]), 0.0)
