from kubeflow_tpu.control.jaxservice.controller import build_controller
from kubeflow_tpu.control.mains import run_controller

run_controller("jaxservice", lambda client, args: build_controller(client))
