"""The training loop: pjit-compiled steps over a named mesh.

This replaces the reference's entire distributed-training data plane. In
the reference, each step is: workers compute grads on GPU, push/pull every
variable to a parameter server over gRPC (launcher.py:74-80) or
ring-allreduce via MPI+NCCL (openmpi-controller). Here the step is ONE
compiled XLA program: forward, backward, gradient reduction (psum /
reduce-scatter over ICI), and optimizer update all fused by GSPMD — zero
host involvement per step.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from functools import partial
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_DCN,
    AXIS_FSDP,
    AXIS_PIPELINE,
    BATCH_AXES,
    MeshSpec,
    build_mesh,
    batch_sharding,
    mesh_summary,
)
from kubeflow_tpu.parallel.shardings import infer_shardings, unbox
from kubeflow_tpu.runtime import metrics as rt_metrics
from kubeflow_tpu.runtime.data import synthetic_images, synthetic_tokens, shard_batch

log = logging.getLogger("kubeflow_tpu.trainer")


@dataclasses.dataclass
class TrainConfig:
    """Declarative training config — the payload section of a JAXJob spec.

    Mirrors the knob surface of the reference's tf-cnn job generator
    (create_job_specs.py:101-121: model, batch_size, data_format,
    num_batches) plus the TPU-native axes the reference lacked.
    """

    model: str = "resnet50"
    model_kwargs: dict = dataclasses.field(default_factory=dict)
    task: str = "classification"  # classification | lm
    global_batch: int = 32        # reference default: --batch_size=32 per worker
    image_size: int = 224
    num_classes: int = 1000
    seq_len: int = 1024
    vocab_size: int = 32000
    mesh: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    optimizer: str = "sgdm"       # sgdm | adamw
    learning_rate: float = 0.1
    weight_decay: float = 1e-4
    warmup_steps: int = 100
    total_steps: int = 1000
    remat: bool = False
    # "full" recomputes everything; "dots" keeps matmul outputs and
    # recomputes only elementwise; "mlp" (LM only) saves everything
    # except the d_ff-wide MLP tensors — most of the memory win at the
    # smallest recompute tax. For task=lm these select the model's
    # per-block remat; elsewhere the whole forward is checkpointed.
    remat_policy: str = "full"
    pp_microbatches: int = 4        # pipeline microbatches when mesh.pipe > 1
    aux_loss_weight: float = 0.01   # weight on sowed aux losses (MoE balance)
    # LM only: compute the head + cross-entropy in this many sequence
    # chunks (ops/xent.py) so the [B, L, V] logits tensor never
    # materializes — frees GBs of activation memory at large batch.
    # 0/1 = classic full-logits loss.
    xent_chunks: int = 0
    # Split each step's batch into this many microbatches, lax.scan the
    # forward+backward over them and apply ONE averaged optimizer update:
    # activation memory scales with the microbatch while the optimizer
    # sees the full global batch. 0/1 = single-shot step.
    grad_accum_steps: int = 0
    seed: int = 0
    log_every: int = 20
    # orbax checkpoint/resume (SURVEY.md §5): async saves + resume-from-
    # latest on gang restart. checkpoint_every=0 => save only at the end.
    checkpoint_dir: str | None = None
    checkpoint_every: int = 0
    checkpoint_keep: int = 3
    resume: bool = True
    # Real data: glob of KFRecord token shards (runtime/records.py). When
    # unset, synthetic batches (the tf_cnn_benchmarks default) are used.
    data_path: str | None = None
    shuffle_buffer: int = 0
    # LM shards written by write_packed_token_shard: batches gain
    # segment_ids (flash masks cross-document attention) and -1 targets
    # at padding/boundaries (ignored by the loss).
    packed_data: bool = False
    # Periodic held-out evaluation (the reference's estimator
    # train_and_evaluate pattern): every eval_every train steps run
    # eval_steps batches from eval_data_path (same shard format as
    # data_path) and log the averaged metrics (+ perplexity for LM).
    # When eval_data_path is unset, eval falls back to the TRAINING
    # source reshuffled at a shifted seed — a smoke eval, not held-out;
    # point eval_data_path at real validation shards for generalization
    # numbers. 0 = no eval.
    eval_every: int = 0
    eval_steps: int = 8
    eval_data_path: str | None = None
    # Flash-attention kernel tiles, so a swept operating point is
    # reproducible from the config alone (0 = kernel default /
    # KFTPU_FLASH_BLOCK_Q/K env). Forwarded into the LM model's config —
    # explicit plumbing, no process-global state.
    flash_block_q: int = 0
    flash_block_k: int = 0
    # xprof trace window (runtime/profiler.py): capture steps
    # [profile_start_step, profile_start_step + profile_steps).
    profile_dir: str | None = None
    profile_start_step: int = 2
    profile_steps: int = 3

    @classmethod
    def from_dict(cls, d: dict) -> "TrainConfig":
        d = dict(d)
        if "mesh" in d and not isinstance(d["mesh"], MeshSpec):
            d["mesh"] = MeshSpec.from_dict(d["mesh"])
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown TrainConfig keys {sorted(unknown)}")
        return cls(**d)


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any            # {} for stateless models
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=max(cfg.total_steps, cfg.warmup_steps + 1),
    )
    if cfg.optimizer == "sgdm":
        return optax.chain(
            optax.add_decayed_weights(cfg.weight_decay),
            optax.sgd(sched, momentum=0.9, nesterov=True),
        )
    if cfg.optimizer == "adamw":
        return optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=cfg.weight_decay)
    if cfg.optimizer == "adafactor":
        # The TPU-native memory-light optimizer (T5 lineage): second moment
        # factored into row+col statistics, so optimizer state is ~0 bytes
        # per param instead of 8 — what lets llama-1b-class models train on
        # a single 16 GB v5e chip (BASELINE.md round-2 note).
        return optax.adafactor(
            learning_rate=sched,
            multiply_by_parameter_scale=True,
            weight_decay_rate=cfg.weight_decay or None,
        )
    raise ValueError(f"unknown optimizer {cfg.optimizer!r}")


def _batch_xy(cfg: TrainConfig, batch: dict):
    """Input/target selection per task. seq_classification = BERT-style
    fine-tuning: token sequences in, one label per sequence out."""
    if cfg.task == "classification":
        return batch["image"], batch["label"]
    if cfg.task == "seq_classification":
        return batch["tokens"], batch["label"]
    return batch["tokens"], batch["targets"]


def _masked_accuracy(pred: jax.Array, labels: jax.Array) -> jax.Array:
    """argmax hit-rate over valid (non-negative) labels only."""
    valid = labels >= 0
    return (jnp.sum((pred == labels) & valid)
            / jnp.maximum(jnp.sum(valid), 1))


def _xent_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Integer-label cross entropy in f32, shared by classification and LM
    (LM logits are [B, L, V], labels [B, L] — mean over all positions).
    Negative labels are ignored (packed-batch padding / document
    boundaries, records.token_batches segmented mode)."""
    valid = labels >= 0
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), jnp.maximum(labels, 0))
    return jnp.sum(ce * valid) / jnp.maximum(jnp.sum(valid), 1)


class Trainer:
    """Builds mesh + model + sharded step functions from a TrainConfig."""

    def __init__(self, cfg: TrainConfig, mesh=None):
        self.cfg = cfg
        if mesh is None:
            # default mesh construction rides the selected collectives
            # backend (parallel/backends.py): ONE placement code path,
            # parameterized by the mesh-axes→levels map. The default
            # (single) backend with the default map is build_mesh
            # byte-for-byte; loopback/tpu lay DCN-level axes over the
            # slice boundary.
            from kubeflow_tpu.parallel import backends as B

            mesh = B.get_backend().mesh(cfg.mesh)
        self.mesh = mesh
        log.info("trainer mesh: %s", mesh_summary(self.mesh))
        # LM models remat per-block inside the model (see _model_kwargs);
        # everything else gets whole-forward jax.checkpoint in _build.
        self._model_self_remat = cfg.remat and cfg.task == "lm"
        self.model = get_model(cfg.model, **self._model_kwargs())
        self.tx = make_optimizer(cfg)
        self._build()

    def _model_kwargs(self) -> dict:
        kw = dict(self.cfg.model_kwargs)
        # LM models (TransformerLM family) handle remat themselves with
        # per-block nn.remat: the backward pass then holds ONE block's
        # intermediates at a time, with only the b·s·d residual stream
        # saved per layer. Wrapping the whole forward in jax.checkpoint
        # (the non-LM fallback in _build) saves almost nothing — the
        # backward recompute still materializes every layer's activations
        # at once, which is why gpt-760m-class models OOMed under it.
        if self._model_self_remat:
            kw.setdefault("remat", True)
            kw.setdefault("remat_policy", self.cfg.remat_policy)
        if self.cfg.task == "lm":
            if self.cfg.flash_block_q:
                kw.setdefault("flash_block_q", self.cfg.flash_block_q)
            if self.cfg.flash_block_k:
                kw.setdefault("flash_block_k", self.cfg.flash_block_k)
            # same guard as num_classes below: synthetic targets draw
            # from cfg.vocab_size, and a model head with a different
            # registry default would see out-of-range labels -> NaN loss
            kw.setdefault("vocab_size", self.cfg.vocab_size)
        if self.cfg.task in ("classification", "seq_classification"):
            if kw.get("num_classes", self.cfg.num_classes) != self.cfg.num_classes:
                # the data generator draws labels from cfg.num_classes; a
                # diverging model head silently yields NaN loss
                raise ValueError(
                    f"model_kwargs.num_classes={kw['num_classes']} conflicts "
                    f"with num_classes={self.cfg.num_classes}; set the "
                    "top-level num_classes only")
            kw.setdefault("num_classes", self.cfg.num_classes)
        pipe = self.mesh.shape.get(AXIS_PIPELINE, 1)
        if pipe > 1:
            if self.cfg.task != "lm":
                raise ValueError("pipeline parallelism (mesh.pipe > 1) is only "
                                 "supported for transformer LM tasks")
            if self.cfg.global_batch % self.cfg.pp_microbatches:
                raise ValueError(
                    f"global_batch {self.cfg.global_batch} not divisible by "
                    f"pp_microbatches {self.cfg.pp_microbatches}"
                )
            kw.setdefault("pipeline_stages", pipe)
            kw.setdefault("pp_microbatches", self.cfg.pp_microbatches)
        return kw

    def _example_batch(self) -> dict:
        cfg = self.cfg
        if cfg.task == "classification":
            return {
                "image": jnp.zeros((cfg.global_batch, cfg.image_size, cfg.image_size, 3), jnp.float32),
                "label": jnp.zeros((cfg.global_batch,), jnp.int32),
            }
        if cfg.task == "seq_classification":
            return {
                "tokens": jnp.zeros((cfg.global_batch, cfg.seq_len), jnp.int32),
                "label": jnp.zeros((cfg.global_batch,), jnp.int32),
            }
        return {
            "tokens": jnp.zeros((cfg.global_batch, cfg.seq_len), jnp.int32),
            "targets": jnp.zeros((cfg.global_batch, cfg.seq_len), jnp.int32),
        }

    def data_iter(self, data_path: str | None = None,
                  seed: int | None = None) -> Iterator[dict]:
        cfg = self.cfg
        data_path = data_path if data_path is not None else cfg.data_path
        seed = seed if seed is not None else cfg.seed
        if data_path:
            import glob as _glob

            paths = sorted(_glob.glob(data_path))
            if not paths:
                raise FileNotFoundError(f"no shards match {data_path!r}")
            if cfg.task == "classification":
                from kubeflow_tpu.runtime.records import image_batches

                return image_batches(paths, cfg.global_batch, cfg.image_size,
                                     shuffle_buffer=cfg.shuffle_buffer,
                                     seed=seed, loop=True)
            from kubeflow_tpu.runtime.records import token_batches

            return token_batches(paths, cfg.global_batch, cfg.seq_len,
                                 shuffle_buffer=cfg.shuffle_buffer,
                                 seed=seed, loop=True,
                                 segmented=cfg.packed_data)
        if cfg.task == "classification":
            return synthetic_images(cfg.global_batch, cfg.image_size, cfg.num_classes, seed)
        if cfg.task == "seq_classification":
            from kubeflow_tpu.runtime.data import synthetic_token_classes

            return synthetic_token_classes(cfg.global_batch, cfg.seq_len,
                                           cfg.vocab_size, cfg.num_classes,
                                           seed)
        return synthetic_tokens(cfg.global_batch, cfg.seq_len, cfg.vocab_size, seed)

    def eval_data_iter(self) -> Iterator[dict]:
        """Held-out batches: eval_data_path shards when given, else the
        training source at a shifted seed (different shuffle/draw)."""
        cfg = self.cfg
        return self.data_iter(data_path=cfg.eval_data_path or cfg.data_path,
                              seed=cfg.seed + 1)

    def _device_iter(self, it: Iterator[dict]) -> Iterator[dict]:
        """Device-put each distinct host batch once. The synthetic
        iterators yield the *same* numpy arrays every step; without this
        cache every step re-uploads the full batch host->device inside the
        metered window (deflating MFU). Keyed by object identity so real
        pipelines that produce fresh arrays still upload each batch."""
        sharding = next(iter(jax.tree.leaves(self.batch_shardings)))
        last_key, last_val = None, None
        for b in it:
            key = tuple(id(a) for a in jax.tree.leaves(b))
            if key != last_key:
                last_val = shard_batch(b, sharding)
                last_key = key
            yield last_val

    # ---- build jitted fns ------------------------------------------------

    def _dp_size(self) -> int:
        """Ways the batch axis is sharded (dcn * data * fsdp * expert)."""
        n = 1
        for a in BATCH_AXES:
            n *= self.mesh.shape[a]
        return n

    def _init_fn(self, rng):
        batch = self._example_batch()
        x = batch["image"] if self.cfg.task == "classification" else batch["tokens"]
        # Init with one row per data-parallel group: parameter shapes don't
        # depend on batch, but the init forward must still satisfy the
        # batch-axis sharding (ring attention shard_maps over it).
        variables = self.model.init(rng, x[:self._dp_size()], train=True)
        return variables

    def _build(self) -> None:
        cfg, mesh = self.cfg, self.mesh
        rng = jax.random.PRNGKey(cfg.seed)

        abstract = jax.eval_shape(self._init_fn, rng)
        self.var_shardings = infer_shardings(abstract, mesh)
        self.n_params = sum(
            leaf.size for leaf in jax.tree.leaves(unbox(abstract)["params"])
        )
        # Strip Partitioned boxes from both the abstract tree and shardings
        # consumers; real arrays are unboxed after init.
        # infer_shardings maps each Partitioned box to a single NamedSharding
        # leaf, so the shardings tree lines up with the *unboxed* variables.
        self._init_jit = jax.jit(
            lambda r: unbox(self._init_fn(r)), out_shardings=self.var_shardings
        )
        self.batch_shardings = jax.tree.map(
            lambda _: batch_sharding(mesh), self._example_batch()
        )

        # Positional-only closure so jax.checkpoint sees pure pytree args
        # (it rejects string kwargs like mutable=[...]). seg is the
        # optional [B, L] sequence-packing ids (LM batches only) — the
        # flash kernel masks cross-document attention from them.
        # "diagnostics" carries per-step observability sows (MoE dispatch
        # fill/drop — ops/moe.py) that must NOT contribute to the loss.
        _MUTABLE = ["batch_stats", "losses", "diagnostics"]

        def forward(variables, x, seg=None):
            kw = {"segment_ids": seg} if seg is not None else {}
            return self.model.apply(
                variables, x, train=True, mutable=_MUTABLE, **kw
            )

        if cfg.remat and not self._model_self_remat:
            if cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            elif cfg.remat_policy == "full":
                policy = jax.checkpoint_policies.nothing_saveable
            else:
                # "mlp" (and anything else) is a per-block LM policy; a
                # silent fallback to full recompute here would look like a
                # mysterious step-time regression instead of a config error
                raise ValueError(
                    f"remat_policy {cfg.remat_policy!r} is not supported for "
                    f"task={cfg.task!r} (whole-forward remat takes dots|full)")
            forward = jax.checkpoint(forward, policy=policy)

        chunked_head = cfg.task == "lm" and cfg.xent_chunks > 1
        if chunked_head:
            from kubeflow_tpu.ops.xent import chunked_lm_xent

            # same operand dtype as LMHead's matmul (bf16 on the standard
            # configs; f32 models stay exact)
            head_dtype = getattr(
                getattr(self.model, "cfg", None), "dtype", jnp.bfloat16)

            def forward_hidden(variables, x, seg=None):
                kw = {"segment_ids": seg} if seg is not None else {}
                return self.model.apply(
                    variables, x, train=True, return_hidden=True,
                    mutable=_MUTABLE, **kw)

            def chunked_loss_acc(params, hidden, y):
                return chunked_lm_xent(
                    hidden, params["lm_head"]["kernel"], y, cfg.xent_chunks,
                    compute_dtype=head_dtype)

        def loss_fn(params, batch_stats, batch):
            variables = {"params": params, **({"batch_stats": batch_stats} if batch_stats else {})}
            x, y = _batch_xy(cfg, batch)
            # optional packed-sequence ids ride in the batch dict (LM only)
            seg = batch.get("segment_ids") if cfg.task == "lm" else None
            if chunked_head:
                # Head + loss chunked over sequence (ops/xent.py): the
                # [B, L, V] logits tensor never materializes; lm_head
                # kernel grads flow through the chunk scan directly.
                hidden, new_vars = forward_hidden(variables, x, seg)
                loss, acc = chunked_loss_acc(params, hidden, y)
            else:
                logits, new_vars = forward(variables, x, seg)
                loss = _xent_loss(logits, y)
                acc = _masked_accuracy(logits.argmax(-1), y)
            # auxiliary losses sowed by modules (e.g. MoE load balancing)
            aux_leaves = jax.tree.leaves(new_vars.get("losses", {}))
            if aux_leaves:
                loss = loss + cfg.aux_loss_weight * sum(a.mean() for a in aux_leaves)
            # valid-position count: the weight grad accumulation must use
            # so packed microbatches with uneven -1 masking still combine
            # into the exact full-batch token-weighted mean
            n_valid = jnp.sum(y >= 0)
            # mean each diagnostics sow into one scalar per name (the
            # sow name is the innermost dict key; sows across layers
            # average), e.g. moe_fill / moe_drop
            from jax.tree_util import tree_flatten_with_path

            sums: dict = {}
            for path, v in tree_flatten_with_path(
                    new_vars.get("diagnostics", {}))[0]:
                name = next((p.key for p in reversed(path)
                             if hasattr(p, "key")), None)
                if name is not None:
                    sums.setdefault(str(name), []).append(v)
            diag = {k: sum(v) / len(v) for k, v in sums.items() if v}
            return loss, (new_vars.get("batch_stats", {}), acc, n_valid, diag)

        accum = max(1, cfg.grad_accum_steps)
        if accum > 1:
            if cfg.global_batch % accum:
                raise ValueError(
                    f"global_batch {cfg.global_batch} not divisible by "
                    f"grad_accum_steps {accum}")
            dp = self._dp_size()
            if (cfg.global_batch // accum) % dp:
                raise ValueError(
                    f"microbatch {cfg.global_batch // accum} not divisible "
                    f"by the {dp}-way batch sharding (dcn*data*fsdp*expert)")
            if (mesh.shape.get(AXIS_PIPELINE, 1) > 1
                    and (cfg.global_batch // accum) % cfg.pp_microbatches):
                raise ValueError(
                    f"microbatch {cfg.global_batch // accum} not divisible "
                    f"by pp_microbatches {cfg.pp_microbatches} (each scanned "
                    "microbatch is re-split by the pipeline)")

        def _microbatches(batch):
            """[B, ...] -> [accum, B/accum, ...] with a STRIDED row split:
            row r lands in microbatch r % accum, so each microbatch draws
            evenly from every device's contiguous batch shard (a block
            split would put whole microbatches on a subset of the mesh).

            The split is device-local under the batch sharding: row
            j*accum+m of a contiguous dp shard maps to row j of the same
            shard in microbatch m. GSPMD cannot see that through
            reshape+swapaxes on its own — without an explicit constraint
            it replicates the stacked tensor and re-partitions it every
            scan iteration ("[SPMD] Involuntary full rematerialization"),
            a per-step full-batch broadcast on real dcn×fsdp jobs."""
            def split(a):
                a = a.reshape(
                    (a.shape[0] // accum, accum) + a.shape[1:]).swapaxes(0, 1)
                spec = P(None, BATCH_AXES, *([None] * (a.ndim - 2)))
                return jax.lax.with_sharding_constraint(
                    a, NamedSharding(mesh, spec))

            return jax.tree.map(split, batch)

        def _apply_update(state, grads, new_stats, loss, acc, diag=None):
            updates, new_opt = state.tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_state = state.replace(
                step=state.step + 1,
                params=new_params,
                batch_stats=new_stats,
                opt_state=new_opt,
            )
            return new_state, {"loss": loss, "accuracy": acc, **(diag or {})}

        def train_step(state: TrainState, batch):
            (loss, (new_stats, acc, _, diag)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(
                state.params, state.batch_stats, batch
            )
            return _apply_update(state, grads, new_stats, loss, acc, diag)

        def train_step_accum(state: TrainState, batch):
            # Per-microbatch losses are means over that microbatch's VALID
            # positions; packed batches (-1 targets) can distribute them
            # unevenly, so the combine weights each microbatch by its
            # valid count — making the cross-entropy term == one big
            # batch EXACTLY, not just for uniform masking. Auxiliary
            # losses (MoE balance) are token-weighted too — deliberate:
            # a microbatch whose router saw more real tokens exerts
            # proportionally more balancing pressure.
            def body(carry, microbatch):
                stats, g_sum, loss_sum, acc_sum, n_sum = carry
                # re-pin the batch sharding on the scanned slice: the scan
                # carries only the stacked tensor's sharding, and the
                # sliced view needs the same anchor or the whole forward
                # propagates from an unconstrained operand
                microbatch = jax.tree.map(
                    lambda a: jax.lax.with_sharding_constraint(
                        a, NamedSharding(
                            mesh, P(BATCH_AXES, *([None] * (a.ndim - 1))))),
                    microbatch)
                (loss, (new_stats, acc, n, diag)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(state.params, stats, microbatch)
                w = n.astype(jnp.float32)
                return (new_stats,
                        jax.tree.map(lambda a, g: a + g * w, g_sum, grads),
                        loss_sum + loss * w, acc_sum + acc * w,
                        n_sum + w), (diag, w)

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
            (new_stats, g_sum, loss_sum, acc_sum, n_sum), (diags, ws) = \
                jax.lax.scan(
                    body,
                    (state.batch_stats, zeros, jnp.float32(0.0),
                     jnp.float32(0.0), jnp.float32(0.0)),
                    _microbatches(batch))
            n = jnp.maximum(n_sum, 1.0)
            grads = jax.tree.map(
                lambda g, p: (g / n).astype(p.dtype), g_sum, state.params)
            # diagnostics combine token-weighted, matching loss/acc: with
            # packed batches the microbatch valid-token counts differ, and
            # an unweighted mean of moe_fill/moe_drop would drift from the
            # single-step definition (ADVICE r4).
            diag = jax.tree.map(lambda a: (a * ws).sum() / n, diags)
            return _apply_update(state, grads, new_stats,
                                 loss_sum / n, acc_sum / n, diag)

        self._train_step = jax.jit(
            train_step_accum if accum > 1 else train_step, donate_argnums=(0,))

        def eval_step(state: TrainState, batch):
            variables = {"params": state.params,
                         **({"batch_stats": state.batch_stats} if state.batch_stats else {})}
            x, y = _batch_xy(cfg, batch)
            seg = batch.get("segment_ids") if cfg.task == "lm" else None
            kw = {"segment_ids": seg} if seg is not None else {}
            if chunked_head:
                # a config that only FITS because training chunks the head
                # must not OOM on its first eval
                hidden = self.model.apply(variables, x, train=False,
                                          return_hidden=True, **kw)
                loss, acc = chunked_loss_acc(state.params, hidden, y)
                return {"loss": loss, "accuracy": acc}
            logits = self.model.apply(variables, x, train=False, **kw)
            return {"loss": _xent_loss(logits, y),
                    "accuracy": _masked_accuracy(logits.argmax(-1), y)}

        self._eval_step = jax.jit(eval_step)

    # ---- public API ------------------------------------------------------

    def init_state(self) -> TrainState:
        rng = jax.random.PRNGKey(self.cfg.seed)
        with self.mesh:
            variables = self._init_jit(rng)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        opt_state = jax.jit(
            self.tx.init,
        )(params)
        log.info("model %s: %.2fM params", self.cfg.model, self.n_params / 1e6)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
            tx=self.tx,
        )

    def train_step(self, state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        batch = shard_batch(batch, next(iter(jax.tree.leaves(self.batch_shardings))))
        with self.mesh:
            return self._train_step(state, batch)

    def eval_step(self, state: TrainState, batch: dict) -> dict:
        batch = shard_batch(batch, next(iter(jax.tree.leaves(self.batch_shardings))))
        with self.mesh:
            return self._eval_step(state, batch)

    def flops_per_step(self) -> float:
        """Analytic train-step FLOPs for the MFU meter.

        Convention: multiply and add count separately (2*MACs), matching
        peak_flops' spec-sheet convention — feeding MAC counts (the
        fvcore/"4.1 GFLOPs resnet50" number) into a 2*MAC peak silently
        halves MFU. Train = 3x fwd (dgrad + wgrad each ~ fwd).
        """
        cfg = self.cfg
        if cfg.model.startswith("resnet"):
            from kubeflow_tpu.models.resnet import fwd_flops

            per_image = fwd_flops(
                cfg.model, image_size=cfg.image_size,
                num_classes=cfg.num_classes,
                num_filters=cfg.model_kwargs.get("num_filters", 64),
                stem=cfg.model_kwargs.get("stem", "conv7"))
            return 3.0 * per_image * cfg.global_batch
        if hasattr(self.model, "fwd_flops_per_image"):
            return 3.0 * self.model.fwd_flops_per_image() * cfg.global_batch
        if hasattr(self.model, "flops_per_token"):
            per_token = self.model.flops_per_token(seq_len=cfg.seq_len)
            return per_token * cfg.global_batch * cfg.seq_len
        # fallback: dense 6*N per token
        return 6.0 * self.n_params * cfg.global_batch * cfg.seq_len

    @staticmethod
    def _gang_agreed_stop(local_stop: Callable[[], bool]) -> Callable[[], bool]:
        """Collective agreement on the stop flag. SIGTERM lands on gang
        workers at different instants, but orbax saves of mesh-sharded
        arrays are collective — every process must break at the SAME
        step. Each poll all-gathers the local flag across processes (a
        matched collective, since every worker polls once per step); any
        worker's notice stops the whole gang at that step."""
        from jax.experimental import multihost_utils

        import numpy as np

        def agreed() -> bool:
            flags = multihost_utils.process_allgather(
                np.asarray(bool(local_stop())))
            return bool(np.any(flags))

        return agreed

    def fit(self, steps: int | None = None, state: TrainState | None = None,
            callback: Callable[[int, dict], None] | None = None,
            stop: Callable[[], bool] | None = None) -> tuple[TrainState, dict]:
        """Run the training loop; returns final state + summary metrics.

        `steps` is the global step target: on a gang restart with
        cfg.checkpoint_dir set, training resumes from the latest orbax
        checkpoint and runs only the remaining steps.

        `stop` is polled once per step (e.g. runtime.preemption's
        SIGTERM notice): when it returns True the loop force-saves a
        checkpoint and returns early with summary["preempted"]=True, so
        a gang restart resumes from the interrupted step instead of the
        last periodic save.
        """
        cfg = self.cfg
        steps = steps or cfg.total_steps
        state = state or self.init_state()
        if stop is not None and jax.process_count() > 1:
            stop = self._gang_agreed_stop(stop)

        ckpt = None
        if cfg.checkpoint_dir:
            from kubeflow_tpu.runtime.checkpoint import Checkpointer

            from kubeflow_tpu.parallel import dist as D

            world = D.active_world()
            ckpt = Checkpointer(cfg.checkpoint_dir, keep=cfg.checkpoint_keep,
                                world_size=jax.process_count(),
                                num_slices=world.num_slices if world else 1)
            if cfg.resume:
                restored = ckpt.restore_latest(state)
                if restored is not None:
                    state = restored
                    log.info("resumed from checkpoint at step %d", int(state.step))
        start_step = int(state.step)
        if start_step >= steps:
            # Target already reached (resume landed at/after it): no-op run.
            # Same summary schema as the normal path; executed count is
            # always steps - start_step.
            if ckpt:
                ckpt.close()
            return state, {"steps": steps, "start_step": start_step,
                           "step_time_s": None,
                           "examples_per_sec": 0.0, "mfu": 0.0, "final": {}}

        from kubeflow_tpu.obs import trace as obs_trace

        data = None
        kind = next(iter(self.mesh.devices.flat)).device_kind
        # tracer=: each metered step emits a train.step span under the
        # ambient context — linked to the gang-admission span when the
        # launcher attached the pod's TRACEPARENT. Metering starts after
        # the compile step, hence the +1 global-step base.
        meter = rt_metrics.StepMeter(self.flops_per_step(), self.mesh.devices.size, kind,
                                     tracer=obs_trace.TRACER,
                                     step_base=start_step + 1)
        last = {}
        last_saved = -1
        first_dt = float("nan")
        import time as _time

        def maybe_save(gstep: int, st) -> None:
            nonlocal last_saved
            if ckpt and cfg.checkpoint_every and gstep % cfg.checkpoint_every == 0:
                if ckpt.save(gstep, st):
                    last_saved = gstep

        last_eval: dict = {}

        def maybe_eval(gstep: int, st) -> None:
            # train_and_evaluate parity: average eval_steps held-out
            # batches; perplexity for LM (exp of the masked mean NLL).
            # A FRESH iterator per eval scores the same leading window of
            # the eval set every time, so the metric is comparable across
            # steps (a persistent iterator would score disjoint slices).
            # Building it here — inside fit's try — also means a bad
            # eval_data_path still closes the checkpointer on unwind.
            nonlocal last_eval
            if not (cfg.eval_every and gstep % cfg.eval_every == 0):
                return
            eval_iter = iter(self.eval_data_iter())
            import math as _m

            sums: dict = {}
            try:
                for _ in range(max(1, cfg.eval_steps)):
                    m = self.eval_step(st, next(eval_iter))
                    for k, v in m.items():
                        sums[k] = sums.get(k, 0.0) + float(v)
            finally:
                # shard-backed iterators hold a native reader thread
                if hasattr(eval_iter, "close"):
                    eval_iter.close()
            last_eval = {k: v / max(1, cfg.eval_steps) for k, v in sums.items()}
            if cfg.task == "lm":
                last_eval["perplexity"] = _m.exp(min(last_eval["loss"], 30.0))
            # Without eval_data_path this "eval" reads the TRAINING source
            # at a shifted seed — a smoke check, not held-out perplexity
            # (with shuffle_buffer=0 it scores the training shards'
            # leading window verbatim). Mark it so the gauges, the log
            # line, and the summary can't be mistaken for generalization.
            smoke = not cfg.eval_data_path
            last_eval["smoke"] = float(smoke)
            kind = "training-data smoke eval" if smoke else "held-out eval"
            for k, v in last_eval.items():
                rt_metrics.REGISTRY.gauge(f"jaxrt_eval_{k}", v,
                                          f"{kind} {k}")
            log.info("%s @ step %d: %s", kind, gstep,
                     " ".join(f"{k}={v:.4f}" for k, v in sorted(last_eval.items())))

        from kubeflow_tpu.runtime.profiler import TraceWindow

        trace = TraceWindow(cfg.profile_dir, cfg.profile_start_step,
                            cfg.profile_steps)

        ok = False
        preempted = False
        # Fit span: nest under the caller's ambient span when one is
        # open (the launcher's "worker" span), else fall back to the
        # pod's TRACEPARENT so a Trainer built outside the launcher
        # still joins the job trace, else start a new root.
        fit_span = obs_trace.TRACER.begin(
            "train.fit",
            parent=obs_trace.TRACER.current() or obs_trace.context_from_env(),
            model=cfg.model, global_batch=cfg.global_batch,
            start_step=start_step, steps=steps)
        try:
            # Data construction inside the try: its failure modes (no
            # shards match the glob, native loader required but missing)
            # must still close the checkpointer on unwind.
            if cfg.data_path:
                # Real data: background host->device prefetch overlaps the
                # upload of batch N+1 with compute of batch N.
                from kubeflow_tpu.runtime.data import Prefetcher

                data = Prefetcher(
                    self.data_iter(),
                    next(iter(jax.tree.leaves(self.batch_shardings))),
                )
            else:
                data = self._device_iter(self.data_iter())
            for i in range(steps - start_step):
                if stop is not None and stop():
                    # preemption notice: persist progress and leave — the
                    # gang restart resumes from exactly this step
                    preempted = True
                    # force=False: if this step already exists on disk
                    # (resume=N then preempted again before N+1), keep it —
                    # force's delete-then-save would open a window where
                    # the only durable checkpoint is gone
                    if ckpt and int(state.step) != last_saved:
                        if ckpt.save(int(state.step), state):
                            last_saved = int(state.step)
                    log.warning("preempted at step %d: checkpoint saved, "
                                "exiting early", int(state.step))
                    break
                trace.step(start_step + i)
                batch = next(data)
                if i == 0:
                    # Step 0 pays XLA compile; keep it out of the meter window
                    # so step_time/throughput/MFU reflect steady state.
                    t0 = _time.perf_counter()
                    with obs_trace.TRACER.span("train.step", step=start_step,
                                               compile=True):
                        state, m = self.train_step(state, batch)
                        jax.block_until_ready(m["loss"])
                    first_dt = _time.perf_counter() - t0
                    log.info("first step (incl. compile): %.2fs", first_dt)
                    last = {k: float(v) for k, v in m.items()}
                    maybe_save(start_step + 1, state)
                    maybe_eval(start_step + 1, state)
                    if callback:
                        callback(i, m)
                    continue
                meter.start()
                state, m = self.train_step(state, batch)
                jax.block_until_ready(m["loss"])
                meter.stop()
                if (i + 1) % cfg.log_every == 0 or i == steps - start_step - 1:
                    last = {k: float(v) for k, v in m.items()}
                    rt_metrics.REGISTRY.gauge("jaxrt_step_seconds", meter.step_time,
                                              "mean step wall time")
                    rt_metrics.REGISTRY.gauge("jaxrt_examples_per_sec",
                                              meter.throughput(cfg.global_batch),
                                              "training throughput")
                    rt_metrics.REGISTRY.gauge("jaxrt_mfu", meter.mfu, "model FLOPs utilization")
                    rt_metrics.REGISTRY.gauge("jaxrt_loss", last["loss"], "training loss")
                    log.info(
                        "step %d loss=%.4f acc=%.3f %.1f ex/s step=%.1fms mfu=%.1f%%",
                        i + 1, last["loss"], last.get("accuracy", float("nan")),
                        meter.throughput(cfg.global_batch), meter.step_time * 1e3,
                        meter.mfu * 100,
                    )
                maybe_save(start_step + i + 1, state)
                maybe_eval(start_step + i + 1, state)
                if callback:
                    callback(i, m)
            ok = True
        finally:
            meter.close()  # a step that raised still exports, as ERROR
            fit_span.attrs["preempted"] = preempted
            if not ok and fit_span.status == "OK":
                fit_span.status = "ERROR"
            obs_trace.TRACER.finish(fit_span)
            trace.stop()
            if hasattr(data, "close"):
                data.close()  # stop the prefetch thread
            if ckpt:
                # Final save only on a completed (not preempted) run: the
                # stop branch already persisted the preempted step, and a
                # force=True save here would reopen the delete-then-save
                # window on the checkpoint it resumed from. Always close so
                # queued async saves finish durably even when unwinding on
                # an exception.
                if ok and not preempted and int(state.step) != last_saved:
                    ckpt.save(int(state.step), state, force=True)
                ckpt.close()
        import math as _math

        if meter.steps == 0 and _math.isfinite(first_dt):
            # single-step run: only the compile step exists to report
            meter._times.append(first_dt)

        def _finite(x: float):
            # summary is json.dumps'ed by the launcher and parsed by
            # controllers; bare NaN is not valid JSON, so a run preempted
            # before any step completed reports null instead
            return x if _math.isfinite(x) else None

        summary = {
            "steps": steps,
            "start_step": start_step,
            "step_time_s": _finite(meter.step_time),
            "examples_per_sec": _finite(meter.throughput(cfg.global_batch)),
            "mfu": _finite(meter.mfu),
            "final": {k: _finite(v) for k, v in last.items()},
        }
        if preempted:
            summary["preempted"] = True
        if last_eval:
            summary["eval"] = {k: _finite(v) for k, v in last_eval.items()}
        return state, summary
