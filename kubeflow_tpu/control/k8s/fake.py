"""FakeCluster — an in-memory Kubernetes apiserver.

The reference has *no* hermetic backend: its controllers are tested with
either injected fakes (bootstrap/cmd/bootstrap/app/kfctlServer.go:66-67)
or kubebuilder envtest binaries, and all distributed behavior runs on
real per-CI GKE clusters (SURVEY.md §4). This class is the deliberate
improvement: a single in-memory store with enough apiserver semantics —
resource versions + optimistic concurrency, label/field selectors,
finalizers + deletionTimestamp, ownerReference cascade GC, and watch
streams — that every controller in kubeflow_tpu.control is testable in
milliseconds, and the same Client interface retargets a live cluster via
``rest.RestClient``.
"""

from __future__ import annotations

import collections
import contextlib
import copy
import queue
import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

from kubeflow_tpu.control.k8s import objects as ob


# Identity/system fields excluded from server-side-apply ownership:
# shared by construction, never conflict, never removed.
_SSA_IDENTITY = {("apiVersion",), ("kind",), ("metadata", "name"),
                 ("metadata", "namespace")}


def _ssa_leaf_paths(obj: dict, prefix: tuple = ()) -> set[tuple]:
    """Leaf field paths of an apply intent (scalars, lists and empty
    dicts are leaves; non-empty dicts recurse), minus identity fields."""
    out: set[tuple] = set()
    for k, v in obj.items():
        p = prefix + (k,)
        if isinstance(v, dict) and v:
            out |= _ssa_leaf_paths(v, p)
        elif p not in _SSA_IDENTITY:
            out.add(p)
    return out


def _ssa_overlaps(p: tuple, q: tuple) -> bool:
    """True when one path is the other (or an ancestor of it) — i.e.
    writing p restructures the field at q or vice versa."""
    n = min(len(p), len(q))
    return p[:n] == q[:n]


def _ssa_get(obj: dict, path: tuple) -> tuple[Any, bool]:
    cur = obj
    for k in path:
        if not isinstance(cur, dict) or k not in cur:
            return None, False
        cur = cur[k]
    return cur, True


def _ssa_set(obj: dict, path: tuple, value: Any) -> None:
    cur = obj
    for k in path[:-1]:
        nxt = cur.get(k)
        if not isinstance(nxt, dict):
            nxt = cur[k] = {}
        cur = nxt
    cur[path[-1]] = value


def _ssa_delete(obj: dict, path: tuple) -> None:
    """Delete a leaf and prune now-empty parent dicts."""
    parents = []
    cur = obj
    for k in path[:-1]:
        if not isinstance(cur, dict) or k not in cur:
            return
        parents.append((cur, k))
        cur = cur[k]
    if isinstance(cur, dict):
        cur.pop(path[-1], None)
    for parent, k in reversed(parents):
        if parent[k] == {}:
            del parent[k]


def _ssa_managed_fields(owners: dict[tuple, set]) -> list[dict]:
    by_mgr: dict[str, list] = {}
    for path, mgrs in owners.items():
        for mg in mgrs:
            by_mgr.setdefault(mg, []).append(list(path))
    return [{"manager": mg, "operation": "Apply", "fields": sorted(fs)}
            for mg, fs in sorted(by_mgr.items())]


@dataclass(frozen=True)
class Key:
    api_version: str
    kind: str
    namespace: str  # "" for cluster-scoped
    name: str


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: dict


@dataclass
class _Watch:
    api_version: str
    kind: str
    namespace: str | None
    q: "queue.Queue[WatchEvent]" = field(default_factory=queue.Queue)
    closed: bool = False


class FakeCluster:
    """In-memory apiserver + Client.

    The Client surface (create/get/list/update/update_status/patch/delete/
    watch/events) is shared with rest.RestClient, so controllers are
    written once against either backend.
    """

    def __init__(self, history_limit: int = 1024):
        self._lock = threading.RLock()
        self._store: dict[Key, dict] = {}
        # Secondary index: (apiVersion, kind) -> namespace -> name -> obj,
        # so list() scans only the matching kind/namespace bucket instead
        # of the whole store (ISSUE 7: a 5k-node fleet's Pod list must
        # not pay for its ConfigMaps). Every store mutation goes through
        # _store_put/_store_pop to keep the two views in lockstep.
        self._kinds: dict[tuple[str, str], dict[str, dict[str, dict]]] = {}
        # Op-count stats (read via .stats/reset_stats): the scale
        # benchmark and the tier-1 op-budget smoke assert list-scan work
        # in objects, which is deterministic where wall time is not.
        self.stats: dict[str, int] = collections.defaultdict(int)
        self._recorder = None  # lazy EventRecorder (obs/events.py)
        self._rv = 0
        self._watches: list[_Watch] = []
        # Mutating-webhook style interceptors: fn(verb, obj) -> obj.
        # Lets tests wire the PodDefault webhook in-process exactly where
        # the real admission chain sits (pod CREATE).
        self._admission: list[Callable[[str, dict], dict]] = []
        # Bounded change history for watch resume-from-resourceVersion
        # (etcd's watch cache). When a requested RV falls below the
        # retained window the watch gets 410 Gone and the client relists —
        # exactly the real apiserver contract controllers must survive.
        self._history: collections.deque[tuple[int, WatchEvent]] = \
            collections.deque(maxlen=history_limit)
        self._truncated_below = 0  # RVs <= this may be missing from history
        # Snapshots backing list continue tokens: a paginated list reads a
        # consistent snapshot even under concurrent writes (etcd MVCC).
        self._continues: collections.OrderedDict[
            str, tuple[list[dict], str]] = \
            collections.OrderedDict()

    # -- internals ----------------------------------------------------------

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _key(self, obj: dict) -> Key:
        m = ob.meta(obj)
        return Key(obj["apiVersion"], obj["kind"], m.get("namespace") or "", m["name"])

    def _store_put(self, key: Key, obj: dict) -> None:
        self._store[key] = obj
        self._kinds.setdefault((key.api_version, key.kind), {}) \
            .setdefault(key.namespace, {})[key.name] = obj

    def _store_pop(self, key: Key) -> dict | None:
        found = self._store.pop(key, None)
        if found is not None:
            buckets = self._kinds.get((key.api_version, key.kind))
            if buckets is not None:
                ns = buckets.get(key.namespace)
                if ns is not None:
                    ns.pop(key.name, None)
                    if not ns:
                        del buckets[key.namespace]
        return found

    def reset_stats(self) -> None:
        with self._lock:
            self.stats = collections.defaultdict(int)

    @contextlib.contextmanager
    def stats_paused(self):
        """Suspend op counting for harness reads: a benchmark's own
        assertions and completion sweeps must not pollute the op
        budgets it is measuring."""
        with self._lock:
            saved, self.stats = self.stats, collections.defaultdict(int)
        try:
            yield
        finally:
            with self._lock:
                self.stats = saved

    def _notify(self, etype: str, obj: dict) -> None:
        ev = WatchEvent(etype, ob.deep_copy(obj))
        if len(self._history) == self._history.maxlen and self._history:
            self._truncated_below = self._history[0][0]
        self._history.append((self._rv, ev))
        for w in self._watches:
            if w.closed:
                continue
            if (w.api_version, w.kind) != (obj["apiVersion"], obj["kind"]):
                continue
            ns = ob.meta(obj).get("namespace") or ""
            if w.namespace is not None and w.namespace != ns:
                continue
            w.q.put(WatchEvent(etype, ob.deep_copy(obj)))

    @property
    def current_rv(self) -> str:
        """The cluster's latest resourceVersion (ListMeta.resourceVersion)."""
        with self._lock:
            return str(self._rv)

    # -- admission ----------------------------------------------------------

    def add_admission_hook(self, fn: Callable[[str, dict], dict]) -> None:
        self._admission.append(fn)

    # -- verbs --------------------------------------------------------------

    def create(self, obj: dict) -> dict:
        with self._lock:
            obj = ob.deep_copy(obj)
            for hook in self._admission:
                obj = hook("CREATE", obj)
            key = self._key(obj)
            if key in self._store:
                raise ob.Conflict(f"{key.kind} {key.namespace}/{key.name} already exists")
            m = ob.meta(obj)
            m.setdefault("uid", str(uuid.uuid4()))
            m["resourceVersion"] = self._next_rv()
            m.setdefault("creationTimestamp", ob.now_iso())
            m.setdefault("generation", 1)
            self._store_put(key, obj)
            self.stats["create"] += 1
            self._notify("ADDED", obj)
            self._gc_if_orphaned(key)
            return ob.deep_copy(obj)

    def _gc_if_orphaned(self, key: Key) -> None:
        """Reap a just-created child whose owner died between the
        reconciler's read and this create (the check-then-act window the
        race tier's happens-before tracer exposed): the kube garbage
        collector deletes dependents with dangling owner uids on its
        next sync, so without this the fake leaks orphans forever."""
        obj = self._store.get(key)
        if obj is None:
            return
        refs = ob.meta(obj).get("ownerReferences") or []
        if not refs:
            return
        live = {ob.meta(o).get("uid") for o in self._store.values()}
        keep = [r for r in refs if not r.get("uid") or r["uid"] in live]
        if len(keep) == len(refs):
            return
        # replace, never mutate in place: list_snapshot hands out store
        # references as frozen-at-their-rv snapshots (informer caches
        # alias them), so every rv bump must land on a FRESH dict
        obj = ob.deep_copy(obj)
        m = ob.meta(obj)
        if keep:
            # prune dangling refs only — with the rv bump + MODIFIED
            # every other mutation path performs, or a watcher's cache
            # could resurrect the dangling ref through update()
            m["ownerReferences"] = keep
            m["resourceVersion"] = self._next_rv()
            self._store_put(key, obj)
            self._notify("MODIFIED", obj)
        elif m.get("finalizers"):
            m.pop("ownerReferences", None)
            m["deletionTimestamp"] = m.get("deletionTimestamp") or ob.now_iso()
            m["resourceVersion"] = self._next_rv()
            self._store_put(key, obj)
            self._notify("MODIFIED", obj)
        else:
            self._delete_now(key)

    def get(self, api_version: str, kind: str, name: str, namespace: str | None = None) -> dict:
        with self._lock:
            key = Key(api_version, kind, namespace or "", name)
            found = self._store.get(key)
            if found is None:
                raise ob.NotFound(f"{kind} {namespace or ''}/{name} not found")
            self.stats["get"] += 1
            return ob.deep_copy(found)

    def list(
        self,
        api_version: str,
        kind: str,
        namespace: str | None = None,
        label_selector: dict | str | None = None,
        field_selector: dict[str, str] | None = None,
    ) -> list[dict]:
        with self._lock:
            out = [ob.deep_copy(o) for o in self._select(
                api_version, kind, namespace, label_selector, field_selector)]
            self.stats["list_copied"] += len(out)
            return out

    def list_snapshot(
        self,
        api_version: str,
        kind: str,
        namespace: str | None = None,
        label_selector: dict | str | None = None,
        field_selector: dict[str, str] | None = None,
    ) -> tuple[list[dict], str]:
        """``(items, resourceVersion)`` WITHOUT copying: the internal
        read-only fast path for informer caches (``control/cache.py``)
        whose initial sync would otherwise deep-copy the whole store
        only to index it. Items are the STORED objects — callers must
        treat them as immutable and write only through the verbs."""
        with self._lock:
            return (self._select(api_version, kind, namespace,
                                 label_selector, field_selector),
                    str(self._rv))

    def _select(
        self,
        api_version: str,
        kind: str,
        namespace: str | None,
        label_selector: dict | str | None,
        field_selector: dict[str, str] | None,
    ) -> list[dict]:
        """Matching stored objects (no copy), via the kind/namespace
        index: list cost is O(matching bucket), not O(store)."""
        if isinstance(label_selector, str):
            label_selector = ob.parse_label_selector(label_selector)
        buckets = self._kinds.get((api_version, kind)) or {}
        if namespace is not None:
            spaces = [buckets.get(namespace or "", {})]
        else:
            spaces = list(buckets.values())
        out = []
        self.stats["list_calls"] += 1
        for ns in spaces:
            self.stats["list_scanned"] += len(ns)
            for obj in ns.values():
                if not ob.match_labels(ob.labels_of(obj), label_selector):
                    continue
                if not ob.match_fields(obj, field_selector):
                    continue
                out.append(obj)
        out.sort(key=lambda o: (ob.meta(o).get("namespace") or "",
                                ob.meta(o)["name"]))
        return out

    def list_page(
        self,
        api_version: str,
        kind: str,
        namespace: str | None = None,
        label_selector: dict | str | None = None,
        field_selector: dict[str, str] | None = None,
        limit: int | None = None,
        continue_token: str | None = None,
    ) -> tuple[list[dict], str, str]:
        """Paginated list: (items, continue, resourceVersion).

        A continue token pins the ORIGINAL snapshot, so later pages are
        consistent with page one even under concurrent writes (the etcd
        MVCC property kube's limit/continue contract guarantees)."""
        with self._lock:
            if continue_token:
                entry = self._continues.pop(continue_token, None)
                if entry is None:
                    raise ob.Expired(
                        f"continue token {continue_token!r} expired")
                remaining, rv = entry
            else:
                remaining = self.list(api_version, kind, namespace,
                                      label_selector, field_selector)
                rv = str(self._rv)
            # every page reports the SNAPSHOT's rv, not the current one:
            # a watch resumed from a paginated list's rv must replay
            # events for objects created mid-pagination (they are absent
            # from the snapshot) — the real apiserver's contract
            if limit is None or len(remaining) <= limit:
                return remaining, "", rv
            page, rest = remaining[:limit], remaining[limit:]
            token = uuid.uuid4().hex
            self._continues[token] = (rest, rv)
            while len(self._continues) > 64:  # bound snapshot memory
                self._continues.popitem(last=False)
            return page, token, rv

    def _update(self, obj: dict, subresource: str | None = None) -> dict:
        with self._lock:
            obj = ob.deep_copy(obj)
            key = self._key(obj)
            found = self._store.get(key)
            if found is None:
                raise ob.NotFound(f"{key.kind} {key.namespace}/{key.name} not found")
            m, fm = ob.meta(obj), ob.meta(found)
            if m.get("resourceVersion") and m["resourceVersion"] != fm["resourceVersion"]:
                raise ob.Conflict(
                    f"{key.kind} {key.name}: resourceVersion {m['resourceVersion']} "
                    f"!= {fm['resourceVersion']} (object was modified)"
                )
            if subresource == "status":
                # status updates cannot touch spec/metadata
                new = ob.deep_copy(found)
                new["status"] = obj.get("status", {})
            else:
                new = obj
                # generation bumps on spec change (apiserver semantics)
                if new.get("spec") != found.get("spec"):
                    ob.meta(new)["generation"] = fm.get("generation", 1) + 1
                else:
                    ob.meta(new)["generation"] = fm.get("generation", 1)
                new["metadata"] = {**fm, **ob.meta(new), "generation": ob.meta(new)["generation"]}
                # immutable fields
                new["metadata"]["uid"] = fm["uid"]
                new["metadata"]["creationTimestamp"] = fm["creationTimestamp"]
                if "deletionTimestamp" in fm:
                    new["metadata"]["deletionTimestamp"] = fm["deletionTimestamp"]
            ob.meta(new)["resourceVersion"] = self._next_rv()
            self._store_put(key, new)
            self.stats["update"] += 1
            self._notify("MODIFIED", new)
            self._maybe_finalize(key)
            return ob.deep_copy(self._store[key]) if key in self._store else ob.deep_copy(new)

    def update(self, obj: dict) -> dict:
        return self._update(obj)

    def update_status(self, obj: dict) -> dict:
        return self._update(obj, subresource="status")

    def patch(
        self,
        api_version: str,
        kind: str,
        name: str,
        patch: dict | list,
        namespace: str | None = None,
    ) -> dict:
        """dict → JSON merge patch; list → RFC6902 JSON patch."""
        with self._lock:
            self.stats["patch"] += 1
            cur = self.get(api_version, kind, name, namespace)
            # a patch carrying metadata.resourceVersion is an optimistic-
            # concurrency precondition: stale -> 409 (apiserver semantics)
            claimed = None
            if isinstance(patch, dict):
                claimed = (patch.get("metadata") or {}).get("resourceVersion")
            if claimed and claimed != ob.meta(cur)["resourceVersion"]:
                raise ob.Conflict(
                    f"{kind} {name}: patch resourceVersion {claimed} != "
                    f"{ob.meta(cur)['resourceVersion']} (object was modified)")
            if isinstance(patch, list):
                new = ob.json_patch(cur, patch)
            else:
                new = ob.merge_patch(cur, patch)
            ob.meta(new)["resourceVersion"] = ob.meta(cur)["resourceVersion"]
            return self._update(new)

    def apply(self, obj: dict, *, field_manager: str,
              force: bool = False) -> dict:
        """Server-side apply (simplified SSA — the apiserver's
        `application/apply-patch+yaml` PATCH; reference controllers'
        CreateOrUpdate flows assume a live apiserver provides this).

        `obj` is the manager's full declarative intent. Semantics kept
        from the real thing:
          - per-field ownership tracked in metadata.managedFields
            (one entry per manager, `fields` = list of leaf paths);
          - changing a field owned by another manager is a 409 Conflict
            naming the owner, unless force=true transfers ownership;
          - applying the same value as another manager shares ownership;
          - a field this manager owned but no longer applies is REMOVED
            (unless co-owned) — the declarative-deletion contract that
            merge-patch cannot express.
        Simplifications (documented, tested): leaf granularity is
        scalars/lists/empty-dicts (lists replace atomically — no
        strategic-merge list keys), and only Apply operations take
        ownership (plain updates don't steal fields).
        """
        if not field_manager:
            raise ob.Invalid("fieldManager is required for server-side apply")
        with self._lock:
            intent = ob.deep_copy(obj)
            m = ob.meta(intent)
            for sys_field in ("managedFields", "resourceVersion", "uid",
                              "creationTimestamp", "generation"):
                m.pop(sys_field, None)
            key = self._key(intent)
            paths = _ssa_leaf_paths(intent)
            found = self._store.get(key)
            if found is None:
                m["managedFields"] = _ssa_managed_fields(
                    {p: {field_manager} for p in paths})
                return self.create(intent)

            owners: dict[tuple, set] = {}
            for entry in ob.meta(found).get("managedFields") or []:
                for ps in entry.get("fields", []):
                    owners.setdefault(tuple(ps), set()).add(entry["manager"])
            conflicts = []
            for p in sorted(paths):
                # ownership guards the whole subtree: an intent path that
                # is a strict ancestor or descendant of another manager's
                # leaf (e.g. applying spec.resources.cpu under an owned
                # spec.resources scalar) restructures that field just as
                # surely as rewriting the exact path
                for q, mgrs in list(owners.items()):
                    others = mgrs - {field_manager}
                    if not others or not _ssa_overlaps(p, q):
                        continue
                    if p == q:
                        cur_val, has = _ssa_get(found, p)
                        new_val, _ = _ssa_get(intent, p)
                        if has and cur_val == new_val:
                            continue  # same value: share ownership
                        if new_val == {} and isinstance(cur_val, dict):
                            # re-asserting a map that now has entries
                            # composes, exactly like the ancestor case
                            continue
                    elif len(p) < len(q):
                        iv, _ = _ssa_get(intent, p)
                        if iv == {}:
                            # asserting an empty map composes with deeper
                            # owners (entries are preserved, not cleared)
                            continue
                    else:
                        cv, has = _ssa_get(found, q)
                        if has and isinstance(cv, dict):
                            # q's owner asserted a map; a deeper write
                            # adds/updates an entry, it does not
                            # restructure their field
                            continue
                    if force:
                        owners[q] -= others  # ownership transfers
                        if not owners[q]:
                            del owners[q]
                    else:
                        conflicts.append((p, q, sorted(others)))
            if conflicts:
                raise ob.Conflict(
                    f"{key.kind} {key.name}: server-side apply conflicts "
                    f"for manager {field_manager!r}: " + "; ".join(
                        (f"{'.'.join(p)}" if p == q
                         else f"{'.'.join(p)} (under {'.'.join(q)})")
                        + f" owned by {', '.join(o)}"
                        for p, q, o in conflicts))

            new = ob.deep_copy(found)
            prev = {p for p, mgrs in owners.items() if field_manager in mgrs}
            for p in prev - paths:
                # only q AT or BELOW p blocks deletion: a strict-ancestor
                # empty-map assert owns the map's existence, not this
                # leaf — counting it would orphan the field forever
                others_hold = any(
                    q[:len(p)] == p and (mgrs - {field_manager})
                    for q, mgrs in owners.items())
                if others_hold:
                    # co- or sub-owned (e.g. this manager owned the map,
                    # another owns an entry under it): relinquish only
                    owners[p].discard(field_manager)
                    if not owners[p]:
                        del owners[p]
                    continue
                _ssa_delete(new, p)
                owners.pop(p, None)
            for p in sorted(paths):
                val, _ = _ssa_get(intent, p)
                if val == {}:
                    cur, has = _ssa_get(new, p)
                    if has and isinstance(cur, dict):
                        # owning an empty map asserts its existence, it
                        # does not clear entries other managers put there
                        owners.setdefault(p, set()).add(field_manager)
                        continue
                _ssa_set(new, p, ob.deep_copy(val))
                owners.setdefault(p, set()).add(field_manager)
            ob.meta(new)["managedFields"] = _ssa_managed_fields(owners)
            ob.meta(new)["resourceVersion"] = \
                ob.meta(found)["resourceVersion"]
            return self._update(new)

    def delete(
        self,
        api_version: str,
        kind: str,
        name: str,
        namespace: str | None = None,
    ) -> None:
        with self._lock:
            key = Key(api_version, kind, namespace or "", name)
            found = self._store.get(key)
            if found is None:
                raise ob.NotFound(f"{kind} {namespace or ''}/{name} not found")
            if ob.meta(found).get("finalizers"):
                # graceful deletion: mark and wait for finalizers to clear
                # (the Profile finalizer path — profile_controller.go:48).
                # Replace-not-mutate: snapshot aliases stay frozen.
                if "deletionTimestamp" not in ob.meta(found):
                    found = ob.deep_copy(found)
                    m = ob.meta(found)
                    m["deletionTimestamp"] = ob.now_iso()
                    m["resourceVersion"] = self._next_rv()
                    self._store_put(key, found)
                    self._notify("MODIFIED", found)
                return
            self._delete_now(key)

    def _delete_now(self, key: Key) -> None:
        found = self._store_pop(key)
        if found is None:
            return
        self.stats["delete"] += 1
        # the DELETED event carries a fresh RV (apiserver semantics) — and
        # watch resume replays strictly-greater RVs, so reusing the prior
        # event's RV would silently drop deletions from resumed streams
        ob.meta(found)["resourceVersion"] = self._next_rv()
        self._notify("DELETED", found)
        self._gc_orphans(found)

    def _maybe_finalize(self, key: Key) -> None:
        """If an object marked for deletion has no finalizers left, reap it."""
        found = self._store.get(key)
        if found is None:
            return
        m = ob.meta(found)
        if "deletionTimestamp" in m and not m.get("finalizers"):
            self._delete_now(key)

    def _gc_orphans(self, deleted: dict) -> None:
        """OwnerReference cascade: children of a deleted controller-owner
        are deleted too (kube-controller-manager garbage collector; this is
        what lets JAXJob/Notebook deletion tear down pods/services)."""
        uid = ob.meta(deleted).get("uid")
        if not uid:
            return
        victims = [
            k
            for k, o in self._store.items()
            if any(r.get("uid") == uid for r in ob.meta(o).get("ownerReferences") or [])
        ]
        for k in victims:
            obj = self._store.get(k)
            if obj is None:
                continue
            refs = [r for r in ob.meta(obj).get("ownerReferences") or []
                    if r.get("uid") != uid]
            # replace-not-mutate (see _gc_if_orphaned): snapshot aliases
            # must stay frozen at the rv they were handed out under
            obj = ob.deep_copy(obj)
            m = ob.meta(obj)
            if refs:
                m["ownerReferences"] = refs
                self._store_put(k, obj)
                continue
            if m.get("finalizers"):
                m.pop("ownerReferences", None)
                m["deletionTimestamp"] = m.get("deletionTimestamp") or ob.now_iso()
                m["resourceVersion"] = self._next_rv()
                self._store_put(k, obj)
                self._notify("MODIFIED", obj)
            else:
                self._delete_now(k)

    # -- watch --------------------------------------------------------------

    def watch(
        self, api_version: str, kind: str, namespace: str | None = None,
        since_rv: str | None = None,
    ) -> "FakeWatchStream":
        """Subscribe to changes. With ``since_rv``, events AFTER that
        resourceVersion are replayed first (watch-cache resume); an RV
        older than the retained history raises 410 Expired and the
        client must relist."""
        with self._lock:
            w = _Watch(api_version, kind, namespace)
            if since_rv:
                rv = int(since_rv)
                if rv < self._truncated_below:
                    raise ob.Expired(
                        f"resourceVersion {since_rv} is too old "
                        f"(retained history starts at {self._truncated_below})")
                for ev_rv, ev in self._history:
                    if ev_rv <= rv:
                        continue
                    o = ev.object
                    if (o["apiVersion"], o["kind"]) != (api_version, kind):
                        continue
                    ns = ob.meta(o).get("namespace") or ""
                    if namespace is not None and namespace != ns:
                        continue
                    w.q.put(WatchEvent(ev.type, ob.deep_copy(o)))
            self._watches.append(w)
            return FakeWatchStream(self, w)

    # -- events (corev1 Events; consumed by the notebook controller's
    #    event-forwarding watch, notebook_controller.go:565-613, and JWA) --

    def record_event(
        self,
        involved: dict,
        reason: str,
        message: str,
        etype: str = "Normal",
        component: str = "kubeflow-tpu",
    ) -> dict:
        """Record through the shared EventRecorder (obs/events.py): real
        Event objects with count-dedup — a controller re-recording the
        same decision bumps count instead of flooding the store."""
        with self._lock:
            if self._recorder is None:
                from kubeflow_tpu.obs.events import EventRecorder

                self._recorder = EventRecorder(self)
            recorder = self._recorder
        return recorder.event(involved, reason, message, etype,
                              component=component)

    # -- convenience --------------------------------------------------------

    def dump(self) -> list[dict]:
        """Snapshot of every stored object (copies) — test/harness helper
        for whole-cluster assertions like apply idempotency."""
        with self._lock:
            return [copy.deepcopy(o) for o in self._store.values()]

    def get_or_none(self, api_version: str, kind: str, name: str, namespace: str | None = None):
        try:
            return self.get(api_version, kind, name, namespace)
        except ob.NotFound:
            return None

    def remove_finalizer(self, obj: dict, finalizer: str) -> dict:
        cur = self.get(
            obj["apiVersion"], obj["kind"], ob.meta(obj)["name"], ob.meta(obj).get("namespace")
        )
        fins = [f for f in ob.meta(cur).get("finalizers") or [] if f != finalizer]
        ob.meta(cur)["finalizers"] = fins
        return self.update(cur)


class FakeWatchStream:
    def __init__(self, cluster: FakeCluster, w: _Watch):
        self._cluster = cluster
        self._w = w

    def __iter__(self) -> Iterator[WatchEvent]:
        while not self._w.closed:
            try:
                yield self._w.q.get(timeout=0.1)
            except queue.Empty:
                continue

    def poll(self, timeout: float = 0.0) -> WatchEvent | None:
        try:
            return self._w.q.get(timeout=timeout) if timeout else self._w.q.get_nowait()
        except queue.Empty:
            return None

    def stop(self) -> None:
        self._w.closed = True
        with self._cluster._lock:
            if self._w in self._cluster._watches:
                self._cluster._watches.remove(self._w)
