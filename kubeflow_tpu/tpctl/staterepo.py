"""Deployment-state persistence to a git repo.

The reference's platform "checkpointing" is pushing the generated app/
config dir to a GCP Cloud Source Repo: clone to a random workdir
(ksServer.go:195-199), write, then add/commit/push with a pull-rebase
retry loop against concurrent writers (ksServer.go:239-267,
sourceRepos.go:188). Same capability here, provider-neutral: any git
remote (Cloud Source Repos, GitHub, a bare repo on NFS) via the git CLI.

What gets persisted per deployment: the TpuDef YAML and the rendered
manifests — enough to re-apply or audit any deployment from the repo
alone (the declarative-config-as-source-of-truth contract).
"""

from __future__ import annotations

import logging
import os
import shutil
import subprocess
import tempfile
import time

log = logging.getLogger("kubeflow_tpu.tpctl.staterepo")

PUSH_RETRIES = 10  # ksServer.go:256 backoff count
RETRY_SLEEP_S = 1.0


class GitError(RuntimeError):
    pass


def _git(args: list[str], cwd: str, check: bool = True,
         ident: tuple[str, str] | None = None) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    if ident:
        # author AND committer: rebase re-commits, so identity must be set
        # for every history-writing command, not just `commit`
        name, email = ident
        env.update(GIT_AUTHOR_NAME=name, GIT_AUTHOR_EMAIL=email,
                   GIT_COMMITTER_NAME=name, GIT_COMMITTER_EMAIL=email)
    p = subprocess.run(["git", *args], cwd=cwd, capture_output=True,
                       text=True, env=env)
    if check and p.returncode != 0:
        raise GitError(f"git {' '.join(args)}: {p.stderr.strip()}")
    return p


class StateRepo:
    """Clone-on-demand writer for a deployment-state git remote."""

    def __init__(self, remote: str, branch: str = "main",
                 author: str = "tpctl <tpctl@kubeflow-tpu>"):
        self.remote = remote
        self.branch = branch
        self.author = author
        name, sep, email = author.partition(" <")
        email = email.rstrip(">")
        if not sep or not name or not email:
            raise ValueError(
                f"author must be 'Name <email>' form, got {author!r} "
                "(git rejects empty idents at commit time)")
        self._ident = (name, email)
        self._dir: str | None = None

    # -- lifecycle ----------------------------------------------------------

    def clone(self) -> str:
        """Fresh clone into a private tempdir (CloneRepoToLocal analogue;
        random dir so concurrent server workers never collide)."""
        if self._dir:
            return self._dir
        d = tempfile.mkdtemp(prefix="tpctl-state-")
        p = _git(["clone", "--branch", self.branch, self.remote, d],
                 cwd="/", check=False)
        if p.returncode != 0:
            # empty remote or missing branch: init and set up the remote
            shutil.rmtree(d, ignore_errors=True)
            os.makedirs(d)
            _git(["init", "-b", self.branch], cwd=d)
            _git(["remote", "add", "origin", self.remote], cwd=d)
        self._dir = d
        return d

    def close(self) -> None:
        if self._dir:
            shutil.rmtree(self._dir, ignore_errors=True)
            self._dir = None

    def __enter__(self):
        self.clone()
        return self

    def __exit__(self, *exc):
        self.close()

    # -- persistence --------------------------------------------------------

    def save_deployment(self, name: str, tpudef_yaml: str,
                        manifests_yaml: str | None = None,
                        message: str | None = None,
                        sleep=time.sleep) -> str:
        """Write <name>/tpudef.yaml (+ manifests.yaml), commit, push with
        pull-rebase retry (SaveAppToRepo semantics). Returns commit sha."""
        d = self.clone()
        app_dir = os.path.join(d, name)
        os.makedirs(app_dir, exist_ok=True)
        with open(os.path.join(app_dir, "tpudef.yaml"), "w") as f:
            f.write(tpudef_yaml)
        if manifests_yaml is not None:
            with open(os.path.join(app_dir, "manifests.yaml"), "w") as f:
                f.write(manifests_yaml)
        _git(["add", "-A"], cwd=d)
        status = _git(["status", "--porcelain"], cwd=d).stdout.strip()
        if not status:
            log.info("staterepo: %s unchanged; nothing to commit", name)
            return _git(["rev-parse", "HEAD"], cwd=d).stdout.strip()
        _git(["commit", "-m", message or f"tpctl: update {name}"],
             cwd=d, ident=self._ident)

        last_err = ""
        for attempt in range(PUSH_RETRIES):
            p = _git(["push", "origin", self.branch], cwd=d, check=False)
            if p.returncode == 0:
                return _git(["rev-parse", "HEAD"], cwd=d).stdout.strip()
            last_err = p.stderr.strip()
            # concurrent writer won: rebase our commit on theirs and retry
            # (the ksServer.go:245-266 backoff loop)
            _git(["pull", "--rebase", "origin", self.branch], cwd=d,
                 check=False, ident=self._ident)
            sleep(RETRY_SLEEP_S)
        raise GitError(f"push failed after {PUSH_RETRIES} attempts: {last_err}")

    def delete_deployment(self, name: str, sleep=time.sleep) -> bool:
        """Remove <name>/ from the repo (commit+push with the same retry);
        returns False when the deployment wasn't present."""
        d = self.clone()
        _git(["pull", "--rebase", "origin", self.branch], cwd=d,
             check=False, ident=self._ident)
        app_dir = os.path.join(d, name)
        if not os.path.isdir(app_dir):
            return False
        shutil.rmtree(app_dir)
        _git(["add", "-A"], cwd=d)
        _git(["commit", "-m", f"tpctl: delete {name}"], cwd=d,
             ident=self._ident)
        last_err = ""
        for _ in range(PUSH_RETRIES):
            p = _git(["push", "origin", self.branch], cwd=d, check=False)
            if p.returncode == 0:
                return True
            last_err = p.stderr.strip()
            _git(["pull", "--rebase", "origin", self.branch], cwd=d,
                 check=False, ident=self._ident)
            sleep(RETRY_SLEEP_S)
        raise GitError(f"push failed after {PUSH_RETRIES} attempts: {last_err}")

    def load_deployment(self, name: str) -> str:
        """Read back <name>/tpudef.yaml from a fresh clone."""
        d = self.clone()
        _git(["pull", "--rebase", "origin", self.branch], cwd=d, check=False)
        path = os.path.join(d, name, "tpudef.yaml")
        if not os.path.exists(path):
            raise FileNotFoundError(f"no deployment {name!r} in {self.remote}")
        with open(path) as f:
            return f.read()

    def list_deployments(self) -> list[str]:
        d = self.clone()
        _git(["pull", "--rebase", "origin", self.branch], cwd=d, check=False)
        return sorted(
            e for e in os.listdir(d)
            if not e.startswith(".")
            and os.path.isfile(os.path.join(d, e, "tpudef.yaml"))
        )
