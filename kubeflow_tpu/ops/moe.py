"""Mixture-of-experts with expert parallelism.

GSPMD-style dense dispatch (Switch/GShard formulation): tokens are routed
top-k with a capacity limit, dispatch/combine are einsums against one-hot
tensors, and expert weights carry an `expert` mesh-axis annotation — XLA
lowers the dispatch einsum into the all-to-all over ICI when tokens are
data-sharded and experts expert-sharded. No scalar loops, static shapes,
so the whole block stays on the MXU.

Reference framework has no MoE (SURVEY.md §2.5 "Expert parallelism:
Absent"); this is TPU-native net-new capability.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.parallel.mesh import AXIS_EXPERT, AXIS_FSDP, AXIS_MODEL


class MoEBlock(nn.Module):
    """Drop-in replacement for the dense SwiGLU MLP."""

    cfg: "TransformerConfig"  # noqa: F821 — structural typing, avoids cycle
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        b, s, d = x.shape
        e, k = cfg.n_experts, cfg.expert_top_k
        init = nn.initializers.normal(0.02)

        # --- router (f32 for stable softmax) ---
        router = nn.DenseGeneral(
            e, use_bias=False, dtype=jnp.float32,
            kernel_init=nn.with_partitioning(init, (AXIS_FSDP, None)),
            name="router",
        )(x.astype(jnp.float32))                      # [b,s,e]
        probs = jax.nn.softmax(router, axis=-1)
        gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [b,s,k]
        gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

        capacity = int(self.capacity_factor * s * k / e) or 1

        # one-hot expert assignment per routing slot: [b,s,k,e]
        assign = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)
        # position of each token within its expert's buffer, per slot
        # cumsum over (s,k) flattened gives arrival order per expert
        flat = assign.reshape(b, s * k, e)
        pos = jnp.cumsum(flat, axis=1) - flat          # [b, s*k, e]
        pos = pos.reshape(b, s, k, e)
        within_cap = pos < capacity
        assign = assign * within_cap                   # drop overflow tokens
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
        # dispatch tensor [b,s,e,c]: 1 where token (b,s) occupies slot c of expert e
        dispatch = jnp.einsum("bske,bskec->bsec", assign, pos_oh)
        combine = jnp.einsum("bsk,bske,bskec->bsec", gate_vals.astype(jnp.float32),
                             assign, pos_oh)

        # --- expert computation ---
        xin = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(cfg.dtype), x)
        w_gate = self.param(
            "w_gate", nn.with_partitioning(init, (AXIS_EXPERT, AXIS_FSDP, AXIS_MODEL)),
            (e, d, cfg.d_ff), jnp.float32)
        w_up = self.param(
            "w_up", nn.with_partitioning(init, (AXIS_EXPERT, AXIS_FSDP, AXIS_MODEL)),
            (e, d, cfg.d_ff), jnp.float32)
        w_down = self.param(
            "w_down", nn.with_partitioning(init, (AXIS_EXPERT, AXIS_MODEL, AXIS_FSDP)),
            (e, cfg.d_ff, d), jnp.float32)
        h = nn.silu(jnp.einsum("ebcd,edf->ebcf", xin, w_gate.astype(cfg.dtype))) * \
            jnp.einsum("ebcd,edf->ebcf", xin, w_up.astype(cfg.dtype))
        out = jnp.einsum("ebcf,efd->ebcd", h, w_down.astype(cfg.dtype))

        # --- combine back to token order ---
        y = jnp.einsum("bsec,ebcd->bsd", combine.astype(cfg.dtype), out)

        # aux load-balancing loss (GShard): mean_e (fraction * prob)
        me = probs.mean(axis=(0, 1))                   # [e]
        ce = assign.sum(axis=2).mean(axis=(0, 1))      # fraction dispatched per expert
        aux = e * jnp.sum(me * ce)
        self.sow("losses", "moe_aux", aux)
        return y.astype(cfg.dtype)
