"""Parallel scan engine for tpulint (``--jobs N``).

``tools/lint_all.sh`` now runs three full passes per CI cycle and the
rule set keeps growing; the scan is embarrassingly parallel once the
program model exists, so this module shards it across a fork pool:

- **File rules** run one task per module (cheap tasks, imap_unordered,
  so a giant module cannot strand the pool behind it).
- **Program rules** run one task each over the shared ``Program``.
- The parent overlaps the Program build (plus the memoized fixpoints
  every lock rule shares) with the file-rule pool, then forks a
  *second* pool for program rules: children forked before the build
  cannot see it, and fork inheritance is the whole point — the parsed
  module table and the program transfer copy-on-write, nothing is
  pickled in, and only Finding lists are pickled out.

Output law (pinned by tests/test_tpulint.py): a ``--jobs N`` scan is
byte-identical to the serial one. Raw findings merge in completion
order; determinism comes from ``_finalize`` being order-independent
(suppression and the stale audit are set-membership checks) plus the
total sort on (path, line, col, rule, message).

Requires ``fork`` (Linux/macOS): callers fall back to the serial path
when it is unavailable or when there is nothing to parallelize.
"""

from __future__ import annotations

import os
from typing import Iterable

# Fork-inherited worker state: populated in the parent immediately
# before each pool is created. Not shared memory — each child gets a
# copy-on-write snapshot at fork time, which is exactly the lifetime
# the scan needs (the table is immutable once parsed).
_STATE: dict = {}


def available() -> bool:
    return hasattr(os, "fork")


def _file_task(args) -> list:
    key, rule_ids = args
    from kubeflow_tpu.analysis.core import REGISTRY

    module = _STATE["modules"][key]
    out: list = []
    for rid in rule_ids:
        out.extend(REGISTRY[rid].check(module))
    return out


def _prog_task(rule_id: str) -> list:
    from kubeflow_tpu.analysis.core import REGISTRY

    return list(REGISTRY[rule_id].check_program(_STATE["program"]))


def run(modules: dict, rules: Iterable, jobs: int) -> list:
    """Raw (pre-suppression) findings — the parallel twin of
    ``core._run_rules``; callers apply ``_finalize`` + sort as usual."""
    import multiprocessing

    from kubeflow_tpu.analysis.core import ProgramRule

    file_rules = [r for r in rules if not isinstance(r, ProgramRule)]
    prog_rules = [r for r in rules if isinstance(r, ProgramRule)]
    ctx = multiprocessing.get_context("fork")
    raw: list = []
    pool1 = pool2 = None
    try:
        _STATE["modules"] = modules
        fut1 = None
        if file_rules:
            ids = [r.id for r in file_rules]
            pool1 = ctx.Pool(jobs)
            fut1 = pool1.imap_unordered(
                _file_task, [(k, ids) for k in modules], chunksize=4)
        fut2 = None
        if prog_rules and modules:
            # built AFTER pool1 forks: the build runs in the parent
            # concurrently with the file-rule children
            from kubeflow_tpu.analysis.callgraph import Program

            program = Program(modules)
            program.locked_entry()
            program.may_held()
            program.writes()
            _STATE["program"] = program
            pool2 = ctx.Pool(min(jobs, len(prog_rules)))
            fut2 = pool2.imap_unordered(
                _prog_task, [r.id for r in prog_rules])
        if fut1 is not None:
            for chunk in fut1:
                raw.extend(chunk)
        if fut2 is not None:
            for chunk in fut2:
                raw.extend(chunk)
    finally:
        for pool in (pool1, pool2):
            if pool is not None:
                pool.close()
                pool.join()
        _STATE.clear()
    return raw
