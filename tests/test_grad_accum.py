"""Gradient accumulation (TrainConfig.grad_accum_steps): a step that
scans k microbatches with one averaged update must equal the single-shot
full-batch step bit-for-bit in math (f32 model), and the strided split
must reject geometries that break the batch sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.parallel.mesh import MeshSpec
from kubeflow_tpu.runtime.data import shard_batch
from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer


def _lm_cfg(**kw):
    base = dict(
        model="transformer-test",
        model_kwargs={"dtype": jnp.float32},
        task="lm",
        global_batch=8,
        seq_len=32,
        vocab_size=256,
        mesh=MeshSpec(data=2, model=4),
        optimizer="adafactor",
        learning_rate=1e-3,
        total_steps=3,
        warmup_steps=1,
        log_every=10**9,
    )
    base.update(kw)
    return TrainConfig.from_dict(base)


def _one_step(cfg):
    trainer = Trainer(cfg)
    state = trainer.init_state()
    batch = shard_batch(next(trainer.data_iter()),
                        next(iter(jax.tree.leaves(trainer.batch_shardings))))
    state, m = trainer.train_step(state, batch)
    return float(m["loss"]), float(m["accuracy"]), state.params


def test_accum_step_equals_full_batch_step():
    loss1, acc1, params1 = _one_step(_lm_cfg())
    loss2, acc2, params2 = _one_step(_lm_cfg(grad_accum_steps=4))
    np.testing.assert_allclose(loss2, loss1, rtol=1e-5)
    np.testing.assert_allclose(acc2, acc1, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        params2, params1)


def test_accum_under_fsdp_equals_full_batch_step():
    """The regime accumulation exists for: gradients shard with the
    fsdp weights, and the accumulated step still equals one big batch."""
    mesh = MeshSpec(data=2, fsdp=2, model=2)
    loss1, acc1, params1 = _one_step(_lm_cfg(mesh=mesh))
    loss2, acc2, params2 = _one_step(
        _lm_cfg(mesh=mesh, grad_accum_steps=2, xent_chunks=4))
    np.testing.assert_allclose(loss2, loss1, rtol=1e-5)
    np.testing.assert_allclose(acc2, acc1, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        params2, params1)


def test_accum_composes_with_chunked_xent():
    loss1, acc1, params1 = _one_step(_lm_cfg())
    loss2, acc2, params2 = _one_step(
        _lm_cfg(grad_accum_steps=2, xent_chunks=4))
    np.testing.assert_allclose(loss2, loss1, rtol=1e-5)
    np.testing.assert_allclose(acc2, acc1, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        params2, params1)


def test_rejects_indivisible_accum():
    with pytest.raises(ValueError, match="not divisible by"):
        Trainer(_lm_cfg(grad_accum_steps=3))


def test_rejects_microbatch_smaller_than_dp():
    # 8 / 8 = microbatch of 1 row over a 2-way batch sharding
    with pytest.raises(ValueError, match="batch sharding"):
        Trainer(_lm_cfg(grad_accum_steps=8, mesh=MeshSpec(data=8)))


def test_accum_equals_full_batch_with_uneven_masking():
    """Packed batches put -1 (ignored) targets unevenly across rows; the
    accumulation combine must weight microbatches by valid-token count so
    accum == one big batch stays EXACT (a mean-of-means would not)."""
    import numpy as np

    cfg1 = _lm_cfg()
    cfg2 = _lm_cfg(grad_accum_steps=4)
    out = {}
    for name, cfg in [("full", cfg1), ("accum", cfg2)]:
        trainer = Trainer(cfg)
        state = trainer.init_state()
        sharding = next(iter(jax.tree.leaves(trainer.batch_shardings)))
        batch = dict(shard_batch(next(trainer.data_iter()), sharding))
        # rows 0-3 keep 4 valid targets, rows 4-7 keep all 32
        tgt = np.array(batch["targets"])  # mutable copy
        tgt[:4, 4:] = -1
        batch["targets"] = shard_batch({"t": jnp.asarray(tgt)},
                                       sharding)["t"]
        state, m = trainer.train_step(state, batch)
        out[name] = (float(m["loss"]), float(m["accuracy"]), state.params)
    np.testing.assert_allclose(out["accum"][0], out["full"][0], rtol=1e-5)
    np.testing.assert_allclose(out["accum"][1], out["full"][1], rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        out["accum"][2], out["full"][2])
