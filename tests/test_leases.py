"""Lease-based leader election (reference: controller-runtime managers'
--enable-leader-election, notebook-controller/main.go:51-62)."""

from kubeflow_tpu.control import leases
from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.jaxjob.controller import build_controller
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.leases import LeaderElector
from kubeflow_tpu.control.runtime import seed_controller


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def make_electors(cluster, clock, n=2, lease_seconds=15.0):
    return [LeaderElector(cluster, "jaxjob-controller", identity=f"pod-{i}",
                          lease_seconds=lease_seconds, clock=clock)
            for i in range(n)]


class TestElection:
    def test_first_wins_second_stands_by(self):
        cluster, clock = FakeCluster(), FakeClock()
        a, b = make_electors(cluster, clock)
        assert a.try_acquire() is True
        assert b.try_acquire() is False
        assert a.is_leader and not b.is_leader
        # renewal keeps the lease fresh
        clock.t += 10
        assert a.try_acquire() is True
        clock.t += 10  # 20s since b's view but a renewed at t+10
        assert b.try_acquire() is False

    def test_expiry_allows_takeover_with_transition_count(self):
        cluster, clock = FakeCluster(), FakeClock()
        a, b = make_electors(cluster, clock)
        assert a.try_acquire()
        clock.t += 16  # past leaseDurationSeconds
        assert b.try_acquire() is True
        lease = cluster.get(leases.API_VERSION, leases.KIND,
                            "jaxjob-controller", "kubeflow")
        assert lease["spec"]["holderIdentity"] == "pod-1"
        assert lease["spec"]["leaseTransitions"] == 1
        # the deposed leader notices on its next round
        assert a.try_acquire() is False

    def test_release_hands_off_immediately(self):
        cluster, clock = FakeCluster(), FakeClock()
        a, b = make_electors(cluster, clock)
        assert a.try_acquire()
        a.release()
        assert not a.is_leader
        assert b.try_acquire() is True  # no 15s wait


class TestControllerFailover:
    def test_standby_takes_over_reconciling(self):
        cluster, clock = FakeCluster(), FakeClock()
        a, b = make_electors(cluster, clock)
        active = seed_controller(
            build_controller(cluster, record_events=False)
        ).with_leader_election(a)
        standby = seed_controller(
            build_controller(cluster, record_events=False)
        ).with_leader_election(b)

        cluster.create(JT.new_jaxjob("train", replicas=2))
        assert active.run_until_idle(advance_delayed=True) > 0
        assert standby.run_until_idle(advance_delayed=True) == 0
        assert len(cluster.list("v1", "Pod", namespace="default")) == 2

        # leader dies (stops renewing); lease expires; standby reconciles
        cluster.create(JT.new_jaxjob("train2", replicas=1))
        clock.t += 16
        assert standby.run_until_idle(advance_delayed=True) > 0
        pods = {ob.meta(p)["name"]
                for p in cluster.list("v1", "Pod", namespace="default")}
        assert "train2-worker-0" in pods


class TestProductionSemantics:
    def test_lease_wire_types_are_apiserver_compatible(self):
        """renewTime/acquireTime must be MicroTime RFC3339 strings and
        leaseDurationSeconds an int — epoch floats would 400 on a real
        apiserver."""
        cluster, clock = FakeCluster(), FakeClock()
        [a] = make_electors(cluster, clock, n=1)
        assert a.try_acquire()
        lease = cluster.get(leases.API_VERSION, leases.KIND,
                            "jaxjob-controller", "kubeflow")
        spec = lease["spec"]
        assert isinstance(spec["renewTime"], str) and "T" in spec["renewTime"]
        assert isinstance(spec["acquireTime"], str)
        assert isinstance(spec["leaseDurationSeconds"], int)
        # round-trips through the parser
        assert leases._from_micro_time(spec["renewTime"]) == clock.t

    def test_held_leadership_is_cached_between_renews(self):
        """The reconcile hot path must not pay a lease GET+PUT per item:
        within lease_seconds/3 of the last renew, try_acquire is a local
        check."""
        cluster, clock = FakeCluster(), FakeClock()

        calls = {"n": 0}
        real_get = cluster.get_or_none

        def counting_get(*a, **k):
            calls["n"] += 1
            return real_get(*a, **k)

        cluster.get_or_none = counting_get
        [a] = make_electors(cluster, clock, n=1)
        assert a.try_acquire()
        first = calls["n"]
        for _ in range(20):  # same instant: all cached
            assert a.try_acquire()
        assert calls["n"] == first
        clock.t += 6  # past lease/3 -> one real renew
        assert a.try_acquire()
        assert calls["n"] == first + 1

    def test_release_after_conflict_still_frees_the_lease(self):
        """release() must check the apiserver even when the cached held
        flag is stale (last round lost a 409), or clean shutdown
        degrades to a full-expiry failover."""
        cluster, clock = FakeCluster(), FakeClock()
        a, b = make_electors(cluster, clock)
        assert a.try_acquire()
        a._held = False  # simulate a stale cache after a lost race
        a.release()
        lease = cluster.get(leases.API_VERSION, leases.KIND,
                            "jaxjob-controller", "kubeflow")
        assert lease["spec"]["renewTime"] is None
        assert b.try_acquire() is True  # immediate hand-off
