"""KFRecord pipeline tests: native C++ loader vs pure-Python oracle,
corruption detection, shuffle semantics, trainer integration."""

import numpy as np
import pytest

from kubeflow_tpu.runtime import records
from kubeflow_tpu import native as native_pkg


def write_shards(tmp_path, n_shards=2, per_shard=20, rb=16, seed=0):
    rng = np.random.default_rng(seed)
    paths, rows = [], []
    for s in range(n_shards):
        data = rng.integers(0, 256, (per_shard, rb), dtype=np.uint8)
        p = str(tmp_path / f"shard-{s}.kfr")
        records.write_records(p, data)
        paths.append(p)
        rows.append(data)
    return paths, np.concatenate(rows)


def test_native_library_builds_and_loads():
    # g++ is in the image: the native path must actually work in CI, not
    # silently fall back.
    assert native_pkg.load() is not None


def test_header_roundtrip(tmp_path):
    paths, all_rows = write_shards(tmp_path, n_shards=1)
    assert records.read_header(paths[0]) == (16, 20)


@pytest.mark.parametrize("native", [True, False])
def test_sequential_read_preserves_order(tmp_path, native):
    paths, all_rows = write_shards(tmp_path)
    ds = records.RecordDataset(paths, batch=8, native=native)
    got = np.concatenate(list(ds))
    assert got.shape == (40, 16)
    np.testing.assert_array_equal(got, all_rows)
    assert ds.native == native


@pytest.mark.parametrize("native", [True, False])
def test_drop_remainder(tmp_path, native):
    paths, _ = write_shards(tmp_path, n_shards=1, per_shard=10)
    ds = records.RecordDataset(paths, batch=4, native=native)
    assert [b.shape[0] for b in ds] == [4, 4]
    ds = records.RecordDataset(paths, batch=4, drop_remainder=False, native=native)
    assert [b.shape[0] for b in ds] == [4, 4, 2]


@pytest.mark.parametrize("native", [True, False])
def test_shuffle_is_permutation(tmp_path, native):
    paths, all_rows = write_shards(tmp_path)
    ds = records.RecordDataset(paths, batch=8, shuffle_buffer=16, seed=3,
                               native=native)
    got = np.concatenate(list(ds))
    assert got.shape == all_rows.shape
    # same multiset of rows, different order
    key = lambda a: sorted(map(bytes, a))  # noqa: E731
    assert key(got) == key(all_rows)
    assert any(bytes(g) != bytes(w) for g, w in zip(got, all_rows))


def test_loop_mode_repeats(tmp_path):
    paths, all_rows = write_shards(tmp_path, n_shards=1, per_shard=8)
    ds = records.RecordDataset(paths, batch=8, loop=True)
    first = next(ds)
    second = next(ds)
    np.testing.assert_array_equal(first, second)
    ds.close()


@pytest.mark.parametrize("native", [True, False])
def test_crc_corruption_detected(tmp_path, native):
    paths, _ = write_shards(tmp_path, n_shards=1)
    raw = bytearray(open(paths[0], "rb").read())
    raw[30] ^= 0xFF  # flip a payload byte of record 0
    open(paths[0], "wb").write(bytes(raw))
    ds = records.RecordDataset(paths, batch=4, native=native)
    with pytest.raises(ValueError, match="crc"):
        list(ds)


@pytest.mark.parametrize("native", [True, False])
def test_record_bytes_mismatch_detected(tmp_path, native):
    paths, _ = write_shards(tmp_path, n_shards=1)
    ds = records.RecordDataset(paths, batch=4, record_bytes=32, native=native)
    with pytest.raises(ValueError, match="mismatch"):
        list(ds)


def test_crc_implementations_agree(tmp_path):
    import zlib

    lib = native_pkg.load()
    assert lib is not None
    import ctypes

    data = np.arange(256, dtype=np.uint8)
    native_crc = lib.kfdl_crc32(
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), data.size)
    assert native_crc == (zlib.crc32(data.tobytes()) & 0xFFFFFFFF)


def test_token_batches_shapes(tmp_path):
    seq = 32
    tok = np.arange(10 * (seq + 1), dtype=np.int32).reshape(10, seq + 1)
    p = str(tmp_path / "tok.kfr")
    records.write_token_shard(p, tok)
    it = records.token_batches([p], batch=4, seq_len=seq, loop=False)
    b = next(it)
    assert b["tokens"].shape == (4, seq) and b["targets"].shape == (4, seq)
    np.testing.assert_array_equal(b["tokens"][0], tok[0, :-1])
    np.testing.assert_array_equal(b["targets"][0], tok[0, 1:])


def test_trainer_on_token_shards(tmp_path, devices8):
    from kubeflow_tpu.parallel.mesh import MeshSpec
    from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

    seq = 32
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 128, (16, seq + 1), dtype=np.int32)
    records.write_token_shard(str(tmp_path / "tok-0.kfr"), tok)
    cfg = TrainConfig.from_dict(dict(
        model="transformer-test",
        task="lm",
        global_batch=8,
        seq_len=seq,
        vocab_size=128,
        mesh=MeshSpec(data=8),
        total_steps=2,
        warmup_steps=1,
        log_every=1,
        learning_rate=0.01,
        data_path=str(tmp_path / "tok-*.kfr"),
    ))
    state, summary = Trainer(cfg).fit(steps=2)
    assert np.isfinite(summary["final"]["loss"])
    assert int(state.step) == 2


class TestImageShards:
    def test_roundtrip_and_batching(self, tmp_path):
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (10, 8, 8, 3), dtype=np.uint8)
        labels = np.arange(10, dtype=np.int32) % 4
        p = str(tmp_path / "imgs.kfr")
        records.write_image_shard(p, imgs, labels)
        got = list(records.image_batches([p], batch=5, image_size=8,
                                         loop=False))
        assert len(got) == 2
        b = got[0]
        assert b["image"].shape == (5, 8, 8, 3)
        assert b["image"].dtype == np.float32
        np.testing.assert_array_equal(b["label"], labels[:5])
        np.testing.assert_allclose(
            b["image"], imgs[:5].astype(np.float32) / 255.0)

    def test_resnet_trains_from_image_shards(self, tmp_path, devices8):
        """The real-data classification path end to end: shards ->
        loader -> pjit train step."""
        from kubeflow_tpu.parallel.mesh import MeshSpec
        from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 256, (32, 32, 32, 3), dtype=np.uint8)
        labels = rng.integers(0, 10, 32).astype(np.int32)
        p = str(tmp_path / "train-0.kfr")
        records.write_image_shard(p, imgs, labels)
        cfg = TrainConfig.from_dict(dict(
            model="resnet18", task="classification", global_batch=8,
            image_size=32, num_classes=10, mesh=MeshSpec(data=8),
            optimizer="sgdm", learning_rate=0.1, total_steps=2,
            warmup_steps=1, data_path=str(tmp_path / "*.kfr"),
            log_every=10**9,
        ))
        trainer = Trainer(cfg)
        _, summary = trainer.fit(steps=2)
        assert np.isfinite(summary["final"]["loss"])


# ---- sequence packing --------------------------------------------------


class TestPacking:
    def test_pack_documents_greedy_and_segments(self):
        import numpy as np

        from kubeflow_tpu.runtime.records import pack_documents

        docs = [np.arange(1, 6), np.arange(10, 13), np.arange(20, 24)]
        tokens, seg = pack_documents(docs, seq_len=8)  # rows of 9
        assert tokens.shape == seg.shape and tokens.shape[1] == 9
        # doc 1 (5 toks) + doc 2 (3 toks) fit one row; doc 3 goes next
        assert (tokens[0, :5] == np.arange(1, 6)).all()
        assert (seg[0, :5] == 1).all()
        assert (tokens[0, 5:8] == np.arange(10, 13)).all()
        assert (seg[0, 5:8] == 2).all()
        assert seg[0, 8] == 0  # tail padding
        assert (seg[1, :4] == 1).all() and seg[1, 4] == 0

    def test_pack_documents_splits_long_docs(self):
        import numpy as np

        from kubeflow_tpu.runtime.records import pack_documents

        tokens, seg = pack_documents([np.arange(20)], seq_len=8)
        # 20 tokens over rows of 9: pieces 9 + 9 + 2
        flat = tokens[seg > 0]
        assert (np.sort(flat) == np.arange(20)).all()

    def test_packed_shard_roundtrip_and_boundary_targets(self, tmp_path):
        import numpy as np

        from kubeflow_tpu.runtime.records import (
            pack_documents, token_batches, write_packed_token_shard)

        docs = [np.arange(1, 6), np.arange(10, 14), np.arange(20, 29)]
        tokens, seg = pack_documents(docs, seq_len=8)
        p = str(tmp_path / "packed-0.kfr")
        write_packed_token_shard(p, tokens, seg)
        batch = next(token_batches([p], batch=tokens.shape[0], seq_len=8,
                                   loop=False, segmented=True))
        assert set(batch) == {"tokens", "targets", "segment_ids"}
        tok, tgt, s = (batch[k] for k in ("tokens", "targets", "segment_ids"))
        assert tok.shape == tgt.shape == s.shape
        # inside a document: next-token shift; at the boundary to another
        # document or into padding: -1 (ignored by the loss)
        for r in range(tok.shape[0]):
            for t in range(tok.shape[1]):
                same_doc = (seg[r, t + 1] == seg[r, t]) and seg[r, t + 1] > 0
                if same_doc:
                    assert tgt[r, t] == tokens[r, t + 1]
                else:
                    assert tgt[r, t] == -1
