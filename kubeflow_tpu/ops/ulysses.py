"""Ulysses sequence parallelism: all-to-all head sharding for attention.

The second long-context strategy of the §2.5 parallelism matrix
(SURVEY.md: "optional Ulysses-style all-to-all head sharding" — the
reference has no sequence parallelism at all). Complements ring
attention:

- **Ring** keeps sequence sharded and rotates K/V around the ICI ring —
  O(L/sp) memory per device, nearest-neighbor traffic, best for very
  long sequences.
- **Ulysses** re-shards *heads* instead: an all-to-all converts
  seq-sharded [B, L/sp, H, D] into head-sharded [B, L, H/sp, D], each
  device runs ordinary (flash) attention over the FULL sequence for its
  head group, and a second all-to-all restores sequence sharding. Two
  collectives per attention instead of sp-1 ppermutes; attention itself
  is completely local, so the fused flash kernel applies unmodified.

Both are exact. On a TPU torus the all-to-all rides ICI; XLA lowers
`lax.all_to_all` to the native collective.

Reference (public technique literature): Jacobs et al., "DeepSpeed
Ulysses: System Optimizations for Enabling Training of Extreme Long
Sequence Transformer Models" (2023).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import (
    BATCH_AXES,
    AXIS_MODEL,
    AXIS_SEQ,
    current_mesh as _current_mesh,
)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = AXIS_SEQ,
    mesh: Mesh | None = None,
    causal: bool = True,
    impl: str = "auto",
    segment_ids: jax.Array | None = None,
    block_q: int = 0,
    block_k: int = 0,
    window: int = 0,
) -> jax.Array:
    """Causal attention over seq-sharded [B, L, H, D] via head all-to-all.

    Requires heads-per-device (H / model-axis) divisible by the seq-axis
    size. ``segment_ids`` ([B, L], seq-sharded) support packed
    sequences: each device all-gathers the ids (int32, tiny next to
    K/V) and the local flash kernel masks cross-document pairs. Falls
    back to the dispatching local attention when the mesh has no `seq`
    axis, so the same model code runs on any mesh spec.
    """
    mesh = mesh or _current_mesh()
    if mesh is None or axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        from kubeflow_tpu.ops.attention import attention

        return attention(q, k, v, causal=causal, impl=impl,
                         segment_ids=segment_ids,
                         block_q=block_q, block_k=block_k, window=window)

    sp = mesh.shape[axis_name]
    h = q.shape[2]
    # GQA: repeat KV heads up to Q heads before sharding (same reasoning
    # as ring_attention: KV weights with few heads are replicated over
    # `model`, so activations arrive with the original head count).
    if k.shape[2] != h:
        assert h % k.shape[2] == 0, (h, k.shape[2])
        k = jnp.repeat(k, h // k.shape[2], axis=2)
        v = jnp.repeat(v, h // v.shape[2], axis=2)

    model_size = mesh.shape.get(AXIS_MODEL, 1) if AXIS_MODEL in mesh.axis_names else 1
    head_axis = AXIS_MODEL if h % max(model_size, 1) == 0 and model_size > 1 else None
    h_local = h // model_size if head_axis else h
    if h_local % sp != 0:
        raise ValueError(
            f"ulysses needs heads-per-device {h_local} divisible by "
            f"seq-axis size {sp} (H={h}, model={model_size})"
        )
    assert q.shape[1] % sp == 0, (q.shape, sp)

    qkv_spec = P(BATCH_AXES, axis_name, head_axis, None)
    seg_spec = P(BATCH_AXES, axis_name)
    has_seg = segment_ids is not None

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec)
        + ((seg_spec,) if has_seg else ()),
        out_specs=qkv_spec,
        check_vma=False,
    )
    def _ulysses(q_blk, k_blk, v_blk, *maybe_seg):
        # [b, L/sp, h_loc, d] -> [b, L, h_loc/sp, d]: gather sequence,
        # scatter heads. tiled=True keeps the named axes merged in-place.
        a2a = functools.partial(
            jax.lax.all_to_all, axis_name=axis_name, tiled=True
        )
        q_g = a2a(q_blk, split_axis=2, concat_axis=1)
        k_g = a2a(k_blk, split_axis=2, concat_axis=1)
        v_g = a2a(v_blk, split_axis=2, concat_axis=1)
        seg_full = None
        if has_seg:
            # attention is over the FULL sequence here: gather the ids
            seg_full = jax.lax.all_gather(
                maybe_seg[0], axis_name, axis=1, tiled=True)

        from kubeflow_tpu.ops.attention import attention

        out = attention(q_g, k_g, v_g, causal=causal, impl=impl,
                        segment_ids=seg_full,
                        block_q=block_q, block_k=block_k, window=window)

        # [b, L, h_loc/sp, d] -> [b, L/sp, h_loc, d]: scatter sequence,
        # gather heads.
        return a2a(out, split_axis=1, concat_axis=2)

    args = (q, k, v) + ((segment_ids,) if has_seg else ())
    return _ulysses(*args)
