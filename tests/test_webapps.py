"""JWA + dashboard backend semantics (reference: jupyter-web-app
backend tests shape; centraldashboard api_workgroup_test.ts shape)."""

import json

import pytest

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.kfam.service import KfamService
from kubeflow_tpu.control.notebook import types as NT
from kubeflow_tpu.control.poddefault import new_poddefault
from kubeflow_tpu.control.profile import types as PT
from kubeflow_tpu.utils.httpd import HttpReq
from kubeflow_tpu.webapps.dashboard import Dashboard
from kubeflow_tpu.webapps.jwa import JupyterWebApp

USER = "alice@example.com"


def mkreq(method, path, user=USER, body=None, query=None):
    h = {"kubeflow-userid": user} if user else {}
    b = json.dumps(body).encode() if body is not None else b""
    return HttpReq(method=method, path=path, params={}, query=query or {},
                   headers=h, body=b)


def J(resp):
    assert resp.status < 300, resp.body
    return json.loads(resp.body)


@pytest.fixture()
def cluster():
    c = FakeCluster()
    c.create(ob.new_object("v1", "Namespace", "team-a"))
    return c


class TestJwa:
    @pytest.fixture()
    def jwa(self, cluster):
        return cluster, JupyterWebApp(cluster).router()

    def test_config_and_namespaces(self, jwa):
        cluster, r = jwa
        cfg = J(r.dispatch(mkreq("GET", "/api/config")))["config"]
        assert "tpu" in cfg
        out = J(r.dispatch(mkreq("GET", "/api/namespaces")))
        assert out["namespaces"] == ["team-a"]

    def test_create_notebook_with_tpu_form(self, jwa):
        cluster, r = jwa
        form = {
            "name": "mynb",
            "image": "kubeflow-tpu/jax-notebook-tpu:latest",
            "cpu": "2", "memory": "4Gi",
            "tpu": {"count": 4, "accelerator": "tpu-v5-lite-podslice",
                    "topology": "2x2"},
            "workspaceVolume": {"name": "ws-mynb", "mountPath": "/home/jovyan"},
        }
        out = J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                                 body=form)))
        assert out["name"] == "mynb"
        nb = cluster.get(NT.API_VERSION, NT.KIND, "mynb", "team-a")
        c0 = nb["spec"]["template"]["spec"]["containers"][0]
        assert c0["resources"]["limits"][NT.RESOURCE_TPU] == 4
        sel = nb["spec"]["template"]["spec"]["nodeSelector"]
        assert sel["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5-lite-podslice"
        assert sel["cloud.google.com/gke-tpu-topology"] == "2x2"
        assert c0["volumeMounts"][0]["mountPath"] == "/home/jovyan"
        # duplicate -> 409
        assert r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                                body=form)).status == 409

    def test_cpu_only_form_has_no_tpu(self, jwa):
        cluster, r = jwa
        J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                           body={"name": "cpu-nb"})))
        nb = cluster.get(NT.API_VERSION, NT.KIND, "cpu-nb", "team-a")
        limits = (nb["spec"]["template"]["spec"]["containers"][0]
                  .get("resources", {}).get("limits", {}))
        assert NT.RESOURCE_TPU not in limits

    def test_list_notebooks_status_phases(self, jwa):
        cluster, r = jwa
        J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                           body={"name": "nb1"})))
        rows = J(r.dispatch(mkreq("GET", "/api/namespaces/team-a/notebooks")))
        assert rows["notebooks"][0]["status"]["phase"] == "waiting"
        nb = cluster.get(NT.API_VERSION, NT.KIND, "nb1", "team-a")
        nb["status"] = {"readyReplicas": 1}
        cluster.update_status(nb)
        rows = J(r.dispatch(mkreq("GET", "/api/namespaces/team-a/notebooks")))
        assert rows["notebooks"][0]["status"]["phase"] == "ready"

    def test_stop_start_notebook(self, jwa):
        cluster, r = jwa
        J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                           body={"name": "nb1"})))
        J(r.dispatch(mkreq("PATCH", "/api/namespaces/team-a/notebooks/nb1",
                           body={"stopped": True})))
        nb = cluster.get(NT.API_VERSION, NT.KIND, "nb1", "team-a")
        assert NT.STOP_ANNOTATION in ob.annotations_of(nb)
        J(r.dispatch(mkreq("PATCH", "/api/namespaces/team-a/notebooks/nb1",
                           body={"stopped": False})))
        nb = cluster.get(NT.API_VERSION, NT.KIND, "nb1", "team-a")
        assert NT.STOP_ANNOTATION not in ob.annotations_of(nb)

    def test_delete_notebook(self, jwa):
        cluster, r = jwa
        J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                           body={"name": "nb1"})))
        assert r.dispatch(mkreq("DELETE",
                                "/api/namespaces/team-a/notebooks/nb1")).status == 200
        assert r.dispatch(mkreq("DELETE",
                                "/api/namespaces/team-a/notebooks/nb1")).status == 404

    def test_pvcs_and_poddefaults(self, jwa):
        cluster, r = jwa
        J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/pvcs",
                           body={"name": "data", "size": "20Gi"})))
        pvcs = J(r.dispatch(mkreq("GET", "/api/namespaces/team-a/pvcs")))["pvcs"]
        assert pvcs == [{"name": "data", "size": "20Gi", "mode": "ReadWriteOnce"}]
        cluster.create(new_poddefault("tpu-access", "team-a", desc="Mount TPU libs"))
        pds = J(r.dispatch(mkreq("GET",
                                 "/api/namespaces/team-a/poddefaults")))["poddefaults"]
        assert pds == [{"name": "tpu-access", "desc": "Mount TPU libs"}]


class TestDashboard:
    @pytest.fixture()
    def dash(self, cluster):
        kfam = KfamService(cluster, cluster_admin="root@example.com")
        return cluster, Dashboard(cluster, kfam=kfam).router()

    def test_exists_and_create_workgroup(self, dash):
        cluster, r = dash
        assert J(r.dispatch(mkreq("GET", "/api/workgroup/exists")))["hasWorkgroup"] is False
        J(r.dispatch(mkreq("POST", "/api/workgroup/create", body={"namespace": "alice"})))
        assert J(r.dispatch(mkreq("GET", "/api/workgroup/exists")))["hasWorkgroup"] is True
        prof = cluster.get(PT.API_VERSION, PT.KIND, "alice")
        assert prof["spec"]["owner"]["name"] == USER

    def test_env_info_lists_roles(self, dash):
        cluster, r = dash
        J(r.dispatch(mkreq("POST", "/api/workgroup/create", body={"namespace": "alice"})))
        # contributor binding in another namespace
        rb = ob.new_object("rbac.authorization.k8s.io/v1", "RoleBinding",
                           "user-alice-clusterrole-edit", "team-a",
                           annotations={PT.ANNO_USER: USER, PT.ANNO_ROLE: "edit"})
        cluster.create(rb)
        info = J(r.dispatch(mkreq("GET", "/api/workgroup/env-info")))
        assert {"namespace": "alice", "role": "owner"} in info["namespaces"]
        assert {"namespace": "team-a", "role": "edit"} in info["namespaces"]
        assert info["isClusterAdmin"] is False

    def test_get_all_namespaces_admin_only(self, dash):
        _, r = dash
        assert r.dispatch(mkreq("GET", "/api/workgroup/get-all-namespaces")).status == 403
        out = J(r.dispatch(mkreq("GET", "/api/workgroup/get-all-namespaces",
                                 user="root@example.com")))
        assert "team-a" in out["namespaces"]

    def test_contributors_listing(self, dash):
        cluster, r = dash
        for u in ("bob@example.com", "eve@example.com"):
            rb = ob.new_object("rbac.authorization.k8s.io/v1", "RoleBinding",
                               f"user-{u.split('@')[0]}-clusterrole-edit", "team-a",
                               annotations={PT.ANNO_USER: u, PT.ANNO_ROLE: "edit"})
            cluster.create(rb)
        out = J(r.dispatch(mkreq(
            "GET", "/api/workgroup/get-contributors/team-a")))
        assert out["contributors"] == ["bob@example.com", "eve@example.com"]

    def test_nuke_self(self, dash):
        cluster, r = dash
        J(r.dispatch(mkreq("POST", "/api/workgroup/create", body={"namespace": "alice"})))
        out = J(r.dispatch(mkreq("DELETE", "/api/workgroup/nuke-self")))
        assert "1" in out["message"]
        # profile has a finalizer; deletionTimestamp set, reconciler would reap
        prof = cluster.get_or_none(PT.API_VERSION, PT.KIND, "alice")
        assert prof is None or "deletionTimestamp" in ob.meta(prof)

    def test_activities_feed(self, dash):
        cluster, r = dash
        nb = cluster.create(ob.new_object(NT.API_VERSION, NT.KIND, "nb", "team-a",
                                          spec={}))
        cluster.record_event(nb, "Created", "statefulset created")
        out = J(r.dispatch(mkreq("GET", "/api/activities/team-a")))
        assert out["events"][0]["reason"] == "Created"

    def test_tpu_chip_metrics(self, dash):
        cluster, r = dash
        node = ob.new_object("v1", "Node", "tpu-node-1",
                             labels={"cloud.google.com/gke-tpu-accelerator":
                                     "tpu-v5-lite-podslice",
                                     "cloud.google.com/gke-tpu-topology": "2x4"})
        node["status"] = {"capacity": {"cpu": "8", "memory": "32Gi",
                                       "google.com/tpu": "4"}}
        cluster.create(node)
        out = J(r.dispatch(mkreq("GET", "/api/metrics/tpu-chips")))
        assert out["values"] == [{"node": "tpu-node-1", "chips": "4",
                                  "accelerator": "tpu-v5-lite-podslice",
                                  "topology": "2x4"}]
        cpu = J(r.dispatch(mkreq("GET", "/api/metrics/node-cpu")))
        assert cpu["values"][0]["capacity"] == "8"
        assert r.dispatch(mkreq("GET", "/api/metrics/bogus")).status == 404

    def test_unauthenticated_401(self, dash):
        _, r = dash
        assert r.dispatch(mkreq("GET", "/api/workgroup/exists", user=None)).status == 401


def test_dashboard_serves_ui(cluster):
    from kubeflow_tpu.webapps.dashboard import Dashboard

    r = Dashboard(cluster).router()
    page = r.dispatch(mkreq("GET", "/"))
    assert page.status == 200 and page.content_type == "text/html"
    assert b"kubeflow-tpu" in page.body and b"/api/workgroup/env-info" in page.body
    # API routes still reachable alongside the UI route
    assert r.dispatch(mkreq("GET", "/api/workgroup/env-info")).status < 500


def test_jwa_serves_spawner_ui(cluster):
    from kubeflow_tpu.webapps.jwa import JupyterWebApp

    r = JupyterWebApp(cluster).router()
    page = r.dispatch(mkreq("GET", "/"))
    assert page.status == 200 and page.content_type == "text/html"
    assert b"/api/config" in page.body and b"TPU chips" in page.body
    assert r.dispatch(mkreq("GET", "/api/config")).status == 200
