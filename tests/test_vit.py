"""ViT classification family: shapes, trainer integration, sharding."""

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.parallel.mesh import MeshSpec
from kubeflow_tpu.runtime.data import shard_batch
from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer


def test_forward_shapes_and_f32_logits():
    m = get_model("vit-test")
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    v = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(v, x, train=False)
    assert out.shape == (2, 10) and out.dtype == jnp.float32


def test_rejects_wrong_image_size():
    import pytest

    m = get_model("vit-test")
    with pytest.raises(ValueError, match="32px"):
        m.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)), train=False)


def test_vit_trains_under_dp_and_tp():
    """One train step on a dp x tp mesh: the mesh-axis annotations on
    qkv/fc kernels must shard and the loss must be finite."""
    cfg = TrainConfig.from_dict(dict(
        model="vit-test",
        task="classification",
        global_batch=8,
        image_size=32,
        num_classes=10,
        mesh=MeshSpec(data=4, model=2),
        optimizer="adamw",
        learning_rate=1e-3,
        total_steps=2,
        warmup_steps=1,
        log_every=10**9,
    ))
    trainer = Trainer(cfg)
    state = trainer.init_state()
    batch = shard_batch(next(trainer.data_iter()),
                        next(iter(jax.tree.leaves(trainer.batch_shardings))))
    state, m = trainer.train_step(state, batch)
    assert np.isfinite(float(m["loss"]))
    # analytic flops hook: ViT path, not the LM fallback
    assert trainer.flops_per_step() == (
        3.0 * trainer.model.fwd_flops_per_image() * 8)


def test_registry_sizes():
    s = get_model("vit-s16")
    b = get_model("vit-b16")
    assert s.cfg.d_model == 384 and s.cfg.n_patches == 196
    assert b.cfg.d_model == 768
    # fwd flops sanity: ViT-B/16 is ~17.6 GMACs per 224px image, so
    # ~35 GF in the 2*MAC convention the MFU meter uses
    assert 30e9 < b.fwd_flops_per_image() < 40e9


def test_vit_serves_through_rest_contract():
    """The new family must ride the TF-Serving REST contract like every
    other zoo model (the reference's test_tf_serving.py golden path)."""
    import json
    import urllib.request

    from kubeflow_tpu.serving.server import ModelServer, serve_flax_classifier

    server = ModelServer()
    server.register(serve_flax_classifier(
        "vit", "vit-test", num_classes=10))
    svc = server.serve(host="127.0.0.1", port=0)
    svc.serve_background()
    try:
        body = json.dumps({
            "instances": np.zeros((2, 32, 32, 3), np.float32).tolist()
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{svc.port}/v1/models/vit:predict",
            data=body, headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=300).read())
        preds = np.asarray(out["predictions"])
        assert preds.shape == (2, 10)
        np.testing.assert_allclose(preds.sum(axis=-1), 1.0, rtol=1e-4)
    finally:
        svc.shutdown()
        server.close()
