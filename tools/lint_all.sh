#!/usr/bin/env bash
# The full static-analysis gate, pytest-free (ISSUE 1 satellite): run
# tpulint (JAX/TPU + lockset/deadlock/sharding rules, whole-program)
# over the package and round tooling, plus the stdlib hygiene gates
# (parse / debugger hooks / conflict markers, yaml manifests) over
# everything that ships — tests and examples ride only the hygiene
# gates, mirroring the pytest lint tier. Pass 4 is the exception-path
# dataflow tier (ISSUE 17): RES7xx resource-lifecycle + WIRE8xx
# wire-contract rules over the shipped tree (tests intentionally
# re-spell wire literals to pin the contract, so they stay out).
#
#   tools/lint_all.sh            # gate: exit nonzero on ANY finding
#   tools/lint_all.sh --json     # write tools/lint_baseline.json
#   tools/lint_all.sh --diff     # ratchet: fail only on NEW findings
#                                # vs the committed baseline
#   tools/lint_all.sh --bench    # decision ratchet: rerun every banked
#                                # bench smoke config (sched / serve /
#                                # obs / mslice / heal / chargeback /
#                                # rollout --check) and fail on
#                                # fingerprint/op-count drift
#
# --sarif-dir DIR (before the mode argument) writes one SARIF artifact
# per pass into DIR — CI uploads them to code scanning without running
# a second scan per format.
#
# The ratchet (ISSUE 2 satellite) lets a rule tighten without a
# flag-day: commit today's findings with --json, gate on --diff, and
# burn the baseline down over time. An empty baseline makes --diff
# equivalent to the plain gate.
set -euo pipefail
cd "$(dirname "$0")/.."

PY=${PYTHON:-python}
BASELINE=tools/lint_baseline.json
# passes 1-4 shard across a fork pool (tpulint --jobs); serial and
# parallel output are byte-identical (pinned by tests/test_tpulint.py),
# so CI can scale this with core count. Override with TPULINT_JOBS=1 to
# force the serial path. On a 1-core box $(nproc) = 1 IS the serial
# path — the >= 2x pass-1 speedup shows up on multi-core runners, and
# the per-pass wall times printed below are the CI log evidence either
# way.
JOBS=${TPULINT_JOBS:-$(nproc)}

SARIF_DIR=""
if [ "${1:-}" = "--sarif-dir" ]; then
    SARIF_DIR=${2:?"--sarif-dir needs a directory"}
    mkdir -p "$SARIF_DIR"
    shift 2
fi
sarif() {  # sarif <pass-label> — emit --sarif-file args when requested
    if [ -n "$SARIF_DIR" ]; then
        printf -- '--sarif-file\n%s/%s.sarif\n' "$SARIF_DIR" "$1"
    fi
}

t0=$SECONDS
pass_done() {  # pass_done <label> — print the wall time of the pass
    echo "lint_all: $1 in $((SECONDS - t0))s"
    t0=$SECONDS
}

# pass 1: tpulint rules over the package and executable round tooling.
# This is also the OBS302 metrics-catalog gate: the full-package scan
# includes the sentinel module, so BOTH drift directions run (code
# metric missing a docs/observability.md row, and stale doc rows).
RULE_PATHS=(kubeflow_tpu tools bench.py __graft_entry__.py)
# pass 2: stdlib hygiene (HYG001-003) over everything shipped
HYG_PATHS=(kubeflow_tpu tools tests examples bench.py __graft_entry__.py)
# pass 3: OBS hygiene (wall-clock duration math) over tests too — span
# and latency assertions in the test tier must obey the same
# perf_counter discipline the package does (pass 1 already covers the
# package + tools)
OBS_PATHS=(tests)
# pass 4: exception-path dataflow (RES) + wire-contract spelling (WIRE)
# over the shipped tree only — tests re-spell wire literals on purpose
# (a test importing the constant could never catch the constant
# drifting) and exercise leak shapes as fixtures
RES_PATHS=("${RULE_PATHS[@]}")

case "${1:-gate}" in
gate)
    mapfile -t S1 < <(sarif pass1)
    "$PY" -m kubeflow_tpu.analysis --jobs "$JOBS" "${S1[@]}" \
        "${RULE_PATHS[@]}"
    pass_done "pass 1 (tpulint rules, --jobs $JOBS)"
    mapfile -t S2 < <(sarif pass2)
    "$PY" -m kubeflow_tpu.analysis --jobs "$JOBS" \
        --select HYG001,HYG002,HYG003 "${S2[@]}" "${HYG_PATHS[@]}"
    pass_done "pass 2 (hygiene, --jobs $JOBS)"
    mapfile -t S3 < <(sarif pass3)
    "$PY" -m kubeflow_tpu.analysis --jobs "$JOBS" --select OBS301 \
        "${S3[@]}" "${OBS_PATHS[@]}"
    pass_done "pass 3 (OBS over tests, --jobs $JOBS)"
    mapfile -t S4 < <(sarif pass4)
    "$PY" -m kubeflow_tpu.analysis --jobs "$JOBS" --rules RES,WIRE \
        "${S4[@]}" "${RES_PATHS[@]}"
    pass_done "pass 4 (RES/WIRE dataflow, --jobs $JOBS)"
    echo "lint_all: all passes clean in ${SECONDS}s total"
    ;;
--json)
    tmp1=$(mktemp) && tmp2=$(mktemp) && tmp3=$(mktemp) && tmp4=$(mktemp)
    trap 'rm -f "$tmp1" "$tmp2" "$tmp3" "$tmp4"' EXIT
    "$PY" -m kubeflow_tpu.analysis --jobs "$JOBS" --write-baseline "$tmp1" \
        "${RULE_PATHS[@]}" >/dev/null
    "$PY" -m kubeflow_tpu.analysis --select HYG001,HYG002,HYG003 \
        --write-baseline "$tmp2" "${HYG_PATHS[@]}" >/dev/null
    "$PY" -m kubeflow_tpu.analysis --select OBS301 \
        --write-baseline "$tmp3" "${OBS_PATHS[@]}" >/dev/null
    "$PY" -m kubeflow_tpu.analysis --jobs "$JOBS" --rules RES,WIRE \
        --write-baseline "$tmp4" "${RES_PATHS[@]}" >/dev/null
    "$PY" - "$tmp1" "$tmp2" "$tmp3" "$tmp4" "$BASELINE" <<'EOF'
import json
import sys

findings = []
for path in sys.argv[1:5]:
    with open(path) as fh:
        findings.extend(json.load(fh)["findings"])
with open(sys.argv[5], "w") as fh:
    json.dump({"version": 1, "findings": sorted(findings)}, fh, indent=2)
    fh.write("\n")
print(f"lint_all: baseline written to {sys.argv[5]} "
      f"({len(findings)} findings)")
EOF
    ;;
--diff)
    test -f "$BASELINE" || {
        echo "lint_all: no $BASELINE — run tools/lint_all.sh --json first" >&2
        exit 2
    }
    rc=0
    "$PY" -m kubeflow_tpu.analysis --jobs "$JOBS" --baseline "$BASELINE" \
        "${RULE_PATHS[@]}" || rc=1
    "$PY" -m kubeflow_tpu.analysis --select HYG001,HYG002,HYG003 \
        --baseline "$BASELINE" "${HYG_PATHS[@]}" || rc=1
    "$PY" -m kubeflow_tpu.analysis --select OBS301 \
        --baseline "$BASELINE" "${OBS_PATHS[@]}" || rc=1
    "$PY" -m kubeflow_tpu.analysis --jobs "$JOBS" --rules RES,WIRE \
        --baseline "$BASELINE" "${RES_PATHS[@]}" || rc=1
    exit $rc
    ;;
--bench)
    # the decision-ratchet tier: each bench reruns its committed smoke
    # bank and fails when the decision fingerprint or exact op counts
    # drift — the "scheduler/plane/fleet DECIDED differently" gate that
    # static analysis can't see. Wall-clock gates inside each --check
    # are 3x-budgeted so a loaded CI box cannot flake this tier.
    rc=0
    for bench in sched_bench serve_bench obs_bench mslice_bench \
            heal_bench chargeback_bench rollout_bench; do
        echo "== $bench --check"
        JAX_PLATFORMS=cpu "$PY" "tools/$bench.py" --check || rc=1
    done
    # the resilience ratchet (ISSUE 14): band-goodput, hedge win rate,
    # breaker round trip and the decision fingerprint vs BENCH_SERVE_r03
    echo "== serve_bench --resilience --check"
    JAX_PLATFORMS=cpu "$PY" tools/serve_bench.py --resilience --check \
        || rc=1
    exit $rc
    ;;
*)
    echo "usage: tools/lint_all.sh [--sarif-dir DIR] [--json|--diff|--bench]" >&2
    exit 2
    ;;
esac
