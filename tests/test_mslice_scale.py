"""Multi-slice bench contract (ISSUE 12 acceptance, tier-1 sized).

Runs tools/mslice_bench.py's smoke config + slice-reclaim drill and
pins what the bank promises:

- **determinism**: the decision fingerprint (placements + slice
  vectors + virtual-time latencies hashed canonically) is byte-stable
  across runs — everything rides the manual clock, so ANY drift is a
  semantic change in admission, not noise;
- **placement quality**: every admitted slice lives in exactly one
  (accelerator, topology) pool (``slices_intact == 1.0``);
- **reclaim semantics**: the drill shrinks to the surviving slice and
  grows back without burning a single restart;
- **ratchet**: ``mslice_bench --check`` passes against the committed
  BENCH_MSLICE_r01.json and fails loudly against a poisoned bank —
  the same gate tools/lint_all.sh-adjacent CI wiring runs.
"""

import importlib.util
import json
import pathlib
import sys

import pytest

TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"
BANK = TOOLS.parent / "BENCH_MSLICE_r01.json"


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "mslice_bench", TOOLS / "mslice_bench.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("mslice_bench", mod)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench():
    return _load_bench()


@pytest.fixture(scope="module")
def smoke(bench):
    return bench.run_admission(**bench.SMOKE_CONFIG)


@pytest.fixture(scope="module")
def drill(bench):
    return bench.run_drill()


@pytest.mark.usefixtures("virtual_time_guard")
class TestMsliceBench:
    def test_double_run_fingerprint_byte_stable(self, bench, smoke):
        again = bench.run_admission(**bench.SMOKE_CONFIG)
        assert again["fingerprint"] == smoke["fingerprint"]
        assert again == smoke  # not just the hash: every banked number

    def test_every_gang_admits_with_intact_slices(self, smoke, bench):
        assert smoke["admitted_gangs"] == bench.SMOKE_CONFIG["gangs"]
        q = smoke["quality"]
        assert q["slices_intact"] == 1.0
        assert q["placed_gangs"] == smoke["admitted_gangs"]
        assert q["slices_total"] >= 2 * smoke["admitted_gangs"]
        # the scheduler counted each multislice admission
        assert smoke["slice_admissions_metric"] >= 1
        assert 0.0 < smoke["admission_p50_s"] <= smoke["admission_p99_s"]

    def test_drill_shrinks_and_grows_without_restarts(self, drill):
        assert drill["restarts"] == 0
        assert drill["preemptions"] == 0
        assert drill["admit_s"] > 0
        assert drill["shrink_s"] > 0
        assert drill["grow_s"] > 0
        assert drill["complete_s"] >= 0

    def test_drill_fingerprint_byte_stable(self, bench, drill):
        assert bench.run_drill()["fingerprint"] == drill["fingerprint"]

    def test_banked_budget_gate(self, bench, smoke, drill, tmp_path):
        """--check passes against an honest bank and fails (exit 1)
        against a poisoned one — both directions, before trusting the
        committed bank below."""
        banked = {
            "smoke_config": dict(bench.SMOKE_CONFIG),
            "smoke": dict(smoke),
            "drill": dict(drill),
        }
        ok_path = tmp_path / "bank_ok.json"
        ok_path.write_text(json.dumps(banked))
        assert bench.check_against(str(ok_path)) == 0
        poisoned = json.loads(ok_path.read_text())
        poisoned["smoke"]["fingerprint"] = "0" * 64
        poisoned["smoke"]["admission_p99_s"] = smoke["admission_p99_s"] / 100
        bad_path = tmp_path / "bank_bad.json"
        bad_path.write_text(json.dumps(poisoned))
        assert bench.check_against(str(bad_path)) == 1
        # a missing bank is a usage error, not a silent pass
        assert bench.check_against(str(tmp_path / "nope.json")) == 2

    def test_committed_bank_check_is_green(self, bench):
        """THE CI wiring: the committed BENCH_MSLICE_r01.json gates
        exactly like sched/serve/obs banks do."""
        assert bench.check_against(str(BANK)) == 0

    def test_committed_bank_meets_acceptance(self):
        banked = json.loads(BANK.read_text())
        assert banked["bench"] == "mslice_bench"
        full = banked["full"]
        assert banked["config"]["gangs"] == 64
        assert full["admitted_gangs"] == 64
        assert full["quality"]["slices_intact"] == 1.0
        # admission exercised its slice-spread freedom at least once
        assert full["quality"]["cross_pool_gangs"] >= 1
        drill = banked["drill"]
        assert drill["restarts"] == 0 and drill["preemptions"] == 0
