"""Platform availability prober (reference: metric-collector/)."""

from kubeflow_tpu.metric_collector.prober import AvailabilityProber  # noqa: F401
