"""Span API: ids, context propagation, collector, exporters.

Design constraints, in order:

- **Zero dependencies.** Runs in the control plane, the launcher pod,
  and CI images with nothing but the stdlib.
- **Monotonic durations.** Every duration is a ``time.perf_counter()``
  delta; wall-clock (``time.time()``) appears exactly once, as the
  module-level anchor that converts perf_counter readings into epoch
  timestamps for export. tpulint's OBS301 enforces this repo-wide.
- **Never lose the exception.** ``Tracer.span`` records status=ERROR
  and re-raises; instrumentation must not change control flow.
- **Bounded memory.** The collector is a ring (default 8192 spans) so a
  million-step training run cannot OOM its own telemetry.

Propagation uses the W3C trace-context wire format
(``00-<32 hex trace id>-<16 hex span id>-<2 hex flags>``) carried in
the ``TRACEPARENT`` env var across processes and in the
``obs.kubeflow.org/traceparent`` annotation across k8s objects.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Iterator

# One authoritative spelling of the propagation carriers (jaxjob stamps
# them, scheduler/launcher/trainer read them).
TRACEPARENT_ENV = "TRACEPARENT"
TRACEPARENT_ANNOTATION = "obs.kubeflow.org/traceparent"

# Wall-clock anchor: epoch seconds at the instant perf_counter read 0.
# Span timestamps are anchor + perf_counter — one wall reading at
# import, monotonic deltas ever after.
_EPOCH = time.time() - time.perf_counter()  # tpulint: disable=OBS301,DET601  wall anchor, not a duration: sampled once at import so all span math stays on perf_counter; never read inside a replayed decision


def new_trace_id() -> str:
    return uuid.uuid4().hex  # tpulint: disable=DET604  trace ids are correlation keys, never decision inputs: fingerprints hash decisions, not span identity


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]  # tpulint: disable=DET604  span ids are correlation keys, never decision inputs: fingerprints hash decisions, not span identity


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """The propagatable identity of a span: what children parent on."""

    trace_id: str
    span_id: str
    sampled: bool = True

    def to_traceparent(self) -> str:
        flags = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flags}"


def parse_traceparent(value) -> SpanContext | None:
    """Decode a W3C traceparent header; None for anything malformed
    (propagation is best-effort — a bad header must never raise)."""
    if not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if (len(version), len(trace_id), len(span_id), len(flags)) != (2, 32, 16, 2):
        return None
    try:
        int(version, 16), int(trace_id, 16), int(span_id, 16)
        flag_bits = int(flags, 16)
    except ValueError:
        return None
    if version == "ff" or set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None  # spec: invalid version / all-zero ids
    return SpanContext(trace_id, span_id, bool(flag_bits & 1))


def context_from_env(environ=None) -> SpanContext | None:
    env = os.environ if environ is None else environ
    return parse_traceparent(env.get(TRACEPARENT_ENV, ""))


@dataclasses.dataclass
class Span:
    """One timed operation. ``start``/``end`` are epoch seconds derived
    from the perf_counter anchor; ``end is None`` while still open."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    start: float = 0.0
    end: float | None = None
    attrs: dict = dataclasses.field(default_factory=dict)
    status: str = "OK"  # OK | ERROR
    error: str | None = None
    pid: int = dataclasses.field(default_factory=os.getpid)
    tid: int = dataclasses.field(default_factory=threading.get_ident)

    @property
    def duration(self) -> float:
        assert self.end is not None, f"span {self.name!r} still open"
        return self.end - self.start

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


class TraceCollector:
    """Thread-safe bounded span sink (a ring: old spans age out)."""

    def __init__(self, capacity: int = 8192):
        self._lock = threading.Lock()
        self._spans: deque[Span] = deque(maxlen=capacity)

    def add(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def trace(self, trace_id: str) -> list[Span]:
        with self._lock:
            return [s for s in self._spans if s.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


# The ambient span context: children parent on it implicitly. A
# contextvar (not a thread-local) so the scheduler's synchronous
# admission pass and async test harnesses both nest correctly.
_CURRENT: contextvars.ContextVar[SpanContext | None] = contextvars.ContextVar(
    "kftpu_span_context", default=None)


class Tracer:
    """Span factory bound to a collector.

    Two API shapes: ``span()`` (context manager — exception-safe, for
    lexically scoped work) and ``begin()``/``finish()`` (for spans held
    open across calls, e.g. a controller's per-object root span)."""

    def __init__(self, collector: TraceCollector | None = None):
        self.collector = collector if collector is not None else TraceCollector()

    # -- ambient context ---------------------------------------------------

    def current(self) -> SpanContext | None:
        return _CURRENT.get()

    def attach(self, ctx: SpanContext | None):
        """Install ``ctx`` as the ambient parent (e.g. the launcher
        installing the pod's TRACEPARENT); returns a reset token."""
        return _CURRENT.set(ctx)

    def detach(self, token) -> None:
        _CURRENT.reset(token)

    # -- span lifecycle ----------------------------------------------------

    def begin(self, name: str, parent: SpanContext | None = None,
              context: SpanContext | None = None, detached: bool = False,
              **attrs) -> Span:
        """Open a span. ``parent`` overrides the ambient context;
        ``context`` pins the span's OWN ids (the jaxjob root span must
        be exactly the ids stamped into the pod traceparent).
        ``detached`` skips ambient installation — required when finish()
        will run in a different call stack (e.g. a later reconcile)."""
        if context is not None:
            trace_id, span_id = context.trace_id, context.span_id
            parent_id = parent.span_id if parent is not None else None
        else:
            up = parent if parent is not None else _CURRENT.get()
            trace_id = up.trace_id if up is not None else new_trace_id()
            parent_id = up.span_id if up is not None else None
            span_id = new_span_id()
        t0 = time.perf_counter()  # tpulint: disable=DET601  span timing is observability payload, not a decision input: no control flow reads span durations
        span = Span(name=name, trace_id=trace_id, span_id=span_id,
                    parent_id=parent_id, start=_EPOCH + t0, attrs=dict(attrs))
        span._t0 = t0
        span._token = None if detached else _CURRENT.set(span.context())
        return span

    def finish(self, span: Span) -> Span:
        span.end = span.start + (time.perf_counter() - span._t0)  # tpulint: disable=DET601  span timing is observability payload, not a decision input: no control flow reads span durations
        token = getattr(span, "_token", None)
        if token is not None:
            span._token = None
            try:
                _CURRENT.reset(token)
            except ValueError:
                pass  # finished from a different context: leave ambient alone
        self.collector.add(span)
        return span

    @contextlib.contextmanager
    def span(self, name: str, parent: SpanContext | None = None,
             **attrs) -> Iterator[Span]:
        sp = self.begin(name, parent=parent, **attrs)
        try:
            yield sp
        except BaseException as e:
            sp.status = "ERROR"
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            self.finish(sp)


COLLECTOR = TraceCollector()
TRACER = Tracer(COLLECTOR)


# -- tree helpers ------------------------------------------------------------

def children_index(spans: list[Span]) -> dict[str | None, list[Span]]:
    out: dict[str | None, list[Span]] = {}
    for s in spans:
        out.setdefault(s.parent_id, []).append(s)
    return out


def reachable(spans: list[Span], root_span_id: str) -> set[str]:
    """Span ids reachable from ``root_span_id`` via parent links —
    the acceptance check that a trace is one connected tree."""
    index = children_index(spans)
    seen: set[str] = {root_span_id}
    frontier = [root_span_id]
    while frontier:
        for child in index.get(frontier.pop(), []):
            if child.span_id not in seen:
                seen.add(child.span_id)
                frontier.append(child.span_id)
    return seen


# -- exporters ---------------------------------------------------------------

def to_chrome_trace(spans: list[Span]) -> dict:
    """Perfetto / chrome://tracing ``trace_event`` JSON (object form).
    Spans become complete ("X") events; microsecond timestamps."""
    events: list[dict] = []
    named: set[int] = set()
    for s in spans:
        if s.end is None:
            continue  # an open span is not a complete event
        if s.pid not in named:
            named.add(s.pid)
            events.append({"ph": "M", "pid": s.pid, "tid": 0,
                           "name": "process_name",
                           "args": {"name": f"kubeflow-tpu:{s.pid}"}})
        args = {**s.attrs, "trace_id": s.trace_id, "span_id": s.span_id,
                "status": s.status}
        if s.parent_id:
            args["parent_id"] = s.parent_id
        if s.error:
            args["error"] = s.error
        events.append({
            "ph": "X", "cat": "kftpu", "name": s.name,
            "ts": round(s.start * 1e6, 3),
            "dur": round(s.duration * 1e6, 3),
            "pid": s.pid, "tid": s.tid, "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def to_jsonl(spans: list[Span]) -> str:
    """Compact one-span-per-line dump (the ``trace2perfetto`` input)."""
    return "".join(json.dumps(s.to_dict(), sort_keys=True) + "\n"
                   for s in spans)


def from_jsonl(text: str) -> list[Span]:
    return [Span.from_dict(json.loads(line))
            for line in text.splitlines() if line.strip()]


def write_jsonl(path: str, spans: list[Span]) -> None:
    """Atomic dump (utils/fsatomic.py): the launcher writes this at
    exit — often BECAUSE the worker is being preempted — and a kill mid-
    write must leave the previous dump intact, not a torn half-file."""
    from kubeflow_tpu.utils.fsatomic import atomic_write_text

    atomic_write_text(path, to_jsonl(spans))


def read_jsonl(path: str) -> list[Span]:
    with open(path) as fh:
        return from_jsonl(fh.read())
