"""Remat memory planner: per-policy saved-residual accounting.

The round-3 hardware ledger showed a hole between remat policies: "mlp"
(save-anything-except-wide) OOMs at bs>=16 on llama-1b while "full"
(nothing saveable) pays ~33% recompute and hits an XLA spill cliff on
gpt-760m. This tool makes the tradeoff measurable BEFORE burning tunnel
time: for each policy it traces one LM train-loss forward on the host
(jax.ad_checkpoint.saved_residuals — abstract tracing, no execution, no
TPU needed) and reports the bytes of residuals the backward will hold,
alongside the analytic recompute tax in block-MAC terms.

Usage:
  python tools/remat_plan.py --model llama-1b --batch 16 [--seq 2048]

CALIBRATION (round-5 hardware ledger): these numbers bound the saved
RESIDUAL bytes only — XLA's compile-time HLO temps amplify the real
footprint well past them (llama-1b bs8 dots: planner said comfortable,
AOT compile needed 19.3G against 15.75G HBM; gpt-760m bs8 slim missed
by 50MB). Use the report to ORDER candidate policies, never to conclude
a config fits; the watcher's compile-probe stages are the ground truth.
"""

from __future__ import annotations

import argparse
import os
import sys

# force-override: this box exports JAX_PLATFORMS=axon (the TPU tunnel)
# and its sitecustomize imports jax before user code runs, so the env
# var is already latched — only config.update reaches the live config.
# An analysis tool must never touch (or hang on) the tunnel.
os.environ["JAX_PLATFORMS"] = "cpu"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402

POLICIES = ["none", "slim", "mlp", "dots", "full"]


def recompute_tax(cfg, policy: str, seq: int) -> float:
    """Replay MACs as a fraction of one block forward (analytic)."""
    d, dff = cfg.d_model, cfg.d_ff
    hd = cfg.head_dim
    proj = d * hd * (cfg.n_heads * 2 + cfg.n_kv_heads * 2)  # q,k,v,o
    mlp = 3 * d * dff
    attn = 2 * cfg.n_heads * hd * seq / 2                   # causal avg
    block = proj + mlp + attn
    if policy == "none":
        return 0.0
    if policy == "full":
        return 1.0
    if policy == "dots":
        # dot outputs + the flash out/lse residuals (named inside the
        # custom_vjp fwd rule) are saved: replay is elementwise only
        return 0.0
    if policy == "mlp":
        return (2 * d * dff) / block
    if policy == "slim":
        # gate/up matmuls replay; flash does not (attn_flash saved)
        return (2 * d * dff) / block
    raise ValueError(policy)


def residual_bytes(model, tokens, policy: str, xent_chunks: int = 8):
    # public alias dropped from jax.ad_checkpoint in this jax version;
    # the implementation is still shipped
    from jax._src.ad_checkpoint import saved_residuals

    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), tokens, train=True))
    from flax.core import meta

    variables = meta.unbox(variables)
    params = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), variables)

    if xent_chunks > 1:
        # mirror the production loss (runtime/trainer.py chunked_head):
        # the [B, L, V] logits pair must not count against the policy
        from kubeflow_tpu.ops.xent import chunked_lm_xent

        def loss(params, tokens):
            hidden = model.apply(params, tokens, train=True,
                                 return_hidden=True)
            y = jnp.roll(tokens, -1, axis=-1)
            l, _ = chunked_lm_xent(hidden, params["params"]["lm_head"]["kernel"],
                                   y, xent_chunks)
            return l
    else:
        def loss(params, tokens):
            logits = model.apply(params, tokens, train=True)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

    res = saved_residuals(loss, params, tokens)
    tot = 0
    items = []
    for aval, descr in res:
        if "from the argument" in descr:
            continue  # parameters/inputs, not activation residuals
        nb = aval.size * aval.dtype.itemsize
        items.append((nb, str(aval.shape), str(aval.dtype), descr))
        tot += nb
    return tot, items


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-1b")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--top", type=int, default=0,
                    help="also print the N largest residuals per policy")
    ap.add_argument("--attention", default="flash",
                    help="attention_impl to trace (flash = the hardware "
                         "path; its custom_vjp residuals q/k/v/out/lse "
                         "are what the backward actually holds)")
    ap.add_argument("--xent-chunks", type=int, default=8)
    args = ap.parse_args()

    from kubeflow_tpu.models.registry import get_model

    rows = []
    for policy in POLICIES:
        kw = {} if policy == "none" else dict(remat=True, remat_policy=policy)
        model = get_model(args.model, max_seq_len=args.seq,
                          attention_impl=args.attention, **kw)
        tokens = jnp.zeros((args.batch, args.seq), jnp.int32)
        tot, items = residual_bytes(model, tokens, policy, args.xent_chunks)
        tax = recompute_tax(model.cfg, policy, args.seq)
        rows.append((policy, tot, tax))
        print(f"{policy:>6}: residuals {tot / 2**30:7.2f} GiB   "
              f"block replay {tax * 100:5.1f}% of fwd MACs")
        if args.top:
            for nb, shape, dt, descr in sorted(items, reverse=True)[:args.top]:
                print(f"         {nb / 2**20:9.1f} MiB  {shape:>22} {dt:>9}  "
                      f"{descr[:80]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
