"""RestClient against a real HTTP apiserver (VERDICT r1 weak #5).

Every other control-plane test talks to FakeCluster in-process; here the
same store is served over HTTP (control/k8s/apiserver.py) and driven
through RestClient — the client-go analogue controllers use on a live
cluster. Covers the claims rest.py makes: CRUD verbs, status subresource,
merge/json patch, label/field selectors, 404/409 mapping, chunked watch
streams, and a controller running identically on both backends.
"""

import threading
import time

import pytest

from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.jaxjob.controller import build_controller, worker_name
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.apiserver import ApiServer, client_for, parse_api_path
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.runtime import seed_controller


@pytest.fixture()
def server():
    s = ApiServer().serve_background()
    yield s
    s.shutdown()


@pytest.fixture()
def client(server):
    return client_for(server)


def wait_for(fn, timeout=10.0, period=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(period)
    raise TimeoutError("condition not met")


class TestPathParsing:
    def test_core_namespaced(self):
        p = parse_api_path("/api/v1/namespaces/ns1/pods/p1")
        assert (p.api_version, p.kind, p.namespace, p.name) == \
            ("v1", "Pod", "ns1", "p1")

    def test_group_crd_with_status(self):
        p = parse_api_path(
            "/apis/kubeflow.org/v1/namespaces/ns1/jaxjobs/j/status")
        assert p.api_version == "kubeflow.org/v1"
        assert (p.kind, p.name, p.subresource) == ("JAXJob", "j", "status")

    def test_cluster_scoped(self):
        p = parse_api_path("/apis/kubeflow.org/v1/profiles/team-a")
        assert (p.kind, p.namespace, p.name) == ("Profile", None, "team-a")

    def test_unknown_plural_rejected(self):
        with pytest.raises(LookupError):
            parse_api_path("/api/v1/frobnicators")


class TestCrudOverHttp:
    def test_create_get_roundtrip(self, client):
        cm = ob.new_object("v1", "ConfigMap", "cm", "default")
        cm["data"] = {"k": "v"}
        client.create(cm)
        got = client.get("v1", "ConfigMap", "cm", "default")
        assert got["data"] == {"k": "v"}
        assert ob.meta(got)["resourceVersion"]

    def test_get_missing_raises_notfound(self, client):
        with pytest.raises(ob.NotFound):
            client.get("v1", "ConfigMap", "nope", "default")
        assert client.get_or_none("v1", "ConfigMap", "nope", "default") is None

    def test_create_duplicate_raises_conflict(self, client):
        obj = ob.new_object("v1", "ConfigMap", "cm", "default")
        client.create(obj)
        with pytest.raises(ob.Conflict):
            client.create(obj)

    def test_update_and_stale_rv_conflict(self, client):
        """The optimistic-concurrency 409 path controllers rely on."""
        cm = ob.new_object("v1", "ConfigMap", "cm", "default")
        cm["data"] = {"v": "1"}
        client.create(cm)
        fresh = client.get("v1", "ConfigMap", "cm", "default")
        stale = ob.deep_copy(fresh)
        fresh["data"]["v"] = "2"
        client.update(fresh)
        stale["data"]["v"] = "3"
        with pytest.raises(ob.Conflict):
            client.update(stale)

    def test_status_subresource_does_not_touch_spec(self, client):
        client.create(JT.new_jaxjob("j1", replicas=1))
        job = client.get(JT.API_VERSION, JT.KIND, "j1", "default")
        job["status"] = {"conditions": [{"type": "Created", "status": "True"}]}
        job["spec"]["replicas"] = 99  # must be ignored by /status
        client.update_status(job)
        got = client.get(JT.API_VERSION, JT.KIND, "j1", "default")
        assert got["status"]["conditions"][0]["type"] == "Created"
        assert got["spec"]["replicas"] == 1

    def test_merge_and_json_patch(self, client):
        cm = ob.new_object("v1", "ConfigMap", "cm", "default")
        cm["data"] = {"a": "1"}
        client.create(cm)
        client.patch("v1", "ConfigMap", "cm", {"data": {"b": "2"}}, "default")
        got = client.get("v1", "ConfigMap", "cm", "default")
        assert got["data"] == {"a": "1", "b": "2"}
        client.patch("v1", "ConfigMap", "cm",
                     [{"op": "remove", "path": "/data/a"}], "default")
        got = client.get("v1", "ConfigMap", "cm", "default")
        assert got["data"] == {"b": "2"}

    def test_delete(self, client):
        client.create(ob.new_object("v1", "ConfigMap", "cm", "default"))
        client.delete("v1", "ConfigMap", "cm", "default")
        assert client.get_or_none("v1", "ConfigMap", "cm", "default") is None

    def test_list_with_selectors(self, client):
        for i, role in enumerate(["web", "web", "db"]):
            client.create(ob.new_object("v1", "Pod", f"p{i}", "default",
                                        labels={"role": role}))
        assert len(client.list("v1", "Pod", "default")) == 3
        web = client.list("v1", "Pod", "default",
                          label_selector={"matchLabels": {"role": "web"}})
        assert {ob.meta(p)["name"] for p in web} == {"p0", "p1"}
        by_name = client.list("v1", "Pod", "default",
                              field_selector={"metadata.name": "p2"})
        assert len(by_name) == 1
        # list items get apiVersion/kind backfilled (apiserver omits them)
        assert by_name[0]["kind"] == "Pod"

    def test_cluster_scoped_objects(self, client):
        client.create(ob.new_object("v1", "Namespace", "team-x"))
        assert client.get("v1", "Namespace", "team-x")["kind"] == "Namespace"


class TestWatchOverHttp:
    def test_watch_streams_added_and_modified(self, client, server):
        stream = client.watch("v1", "ConfigMap", "default")
        events = []
        got_two = threading.Event()

        def consume():
            for ev in stream:
                events.append(ev)
                if len(events) >= 2:
                    got_two.set()
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)  # let the watch connect
        cm = ob.new_object("v1", "ConfigMap", "cm", "default")
        cm["data"] = {"v": "1"}
        client.create(cm)
        obj = client.get("v1", "ConfigMap", "cm", "default")
        obj["data"]["v"] = "2"
        client.update(obj)
        assert got_two.wait(10.0), f"saw only {events}"
        stream.stop()
        assert [e.type for e in events[:2]] == ["ADDED", "MODIFIED"]
        assert events[1].object["data"]["v"] == "2"


class TestControllerOverHttp:
    def test_jaxjob_gang_identical_on_both_backends(self, server, client):
        """VERDICT 'done' bar: one controller test passing identically on
        FakeCluster and RestClient backends."""
        # -- HTTP backend: production run() mode (threads + watch streams)
        ctl = build_controller(client)
        ctl.run(workers=1)
        try:
            client.create(JT.new_jaxjob("train", replicas=2,
                                        accelerator="tpu-v5-lite-podslice",
                                        topology="2x4"))
            pods = wait_for(
                lambda: (lambda ps: ps if len(ps) == 2 else None)(
                    client.list("v1", "Pod", "default")))
        finally:
            ctl.stop()
        http_names = {ob.meta(p)["name"] for p in pods}

        # -- in-process FakeCluster backend: hermetic drain mode
        fake = FakeCluster()
        fctl = seed_controller(build_controller(fake))
        fake.create(JT.new_jaxjob("train", replicas=2,
                                  accelerator="tpu-v5-lite-podslice",
                                  topology="2x4"))
        for _ in range(6):
            fctl.run_until_idle(advance_delayed=True)
        fake_names = {ob.meta(p)["name"]
                      for p in fake.list("v1", "Pod", namespace="default")}

        assert http_names == fake_names == {worker_name("train", i)
                                            for i in range(2)}
        # env contract survives the HTTP round trip
        pod = client.get("v1", "Pod", worker_name("train", 1), "default")
        env = {e["name"]: e["value"]
               for e in pod["spec"]["containers"][0]["env"]}
        assert env[JT.ENV_NPROC] == "2"


class TestLeaderElectionOverHttp:
    def test_two_electors_through_rest_client(self, server):
        """Leader election over the real HTTP wire: JSON-serialized
        MicroTime strings, 409 arbitration between two RestClients."""
        from kubeflow_tpu.control.k8s.rest import RestClient
        from kubeflow_tpu.control.leases import LeaderElector

        t = {"now": 5000.0}
        a = LeaderElector(RestClient(base_url=server.url),
                          "nb-controller", identity="pod-a",
                          clock=lambda: t["now"])
        b = LeaderElector(RestClient(base_url=server.url),
                          "nb-controller", identity="pod-b",
                          clock=lambda: t["now"])
        assert a.try_acquire() is True
        assert b.try_acquire() is False
        t["now"] += 16  # expiry -> takeover over HTTP
        assert b.try_acquire() is True
        assert a.try_acquire() is False
        b.release()
        assert a.try_acquire() is True


class TestWatchConformance:
    """The corners real kube-apiservers exercise that VERDICT r2 flagged:
    resume-after-disconnect, bookmarks, 410 Gone -> relist, paginated
    lists under concurrent writes, stale-patch 409."""

    def _consume(self, stream, events, stop_at):
        done = threading.Event()

        def run():
            for ev in stream:
                events.append(ev)
                if len(events) >= stop_at:
                    done.set()
                    return

        threading.Thread(target=run, daemon=True).start()
        return done

    def test_watch_resumes_after_dropped_connection(self, client, server):
        """Events created while the client is between connections MUST be
        delivered after reconnect (resume from last resourceVersion)."""
        stream = client.watch("v1", "ConfigMap", "default")
        events: list = []
        done = self._consume(stream, events, stop_at=3)
        time.sleep(0.3)
        cm = ob.new_object("v1", "ConfigMap", "a", "default")
        client.create(cm)
        for _ in range(100):  # the first event pins the client's rv
            if events:
                break
            time.sleep(0.05)
        assert events, "watch never delivered the first event"
        server.drop_watches()  # mid-stream disconnect
        # these happen while the client has no connection
        client.create(ob.new_object("v1", "ConfigMap", "b", "default"))
        client.create(ob.new_object("v1", "ConfigMap", "c", "default"))
        assert done.wait(10.0), f"saw only {[e.object['metadata']['name'] for e in events]}"
        stream.stop()
        names = [e.object["metadata"]["name"] for e in events[:3]]
        assert names == ["a", "b", "c"]  # nothing lost, nothing duplicated

    def test_bookmarks_advance_resume_point_past_other_kinds(self):
        """An idle ConfigMap watch must not rewind behind churn on other
        kinds: BOOKMARKs advance rv past the churn, so after a drop the
        resume succeeds directly. The tiny history window makes the
        no-bookmark fallback observable: without bookmarks the resume
        would 410 -> relist and re-yield 'seen' as a duplicate MODIFIED —
        the assertion below fails in that world."""
        cluster = FakeCluster(history_limit=6)
        srv = ApiServer(cluster).serve_background()
        srv.bookmark_interval = 0.2
        try:
            c = client_for(srv)
            stream = c.watch("v1", "ConfigMap", "default")
            events: list = []
            done = self._consume(stream, events, stop_at=2)
            time.sleep(0.3)
            c.create(ob.new_object("v1", "ConfigMap", "seen", "default"))
            # churn another kind PAST the history window, then idle long
            # enough for a bookmark carrying the post-churn rv
            for i in range(8):
                c.create(ob.new_object("v1", "Secret", f"s{i}", "default"))
            time.sleep(0.8)
            srv.drop_watches()
            c.create(ob.new_object("v1", "ConfigMap", "after", "default"))
            assert done.wait(10.0)
            stream.stop()
            assert [(e.type, e.object["metadata"]["name"])
                    for e in events[:2]] == \
                [("ADDED", "seen"), ("ADDED", "after")]
        finally:
            srv.shutdown()

    def test_too_old_rv_gets_410_then_relist(self, server):
        """History window exhausted: the watch must 410 and the client
        must relist (each live object re-yielded) and keep going."""
        cluster = FakeCluster(history_limit=4)
        srv = ApiServer(cluster).serve_background()
        try:
            c = client_for(srv)
            stream = c.watch("v1", "ConfigMap", "default")
            events: list = []

            def consume_forever():
                for ev in stream:
                    events.append(ev)

            threading.Thread(target=consume_forever, daemon=True).start()
            time.sleep(0.3)
            c.create(ob.new_object("v1", "ConfigMap", "first", "default"))
            for _ in range(100):
                if events:
                    break
                time.sleep(0.05)
            assert events, "watch never delivered the first event"
            srv.drop_watches()
            # blow past the 4-event history while disconnected
            for i in range(8):
                c.create(ob.new_object("v1", "Secret", f"x{i}", "default"))
            c.create(ob.new_object("v1", "ConfigMap", "second", "default"))
            # reconnect -> 410 -> relist: both live ConfigMaps re-yielded
            seen = threading.Event()

            def wait_for_second():
                while not any(
                        e.object["metadata"]["name"] == "second"
                        for e in events):
                    time.sleep(0.05)
                seen.set()

            threading.Thread(target=wait_for_second, daemon=True).start()
            assert seen.wait(10.0), \
                f"relist never surfaced: {[e.object['metadata']['name'] for e in events]}"
            stream.stop()
            names = {e.object["metadata"]["name"] for e in events}
            assert {"first", "second"} <= names
        finally:
            srv.shutdown()

    def test_relist_synthesizes_deleted_for_gap_deletions(self):
        """An object the stream had seen that vanishes during a 410 gap
        must surface as a DELETED event after the relist (informers diff
        the relist against their store the same way)."""
        cluster = FakeCluster(history_limit=4)
        srv = ApiServer(cluster).serve_background()
        try:
            c = client_for(srv)
            stream = c.watch("v1", "ConfigMap", "default")
            events: list = []

            def consume_forever():
                for ev in stream:
                    events.append(ev)

            threading.Thread(target=consume_forever, daemon=True).start()
            time.sleep(0.3)
            doomed = ob.new_object("v1", "ConfigMap", "doomed", "default",
                                   labels={"owner-label": "gang-a"})
            c.create(doomed)
            c.create(ob.new_object("v1", "ConfigMap", "keeper", "default"))
            for _ in range(100):
                if len(events) >= 2:
                    break
                time.sleep(0.05)
            assert len(events) >= 2
            srv.drop_watches()
            c.delete("v1", "ConfigMap", "doomed", "default")
            for i in range(8):  # truncate history past the deletion
                c.create(ob.new_object("v1", "Secret", f"z{i}", "default"))
            deleted_seen = threading.Event()

            def wait_deleted():
                while not any(e.type == "DELETED" and
                              e.object["metadata"]["name"] == "doomed"
                              for e in events):
                    time.sleep(0.05)
                deleted_seen.set()

            threading.Thread(target=wait_deleted, daemon=True).start()
            assert deleted_seen.wait(10.0), \
                f"no DELETED for doomed in {[(e.type, e.object['metadata']['name']) for e in events]}"
            stream.stop()
            # the survivor resyncs as MODIFIED, not DELETED
            assert not any(e.type == "DELETED" and
                           e.object["metadata"]["name"] == "keeper"
                           for e in events)
            # informer semantics: the synthesized DELETED carries the
            # LAST-KNOWN full object (labels/ownerRefs) so secondary
            # mappers still resolve the owning CR
            deleted = next(e for e in events if e.type == "DELETED" and
                           e.object["metadata"]["name"] == "doomed")
            assert deleted.object["metadata"].get("labels", {}).get(
                "owner-label") == "gang-a"
        finally:
            srv.shutdown()


class TestListPagination:
    def test_client_follows_continue_tokens(self, client, server):
        for i in range(7):
            client.create(ob.new_object("v1", "ConfigMap", f"cm{i}", "default"))
        client.list_chunk = 3  # force 3 pages
        items = client.list("v1", "ConfigMap", "default")
        assert [ob.meta(o)["name"] for o in items] == [f"cm{i}" for i in range(7)]
        assert all(o.get("kind") == "ConfigMap" for o in items)

    def test_pages_are_snapshot_consistent_under_writes(self, server):
        """Objects created/deleted between page fetches must not corrupt
        the pagination: later pages come from the original snapshot."""
        cluster = server.cluster
        for i in range(6):
            cluster.create(ob.new_object("v1", "ConfigMap", f"p{i}", "default"))
        page1, cont, rv = cluster.list_page("v1", "ConfigMap", "default",
                                            limit=3)
        assert [ob.meta(o)["name"] for o in page1] == ["p0", "p1", "p2"]
        # concurrent writes between pages
        cluster.create(ob.new_object("v1", "ConfigMap", "p2a", "default"))
        cluster.delete("v1", "ConfigMap", "p4", "default")
        page2, cont2, _ = cluster.list_page("v1", "ConfigMap", "default",
                                            limit=3, continue_token=cont)
        assert cont2 == ""
        # the snapshot still shows p4 and not p2a — page1+page2 is exactly
        # the collection as of the first request
        assert [ob.meta(o)["name"] for o in page2] == ["p3", "p4", "p5"]

    def test_expired_continue_token_is_410(self, server):
        cluster = server.cluster
        for i in range(4):
            cluster.create(ob.new_object("v1", "ConfigMap", f"q{i}", "default"))
        _, cont, _ = cluster.list_page("v1", "ConfigMap", "default", limit=2)
        cluster.list_page("v1", "ConfigMap", "default", limit=2,
                          continue_token=cont)  # consumes the token
        with pytest.raises(ob.Expired):
            cluster.list_page("v1", "ConfigMap", "default", limit=2,
                              continue_token=cont)


class TestStalePatch:
    def test_patch_with_stale_rv_is_409_over_http(self, client, server):
        cm = ob.new_object("v1", "ConfigMap", "sp", "default")
        cm["data"] = {"v": "1"}
        created = client.create(cm)
        stale_rv = ob.meta(created)["resourceVersion"]
        # someone else updates
        cur = client.get("v1", "ConfigMap", "sp", "default")
        cur["data"]["v"] = "2"
        client.update(cur)
        with pytest.raises(ob.Conflict):
            client.patch("v1", "ConfigMap", "sp",
                         {"metadata": {"resourceVersion": stale_rv},
                          "data": {"v": "3"}}, "default")
        # without the precondition the patch applies (merge semantics)
        out = client.patch("v1", "ConfigMap", "sp", {"data": {"v": "3"}},
                           "default")
        assert out["data"]["v"] == "3"


def test_continue_pages_report_snapshot_rv(server):
    """A watch resumed from a paginated list's rv must see objects
    created mid-pagination: every page carries the SNAPSHOT's rv."""
    cluster = server.cluster
    for i in range(6):
        cluster.create(ob.new_object("v1", "ConfigMap", f"s{i}", "default"))
    page1, cont, rv1 = cluster.list_page("v1", "ConfigMap", "default",
                                         limit=4)
    cluster.create(ob.new_object("v1", "ConfigMap", "mid-pagination",
                                 "default"))
    _page2, _cont2, rv2 = cluster.list_page("v1", "ConfigMap", "default",
                                            limit=4, continue_token=cont)
    assert rv2 == rv1  # pinned, NOT the post-creation current rv
    # resuming a watch from that rv replays the mid-pagination creation
    stream = cluster.watch("v1", "ConfigMap", "default", since_rv=rv2)
    names = []
    while True:
        ev = stream.poll()
        if ev is None:
            break
        names.append(ev.object["metadata"]["name"])
    stream.stop()
    assert "mid-pagination" in names


class TestServerSideApply:
    """Server-side apply over HTTP (VERDICT r4 #6): fieldManager
    ownership, apply conflicts + force transfer, and declarative field
    removal — the apiserver behaviors CreateOrUpdate-style controllers
    assume (reference: notebook_controller.go:85 reconcile updates)."""

    AV, KIND = "kubeflow.org/v1", "Notebook"

    def _intent(self, **spec):
        return {"apiVersion": self.AV, "kind": self.KIND,
                "metadata": {"name": "nb", "namespace": "user1"},
                "spec": spec}

    def test_apply_creates_and_records_ownership(self, client):
        out = client.apply(self._intent(image="jax:0.8", replicas=1),
                           field_manager="ctrl")
        assert out["spec"] == {"image": "jax:0.8", "replicas": 1}
        mf = out["metadata"]["managedFields"]
        assert [e["manager"] for e in mf] == ["ctrl"]
        assert ["spec", "image"] in mf[0]["fields"]

    def test_disjoint_managers_coexist(self, client):
        client.apply(self._intent(image="jax:0.8"), field_manager="ctrl")
        out = client.apply(
            {"apiVersion": self.AV, "kind": self.KIND,
             "metadata": {"name": "nb", "namespace": "user1",
                          "labels": {"team": "ml"}}},
            field_manager="labeler")
        # both managers' fields persist, each owned separately
        assert out["spec"]["image"] == "jax:0.8"
        assert out["metadata"]["labels"] == {"team": "ml"}
        mgrs = {e["manager"] for e in out["metadata"]["managedFields"]}
        assert mgrs == {"ctrl", "labeler"}

    def test_conflicting_apply_is_409_until_forced(self, client):
        client.apply(self._intent(image="jax:0.8"), field_manager="ctrl")
        with pytest.raises(ob.Conflict, match="owned by ctrl"):
            client.apply(self._intent(image="jax:0.9"),
                         field_manager="intruder")
        # force transfers ownership; the original manager now conflicts
        out = client.apply(self._intent(image="jax:0.9"),
                           field_manager="intruder", force=True)
        assert out["spec"]["image"] == "jax:0.9"
        with pytest.raises(ob.Conflict, match="owned by intruder"):
            client.apply(self._intent(image="jax:1.0"),
                         field_manager="ctrl")

    def test_same_value_shares_ownership(self, client):
        client.apply(self._intent(image="jax:0.8"), field_manager="a")
        out = client.apply(self._intent(image="jax:0.8"),
                           field_manager="b")  # no conflict: same value
        owning = [e["manager"] for e in out["metadata"]["managedFields"]
                  if ["spec", "image"] in e["fields"]]
        assert sorted(owning) == ["a", "b"]
        # a drops the field from its intent; b still owns it -> retained
        out = client.apply(self._intent(), field_manager="a")
        assert out["spec"]["image"] == "jax:0.8"

    def test_dropped_field_is_removed(self, client):
        client.apply(self._intent(image="jax:0.8", replicas=2),
                     field_manager="ctrl")
        out = client.apply(self._intent(image="jax:0.8"),
                           field_manager="ctrl")
        # declarative removal: replicas no longer applied -> gone
        assert "replicas" not in out["spec"]

    def test_apply_does_not_steal_unowned_update_fields(self, client):
        client.apply(self._intent(image="jax:0.8"), field_manager="ctrl")
        # a status writer (plain update, no ownership) sets status
        cur = client.get(self.AV, self.KIND, "nb", "user1")
        cur["status"] = {"phase": "Running"}
        client.update_status(cur)
        # ctrl re-applies without status: status survives (unowned
        # fields are never removed)
        out = client.apply(self._intent(image="jax:0.8"),
                           field_manager="ctrl")
        assert out["status"] == {"phase": "Running"}

    def test_missing_field_manager_is_invalid_on_both_backends(self, client):
        # 422 round-trips to ob.Invalid so error handling is
        # backend-independent (same exception on FakeCluster directly)
        with pytest.raises(ob.Invalid):
            client.apply(self._intent(image="x"), field_manager="")
        with pytest.raises(ob.Invalid):
            FakeCluster().apply(self._intent(image="x"), field_manager="")

    def test_descendant_of_owned_leaf_conflicts(self, client):
        """Ownership guards the subtree: applying spec.resources.cpu
        under another manager's owned spec.resources scalar is a 409,
        not a silent clobber."""
        client.apply(self._intent(resources="small"), field_manager="a")
        deeper = {"apiVersion": self.AV, "kind": self.KIND,
                  "metadata": {"name": "nb", "namespace": "user1"},
                  "spec": {"resources": {"cpu": 2}}}
        with pytest.raises(ob.Conflict, match="owned by a"):
            client.apply(deeper, field_manager="b")
        out = client.apply(deeper, field_manager="b", force=True)
        assert out["spec"]["resources"] == {"cpu": 2}
        # ancestor direction: a's scalar would flatten b's map -> 409
        with pytest.raises(ob.Conflict, match="owned by b"):
            client.apply(self._intent(resources="small"),
                         field_manager="a")

    def test_map_owner_dropping_it_keeps_other_managers_entries(self, client):
        """A manager that owned only the map itself (spec: {}) and stops
        applying it must not wipe entries other managers own under it."""
        client.apply(self._intent(), field_manager="a")  # owns spec map
        client.apply(self._intent(image="jax:0.8"), field_manager="b")
        out = client.apply(
            {"apiVersion": self.AV, "kind": self.KIND,
             "metadata": {"name": "nb", "namespace": "user1"}},
            field_manager="a")  # a no longer applies spec at all
        assert out["spec"]["image"] == "jax:0.8"

    def test_fake_and_rest_identical(self, client, server):
        """The same apply sequence on FakeCluster directly and through
        HTTP produces identical objects (modulo uid/rv/timestamps)."""
        fake = FakeCluster()
        for backend in (fake, client):
            backend.apply(self._intent(image="jax:0.8", replicas=2),
                          field_manager="ctrl")
            backend.apply(
                {"apiVersion": self.AV, "kind": self.KIND,
                 "metadata": {"name": "nb", "namespace": "user1",
                              "labels": {"team": "ml"}}},
                field_manager="labeler")
            backend.apply(self._intent(image="jax:0.9"),
                          field_manager="ctrl")
        via_fake = fake.get(self.AV, self.KIND, "nb", "user1")
        via_rest = client.get(self.AV, self.KIND, "nb", "user1")
        for doc in (via_fake, via_rest):
            for k in ("uid", "creationTimestamp", "resourceVersion"):
                doc["metadata"].pop(k, None)
        assert via_fake == via_rest

    def test_sub_owner_removal_succeeds_under_map_assert(self, client):
        """The inverse of the map-owner case: b owns spec.image under
        a's spec map-assert; when b stops applying it, the field is
        REMOVED (an ancestor assert owns the map's existence, not the
        leaf — counting it as co-ownership would orphan the field
        forever)."""
        client.apply(self._intent(), field_manager="a")  # spec map assert
        client.apply(self._intent(image="jax:0.8"), field_manager="b")
        out = client.apply(self._intent(), field_manager="b")
        assert "image" not in (out.get("spec") or {})

    def test_reasserting_populated_map_composes(self, client):
        """Re-applying {spec: {}} against a spec that now has entries is
        NOT a conflict: asserting the map composes with deeper owners."""
        client.apply(self._intent(), field_manager="a")
        client.apply(self._intent(image="jax:0.8"), field_manager="b")
        out = client.apply(self._intent(), field_manager="a")  # no 409
        assert out["spec"]["image"] == "jax:0.8"

    def test_apply_body_url_mismatch_is_400(self, client):
        body = {"apiVersion": self.AV, "kind": self.KIND,
                "metadata": {"name": "OTHER", "namespace": "user1"},
                "spec": {"image": "x"}}
        import json as _json

        import requests

        r = requests.patch(
            client.base_url + "/apis/kubeflow.org/v1/namespaces/user1/"
            "notebooks/nb?fieldManager=ctrl",
            data=_json.dumps(body),
            headers={"Content-Type": "application/apply-patch+yaml"})
        assert r.status_code == 400
        # and nothing was applied anywhere
        assert client.get_or_none(self.AV, self.KIND, "OTHER", "user1") is None
        assert client.get_or_none(self.AV, self.KIND, "nb", "user1") is None

    def test_error_text_survives_non_dict_json_body(self, client, server):
        """A proxy answering 404 with a bare JSON string must still
        surface NotFound, not an AttributeError from .get on a str."""
        import pytest as _pytest

        class FakeResp:
            status_code = 404
            content = b'"not found"'
            text = '"not found"'

            def json(self):
                return "not found"

        orig = client._s.request
        client._s.request = lambda *a, **k: FakeResp()
        try:
            with _pytest.raises(ob.NotFound, match="not found"):
                client.get("v1", "ConfigMap", "x", "default")
        finally:
            client._s.request = orig
