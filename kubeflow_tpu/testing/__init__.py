"""CI/E2E harness: the `testing/` tier of the platform (SURVEY.md §4).

The reference drives E2E through Prow -> Argo workflow DAGs
(testing/workflows/components/kfctl_go_test.jsonnet) whose steps run
pytest suites emitting junit XML for Gubernator/testgrid. This package
is the same capability in-tree: a workflow DAG runner (workflow.py),
junit emission (junit.py), and readiness/condition waiters (waiters.py)
— usable both hermetically against the fake cluster and against a real
one.
"""

from kubeflow_tpu.testing.junit import TestCase, TestSuite  # noqa: F401
from kubeflow_tpu.testing.waiters import (  # noqa: F401
    wait_for,
    wait_for_condition,
    wait_for_deployments_ready,
)
from kubeflow_tpu.testing.workflow import Step, Workflow  # noqa: F401
