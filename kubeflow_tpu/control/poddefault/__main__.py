"""Webhook server entry: python -m kubeflow_tpu.control.poddefault."""
import argparse

from kubeflow_tpu.control.k8s.rest import RestClient
from kubeflow_tpu.control.poddefault import PodDefaultMutator

p = argparse.ArgumentParser("poddefault-webhook")
p.add_argument("--port", type=int, default=4443)
p.add_argument("--apiserver", default="")
args = p.parse_args()
svc = PodDefaultMutator(RestClient(base_url=args.apiserver or None)).serve(port=args.port)
print(f"poddefault webhook on :{svc.port}")
svc.serve_forever()
