"""kubeflow_tpu.control.jaxservice — the production serving plane CRD.

A JAXService runs N interchangeable model-server replicas behind the
token-aware router (``serving/router.py``), autoscaled on router queue
depth and tokens/sec between ``spec.replicas.min`` and ``.max``, with
drain-before-delete scale-down. See docs/serving.md.

- ``types``      — CRD spec/validation, the endpoints annotation
  re-export, condition vocabulary.
- ``controller`` — the Reconciler: provisioning through the gang
  scheduler, readiness tracking, endpoints publication, hysteretic
  autoscaling, the cordon → drain → delete state machine.
"""

from __future__ import annotations


def watch_endpoints(apiserver: str, namespace: str, name: str,
                    router) -> None:  # pragma: no cover - container glue
    """Router-side membership feed: watch ONE JAXService and apply its
    endpoints annotation to the router on every event (plus an initial
    read). Runs forever; stream death resubscribes (the control/runtime
    watch discipline)."""
    import logging
    import time as _time

    from kubeflow_tpu.control.jaxservice import types as T
    from kubeflow_tpu.control.k8s.rest import RestClient
    from kubeflow_tpu.serving.router import HttpTransport

    log = logging.getLogger("kubeflow_tpu.jaxservice")
    client = RestClient(base_url=apiserver or None)
    factory = lambda ep: HttpTransport(ep["addr"])  # noqa: E731
    while True:
        try:
            obj = client.get_or_none(T.API_VERSION, T.KIND, name, namespace)
            if obj is not None:
                router.sync_from_object(obj, transport_factory=factory)
            for ev in client.watch(T.API_VERSION, T.KIND):
                m = (ev.object.get("metadata") or {})
                if m.get("name") == name \
                        and (m.get("namespace") or "default") == namespace:
                    router.sync_from_object(
                        ev.object, transport_factory=factory)
        except Exception:
            log.exception("endpoints watch failed; resubscribing")
        _time.sleep(0.5)
