"""Profiler trace-window tests: xprof capture during training."""

import glob
import os

import numpy as np

from kubeflow_tpu.parallel.mesh import MeshSpec
from kubeflow_tpu.runtime.profiler import TraceWindow
from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer


def test_trace_window_state_machine(tmp_path):
    w = TraceWindow(str(tmp_path / "t"), start_step=2, num_steps=2)
    w.step(0)
    assert not w._active
    w.step(2)
    assert w._active
    w.step(3)
    assert w._active
    w.step(4)
    assert not w._active and w.captured
    # one-shot: does not re-arm
    w.step(2)
    assert not w._active


def test_trace_window_disabled_without_dir():
    w = TraceWindow(None)
    w.step(2)
    assert not w._active and not w.captured


def test_fit_writes_xplane_trace(tmp_path, devices8):
    d = str(tmp_path / "prof")
    cfg = TrainConfig.from_dict(dict(
        model="transformer-test",
        task="lm",
        global_batch=8,
        seq_len=32,
        vocab_size=128,
        mesh=MeshSpec(data=8),
        total_steps=5,
        warmup_steps=1,
        log_every=2,
        learning_rate=0.01,
        profile_dir=d,
        profile_start_step=1,
        profile_steps=2,
    ))
    _, summary = Trainer(cfg).fit(steps=5)
    assert np.isfinite(summary["final"]["loss"])
    traces = glob.glob(os.path.join(d, "plugins", "profile", "*", "*"))
    assert traces, f"no xprof trace files under {d}"
