"""JAXJob CRD: API types, defaults, validation.

The reference's TFJob spec shape (replicaSpecs with per-replica pod
templates — tf-controller-examples/tf-cnn/create_job_specs.py:125-191)
collapses on TPU: parameter servers disappear (synchronous in-XLA
allreduce replaces them) and MASTER/WORKER distinction reduces to
process_id 0. A JAXJob is therefore one homogeneous worker set plus TPU
slice topology.

Condition types follow the Katib/TFJob contract that E2E tests poll
(testing/katib_studyjob_test.py:128-194 waits on
status.conditions[].type == Running): Created, Running, Restarting,
Succeeded, Failed.
"""

from __future__ import annotations

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.scheduler import SCHEDULER_NAME
from kubeflow_tpu.control.scheduler.topology import parse_topology

GROUP = "kubeflow.org"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "JAXJob"

# Condition types (katib/tf-operator contract)
COND_CREATED = "Created"
COND_RUNNING = "Running"
COND_RESTARTING = "Restarting"
COND_SUCCEEDED = "Succeeded"
COND_FAILED = "Failed"

# Pod labels (the `notebook-name` analogue, notebook_controller.go:541-563)
LABEL_JOB_NAME = "jaxjob.kubeflow.org/job-name"
LABEL_REPLICA_INDEX = "jaxjob.kubeflow.org/replica-index"
LABEL_SLICE_INDEX = "jaxjob.kubeflow.org/slice-index"

# Pod incarnation marker: the gang epoch (status.restarts +
# status.preemptions at creation time). A pod whose epoch is older than
# the job's current epoch belongs to a TORN-DOWN incarnation — the
# controller condemns it (deletes, excludes from status derivation)
# instead of re-reading its phase as a fresh failure. This is what
# makes gang restart resumable across transient apiserver errors
# without double-counting the restart budget.
ANNOTATION_EPOCH = "jaxjob.kubeflow.org/epoch"

# Env contract consumed by kubeflow_tpu.parallel.dist.initialize_from_env.
# Re-exported from dist (ONE authoritative spelling of the wire contract);
# the import is jax-free — parallel/__init__ is lazy exactly so the
# control plane can import dist, and test_dist.py pins that property.
from kubeflow_tpu.parallel.dist import (  # noqa: E402
    ENV_COORD,
    ENV_NAME,
    ENV_NAMESPACE,
    ENV_NPROC,
    ENV_NUM_SLICES,
    ENV_PID,
    ENV_SLICE_ID,
)

# GKE TPU scheduling surface (the nvidia.com/gpu swap point —
# create_job_specs.py:165-170 sets resources.limits["nvidia.com/gpu"])
RESOURCE_TPU = "google.com/tpu"
NODESELECTOR_ACCEL = "cloud.google.com/gke-tpu-accelerator"
NODESELECTOR_TOPOLOGY = "cloud.google.com/gke-tpu-topology"

DEFAULT_COORDINATOR_PORT = 8476
RESTART_GANG = "GangOnFailure"
RESTART_NEVER = "Never"

# The launcher's graceful-preemption exit status (runtime/preemption.py
# EX_TEMPFAIL): the worker checkpointed and asked for a gang restart.
# Preemptions are counted in status.preemptions and do NOT consume the
# maxRestarts crash budget — TPU maintenance can evict a slice many
# times without the job being at fault.
EXIT_PREEMPTED = 75
# GKE taints nodes ahead of TPU maintenance/preemption; treat as unhealthy
TAINT_IMPENDING_TERMINATION = "cloud.google.com/impending-node-termination"


def gang_size(spec: dict) -> int:
    """Total worker pods = replicas-per-slice x sliceCount. The whole
    multislice set is ONE gang and ONE jax.distributed world; the mesh's
    `dcn` axis spans the slice boundary (parallel/mesh.py)."""
    return spec.get("replicas", 1) * spec.get("sliceCount", 1)


def new_jaxjob(
    name: str,
    namespace: str = "default",
    *,
    replicas: int = 1,
    slice_count: int = 1,
    image: str = "kubeflow-tpu/jaxrt:latest",
    command: list[str] | None = None,
    accelerator: str | None = None,
    topology: str | None = None,
    chips_per_worker: int = 4,
    restart_policy: str = RESTART_GANG,
    max_restarts: int = 3,
    priority: int = 0,
    gang_schedule: bool = False,
) -> dict:
    """Convenience constructor (the create_job_specs.py analogue).

    ``replicas`` is the worker count PER SLICE; ``slice_count`` > 1 asks
    for a multislice deployment (the reference's closest analogue is the
    multi-replica TFJob topology, create_job_specs.py:125-191 — but DCN
    replaces the PS/gRPC fabric).

    ``gang_schedule=True`` opts the job into the TPU gang scheduler
    (control/scheduler): generated pods get spec.schedulerName plus a
    scheduling gate, and are only run once the whole gang is bound
    all-or-nothing. ``priority`` orders admission; a higher-priority
    gang may preempt a running lower-priority one."""
    spec: dict = {
        "replicas": replicas,
        "template": {
            "metadata": {"labels": {}},
            "spec": {
                "containers": [
                    {
                        "name": "jax",
                        "image": image,
                        "command": command
                        or ["python", "-m", "kubeflow_tpu.runtime.launcher"],
                    }
                ],
                "restartPolicy": "Never",
            },
        },
        "coordinatorPort": DEFAULT_COORDINATOR_PORT,
        "restartPolicy": restart_policy,
        "maxRestarts": max_restarts,
    }
    if slice_count > 1:
        spec["sliceCount"] = slice_count
    if priority:
        spec["priority"] = priority
    if gang_schedule:
        spec["schedulerName"] = SCHEDULER_NAME
    if accelerator:
        spec["tpu"] = {
            "accelerator": accelerator,
            "topology": topology or "",
            "chipsPerWorker": chips_per_worker,
        }
    return ob.new_object(API_VERSION, KIND, name, namespace, spec=spec)


def validate(job: dict) -> list[str]:
    """Spec validation; returned problems become Failed-condition reasons."""
    errs = []
    spec = job.get("spec") or {}
    replicas = spec.get("replicas", 1)
    if not isinstance(replicas, int) or replicas < 1:
        errs.append(f"spec.replicas must be a positive int, got {replicas!r}")
    slices = spec.get("sliceCount", 1)
    if not isinstance(slices, int) or slices < 1:
        errs.append(f"spec.sliceCount must be a positive int, got {slices!r}")
    tmpl = spec.get("template") or {}
    containers = (tmpl.get("spec") or {}).get("containers") or []
    if not containers:
        errs.append("spec.template.spec.containers must have at least one container")
    rp = spec.get("restartPolicy", RESTART_GANG)
    if rp not in (RESTART_GANG, RESTART_NEVER):
        errs.append(f"spec.restartPolicy must be {RESTART_GANG} or {RESTART_NEVER}")
    port = spec.get("coordinatorPort", DEFAULT_COORDINATOR_PORT)
    if not isinstance(port, int) or not (0 < port < 65536):
        errs.append(f"spec.coordinatorPort invalid: {port!r}")
    prio = spec.get("priority", 0)
    if not isinstance(prio, int) or isinstance(prio, bool):
        errs.append(f"spec.priority must be an int, got {prio!r}")
    errs += _validate_tpu_topology(spec)
    return errs


def _validate_tpu_topology(spec: dict) -> list[str]:
    """Slice-geometry consistency: the topology's chip count must equal
    replicas x chipsPerWorker, or the gang can never be placed on one
    slice — catching it at admission beats a forever-Pending pod set."""
    tpu = spec.get("tpu") or {}
    topology = tpu.get("topology") or ""
    chips = tpu.get("chipsPerWorker")
    if not topology or not chips:
        return []
    try:
        # the ONE topology parser (control/scheduler/topology.py);
        # AST-pinned against reimplementation in tests/test_scheduler.py
        slice_chips = parse_topology(topology).chips
    except ValueError:
        return [f"spec.tpu.topology {topology!r} is not NxM[xK]"]
    replicas = spec.get("replicas", 1)
    if isinstance(replicas, int) and replicas >= 1 \
            and slice_chips != replicas * chips:
        return [f"spec.tpu.topology {topology} has {slice_chips} chips but "
                f"replicas x chipsPerWorker = {replicas} x {chips} = "
                f"{replicas * chips}; the gang cannot tile the slice"]
    return []


def crd_manifest() -> dict:
    """The CustomResourceDefinition applied by tpctl."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"jaxjobs.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": KIND,
                "listKind": "JAXJobList",
                "plural": "jaxjobs",
                "singular": "jaxjob",
                "shortNames": ["jj"],
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "x-kubernetes-preserve-unknown-fields": True,
                        }
                    },
                }
            ],
        },
    }
