"""Graceful TPU preemption/maintenance handling.

SURVEY.md §5 lists slice preemption as a hard part with no reference
precedent (the reference's failure story is per-replica restartPolicy).
The TPU-native answer: when the platform warns a worker (SIGTERM from
the kubelet on pod eviction; GKE sends it ahead of TPU maintenance),
the trainer finishes the in-flight step, force-saves a checkpoint, and
exits EX_TEMPFAIL — the JAXJob controller then gang-restarts the job,
which resumes from that checkpoint instead of losing the interval since
the last periodic save.

Usage (wired by the launcher):
    notice = PreemptionNotice().install()
    state, summary = trainer.fit(stop=notice)
    if summary.get("preempted"):
        sys.exit(EX_TEMPFAIL)
"""

from __future__ import annotations

import logging
import signal
import threading

log = logging.getLogger("kubeflow_tpu.preemption")

# A preempted worker must NOT exit 0 (the controller would count it
# Succeeded) nor look like a crash-only failure: EX_TEMPFAIL is the
# conventional "transient, retry me" exit status.
EX_TEMPFAIL = 75


class PreemptionNotice:
    """Callable flag set by SIGTERM (and available for tests/manual
    triggering via .trigger())."""

    def __init__(self):
        self._event = threading.Event()
        self._prev_handler = None
        self._signum: int | None = None

    def install(self, signum: int = signal.SIGTERM) -> "PreemptionNotice":
        """Install the signal handler (main thread only — launcher entry).
        Chains to any previously installed handler. Idempotent: a second
        install() of the same signal is a no-op — naive re-chaining
        would make the handler its own "previous" and fire it twice per
        signal (and uninstall() could never reach the original)."""
        if self._signum is not None:
            if signum != self._signum:
                raise ValueError(
                    f"already installed on signal {self._signum}; "
                    f"uninstall() before moving to signal {signum}")
            return self
        prev = signal.getsignal(signum)

        def handler(sig, frame):
            log.warning("preemption notice (signal %d): will checkpoint "
                        "and exit after the current step", sig)
            self._event.set()
            if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
                prev(sig, frame)

        self._prev_handler = prev
        self._signum = signum
        signal.signal(signum, handler)
        return self

    def uninstall(self) -> "PreemptionNotice":
        """Restore the handler that was active before install() — a
        library embedding the trainer (a notebook kernel, a test
        harness) gets its own SIGTERM behavior back on teardown.
        Idempotent; keeps the notice's triggered state."""
        if self._signum is not None:
            signal.signal(self._signum, self._prev_handler
                          if self._prev_handler is not None
                          else signal.SIG_DFL)
            self._prev_handler = None
            self._signum = None
        return self

    @property
    def installed(self) -> bool:
        return self._signum is not None

    def trigger(self) -> None:
        self._event.set()

    def __call__(self) -> bool:
        return self._event.is_set()
