"""A minimal JavaScript interpreter + DOM for executing the web UIs in tests.

The reference drives its spawner UI through real browsers with Selenium
(testing/test_jwa.py — 423 LoC of WebDriver). This container has no
browser and no node, so the capability is rebuilt as infrastructure: a
tree-walking interpreter for the ES2017 subset the in-tree UIs use
(arrow functions, async/await over a real microtask/macrotask event
loop — see EventLoop, template literals, for-of with array
destructuring, try/catch, regex literals, spread) plus
a DOM with enough fidelity for the pages (createElement/appendChild,
getElementById, querySelectorAll with tag/#id/.class/descendant and
:checked, innerHTML parse/serialize, event listeners, forms/FormData)
and a `fetch` bridged straight into a platform Router.

Tests execute the REAL `<script>` payloads served by
webapps/dashboard_ui.py and jwa_ui.py against the real backends: a test
fails when the registration-flow JS breaks — the VERDICT #5 bar.

This is NOT a general JS engine. Unsupported syntax raises JSError at
parse time, loudly; growing the subset is preferable to silently
mis-executing.
"""

from __future__ import annotations

import html.parser
import json as _json
import re as _re
from typing import Any

# ---------------------------------------------------------------------------
# values


class JSUndefined:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "undefined"

    def __bool__(self):
        return False


undefined = JSUndefined()


class JSError(Exception):
    """Parse/runtime error in the harness itself."""


class JSThrow(Exception):
    """A JS `throw`: .value is the thrown JS value."""

    def __init__(self, value):
        super().__init__(js_str(value))
        self.value = value


class JSObject(dict):
    """Plain JS object: property bag."""


def new_error(message) -> JSObject:
    return JSObject({"name": "Error", "message": message})


class JSFunction:
    def __init__(self, params, body, env, interp, *, is_arrow=False,
                 is_async=False, name="", is_expr_body=False):
        self.params = params        # list of (name, default|None, rest:bool)
        self.body = body
        self.env = env
        self.interp = interp
        self.is_arrow = is_arrow
        self.is_async = is_async
        self.name = name
        self.is_expr_body = is_expr_body

    def call(self, args, this=undefined):
        return self.interp.call_function(self, args, this)


class EventLoop:
    """Microtask + macrotask queues (VERDICT r4 weak #5: the round-3
    harness resolved promises eagerly, so `await`/`then` ordering races
    in the very fetch-then-render flows the UI tests exercise were
    untestable by construction). The harness drains at every entry point
    (script run, user action, timer fire), and `await` on a pending
    promise drains until it settles — handler ordering follows queue
    discipline, matching what Selenium observes against a real browser
    (reference: testing/test_jwa.py state-transition waits)."""

    def __init__(self):
        import collections

        self.microtasks = collections.deque()
        self.macrotasks = collections.deque()
        # rejected promises born on THIS loop (scoped per interpreter:
        # a rejection leaking past one Browser's last drain must not
        # fail an unrelated Browser's next entry point)
        self.unhandled: list["JSPromise"] = []

    def microtask(self, fn) -> None:
        self.microtasks.append(fn)

    def macrotask(self, fn) -> None:
        self.macrotasks.append(fn)

    def _step(self) -> bool:
        if self.microtasks:
            self.microtasks.popleft()()
            return True
        if self.macrotasks:
            self.macrotasks.popleft()()
            return True
        return False

    def drain(self) -> None:
        while self._step():
            pass

    def drain_until(self, done) -> None:
        while not done():
            if not self._step():
                raise JSError("await on a promise that can never settle "
                              "(event loop exhausted)")


# Rejected promises register at settle time — on their loop when known,
# else here; _handled flips when a reaction (then/catch/finally/await)
# attaches. Harness entry points call check_unhandled_rejections() after
# draining — an unhandled rejection must FAIL the test, not vanish (the
# harness's worst failure mode).
_UNHANDLED_REJECTIONS: list["JSPromise"] = []


def check_unhandled_rejections(loop: "EventLoop | None" = None) -> None:
    pend = [p for p in _UNHANDLED_REJECTIONS if not p._handled]
    _UNHANDLED_REJECTIONS.clear()
    if loop is not None:
        pend += [p for p in loop.unhandled if not p._handled]
        loop.unhandled.clear()
    if pend:
        raise JSThrow(pend[0].error)


class JSPromise:
    """Promise with a real pending state. Internal producers may still
    construct settled promises directly; every CONSUMER (then/catch/
    finally/await/Promise.all) defers its reactions through the event
    loop, so ordering is queue-driven, never eager."""

    PENDING, OK, ERR = 0, 1, 2

    def __init__(self, value=undefined, error=None, loop=None):
        self.state = self.ERR if error is not None else self.OK
        self.value = value
        self.error = error  # a JSThrow-able value or None
        self._callbacks: list = []  # (fn, loop) pairs awaiting settle
        self._handled = False
        self._loop: EventLoop | None = loop
        if self.state == self.ERR:
            self._register_rejection()

    def _register_rejection(self) -> None:
        (self._loop.unhandled if self._loop is not None
         else _UNHANDLED_REJECTIONS).append(self)

    @property
    def rejected(self):
        return self.state == self.ERR

    @classmethod
    def make_pending(cls, loop: "EventLoop | None" = None) -> "JSPromise":
        p = cls(loop=loop)
        p.state = cls.PENDING
        p.value = undefined
        p.error = None
        return p

    def on_settle(self, cb, loop: EventLoop) -> None:
        if self.state == self.PENDING:
            self._callbacks.append((cb, loop))
        else:
            loop.microtask(cb)

    def _flush(self) -> None:
        for cb, loop in self._callbacks:
            loop.microtask(cb)
        self._callbacks.clear()

    def settle_ok(self, v) -> None:
        if self.state != self.PENDING:
            return
        self.state, self.value = self.OK, v
        self._flush()

    def settle_err(self, e) -> None:
        if self.state != self.PENDING:
            return
        self.state, self.error = self.ERR, e
        self._register_rejection()
        self._flush()

    @staticmethod
    def resolve(v):
        if isinstance(v, JSPromise):
            return v
        return JSPromise(value=v)

    @staticmethod
    def reject(e, loop: "EventLoop | None" = None):
        return JSPromise(error=e, loop=loop)


def _call1(handler, arg):
    """Invoke a JS or python callback with one argument."""
    return handler.call([arg]) if isinstance(handler, JSFunction) \
        else handler(arg)


def _adopt(out: JSPromise, v, loop: EventLoop) -> None:
    """Settle `out` from a handler's return value, unwrapping promises
    (thenable adoption)."""
    if isinstance(v, JSPromise):
        v._handled = True

        def chain():
            if v.state == JSPromise.ERR:
                out.settle_err(v.error)
            else:
                out.settle_ok(v.value)

        v.on_settle(chain, loop)
    else:
        out.settle_ok(v)


def _then(p: JSPromise, on_ok, on_err, loop: EventLoop) -> JSPromise:
    """The one deferred reaction primitive: then/catch/finally and
    Promise.all all reduce to it."""
    p._handled = True
    out = JSPromise.make_pending(loop)

    def react():
        if p.state == JSPromise.ERR:
            if on_err is None:
                out.settle_err(p.error)
                return
            try:
                _adopt(out, _call1(on_err, p.error), loop)
            except JSThrow as t:
                out.settle_err(t.value)
        else:
            if on_ok is None:
                out.settle_ok(p.value)
                return
            try:
                _adopt(out, _call1(on_ok, p.value), loop)
            except JSThrow as t:
                out.settle_err(t.value)

    p.on_settle(react, loop)
    return out


def _raise_if_rejected(v):
    """Entry-point guard for values handed back to the harness: a
    settled-rejected promise raises immediately. Pending promises pass
    through — the caller drains the loop and
    check_unhandled_rejections() catches what settles rejected."""
    if isinstance(v, JSPromise) and v.rejected:
        v._handled = True
        raise JSThrow(v.error)
    return v


# ---------------------------------------------------------------------------
# lexer

_KEYWORDS = {
    "const", "let", "var", "function", "return", "if", "else", "for", "of",
    "in", "while", "break", "continue", "try", "catch", "finally", "throw",
    "new", "typeof", "async", "await", "true", "false", "null", "undefined",
    "delete", "instanceof", "do",
    # recognized only to FAIL loudly at parse time (unsupported subset)
    "class", "switch", "case", "extends", "super", "yield",
}

_PUNCT = [
    "...", "===", "!==", "**=", ">>>", "=>", "==", "!=", "<=", ">=", "&&",
    "||", "??", "?.", "++", "--", "+=", "-=", "*=", "/=", "%=", "**",
    "(", ")",
    "{", "}", "[", "]", ";", ",", ".", "?", ":", "=", "+", "-", "*", "/",
    "%", "<", ">", "!", "&", "|", "^", "~",
]

# tokens after which a `/` starts a REGEX literal, not division
_REGEX_PRECEDERS = {
    "=", "(", ",", "[", "{", ";", ":", "?", "&&", "||", "!", "==", "===",
    "!=", "!==", "return", "=>", "+", "typeof", "new", "throw",
}


def tokenize(src: str):
    toks: list[tuple[str, Any]] = []  # (kind, value); kind: num str tmpl re id kw punct
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c in " \t\r\n":
            i += 1
            continue
        if src.startswith("//", i):
            j = src.find("\n", i)
            i = n if j < 0 else j
            continue
        if src.startswith("/*", i):
            j = src.find("*/", i)
            if j < 0:
                raise JSError("unterminated block comment")
            i = j + 2
            continue
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            m = _re.match(r"\d*\.?\d+(?:[eE][+-]?\d+)?", src[i:])
            text = m.group(0)
            toks.append(("num", float(text) if ("." in text or "e" in text
                                               or "E" in text) else int(text)))
            i += len(text)
            continue
        if c in "'\"":
            j, out = i + 1, []
            while j < n and src[j] != c:
                if src[j] == "\\":
                    out.append(_unescape(src[j + 1]))
                    j += 2
                else:
                    out.append(src[j])
                    j += 1
            if j >= n:
                raise JSError("unterminated string")
            toks.append(("str", "".join(out)))
            i = j + 1
            continue
        if c == "`":
            parts, j, buf = [], i + 1, []  # parts: ("str", s) | ("expr", toks)
            while j < n and src[j] != "`":
                if src[j] == "\\":
                    buf.append(_unescape(src[j + 1]))
                    j += 2
                elif src.startswith("${", j):
                    parts.append(("str", "".join(buf)))
                    buf = []
                    depth, k = 1, j + 2
                    while k < n and depth:
                        if src[k] == "{":
                            depth += 1
                        elif src[k] == "}":
                            depth -= 1
                        k += 1
                    parts.append(("expr", tokenize(src[j + 2:k - 1])))
                    j = k
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise JSError("unterminated template literal")
            parts.append(("str", "".join(buf)))
            toks.append(("tmpl", parts))
            i = j + 1
            continue
        if c == "/" and _regex_ok(toks):
            j, in_cls = i + 1, False
            while j < n:
                if src[j] == "\\":
                    j += 2
                    continue
                if src[j] == "[":
                    in_cls = True
                elif src[j] == "]":
                    in_cls = False
                elif src[j] == "/" and not in_cls:
                    break
                j += 1
            if j >= n:
                raise JSError("unterminated regex literal")
            body = src[i + 1:j]
            k = j + 1
            while k < n and src[k].isalpha():
                k += 1
            toks.append(("re", (body, src[j + 1:k])))
            i = k
            continue
        if c.isalpha() or c in "_$":
            m = _re.match(r"[A-Za-z_$][A-Za-z0-9_$]*", src[i:])
            word = m.group(0)
            toks.append(("kw" if word in _KEYWORDS else "id", word))
            i += len(word)
            continue
        for p in _PUNCT:
            if src.startswith(p, i):
                toks.append(("punct", p))
                i += len(p)
                break
        else:
            raise JSError(f"unexpected character {c!r} at {i}")
    toks.append(("eof", None))
    return toks


def _unescape(c: str) -> str:
    return {"n": "\n", "t": "\t", "r": "\r", "0": "\0"}.get(c, c)


def _regex_ok(toks) -> bool:
    for kind, val in reversed(toks):
        return kind in ("punct", "kw") and val in _REGEX_PRECEDERS
    return True  # start of input


# ---------------------------------------------------------------------------
# parser (Pratt for expressions, recursive descent for statements)


class Parser:
    def __init__(self, toks):
        self.toks = toks
        self.i = 0

    def peek(self, k=0):
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def at(self, kind, val=None):
        t = self.peek()
        return t[0] == kind and (val is None or t[1] == val)

    def eat(self, kind, val=None):
        if not self.at(kind, val):
            raise JSError(f"expected {kind} {val!r}, got {self.peek()!r} "
                          f"(tok {self.i})")
        t = self.toks[self.i]
        self.i += 1
        return t

    def opt(self, kind, val=None):
        if self.at(kind, val):
            self.i += 1
            return True
        return False

    # -- statements ---------------------------------------------------------

    def parse_program(self):
        body = []
        while not self.at("eof"):
            body.append(self.statement())
        return ("block", body)

    def statement(self):
        if self.opt("punct", ";"):
            return ("empty",)
        if self.at("punct", "{"):
            return self.block()
        if self.at("kw", "const") or self.at("kw", "let") or self.at("kw", "var"):
            s = self.var_decl()
            self.opt("punct", ";")
            return s
        if self.at("kw", "function") or (
                self.at("kw", "async") and self.peek(1) == ("kw", "function")):
            is_async = self.opt("kw", "async")
            self.eat("kw", "function")
            name = self.eat("id")[1]
            fn = self.function_rest(is_async=is_async, name=name)
            return ("fundecl", name, fn)
        if self.opt("kw", "if"):
            self.eat("punct", "(")
            cond = self.expression()
            self.eat("punct", ")")
            then = self.statement()
            alt = self.statement() if self.opt("kw", "else") else None
            return ("if", cond, then, alt)
        if self.opt("kw", "while"):
            self.eat("punct", "(")
            cond = self.expression()
            self.eat("punct", ")")
            return ("while", cond, self.statement())
        if self.opt("kw", "for"):
            return self.for_stmt()
        if self.opt("kw", "return"):
            if self.at("punct", ";") or self.at("punct", "}") or self.at("eof"):
                self.opt("punct", ";")
                return ("return", None)
            e = self.expression()
            self.opt("punct", ";")
            return ("return", e)
        if self.opt("kw", "throw"):
            e = self.expression()
            self.opt("punct", ";")
            return ("throw", e)
        if self.opt("kw", "break"):
            self.opt("punct", ";")
            return ("break",)
        if self.opt("kw", "continue"):
            self.opt("punct", ";")
            return ("continue",)
        if self.opt("kw", "try"):
            block = self.block()
            param, handler, fin = None, None, None
            if self.opt("kw", "catch"):
                if self.opt("punct", "("):
                    param = self.eat("id")[1]
                    self.eat("punct", ")")
                handler = self.block()
            if self.opt("kw", "finally"):
                fin = self.block()
            return ("try", block, param, handler, fin)
        e = self.expression()
        self.opt("punct", ";")
        return ("expr", e)

    def block(self):
        self.eat("punct", "{")
        body = []
        while not self.at("punct", "}"):
            body.append(self.statement())
        self.eat("punct", "}")
        return ("block", body)

    def var_decl(self):
        kind = self.eat("kw")[1]
        decls = []
        while True:
            decls.append(self.binding())
            if not self.opt("punct", ","):
                break
        return ("var", kind, decls)

    def binding(self):
        """(target, init): target is ('id', name) or ('arr', [names])."""
        if self.opt("punct", "["):
            names = []
            while not self.at("punct", "]"):
                names.append(self.eat("id")[1])
                if not self.opt("punct", ","):
                    break
            self.eat("punct", "]")
            target = ("arr", names)
        else:
            target = ("id", self.eat("id")[1])
        init = self.assignment() if self.opt("punct", "=") else None
        return (target, init)

    def for_stmt(self):
        self.eat("punct", "(")
        # for (const x of e) / for (const [a,b] of e) / classic for(;;)
        if self.at("kw", "const") or self.at("kw", "let") or self.at("kw", "var"):
            save = self.i
            self.eat("kw")
            if self.opt("punct", "["):
                names = []
                while not self.at("punct", "]"):
                    names.append(self.eat("id")[1])
                    if not self.opt("punct", ","):
                        break
                self.eat("punct", "]")
                target = ("arr", names)
            else:
                target = ("id", self.eat("id")[1])
            if self.opt("kw", "of"):
                iterable = self.expression()
                self.eat("punct", ")")
                return ("forof", target, iterable, self.statement())
            self.i = save  # classic for with declaration init
        init = None
        if not self.at("punct", ";"):
            if self.at("kw", "const") or self.at("kw", "let") or self.at("kw", "var"):
                init = self.var_decl()
            else:
                init = ("expr", self.expression())
        self.eat("punct", ";")
        cond = None if self.at("punct", ";") else self.expression()
        self.eat("punct", ";")
        step = None if self.at("punct", ")") else self.expression()
        self.eat("punct", ")")
        return ("for", init, cond, step, self.statement())

    # -- functions ----------------------------------------------------------

    def function_rest(self, is_async: bool, name: str = ""):
        self.eat("punct", "(")
        params = self.param_list()
        body = self.block()
        return ("func", params, body, is_async, False, name, False)

    def param_list(self):
        params = []
        while not self.at("punct", ")"):
            rest = self.opt("punct", "...")
            pname = self.eat("id")[1]
            default = self.assignment() if self.opt("punct", "=") else None
            params.append((pname, default, rest))
            if not self.opt("punct", ","):
                break
        self.eat("punct", ")")
        return params

    # -- expressions --------------------------------------------------------

    def expression(self):
        e = self.assignment()
        while self.at("punct", ","):
            # comma operator is rare in the UIs; treat as sequence
            self.eat("punct", ",")
            e = ("seq", e, self.assignment())
        return e

    def assignment(self):
        if self._arrow_ahead():
            return self.arrow()
        left = self.ternary()
        for op in ("=", "+=", "-=", "*=", "/=", "%="):
            if self.at("punct", op):
                self.eat("punct", op)
                right = self.assignment()
                return ("assign", op, left, right)
        return left

    def _arrow_ahead(self) -> bool:
        """Lookahead: `x =>`, `async x =>`, `(...) =>`, `async (...) =>`."""
        j = self.i
        if self.toks[j] == ("kw", "async"):
            j += 1
        t = self.toks[j]
        if t[0] == "id" and self.toks[j + 1] == ("punct", "=>"):
            return True
        if t == ("punct", "("):
            depth = 0
            while j < len(self.toks):
                tk = self.toks[j]
                if tk == ("punct", "("):
                    depth += 1
                elif tk == ("punct", ")"):
                    depth -= 1
                    if depth == 0:
                        return self.toks[j + 1] == ("punct", "=>")
                elif tk[0] == "eof":
                    return False
                j += 1
        return False

    def arrow(self):
        is_async = self.opt("kw", "async")
        if self.at("id"):
            params = [(self.eat("id")[1], None, False)]
        else:
            self.eat("punct", "(")
            params = self.param_list()
        self.eat("punct", "=>")
        if self.at("punct", "{"):
            body = self.block()
            return ("func", params, body, is_async, True, "", False)
        body = self.assignment()
        return ("func", params, body, is_async, True, "", True)

    def ternary(self):
        cond = self.nullish()
        if self.opt("punct", "?"):
            a = self.assignment()
            self.eat("punct", ":")
            b = self.assignment()
            return ("cond", cond, a, b)
        return cond

    def nullish(self):
        e = self.logic_or()
        while self.opt("punct", "??"):
            e = ("nullish", e, self.logic_or())
        return e

    def logic_or(self):
        e = self.logic_and()
        while self.opt("punct", "||"):
            e = ("or", e, self.logic_and())
        return e

    def logic_and(self):
        e = self.equality()
        while self.opt("punct", "&&"):
            e = ("and", e, self.equality())
        return e

    def equality(self):
        e = self.relational()
        while True:
            for op in ("===", "!==", "==", "!="):
                if self.at("punct", op):
                    self.eat("punct", op)
                    e = ("bin", op, e, self.relational())
                    break
            else:
                return e

    def relational(self):
        e = self.additive()
        while True:
            for op in ("<=", ">=", "<", ">"):
                if self.at("punct", op):
                    self.eat("punct", op)
                    e = ("bin", op, e, self.additive())
                    break
            else:
                if self.opt("kw", "instanceof"):
                    e = ("bin", "instanceof", e, self.additive())
                    continue
                if self.opt("kw", "in"):
                    e = ("bin", "in", e, self.additive())
                    continue
                return e

    def additive(self):
        e = self.multiplicative()
        while self.at("punct", "+") or self.at("punct", "-"):
            op = self.eat("punct")[1]
            e = ("bin", op, e, self.multiplicative())
        return e

    def multiplicative(self):
        e = self.exponent()
        while self.at("punct", "*") or self.at("punct", "/") or self.at("punct", "%"):
            op = self.eat("punct")[1]
            e = ("bin", op, e, self.exponent())
        return e

    def exponent(self):
        e = self.unary()
        if self.at("punct", "**"):
            self.eat("punct", "**")
            return ("bin", "**", e, self.exponent())  # right-assoc
        return e

    def unary(self):
        if self.at("punct", "!"):
            self.eat("punct", "!")
            return ("not", self.unary())
        if self.at("punct", "-"):
            self.eat("punct", "-")
            return ("neg", self.unary())
        if self.at("punct", "+"):
            self.eat("punct", "+")
            return ("tonum", self.unary())
        if self.opt("kw", "typeof"):
            return ("typeof", self.unary())
        if self.opt("kw", "await"):
            return ("await", self.unary())
        if self.opt("kw", "delete"):
            return ("delete", self.unary())
        if self.opt("kw", "new"):
            callee = self.member_chain(self.primary(), no_call=True)
            args = []
            if self.opt("punct", "("):
                args = self.arguments()
            # member/call chains continue off the constructed object:
            # new FormData(f).entries()
            return self.member_chain(("new", callee, args))
        if self.at("punct", "++") or self.at("punct", "--"):
            op = self.eat("punct")[1]
            return ("preinc", op, self.unary())
        e = self.postfix()
        return e

    def postfix(self):
        e = self.member_chain(self.primary())
        if self.at("punct", "++") or self.at("punct", "--"):
            op = self.eat("punct")[1]
            return ("postinc", op, e)
        return e

    def member_chain(self, e, no_call=False):
        while True:
            if self.opt("punct", "."):
                e = ("member", e, self.eat_name(), False)
            elif self.opt("punct", "?."):
                e = ("member", e, self.eat_name(), True)
            elif self.opt("punct", "["):
                idx = self.expression()
                self.eat("punct", "]")
                e = ("index", e, idx)
            elif not no_call and self.at("punct", "("):
                self.eat("punct", "(")
                e = ("call", e, self.arguments())
            else:
                return e

    def eat_name(self) -> str:
        t = self.peek()
        if t[0] in ("id", "kw"):
            self.i += 1
            return t[1]
        raise JSError(f"expected property name, got {t!r}")

    def arguments(self):
        args = []
        while not self.at("punct", ")"):
            if self.opt("punct", "..."):
                args.append(("spread", self.assignment()))
            else:
                args.append(self.assignment())
            if not self.opt("punct", ","):
                break
        self.eat("punct", ")")
        return args

    def primary(self):
        t = self.peek()
        if t[0] == "num" or t[0] == "str":
            self.i += 1
            return ("lit", t[1])
        if t[0] == "re":
            self.i += 1
            return ("regex", t[1])
        if t[0] == "tmpl":
            self.i += 1
            parts = []
            for kind, payload in t[1]:
                if kind == "str":
                    parts.append(("lit", payload))
                else:
                    parts.append(Parser(payload).expression())
            return ("tmplexpr", parts)
        if t == ("kw", "true"):
            self.i += 1
            return ("lit", True)
        if t == ("kw", "false"):
            self.i += 1
            return ("lit", False)
        if t == ("kw", "null"):
            self.i += 1
            return ("lit", None)
        if t == ("kw", "undefined"):
            self.i += 1
            return ("lit", undefined)
        if t == ("kw", "function") or (
                t == ("kw", "async") and self.peek(1) == ("kw", "function")):
            is_async = self.opt("kw", "async")
            self.eat("kw", "function")
            name = self.eat("id")[1] if self.at("id") else ""
            return self.function_rest(is_async=is_async, name=name)
        if t == ("punct", "("):
            self.eat("punct", "(")
            e = self.expression()
            self.eat("punct", ")")
            return e
        if t == ("punct", "["):
            self.eat("punct", "[")
            items = []
            while not self.at("punct", "]"):
                if self.opt("punct", "..."):
                    items.append(("spread", self.assignment()))
                else:
                    items.append(self.assignment())
                if not self.opt("punct", ","):
                    break
            self.eat("punct", "]")
            return ("array", items)
        if t == ("punct", "{"):
            self.eat("punct", "{")
            props = []
            while not self.at("punct", "}"):
                if self.opt("punct", "..."):
                    props.append(("spread", self.assignment()))
                elif self.at("punct", "["):
                    self.eat("punct", "[")
                    key = self.expression()
                    self.eat("punct", "]")
                    self.eat("punct", ":")
                    props.append((("computed", key), self.assignment()))
                else:
                    kt = self.peek()
                    if kt[0] in ("id", "kw", "str", "num"):
                        self.i += 1
                        key = str(kt[1])
                    else:
                        raise JSError(f"bad object key {kt!r}")
                    if self.opt("punct", ":"):
                        props.append((key, self.assignment()))
                    elif self.at("punct", "("):  # method shorthand
                        props.append((key, self.function_rest(is_async=False,
                                                              name=key)))
                    else:  # shorthand {a}
                        props.append((key, ("name", key)))
                if not self.opt("punct", ","):
                    break
            self.eat("punct", "}")
            return ("object", props)
        if t[0] == "id":
            self.i += 1
            return ("name", t[1])
        raise JSError(f"unexpected token {t!r}")


# ---------------------------------------------------------------------------
# control-flow signals


class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


# ---------------------------------------------------------------------------
# interpreter


def js_truthy(v) -> bool:
    if v is undefined or v is None:
        return False
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return v != 0
    if isinstance(v, str):
        return v != ""
    return True


def js_str(v) -> str:
    if v is undefined:
        return "undefined"
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if v != v:
            return "NaN"
        if v == float("inf"):
            return "Infinity"
        if v == float("-inf"):
            return "-Infinity"
        if v == int(v):
            return str(int(v))
        return repr(v)
    if isinstance(v, list):
        return ",".join("" if x is undefined or x is None else js_str(x)
                        for x in v)
    if isinstance(v, JSObject):
        if "message" in v and v.get("name") == "Error":
            return f"Error: {js_str(v['message'])}"
        return "[object Object]"
    return str(v)


def js_num(v) -> float:
    if isinstance(v, bool):
        return 1 if v else 0
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        s = v.strip()
        if s == "":
            return 0
        try:
            return int(s)
        except ValueError:
            try:
                return float(s)
            except ValueError:
                return float("nan")
    if v is None:
        return 0
    return float("nan")


def js_eq_loose(a, b) -> bool:
    if (a is None or a is undefined) and (b is None or b is undefined):
        return True
    if a is None or a is undefined or b is None or b is undefined:
        return False
    if isinstance(a, (dict, list)) or isinstance(b, (dict, list)):
        return a is b  # loose == on two objects is still identity in JS
    if type(a) is type(b) or (isinstance(a, (int, float))
                              and isinstance(b, (int, float))):
        return a == b
    return js_num(a) == js_num(b)


class Env:
    __slots__ = ("vars", "parent")

    def __init__(self, parent=None):
        self.vars: dict[str, Any] = {}
        self.parent = parent

    def get(self, name):
        e = self
        while e is not None:
            if name in e.vars:
                return e.vars[name]
            e = e.parent
        raise JSThrow(new_error(f"{name} is not defined"))

    def set(self, name, value):
        e = self
        while e is not None:
            if name in e.vars:
                e.vars[name] = value
                return
            e = e.parent
        # implicit global (sloppy mode)
        root = self
        while root.parent is not None:
            root = root.parent
        root.vars[name] = value

    def declare(self, name, value):
        self.vars[name] = value


class Interpreter:
    def __init__(self, global_env: Env):
        self.genv = global_env
        self.loop = EventLoop()

    # -- function invocation ------------------------------------------------

    def call_function(self, fn, args, this=undefined):
        if callable(fn) and not isinstance(fn, JSFunction):
            return fn(*args)
        env = Env(fn.env)
        if not fn.is_arrow:  # arrows keep the LEXICAL this
            env.declare("this", this)
            env.declare("arguments", list(args))
        for i, (pname, default, rest) in enumerate(fn.params):
            if rest:
                env.declare(pname, list(args[i:]))
                break
            v = args[i] if i < len(args) else undefined
            if v is undefined and default is not None:
                v = self.eval(default, env)
            env.declare(pname, v)

        def run():
            if fn.is_expr_body:
                return self.eval(fn.body, env)
            try:
                self.exec(fn.body, env)
            except _Return as r:
                return r.value
            return undefined

        if fn.is_async:
            try:
                return JSPromise.resolve(run())
            except JSThrow as t:
                return JSPromise.reject(t.value, self.loop)
        return run()

    def make_function(self, node, env):
        _, params, body, is_async, is_arrow, name, is_expr = node
        return JSFunction(params, body, env, self, is_arrow=is_arrow,
                          is_async=is_async, name=name, is_expr_body=is_expr)

    # -- statements ---------------------------------------------------------

    def exec(self, node, env):
        op = node[0]
        if op == "block":
            benv = Env(env)
            # function declarations hoist within the block
            for s in node[1]:
                if s[0] == "fundecl":
                    benv.declare(s[1], self.make_function(s[2], benv))
            for s in node[1]:
                self.exec(s, benv)
        elif op == "expr":
            _raise_if_rejected(self.eval(node[1], env))
        elif op == "var":
            for target, init in node[2]:
                v = self.eval(init, env) if init is not None else undefined
                self._bind(target, v, env)
        elif op == "fundecl":
            pass  # hoisted in block
        elif op == "if":
            if js_truthy(self.eval(node[1], env)):
                self.exec(node[2], env)
            elif node[3] is not None:
                self.exec(node[3], env)
        elif op == "while":
            while js_truthy(self.eval(node[1], env)):
                try:
                    self.exec(node[2], env)
                except _Break:
                    break
                except _Continue:
                    continue
        elif op == "for":
            fenv = Env(env)
            if node[1] is not None:
                self.exec(node[1], fenv)
            while node[2] is None or js_truthy(self.eval(node[2], fenv)):
                try:
                    self.exec(node[4], fenv)
                except _Break:
                    break
                except _Continue:
                    pass
                if node[3] is not None:
                    self.eval(node[3], fenv)
        elif op == "forof":
            it = self.eval(node[2], env)
            if isinstance(it, JSObject):
                raise JSThrow(new_error("object is not iterable"))
            if it is undefined or it is None:
                raise JSThrow(new_error("iterable is null/undefined"))
            for item in list(it):
                fenv = Env(env)
                self._bind(node[1], item, fenv)
                try:
                    self.exec(node[3], fenv)
                except _Break:
                    break
                except _Continue:
                    continue
        elif op == "return":
            raise _Return(self.eval(node[1], env)
                          if node[1] is not None else undefined)
        elif op == "throw":
            raise JSThrow(self.eval(node[1], env))
        elif op == "break":
            raise _Break()
        elif op == "continue":
            raise _Continue()
        elif op == "try":
            _, block, param, handler, fin = node
            try:
                try:
                    self.exec(block, env)
                except JSThrow as t:
                    if handler is None:
                        raise
                    henv = Env(env)
                    if param:
                        henv.declare(param, t.value)
                    self.exec(handler, henv)
            finally:
                if fin is not None:
                    self.exec(fin, env)
        elif op == "empty":
            pass
        else:
            raise JSError(f"unknown statement {op}")

    def _bind(self, target, value, env):
        if target[0] == "id":
            env.declare(target[1], value)
        else:  # ("arr", names)
            seq = value if isinstance(value, (list, tuple)) else []
            for k, nm in enumerate(target[1]):
                env.declare(nm, seq[k] if k < len(seq) else undefined)

    # -- expressions --------------------------------------------------------

    def eval(self, node, env):
        op = node[0]
        if op == "lit":
            return node[1]
        if op == "name":
            return env.get(node[1])
        if op == "tmplexpr":
            return "".join(js_str(self.eval(p, env)) for p in node[1])
        if op == "regex":
            body, flags = node[1]
            return JSRegExp(body, flags)
        if op == "array":
            out = []
            for item in node[1]:
                if item[0] == "spread":
                    out.extend(list(self.eval(item[1], env)))
                else:
                    out.append(self.eval(item, env))
            return out
        if op == "object":
            o = JSObject()
            for key, vexpr in node[1]:
                if key == "spread":
                    src = self.eval(vexpr, env)
                    if isinstance(src, dict):
                        o.update(src)
                    continue
                if isinstance(key, tuple) and key[0] == "computed":
                    key = js_str(self.eval(key[1], env))
                o[key] = self.eval(vexpr, env)
            return o
        if op == "func":
            return self.make_function(node, env)
        if op == "seq":
            self.eval(node[1], env)
            return self.eval(node[2], env)
        if op == "cond":
            return (self.eval(node[2], env) if js_truthy(self.eval(node[1], env))
                    else self.eval(node[3], env))
        if op == "or":
            v = self.eval(node[1], env)
            return v if js_truthy(v) else self.eval(node[2], env)
        if op == "and":
            v = self.eval(node[1], env)
            return self.eval(node[2], env) if js_truthy(v) else v
        if op == "nullish":
            v = self.eval(node[1], env)
            return self.eval(node[2], env) if v is None or v is undefined else v
        if op == "not":
            return not js_truthy(self.eval(node[1], env))
        if op == "neg":
            return -js_num(self.eval(node[1], env))
        if op == "tonum":
            return js_num(self.eval(node[1], env))
        if op == "typeof":
            try:
                v = self.eval(node[1], env)
            except JSThrow:
                # JS only special-cases an unresolvable *reference*;
                # typeof obj.missing.deep must propagate the TypeError
                if node[1][0] == "name":
                    return "undefined"
                raise
            if v is undefined:
                return "undefined"
            if v is None:
                return "object"
            if isinstance(v, bool):
                return "boolean"
            if isinstance(v, (int, float)):
                return "number"
            if isinstance(v, str):
                return "string"
            if isinstance(v, JSFunction) or callable(v):
                return "function"
            return "object"
        if op == "await":
            v = self.eval(node[1], env)
            if isinstance(v, JSPromise):
                if v.state == JSPromise.PENDING:
                    # cooperative await: run OTHER queued reactions until
                    # this promise settles — the interleaving real async
                    # code observes (note the enclosing async fn still
                    # runs to completion before its caller resumes; true
                    # continuation suspension is out of scope)
                    self.loop.drain_until(
                        lambda: v.state != JSPromise.PENDING)
                v._handled = True
                if v.state == JSPromise.ERR:
                    raise JSThrow(v.error)
                return v.value
            return v
        if op == "delete":
            t = node[1]
            if t[0] == "member":
                obj = self.eval(t[1], env)
                if isinstance(obj, dict):
                    obj.pop(t[2], None)
            elif t[0] == "index":
                obj = self.eval(t[1], env)
                key = self.eval(t[2], env)
                if isinstance(obj, dict):
                    obj.pop(js_str(key), None)
            return True
        if op == "bin":
            return self._binop(node[1], node[2], node[3], env)
        if op == "assign":
            return self._assign(node[1], node[2], node[3], env)
        if op in ("preinc", "postinc"):
            delta = 1 if node[1] == "++" else -1
            old = js_num(self.eval(node[2], env))
            self._assign("=", node[2], ("lit", old + delta), env)
            return old + delta if op == "preinc" else old
        if op == "member":
            obj = self.eval(node[1], env)
            if node[3] and (obj is undefined or obj is None):
                return undefined
            return self.get_member(obj, node[2])
        if op == "index":
            obj = self.eval(node[1], env)
            key = self.eval(node[2], env)
            if isinstance(obj, list) and isinstance(key, (int, float)):
                k = int(key)
                return obj[k] if 0 <= k < len(obj) else undefined
            if isinstance(obj, str) and isinstance(key, (int, float)):
                k = int(key)
                return obj[k] if 0 <= k < len(obj) else undefined
            return self.get_member(obj, js_str(key))
        if op == "call":
            return self._call(node, env)
        if op == "new":
            ctor = self.eval(node[1], env)
            args = [self.eval(a, env) for a in node[2]]
            if isinstance(ctor, JSFunction):
                this = JSObject()
                r = ctor.call(args, this=this)
                return r if isinstance(r, (JSObject, list)) else this
            if callable(ctor):
                return ctor(*args)
            raise JSThrow(new_error("not a constructor"))
        raise JSError(f"unknown expression {op}")

    def _binop(self, op, ln, rn, env):
        a = self.eval(ln, env)
        b = self.eval(rn, env)
        if op == "+":
            if isinstance(a, str) or isinstance(b, str) or \
                    isinstance(a, (list, JSObject)) or isinstance(b, (list, JSObject)):
                return js_str(a) + js_str(b)
            return js_num(a) + js_num(b)
        if op == "-":
            return js_num(a) - js_num(b)
        if op == "*":
            return js_num(a) * js_num(b)
        if op == "/":
            d = js_num(b)
            if d == 0:
                return float("inf") if js_num(a) > 0 else float("-inf") \
                    if js_num(a) < 0 else float("nan")
            return js_num(a) / d
        if op == "%":
            d = js_num(b)
            if d == 0:
                return float("nan")
            import math

            return math.fmod(js_num(a), d)  # JS takes the dividend's sign
        if op == "**":
            return js_num(a) ** js_num(b)
        if op == "===":
            return self._strict_eq(a, b)
        if op == "!==":
            return not self._strict_eq(a, b)
        if op == "==":
            return js_eq_loose(a, b)
        if op == "!=":
            return not js_eq_loose(a, b)
        if op in ("<", ">", "<=", ">="):
            if isinstance(a, str) and isinstance(b, str):
                pass
            else:
                a, b = js_num(a), js_num(b)
            return {"<": a < b, ">": a > b, "<=": a <= b, ">=": a >= b}[op]
        if op == "instanceof":
            return isinstance(a, JSObject) and isinstance(b, (JSFunction,)) \
                or (b is self.genv.vars.get("Error")
                    and isinstance(a, JSObject) and a.get("name") == "Error")
        if op == "in":
            return js_str(a) in b if isinstance(b, dict) else False
        raise JSError(f"unknown binop {op}")

    @staticmethod
    def _strict_eq(a, b):
        if isinstance(a, bool) != isinstance(b, bool):
            return False
        if a is undefined or a is None or b is undefined or b is None:
            return a is b
        # JS === is reference identity for objects/arrays/functions
        if isinstance(a, (dict, list, JSFunction)) or \
                isinstance(b, (dict, list, JSFunction)):
            return a is b
        return a == b

    def _assign(self, op, left, rnode, env):
        value = self.eval(rnode, env)
        if op != "=":
            cur = self.eval(left, env)
            base = {"+=": "+", "-=": "-", "*=": "*", "/=": "/", "%=": "%"}[op]
            value = self._binop(base, ("lit", cur), ("lit", value), env)
        if left[0] == "name":
            env.set(left[1], value)
        elif left[0] == "member":
            obj = self.eval(left[1], env)
            self.set_member(obj, left[2], value)
        elif left[0] == "index":
            obj = self.eval(left[1], env)
            key = self.eval(left[2], env)
            if isinstance(obj, list) and isinstance(key, (int, float)):
                k = int(key)
                while len(obj) <= k:
                    obj.append(undefined)
                obj[k] = value
            else:
                self.set_member(obj, js_str(key), value)
        else:
            raise JSError(f"bad assignment target {left[0]}")
        return value

    def _call(self, node, env):
        _, callee, argnodes = node
        args = []
        for a in argnodes:
            if a[0] == "spread":
                args.extend(list(self.eval(a[1], env)))
            else:
                args.append(self.eval(a, env))
        # method call: bind `this`
        if callee[0] == "member":
            obj = self.eval(callee[1], env)
            if callee[3] and (obj is undefined or obj is None):
                return undefined
            fn = self.get_member(obj, callee[2])
            if fn is undefined:
                raise JSThrow(new_error(
                    f"{callee[2]} is not a function on {type(obj).__name__}"))
            if isinstance(fn, JSFunction):
                return fn.call(args, this=obj)
            return fn(*args)
        fn = self.eval(callee, env)
        if isinstance(fn, JSFunction):
            return fn.call(args)
        if callable(fn):
            return fn(*args)
        raise JSThrow(new_error("not a function"))

    # -- member access (builtin method tables) ------------------------------

    def get_member(self, obj, name):
        if obj is undefined or obj is None:
            raise JSThrow(new_error(
                f"cannot read property {name!r} of {js_str(obj)}"))
        if isinstance(obj, JSPromise):
            return _promise_member(obj, name, self)
        if isinstance(obj, str):
            return _string_member(obj, name)
        if isinstance(obj, list):
            return _array_member(obj, name, self)
        if isinstance(obj, (int, float)) and not isinstance(obj, bool):
            return _number_member(obj, name)
        if isinstance(obj, JSRegExp):
            return getattr(obj, name)
        if isinstance(obj, JSObject):
            if name in obj:
                return obj[name]
            return undefined
        if isinstance(obj, dict):
            return obj.get(name, undefined)
        # host objects (DOM elements, fetch responses, ...) expose
        # python attributes/properties directly
        try:
            return getattr(obj, name)
        except AttributeError:
            return undefined

    def set_member(self, obj, name, value):
        if isinstance(obj, dict):
            obj[name] = value
            return
        setattr(obj, name, value)


class JSRegExp:
    def __init__(self, body, flags):
        self.source = body
        if isinstance(flags, str):
            unknown = set(flags) - set("gims")
            if unknown:
                raise JSError(f"unsupported regex flags {''.join(unknown)!r}")
            self.global_ = "g" in flags
            pyflags = (_re.IGNORECASE if "i" in flags else 0) | \
                (_re.MULTILINE if "m" in flags else 0) | \
                (_re.DOTALL if "s" in flags else 0)
        else:  # legacy int flags
            self.global_ = False
            pyflags = flags
        self._rx = _re.compile(_js_regex_to_py(body), pyflags)

    def test(self, s=""):
        return self._rx.search(js_str(s)) is not None

    def exec(self, s=""):
        m = self._rx.search(js_str(s))
        if m is None:
            return None
        return [m.group(0)] + [g if g is not None else undefined
                               for g in m.groups()]


def _js_regex_to_py(body: str) -> str:
    # the UI regexes are plain ERE-compatible; pass through
    return body


def _string_member(s: str, name):
    simple = {
        "length": len(s),
    }
    if name in simple:
        return simple[name]
    table = {
        "trim": lambda: s.strip(),
        "toLowerCase": lambda: s.lower(),
        "toUpperCase": lambda: s.upper(),
        "includes": lambda sub="": js_str(sub) in s,
        "startsWith": lambda sub="": s.startswith(js_str(sub)),
        "endsWith": lambda sub="": s.endswith(js_str(sub)),
        "indexOf": lambda sub="": s.find(js_str(sub)),
        "slice": lambda a=0, b=None: s[_slice(a, b, len(s))],
        "substring": lambda a=0, b=None: s[_slice(a, b, len(s))],
        "split": lambda sep=undefined: _js_split(s, sep),
        "replace": lambda pat, rep: (
            pat._rx.sub(_js_replacement(rep), s,
                        count=0 if pat.global_ else 1)
            if isinstance(pat, JSRegExp) else s.replace(js_str(pat),
                                                        js_str(rep), 1)),
        "replaceAll": lambda pat, rep: (
            pat._rx.sub(_js_replacement(rep), s)
            if isinstance(pat, JSRegExp)
            else s.replace(js_str(pat), js_str(rep))),
        "charAt": lambda i=0: s[int(i)] if 0 <= int(i) < len(s) else "",
        "repeat": lambda k: s * int(k),
        "padStart": lambda w, c=" ": s.rjust(int(w), js_str(c)),
        "match": lambda rx: rx.exec(s) if isinstance(rx, JSRegExp) else None,
        "concat": lambda *a: s + "".join(js_str(x) for x in a),
        "toString": lambda: s,
    }
    if name in table:
        return table[name]
    return undefined


def _js_split(s: str, sep):
    if sep is undefined:
        return [s]  # JS no-arg split does NOT char the string
    if isinstance(sep, JSRegExp):
        return sep._rx.split(s)
    sep = js_str(sep)
    if sep == "":
        return list(s)
    return s.split(sep)


def _js_replacement(rep) -> str:
    """JS $n/$& replacement tokens -> Python re templates."""
    out = _re.sub(r"\$(\d+)", r"\\\1", js_str(rep))
    out = out.replace("$&", "\\g<0>")
    return out


def _slice(a, b, n):
    a = int(js_num(a)) if a is not None and a is not undefined else 0
    if a < 0:
        a += n
    if b is None or b is undefined:
        return slice(max(a, 0), None)
    b = int(js_num(b))
    if b < 0:
        b += n
    return slice(max(a, 0), max(b, 0))


def _array_member(arr: list, name, interp):
    def call(f, *a):
        return f.call(list(a)) if isinstance(f, JSFunction) else f(*a)

    if name == "length":
        return len(arr)
    table = {
        "push": lambda *a: (arr.extend(a), len(arr))[1],
        "pop": lambda: arr.pop() if arr else undefined,
        "shift": lambda: arr.pop(0) if arr else undefined,
        "unshift": lambda *a: (arr.__setitem__(slice(0, 0), list(a)),
                               len(arr))[1],
        "map": lambda f: [call(f, v, i) for i, v in enumerate(arr)],
        "filter": lambda f: [v for i, v in enumerate(arr)
                             if js_truthy(call(f, v, i))],
        "forEach": lambda f: ([call(f, v, i) for i, v in enumerate(arr)],
                              undefined)[1],
        "find": lambda f: next((v for i, v in enumerate(arr)
                                if js_truthy(call(f, v, i))), undefined),
        "findIndex": lambda f: next((i for i, v in enumerate(arr)
                                     if js_truthy(call(f, v, i))), -1),
        "some": lambda f: any(js_truthy(call(f, v, i))
                              for i, v in enumerate(arr)),
        "every": lambda f: all(js_truthy(call(f, v, i))
                               for i, v in enumerate(arr)),
        "includes": lambda v: any(Interpreter._strict_eq(x, v) for x in arr),
        "indexOf": lambda v: next(
            (i for i, x in enumerate(arr)
             if Interpreter._strict_eq(x, v)), -1),
        "join": lambda sep=",": js_str(sep).join(
            "" if v is undefined or v is None else js_str(v) for v in arr),
        "slice": lambda a=0, b=None: arr[_slice(a, b, len(arr))],
        "concat": lambda *a: arr + [x for chunk in a for x in
                                    (chunk if isinstance(chunk, list)
                                     else [chunk])],
        "reverse": lambda: (arr.reverse(), arr)[1],
        "flat": lambda: [x for v in arr for x in
                         (v if isinstance(v, list) else [v])],
        "sort": lambda f=None: (_js_sort(arr, f), arr)[1],
        "reduce": lambda f, init=undefined: _js_reduce(arr, f, init),
        "splice": lambda start, count=None, *items: _js_splice(
            arr, int(start), count, items),
        "toString": lambda: js_str(arr),
    }
    if name in table:
        return table[name]
    return undefined


def _js_sort(arr, f):
    import functools

    if f is None or f is undefined:
        arr.sort(key=js_str)
    else:
        arr.sort(key=functools.cmp_to_key(
            lambda a, b: (lambda r: -1 if r < 0 else (1 if r > 0 else 0))(
                js_num(f.call([a, b])))))


def _js_reduce(arr, f, init):
    it = iter(enumerate(arr))
    if init is undefined:
        _, acc = next(it)
    else:
        acc = init
    for i, v in it:
        acc = f.call([acc, v, i])
    return acc


def _js_splice(arr, start, count, items):
    if count is None or count is undefined:
        removed = arr[start:]
        arr[start:] = list(items)
    else:
        removed = arr[start:start + int(count)]
        arr[start:start + int(count)] = list(items)
    return removed


def _number_member(x, name):
    table = {
        "toFixed": lambda d=0: f"{x:.{int(d)}f}",
        "toString": lambda: js_str(x),
    }
    return table.get(name, undefined)


def _promise_member(p: JSPromise, name, interp):
    loop = interp.loop
    if name == "then":
        return lambda on_ok=None, on_err=None: _then(p, on_ok, on_err, loop)
    if name == "catch":
        return lambda on_err: _then(p, None, on_err, loop)
    if name == "finally":
        def fin(f):
            # runs on either outcome, passes the settlement through
            def ok(v):
                _call1(f, undefined)
                return v

            def err(e):
                _call1(f, undefined)
                raise JSThrow(e)

            return _then(p, ok, err, loop)
        return fin
    return undefined


# ---------------------------------------------------------------------------
# DOM

_VOID_TAGS = {"br", "hr", "img", "input", "meta", "link"}


class Element:
    def __init__(self, tag: str, doc: "Document"):
        self.tagName = tag.upper()
        self.tag = tag.lower()
        self._doc = doc
        self.attrs: dict[str, str] = {}
        self.children: list[Element] = []
        self.parent: "Element | None" = None
        self._text = ""          # for text nodes (tag == "#text")
        self._listeners: dict[str, list] = {}
        self.dataset = JSObject()
        # live property bag for value/checked/disabled/selected etc.
        self._props: dict[str, Any] = {}

    # -- tree ---------------------------------------------------------------

    def appendChild(self, child: "Element"):
        if child.parent is not None:
            child.parent.children.remove(child)
        child.parent = self
        self.children.append(child)
        return child

    def append(self, *children):
        for c in children:
            if isinstance(c, str):
                c = self._doc.createTextNode(c)
            self.appendChild(c)

    def removeChild(self, child):
        self.children.remove(child)
        child.parent = None
        return child

    def remove(self):
        if self.parent is not None:
            self.parent.removeChild(self)

    # -- text/html ----------------------------------------------------------

    @property
    def textContent(self):
        if self.tag == "#text":
            return self._text
        return "".join(c.textContent for c in self.children)

    @textContent.setter
    def textContent(self, v):
        if self.tag == "#text":
            self._text = js_str(v)
            return
        self.children = []
        if js_str(v):
            t = self._doc.createTextNode(js_str(v))
            self.appendChild(t)

    @property
    def innerHTML(self):
        return "".join(_serialize(c) for c in self.children)

    @innerHTML.setter
    def innerHTML(self, v):
        self.children = []
        for node in _parse_fragment(js_str(v), self._doc):
            self.appendChild(node)

    # -- attributes / properties -------------------------------------------

    def getAttribute(self, name):
        return self.attrs.get(js_str(name), None)

    def setAttribute(self, name, value):
        name = js_str(name)
        self.attrs[name] = js_str(value)
        if name.startswith("data-"):
            self.dataset[_camel(name[5:])] = js_str(value)
        if name == "value":
            self._props.setdefault("value", js_str(value))

    def removeAttribute(self, name):
        self.attrs.pop(js_str(name), None)

    def hasAttribute(self, name):
        return js_str(name) in self.attrs

    @property
    def id(self):
        return self.attrs.get("id", "")

    @property
    def className(self):
        return self.attrs.get("class", "")

    @className.setter
    def className(self, v):
        self.attrs["class"] = js_str(v)

    @property
    def classList(self):
        el = self

        class _CL:
            def add(self, *names):
                cur = el.className.split()
                for nm in names:
                    if nm not in cur:
                        cur.append(js_str(nm))
                el.className = " ".join(cur)

            def remove(self, *names):
                cur = [c for c in el.className.split()
                       if c not in [js_str(n) for n in names]]
                el.className = " ".join(cur)

            def toggle(self, name, force=undefined):
                name = js_str(name)
                has = name in el.className.split()
                want = (not has) if force is undefined else js_truthy(force)
                (self.add if want else self.remove)(name)
                return want

            def contains(self, name):
                return js_str(name) in el.className.split()

        return _CL()

    @property
    def style(self):
        # style as a live property bag persisted across reads
        if "style" not in self._props:
            self._props["style"] = JSObject()
        return self._props["style"]

    # form element properties ------------------------------------------------

    @property
    def value(self):
        if "value" in self._props:
            return self._props["value"]
        if self.tag == "select":
            opts = self.querySelectorAll("option")
            for o in opts:
                if "selected" in o.attrs:
                    return o.value
            return opts[0].value if opts else ""
        if self.tag == "option":
            return self.attrs.get("value", self.textContent)
        if self.tag == "textarea":
            return self.textContent
        return self.attrs.get("value", "")

    @value.setter
    def value(self, v):
        self._props["value"] = js_str(v)

    @property
    def checked(self):
        return self._props.get("checked", "checked" in self.attrs)

    @checked.setter
    def checked(self, v):
        self._props["checked"] = js_truthy(v)

    @property
    def disabled(self):
        return self._props.get("disabled", "disabled" in self.attrs)

    @disabled.setter
    def disabled(self, v):
        self._props["disabled"] = js_truthy(v)

    @property
    def name(self):
        return self.attrs.get("name", "")

    @property
    def type(self):
        return self.attrs.get("type", "")

    @type.setter
    def type(self, v):
        self.attrs["type"] = js_str(v)

    @property
    def href(self):
        return self.attrs.get("href", "")

    @href.setter
    def href(self, v):
        self.attrs["href"] = js_str(v)

    @property
    def src(self):
        return self.attrs.get("src", "")

    @src.setter
    def src(self, v):
        self.attrs["src"] = js_str(v)

    @property
    def options(self):
        return self.querySelectorAll("option")

    @property
    def selectedIndex(self):
        opts = self.options
        val = self.value
        for i, o in enumerate(opts):
            if o.value == val:
                return i
        return -1

    # -- selectors ----------------------------------------------------------

    def _walk(self):
        for c in self.children:
            if c.tag != "#text":
                yield c
                yield from c._walk()

    def querySelectorAll(self, sel):
        out = []
        parts = js_str(sel).strip().split()
        for el in self._walk():
            if _matches(el, parts[-1]):
                # check ancestor chain for descendant combinators
                anc, ok = el.parent, True
                for p in reversed(parts[:-1]):
                    while anc is not None and not _matches(anc, p):
                        anc = anc.parent
                    if anc is None:
                        ok = False
                        break
                    anc = anc.parent
                if ok:
                    out.append(el)
        return out

    def querySelector(self, sel):
        found = self.querySelectorAll(sel)
        return found[0] if found else None

    def getElementById(self, eid):
        eid = js_str(eid)
        for el in self._walk():
            if el.attrs.get("id") == eid:
                return el
        return None

    # -- events -------------------------------------------------------------

    def addEventListener(self, etype, fn, *a):
        self._listeners.setdefault(js_str(etype), []).append(fn)

    def removeEventListener(self, etype, fn, *a):
        ls = self._listeners.get(js_str(etype), [])
        if fn in ls:
            ls.remove(fn)

    def dispatchEvent(self, event: "JSObject"):
        etype = js_str(event.get("type"))
        event.setdefault("target", self)
        # stopPropagation halts the walk BEFORE the next ancestor; the
        # current node's remaining listeners still run (DOM semantics —
        # only stopImmediatePropagation would cut those, unsupported)
        stopped = []
        event["stopPropagation"] = lambda: stopped.append(True)
        node = self
        while node is not None:  # bubble
            for fn in list(node._listeners.get(etype, [])):
                r = (fn.call([event]) if isinstance(fn, JSFunction)
                     else fn(event))
                _raise_if_rejected(r)  # broken async handler = test fails
            if stopped:
                break
            node = node.parent
        return True

    def click(self):
        ev = JSObject({"type": "click", "target": self,
                       "preventDefault": lambda: None})
        self.dispatchEvent(ev)

    def focus(self):
        pass

    def preventDefault(self):  # pragma: no cover - defensive
        pass


def _camel(s: str) -> str:
    parts = s.split("-")
    return parts[0] + "".join(p.title() for p in parts[1:])


def _matches(el: Element, simple: str) -> bool:
    """tag, #id, .class, [attr], :checked — possibly compounded."""
    rest = simple
    while rest:
        m = _re.match(r"^([a-zA-Z][a-zA-Z0-9-]*)", rest)
        if m and rest is simple:
            if el.tag != m.group(1).lower():
                return False
            rest = rest[m.end():]
            continue
        m = _re.match(r"^#([\w-]+)", rest)
        if m:
            if el.attrs.get("id") != m.group(1):
                return False
            rest = rest[m.end():]
            continue
        m = _re.match(r"^\.([\w-]+)", rest)
        if m:
            if m.group(1) not in el.className.split():
                return False
            rest = rest[m.end():]
            continue
        m = _re.match(r"^\[([\w-]+)\]", rest)
        if m:
            if m.group(1) not in el.attrs:
                return False
            rest = rest[m.end():]
            continue
        m = _re.match(r"^:checked", rest)
        if m:
            if not el.checked:
                return False
            rest = rest[m.end():]
            continue
        raise JSError(f"unsupported selector {simple!r}")
    return True


def _serialize(el: Element) -> str:
    if el.tag == "#text":
        return (el._text.replace("&", "&amp;").replace("<", "&lt;")
                .replace(">", "&gt;"))
    attrs = "".join(f' {k}="{v}"' for k, v in el.attrs.items())
    if el.tag in _VOID_TAGS:
        return f"<{el.tag}{attrs}>"
    return f"<{el.tag}{attrs}>{el.innerHTML}</{el.tag}>"


class _FragmentParser(html.parser.HTMLParser):
    def __init__(self, doc):
        super().__init__(convert_charrefs=True)
        self.doc = doc
        self.root = Element("#fragment", doc)
        self.stack = [self.root]

    def handle_starttag(self, tag, attrs):
        el = self.doc.createElement(tag)
        for k, v in attrs:
            el.setAttribute(k, v if v is not None else "")
        self.stack[-1].appendChild(el)
        if tag not in _VOID_TAGS:
            self.stack.append(el)

    def handle_endtag(self, tag):
        for i in range(len(self.stack) - 1, 0, -1):
            if self.stack[i].tag == tag:
                del self.stack[i:]
                break

    def handle_data(self, data):
        if data:
            self.stack[-1].appendChild(self.doc.createTextNode(data))


def _parse_fragment(markup: str, doc) -> list[Element]:
    p = _FragmentParser(doc)
    p.feed(markup)
    return list(p.root.children)


class Document(Element):
    def __init__(self):
        super().__init__("#document", self)
        self._doc = self

    def createElement(self, tag):
        return Element(js_str(tag), self)

    def createTextNode(self, text):
        t = Element("#text", self)
        t._text = js_str(text)
        return t

    @property
    def body(self):
        for el in self._walk():
            if el.tag == "body":
                return el
        return self


class FormData:
    """new FormData(form): input/select/textarea name=value pairs."""

    def __init__(self, form: Element | None = None):
        self._items: list[tuple[str, str]] = []
        if form is not None:
            for el in form.querySelectorAll("input") + \
                    form.querySelectorAll("select") + \
                    form.querySelectorAll("textarea"):
                nm = el.name
                if not nm:
                    continue
                if el.tag == "input" and \
                        el.attrs.get("type") in ("checkbox", "radio"):
                    if not el.checked:
                        continue
                    self._items.append((nm, el.value or "on"))
                else:
                    self._items.append((nm, js_str(el.value)))

    def get(self, name):
        for k, v in self._items:
            if k == js_str(name):
                return v
        return None

    def getAll(self, name):
        return [v for k, v in self._items if k == js_str(name)]

    def entries(self):
        return [[k, v] for k, v in self._items]

    def append(self, k, v):
        self._items.append((js_str(k), js_str(v)))


# ---------------------------------------------------------------------------
# JS <-> Python data conversion for the fetch bridge


def to_js(v):
    if isinstance(v, dict) and not isinstance(v, JSObject):
        return JSObject({k: to_js(x) for k, x in v.items()})
    if isinstance(v, JSObject):
        return JSObject({k: to_js(x) for k, x in v.items()})
    if isinstance(v, list):
        return [to_js(x) for x in v]
    return v


def to_py(v):
    if v is undefined:
        return None
    if isinstance(v, dict):
        return {k: to_py(x) for k, x in v.items()}
    if isinstance(v, list):
        return [to_py(x) for x in v]
    if isinstance(v, float) and v == int(v):
        return int(v)
    return v


# ---------------------------------------------------------------------------
# browser harness


class Browser:
    """Load an HTML page, execute its inline scripts, drive it like a user.

    `router` is a kubeflow_tpu.utils.httpd.Router (the real backend):
    fetch() dispatches HttpReq into it synchronously. Extra routers can
    be mounted under path prefixes with mount() BEFORE load() — the
    dashboard proxies /jupyter/ to JWA the same way the gateway does.
    """

    def __init__(self, router=None):
        self.document = Document()
        self.routers: list[tuple[str, Any]] = []
        if router is not None:
            self.routers.append(("", router))
        self.location = JSObject({"hash": "", "href": "/", "pathname": "/",
                                  "search": ""})
        self.window = Element("#window", self.document)
        self.timers: dict[int, Any] = {}    # id -> interval fn (refire)
        self.timeouts: dict[int, Any] = {}  # id -> one-shot fn (fire once)
        self._timer_seq = 0
        self.console: list[str] = []
        self.requests: list[tuple[str, str]] = []  # (method, path) log
        # headers an auth proxy (gatekeeper/IAP) would inject on every
        # request, e.g. {"kubeflow-userid": "alice@example.com"}
        self.default_headers: dict[str, str] = {}
        self._interp: Interpreter | None = None

    def mount(self, prefix: str, router) -> "Browser":
        self.routers.insert(0, (prefix.rstrip("/"), router))
        return self

    # -- network ------------------------------------------------------------

    def _fetch(self, url, opts=undefined):
        from urllib.parse import parse_qs, urlparse

        from kubeflow_tpu.utils.httpd import HttpReq

        url = js_str(url)
        opts = opts if isinstance(opts, dict) else {}
        method = js_str(opts.get("method", "GET")).upper()
        headers = {k.lower(): v for k, v in self.default_headers.items()}
        headers.update({js_str(k).lower(): js_str(v)
                        for k, v in (opts.get("headers") or {}).items()})
        body = opts.get("body", undefined)
        if isinstance(body, FormData):
            from urllib.parse import urlencode

            raw = urlencode(body._items).encode()
            headers.setdefault("content-type",
                               "application/x-www-form-urlencoded")
        elif body is undefined:
            raw = b""
        else:
            raw = js_str(body).encode()
        parsed = urlparse(url)
        path = parsed.path
        if not path.startswith("/"):  # relative URL: resolve against /
            path = "/" + path
        router = None
        for prefix, r in self.routers:
            if prefix and path.startswith(prefix + "/"):
                router, path = r, path[len(prefix):]
                break
            if not prefix:
                router = r
        if router is None:
            raise JSError(f"no router mounted for {url}")
        self.requests.append((method, path))
        req = HttpReq(method=method, path=path, params={},
                      query=parse_qs(parsed.query), headers=headers, body=raw)
        resp = router.dispatch(req)
        body_bytes = resp.body

        loop = self._interpreter().loop

        def _json():
            try:
                return JSPromise.resolve(
                    to_js(_json_mod_loads(body_bytes.decode() or "null")))
            except Exception:
                return JSPromise.reject(new_error("invalid json"), loop)

        r = JSObject({
            "ok": 200 <= resp.status < 300,
            "status": resp.status,
            "json": _json,
            "text": lambda: JSPromise.resolve(body_bytes.decode()),
        })
        # the request itself ran synchronously above, but the promise
        # settles on a MACROtask (like real network completion): code
        # after the fetch() call — and reactions of earlier fetches —
        # runs first, in queue order
        p = JSPromise.make_pending(loop)
        loop.macrotask(lambda: p.settle_ok(r))
        return p

    # -- page load ----------------------------------------------------------

    def load(self, page_html: str, *, run_scripts: bool = True) -> "Browser":
        self.document.children = []
        for node in _parse_fragment(page_html, self.document):
            self.document.appendChild(node)
        if run_scripts:
            for script in self.document.querySelectorAll("script"):
                src = script.textContent
                if src.strip():
                    self.run(src)
        return self

    def _drain(self) -> "Browser":
        """Run the event loop dry, then fail on any unhandled rejection.
        Called at every harness entry point — the analogue of Selenium's
        'wait for the page to go quiet' between actions."""
        loop = self._interpreter().loop
        loop.drain()
        check_unhandled_rejections(loop)
        return self

    def run(self, js_src: str):
        interp = self._interpreter()
        ast = Parser(tokenize(js_src)).parse_program()
        # top-level scripts share the global env (page scripts do)
        benv = self._genv
        for s in ast[1]:
            if s[0] == "fundecl":
                benv.declare(s[1], interp.make_function(s[2], benv))
        for s in ast[1]:
            interp.exec(s, benv)
        return self._drain()

    def eval(self, js_expr: str):
        """Evaluate an expression in page context (test assertions).
        Trailing tokens are an error — a truncated assertion must never
        pass vacuously."""
        interp = self._interpreter()
        parser = Parser(tokenize(js_expr))
        ast = parser.expression()
        if not parser.at("eof"):
            raise JSError(
                f"trailing tokens after expression: {parser.peek()!r}")
        self._drain()  # pending work settles before the assertion reads
        v = _raise_if_rejected(interp.eval(ast, self._genv))
        if isinstance(v, JSPromise):
            # an expression yielding a promise: settle it for the caller
            interp.loop.drain_until(lambda: v.state != JSPromise.PENDING)
            v = _raise_if_rejected(v).value
        # the expression itself may have created (and orphaned) work
        check_unhandled_rejections(interp.loop)
        return v

    # -- user actions -------------------------------------------------------

    def by_id(self, eid) -> Element:
        el = self.document.getElementById(eid)
        if el is None:
            raise AssertionError(f"no element with id {eid!r}")
        return el

    def click(self, eid):
        self.by_id(eid).click()
        return self._drain()

    def type_into(self, eid, text):
        el = self.by_id(eid)
        el.value = text
        el.dispatchEvent(JSObject({"type": "input", "target": el}))
        el.dispatchEvent(JSObject({"type": "change", "target": el}))
        return self._drain()

    def select(self, eid, value):
        el = self.by_id(eid)
        el.value = value
        el.dispatchEvent(JSObject({"type": "change", "target": el}))
        return self._drain()

    def submit(self, eid):
        el = self.by_id(eid)
        ev = JSObject({"type": "submit", "target": el,
                       "preventDefault": lambda: None})
        el.dispatchEvent(ev)
        return self._drain()

    def set_hash(self, value):
        self.location["hash"] = js_str(value)
        ev = JSObject({"type": "hashchange"})
        for fn in self.window._listeners.get("hashchange", []):
            _raise_if_rejected(
                fn.call([ev]) if isinstance(fn, JSFunction) else fn(ev))
        return self._drain()

    def fire_timers(self):
        """Run every live interval callback once and drain pending
        one-shot timeouts (they never refire — setTimeout semantics).
        Rejected async callbacks raise: a broken timer must fail tests."""
        for fn in list(self.timers.values()):
            _raise_if_rejected(
                fn.call([]) if isinstance(fn, JSFunction) else fn())
        pending, self.timeouts = self.timeouts, {}
        for fn in pending.values():
            _raise_if_rejected(
                fn.call([]) if isinstance(fn, JSFunction) else fn())
        return self._drain()

    def text(self, eid) -> str:
        return self.by_id(eid).textContent

    # -- globals ------------------------------------------------------------

    def _interpreter(self) -> Interpreter:
        if self._interp is not None:
            return self._interp
        g = Env()
        self._genv = g
        interp = Interpreter(g)
        self._interp = interp
        doc = self.document

        def _set_interval(fn, delay=0, *a):
            self._timer_seq += 1
            self.timers[self._timer_seq] = fn
            return self._timer_seq

        def _set_timeout(fn, delay=0, *a):
            self._timer_seq += 1
            self.timeouts[self._timer_seq] = fn
            return self._timer_seq

        def _clear(tid=None):
            # a cancelled timer must NOT fire in fire_timers
            self.timers.pop(tid, None)
            self.timeouts.pop(tid, None)

        def _console_log(*a):
            self.console.append(" ".join(js_str(x) for x in a))

        math = JSObject({
            "max": lambda *a: max(js_num(x) for x in a),
            "min": lambda *a: min(js_num(x) for x in a),
            "round": lambda x: round(js_num(x)),
            "floor": lambda x: int(js_num(x) // 1),
            "abs": lambda x: abs(js_num(x)),
            "random": lambda: 0.42,  # deterministic tests
        })
        obj_ns = JSObject({
            "entries": lambda o: [[k, v] for k, v in o.items()],
            "keys": lambda o: list(o.keys()),
            "values": lambda o: list(o.values()),
            "assign": lambda t, *srcs: (
                [t.update(s) for s in srcs if isinstance(s, dict)], t)[1],
            "fromEntries": lambda pairs: JSObject(
                {js_str(k): v for k, v in pairs}),
        })
        json_ns = JSObject({
            "stringify": lambda v, *a: _json_mod_dumps(to_py(v)),
            "parse": lambda s: to_js(_json_mod_loads(js_str(s))),
        })
        promise_ns = JSObject({
            "resolve": JSPromise.resolve,
            "reject": lambda e: JSPromise.reject(e, interp.loop),
            "all": lambda ps: _promise_all(ps, interp.loop),
        })

        def _error_ctor(message=""):
            return new_error(js_str(message))

        class _URLSearchParams:
            def __init__(self, qs=""):
                from urllib.parse import parse_qs

                self._q = parse_qs(js_str(qs).lstrip("?"),
                                   keep_blank_values=True)

            def get(self, key):
                vals = self._q.get(js_str(key))
                return vals[0] if vals else None

            def getAll(self, key):
                return self._q.get(js_str(key), [])

            def has(self, key):
                return js_str(key) in self._q

        for name, val in {
            "document": doc,
            "window": self.window,
            "location": self.location,
            "history": JSObject({"pushState": lambda *a: undefined,
                                 "replaceState": lambda *a: undefined}),
            "fetch": self._fetch,
            "console": JSObject({"log": _console_log, "warn": _console_log,
                                 "error": _console_log}),
            "JSON": json_ns,
            "Object": obj_ns,
            "Math": math,
            "Promise": promise_ns,
            "Number": lambda v=0: js_num(v),
            "String": lambda v="": js_str(v),
            "Boolean": lambda v=False: js_truthy(v),
            "Array": JSObject({"isArray": lambda v: isinstance(v, list),
                               "from": lambda v: list(v)}),
            "Error": _error_ctor,
            "FormData": FormData,
            "URLSearchParams": _URLSearchParams,
            "parseInt": lambda s, base=10: _parse_int(s, base),
            "parseFloat": lambda s: js_num(s),
            "isNaN": lambda v: js_num(v) != js_num(v),
            "setInterval": _set_interval,
            "setTimeout": _set_timeout,
            "clearInterval": _clear,
            "clearTimeout": _clear,
            "encodeURIComponent": _encode_uri,
            "decodeURIComponent": lambda s: __import__(
                "urllib.parse", fromlist=["unquote"]).unquote(js_str(s)),
            "undefined": undefined,
            "NaN": float("nan"),
            "Infinity": float("inf"),
            "alert": lambda *a: self.console.append(
                "alert: " + " ".join(js_str(x) for x in a)),
            "confirm": lambda *a: True,
        }.items():
            g.declare(name, val)
        # window aliases itself + the globals commonly accessed off it
        self.window.location = self.location
        return interp


def _promise_all(ps, loop: EventLoop) -> JSPromise:
    ps = [JSPromise.resolve(p) for p in ps]
    out = JSPromise.make_pending(loop)
    if not ps:
        out.settle_ok([])
        return out
    results = [undefined] * len(ps)
    left = [len(ps)]
    for i, pr in enumerate(ps):
        pr._handled = True

        def react(i=i, pr=pr):
            if out.state != JSPromise.PENDING:
                return  # already rejected by an earlier settle
            if pr.state == JSPromise.ERR:
                out.settle_err(pr.error)
                return
            results[i] = pr.value
            left[0] -= 1
            if left[0] == 0:
                out.settle_ok(results)

        pr.on_settle(react, loop)
    return out


def _parse_int(s, base=10):
    try:
        return int(js_str(s).strip().split(".")[0], int(base))
    except (ValueError, TypeError):
        return float("nan")


def _encode_uri(s):
    from urllib.parse import quote

    return quote(js_str(s), safe="")


def _json_mod_dumps(v):
    return _json.dumps(v)


def _json_mod_loads(s):
    return _json.loads(s)
