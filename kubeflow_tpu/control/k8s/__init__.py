"""In-tree Kubernetes API machinery.

The reference leans on client-go / controller-runtime (Go). Here the same
concepts are provided natively:

- ``objects``  — unstructured dict objects + metadata/condition/selector
  helpers (client-go's unstructured + apimachinery analogue).
- ``fake``     — ``FakeCluster``: an in-memory apiserver with resource
  versions, optimistic concurrency, label selectors, finalizers,
  deletionTimestamps, ownerReference garbage collection and watch
  streams. This is the hermetic test backend the reference never had
  (it tested distributed behavior only on live GKE — SURVEY.md §4).
- ``rest``     — ``RestClient``: the same Client interface speaking HTTPS
  to a real apiserver (in-cluster config: serviceaccount token + CA).
"""

from kubeflow_tpu.control.k8s.objects import (  # noqa: F401
    ApiError,
    Conflict,
    NotFound,
    cond_get,
    cond_set,
    gvk,
    match_labels,
    meta,
    new_object,
    owner_ref,
    set_owner,
)
from kubeflow_tpu.control.k8s.fake import FakeCluster  # noqa: F401
