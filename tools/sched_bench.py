#!/usr/bin/env python
"""sched_bench — deterministic synthetic-fleet control-plane benchmark.

Stands up a FakeCluster fleet (default 5k nodes / 1k gangs / 10k pods),
drives the gang scheduler over it in creation waves with completion and
node-health churn, and measures the control plane's raw speed: pass
duration percentiles, admissions/sec, and FakeCluster op counts (the
deterministic half — objects scanned per pass does not depend on the
machine). Two arms share one seeded workload:

- ``cache``  — the ISSUE 7 scheduler on the indexed ``ClusterCache``;
- ``legacy`` — the same scheduler with ``cache=False``: every hot-path
  read is a full relist (the pre-ISSUE-7 shape, kept in-tree exactly
  for this A/B).

Everything runs on the injectable clock (``GangQueue(clock=...)``) and
``run_until_idle(advance_delayed=True)`` — zero wall-clock sleeps, so
the SCHEDULING DECISIONS and op counts replay exactly per seed; only
the duration measurements vary with the machine.

    python tools/sched_bench.py                      # full + smoke, write JSON
    python tools/sched_bench.py --nodes 200 --gangs 50 --pods 500
    python tools/sched_bench.py --check              # CI gate: rerun the
        # smoke config and fail if the committed BENCH_SCHED_r01.json's
        # cache-arm budget (scan/pass, p99) regresses by > 25%
"""

from __future__ import annotations

import argparse
import json
import math
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.control.jaxjob import types as JT  # noqa: E402
from kubeflow_tpu.control.k8s import objects as ob  # noqa: E402
from kubeflow_tpu.control.k8s.fake import FakeCluster  # noqa: E402
from kubeflow_tpu.control.runtime import seed_controller  # noqa: E402
from kubeflow_tpu.control.scheduler import (  # noqa: E402
    ANNOTATION_ELASTIC_MIN, ANNOTATION_GANG_SIZE, ANNOTATION_PRIORITY,
    GATE_GANG, SCHEDULER_NAME,
)
from kubeflow_tpu.control.scheduler import nodes as N  # noqa: E402
from kubeflow_tpu.control.scheduler.scheduler import (  # noqa: E402
    build_scheduler,
)
from kubeflow_tpu.runtime.metrics import MetricsRegistry  # noqa: E402

DEFAULT_OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_SCHED_r01.json")

# The fleet's TPU pools: (accelerator, topology, weight). Node counts
# and gang pool picks follow the weights, so pools are contended
# unevenly — some gangs must queue, requeue and back off.
POOLS = (
    ("tpu-v5-lite-podslice", "2x4", 4),
    ("tpu-v5-lite-podslice", "4x4", 3),
    ("tpu-v5p-slice", "2x2", 2),
    ("tpu-v6e-slice", "2x4", 1),
)
SPOT_FRACTION = 0.15   # of pool 0, rounded down
TENANTS = 8


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _pool_of(i: int, total: int) -> tuple[str, str]:
    wsum = sum(w for _, _, w in POOLS)
    acc = 0
    for accel, topo, w in POOLS:
        acc += w
        if i * wsum < total * acc:
            return accel, topo
    return POOLS[-1][0], POOLS[-1][1]


def build_fleet(cluster: FakeCluster, nodes: int) -> None:
    spot_cut = int(nodes * POOLS[0][2] / sum(w for _, _, w in POOLS)
                   * SPOT_FRACTION)
    for i in range(nodes):
        accel, topo = _pool_of(i, nodes)
        cluster.create(N.new_tpu_node(
            f"node-{i:05d}", accelerator=accel, topology=topo,
            chips_per_node=4, spot=i < spot_cut))


def gang_sizes(rng: random.Random, gangs: int, pods: int,
               lo: int = 2, hi: int = 16) -> list[int]:
    """``gangs`` sizes in [lo, hi] summing exactly to ``pods``."""
    sizes = []
    remaining = pods
    for i in range(gangs):
        left = gangs - i - 1
        a = max(lo, remaining - hi * left)
        b = min(hi, remaining - lo * left)
        size = rng.randint(a, b) if b >= a else max(lo, min(hi, remaining))
        sizes.append(size)
        remaining -= size
    return sizes


def make_gang(cluster: FakeCluster, rng: random.Random, namespace: str,
              name: str, size: int, chips: int, pool: tuple[str, str],
              priority: int, elastic_min: int | None) -> None:
    annotations = {
        ANNOTATION_GANG_SIZE: str(size),
        ANNOTATION_PRIORITY: str(priority),
    }
    if elastic_min is not None:
        annotations[ANNOTATION_ELASTIC_MIN] = str(elastic_min)
    for i in range(size):
        pod = ob.new_object(
            "v1", "Pod", f"{name}-worker-{i}", namespace,
            labels={JT.LABEL_JOB_NAME: name},
            annotations=dict(annotations))
        spec = {
            "schedulerName": SCHEDULER_NAME,
            "schedulingGates": [{"name": GATE_GANG}],
            "nodeSelector": {
                JT.NODESELECTOR_ACCEL: pool[0],
                JT.NODESELECTOR_TOPOLOGY: pool[1],
            },
            "containers": [{"name": "jax", "resources": {
                "limits": {JT.RESOURCE_TPU: chips}}}],
        }
        if elastic_min is not None:
            spec["tolerations"] = [dict(N.spot_taint())]
        pod["spec"] = spec
        cluster.create(pod)


def drain(ctl, clock: ManualClock, rounds: int = 6) -> int:
    done = 0
    for _ in range(rounds):
        n = ctl.run_until_idle(max_rounds=100000, advance_delayed=True)
        done += n
        clock.advance(2.0)
        if n == 0:
            break
    return done


def complete_gangs(cluster: FakeCluster, fraction: float = 0.4) -> int:
    """Mark the name-ordered first ``fraction`` of fully-bound running
    gangs Succeeded — frees their chips and exercises terminal-phase
    accounting + backoff kicks, deterministically."""
    by_gang: dict[tuple[str, str], list[dict]] = {}
    for p in cluster.list("v1", "Pod"):
        spec = p.get("spec") or {}
        if spec.get("schedulerName") != SCHEDULER_NAME:
            continue
        job = ob.labels_of(p).get(JT.LABEL_JOB_NAME)
        if job:
            m = ob.meta(p)
            by_gang.setdefault((m.get("namespace") or "", job), []).append(p)
    runnable = sorted(
        key for key, pods in by_gang.items()
        if all((p["spec"].get("nodeName")
                and (p.get("status") or {}).get("phase")
                not in N.TERMINAL_PHASES) for p in pods))
    ncomplete = math.ceil(len(runnable) * fraction)
    for key in runnable[:ncomplete]:
        for p in by_gang[key]:
            cur = cluster.get("v1", "Pod", ob.meta(p)["name"], key[0])
            cur.setdefault("status", {})["phase"] = "Succeeded"
            cluster.update_status(cur)
    return ncomplete


def verify_invariants(cluster: FakeCluster) -> list[str]:
    """No node may be oversubscribed, and no pod may be bound while
    still carrying our gate — whatever the arm, however the churn."""
    problems = []
    alloc = {ob.meta(n)["name"]:
             int(((n.get("status") or {}).get("allocatable") or {})
                 .get(JT.RESOURCE_TPU) or 0)
             for n in cluster.list("v1", "Node")}
    used: dict[str, int] = {}
    for p in cluster.list("v1", "Pod"):
        spec = p.get("spec") or {}
        node = spec.get("nodeName")
        gated = any(g.get("name") == GATE_GANG
                    for g in spec.get("schedulingGates") or [])
        if node and gated:
            problems.append(f"bound-but-gated pod {ob.meta(p)['name']}")
        if not node:
            continue
        if (p.get("status") or {}).get("phase") in N.TERMINAL_PHASES:
            continue
        used[node] = used.get(node, 0) + N.pod_tpu_request(p)
    for node, n in used.items():
        if node in alloc and n > alloc[node]:
            problems.append(f"node {node} oversubscribed: {n}/{alloc[node]}")
    return problems


def _percentile(samples: list[float], q: float) -> float:
    if not samples:
        return 0.0
    xs = sorted(samples)
    return xs[min(len(xs) - 1, int(math.ceil(q * len(xs))) - 1)]


def _admitted_total(registry: MetricsRegistry) -> int:
    total = 0
    for line in registry.render().splitlines():
        if line.startswith("scheduler_gangs_admitted_total{"):
            total += int(float(line.rsplit(" ", 1)[1]))
    return total


def _tenant_summary(registry: MetricsRegistry) -> dict:
    """Per-tenant admission-latency / preemption / requeue cut, parsed
    from the tenant-labeled scheduler series through the ONE exposition
    parser. Observability only: the scheduler's decisions (and the
    banked bindings fingerprint) are identical with or without this
    read."""
    from kubeflow_tpu.obs import expofmt

    out: dict[str, dict] = {}

    def row(tenant: str) -> dict:
        return out.setdefault(tenant, {
            "admitted": 0, "preemptions": 0, "requeues": 0,
            "_lat_sum": 0.0, "_lat_count": 0})

    for s in expofmt.parse(registry.render()):
        labels = s.labels_dict()
        tenant = labels.get("tenant")
        if not tenant:
            continue
        if s.name == "scheduler_gangs_admitted_total":
            row(tenant)["admitted"] += int(s.value)
        elif s.name == "scheduler_preemptions_total":
            row(tenant)["preemptions"] += int(s.value)
        elif s.name == "scheduler_requeues_total":
            row(tenant)["requeues"] += int(s.value)
        elif s.name == "scheduler_bind_latency_seconds_sum":
            row(tenant)["_lat_sum"] += s.value
        elif s.name == "scheduler_bind_latency_seconds_count":
            row(tenant)["_lat_count"] += int(s.value)
    for r in out.values():
        n = r.pop("_lat_count")
        total = r.pop("_lat_sum")
        r["bound"] = n
        r["admission_latency_mean_s"] = round(total / n, 6) if n else 0.0
    return dict(sorted(out.items()))


def bindings_fingerprint(cluster: FakeCluster) -> dict[str, str | None]:
    """(namespace/pod) -> node for every scheduler pod — the two arms
    must agree exactly (no semantic drift from the indexed rewrite)."""
    out = {}
    for p in cluster.list("v1", "Pod"):
        if (p.get("spec") or {}).get("schedulerName") != SCHEDULER_NAME:
            continue
        m = ob.meta(p)
        out[f"{m.get('namespace')}/{m['name']}"] = p["spec"].get("nodeName")
    return out


def run_bench(nodes: int, gangs: int, pods: int, seed: int = 0,
              waves: int = 10, cache: bool = True,
              node_churn: bool = True) -> dict:
    rng = random.Random(seed)
    clock = ManualClock()
    cluster = FakeCluster(history_limit=65536)
    registry = MetricsRegistry()
    ctl = seed_controller(build_scheduler(
        cluster, registry=registry, record_events=False, clock=clock,
        cache=cache))
    rec = ctl.reconciler
    durations: list[float] = []
    rec.pass_observer = durations.append

    build_fleet(cluster, nodes)
    drain(ctl, clock)

    sizes = gang_sizes(rng, gangs, pods)
    specs = []
    for i, size in enumerate(sizes):
        pool_i = rng.randrange(len(POOLS))
        accel, topo, _w = POOLS[pool_i]
        elastic = None
        if i % 10 == 0 and size >= 4:
            elastic = max(2, size // 2)
        specs.append({
            "namespace": f"tenant-{i % TENANTS}",
            "name": f"gang-{i:04d}",
            "size": size,
            "chips": 1 if rng.random() < 0.2 else 2,
            "pool": (accel, topo),
            "priority": 0 if rng.random() < 0.7 else rng.randint(1, 10),
            "elastic_min": elastic,
        })

    cluster.reset_stats()
    durations.clear()
    t0 = time.perf_counter()
    per_wave = math.ceil(len(specs) / waves)
    for wave in range(waves):
        for spec in specs[wave * per_wave:(wave + 1) * per_wave]:
            make_gang(cluster, rng, **spec)
        drain(ctl, clock)
        if node_churn and wave % 4 == 3:
            # a node dies under whatever it was running, then heals
            victim = f"node-{(wave * 131) % nodes:05d}"
            node = cluster.get("v1", "Node", victim)
            node["status"]["conditions"] = [
                {"type": "Ready", "status": "False"}]
            cluster.update_status(node)
            drain(ctl, clock)
            node = cluster.get("v1", "Node", victim)
            node["status"]["conditions"] = [
                {"type": "Ready", "status": "True"}]
            cluster.update_status(node)
            drain(ctl, clock)
        if wave % 2 == 1:
            with cluster.stats_paused():
                complete_gangs(cluster)
            drain(ctl, clock)
    wall = time.perf_counter() - t0

    stats = dict(cluster.stats)
    with cluster.stats_paused():
        problems = verify_invariants(cluster)
    if problems:
        raise AssertionError(f"invariants violated: {problems[:5]}")
    passes = max(len(durations), 1)
    admitted = _admitted_total(registry)
    return {
        "arm": "cache" if cache else "legacy",
        "passes": len(durations),
        "pass_p50_ms": round(_percentile(durations, 0.50) * 1e3, 4),
        "pass_p99_ms": round(_percentile(durations, 0.99) * 1e3, 4),
        "pass_max_ms": round(max(durations, default=0.0) * 1e3, 4),
        "wall_s": round(wall, 3),
        "admitted_gangs": admitted,
        "admissions_per_sec": round(admitted / wall, 2) if wall else 0.0,
        "ops": {k: stats.get(k, 0)
                for k in ("list_calls", "list_scanned", "list_copied",
                          "get", "patch", "update", "create", "delete")},
        "scan_per_pass": round(stats.get("list_scanned", 0) / passes, 2),
        "copies_per_pass": round(stats.get("list_copied", 0) / passes, 2),
        "tenants": _tenant_summary(registry),
        "bindings": bindings_fingerprint(cluster),
    }


def _strip(arm: dict) -> dict:
    arm.pop("bindings", None)
    return arm


def compare(legacy: dict, cache: dict) -> dict:
    def ratio(a, b):
        return round(a / b, 2) if b else float("inf")

    return {
        "scan_reduction_x": ratio(legacy["scan_per_pass"],
                                  max(cache["scan_per_pass"], 0.01)),
        "copy_reduction_x": ratio(legacy["copies_per_pass"],
                                  max(cache["copies_per_pass"], 0.01)),
        "p99_speedup_x": ratio(legacy["pass_p99_ms"], cache["pass_p99_ms"]),
        "wall_speedup_x": ratio(legacy["wall_s"], cache["wall_s"]),
        "bindings_identical": legacy["bindings"] == cache["bindings"],
    }


def run_pair(config: dict) -> dict:
    cache = run_bench(cache=True, **config)
    legacy = run_bench(cache=False, **config)
    cmp_ = compare(legacy, cache)
    # the fingerprint is an equivalence check, not a result to bank
    return {"config": config, "legacy": _strip(legacy),
            "cache": _strip(cache), "comparison": cmp_}


SMOKE_CONFIG = {"nodes": 200, "gangs": 50, "pods": 500, "seed": 0,
                "waves": 5}


def check_against(banked_path: str) -> int:
    """CI ratchet: rerun the banked smoke config; fail (1) when the
    cache arm's scan-per-pass or pass p99 regresses by more than 25%
    over the committed numbers."""
    with open(banked_path) as fh:
        banked = json.load(fh)
    smoke = banked.get("smoke")
    if not smoke:
        print(f"check: no smoke section in {banked_path}", file=sys.stderr)
        return 2
    config = dict(smoke["config"])
    now = run_bench(cache=True, **config)
    now.pop("bindings")
    budget_scan = smoke["cache"]["scan_per_pass"] * 1.25
    budget_p99 = smoke["cache"]["pass_p99_ms"] * 1.25
    ok = True
    if now["scan_per_pass"] > budget_scan:
        print(f"check: scan_per_pass {now['scan_per_pass']} exceeds "
              f"budget {budget_scan:.2f} "
              f"(banked {smoke['cache']['scan_per_pass']})",
              file=sys.stderr)
        ok = False
    if now["pass_p99_ms"] > budget_p99:
        print(f"check: pass_p99_ms {now['pass_p99_ms']} exceeds budget "
              f"{budget_p99:.3f} (banked {smoke['cache']['pass_p99_ms']})",
              file=sys.stderr)
        ok = False
    print(json.dumps({"check": "ok" if ok else "REGRESSED",
                      "scan_per_pass": now["scan_per_pass"],
                      "pass_p99_ms": now["pass_p99_ms"],
                      "budget": {"scan_per_pass": round(budget_scan, 2),
                                 "pass_p99_ms": round(budget_p99, 3)}},
                     indent=2))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--gangs", type=int, default=1000)
    ap.add_argument("--pods", type=int, default=10000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--waves", type=int, default=10)
    ap.add_argument("--out", default=DEFAULT_OUT)
    ap.add_argument("--no-smoke", action="store_true",
                    help="skip the 200-node smoke section")
    ap.add_argument("--check", action="store_true",
                    help="rerun the banked smoke config and gate on a "
                         ">25%% budget regression")
    args = ap.parse_args(argv)
    if args.check:
        return check_against(args.out)

    config = {"nodes": args.nodes, "gangs": args.gangs, "pods": args.pods,
              "seed": args.seed, "waves": args.waves}
    result = {
        "bench": "sched_bench",
        "round": "r01",
        "full": run_pair(config),
    }
    if not args.no_smoke:
        result["smoke"] = run_pair(dict(SMOKE_CONFIG))
    full = result["full"]
    if not full["comparison"]["bindings_identical"]:
        print("WARNING: cache and legacy arms disagree on final bindings",
              file=sys.stderr)
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps({"out": args.out,
                      "full": full["comparison"],
                      "cache_p99_ms": full["cache"]["pass_p99_ms"],
                      "legacy_p99_ms": full["legacy"]["pass_p99_ms"],
                      "scan_per_pass": {
                          "cache": full["cache"]["scan_per_pass"],
                          "legacy": full["legacy"]["scan_per_pass"]}},
                     indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
