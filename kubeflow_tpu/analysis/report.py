"""tpulint reporters: human text and machine JSON.

The JSON schema is versioned so round tooling (tools/lint_all.sh, CI
dashboards) can consume it without scraping: ``{"version": 1,
"count": N, "findings": [{rule, path, line, col, message}, ...]}``.
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Iterable

from kubeflow_tpu.analysis.core import Finding

JSON_VERSION = 1


def render_text(findings: Iterable[Finding]) -> str:
    """One `path:line:col: RULE message` per finding plus a summary."""
    findings = list(findings)
    lines = [f.render() for f in findings]
    if findings:
        by_rule = Counter(f.rule for f in findings)
        breakdown = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items()))
        lines.append(f"tpulint: {len(findings)} finding"
                     f"{'s' if len(findings) != 1 else ''} ({breakdown})")
    else:
        lines.append("tpulint: clean")
    return "\n".join(lines)


def render_json(findings: Iterable[Finding]) -> str:
    findings = list(findings)
    return json.dumps({
        "version": JSON_VERSION,
        "count": len(findings),
        "findings": [f.to_dict() for f in findings],
    }, indent=2, sort_keys=True)
