"""JAXJob — the gang-scheduled TPU training-job operator.

The TFJob/OpenMPI replacement (SURVEY.md §2.5, §3.2): where the reference
wires GPU pods together with `TF_CONFIG` parameter-server gRPC
(tf-controller-examples/tf-cnn/launcher.py:68-80) or MPI/NCCL
(components/openmpi-controller), a JAXJob boots its workers into one
`jax.distributed` cluster and gradient reduction happens inside the
compiled step over ICI.
"""

from kubeflow_tpu.control.jaxjob.types import (  # noqa: F401
    API_VERSION,
    KIND,
    new_jaxjob,
)
from kubeflow_tpu.control.jaxjob.controller import JAXJobReconciler, build_controller  # noqa: F401
