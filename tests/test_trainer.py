"""Trainer smoke tests on the virtual CPU mesh: the fake-backend
equivalent of the reference's real-cluster tf-cnn E2E (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.parallel.mesh import MeshSpec
from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer


def tiny_resnet_cfg(**over):
    cfg = dict(
        model="resnet18",
        task="classification",
        global_batch=16,
        image_size=32,
        num_classes=10,
        mesh=MeshSpec(data=8),
        total_steps=4,
        warmup_steps=1,
        log_every=2,
        learning_rate=0.01,
    )
    cfg.update(over)
    return TrainConfig.from_dict(cfg)


def test_resnet_dp_training_runs(devices8):
    trainer = Trainer(tiny_resnet_cfg())
    state, summary = trainer.fit(steps=3)
    assert summary["steps"] == 3
    assert jnp.isfinite(summary["final"]["loss"])
    assert int(state.step) == 3


def test_resnet_loss_decreases_on_fixed_batch(devices8):
    # synthetic data repeats the same batch => loss must fall
    trainer = Trainer(tiny_resnet_cfg(total_steps=8, learning_rate=0.05))
    state = trainer.init_state()
    data = trainer.data_iter()
    batch = next(data)
    losses = []
    for _ in range(8):
        state, m = trainer.train_step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses


def test_fsdp_mesh_shards_params(devices8):
    trainer = Trainer(tiny_resnet_cfg(mesh=MeshSpec(data=2, fsdp=4)))
    state = trainer.init_state()
    # at least one large parameter should actually be sharded over fsdp
    sharded = [
        p for p in jax.tree.leaves(state.params)
        if p.size >= 2**14 and not p.sharding.is_fully_replicated
    ]
    assert sharded, "expected some fsdp-sharded parameters"
    # training still steps
    state, m = trainer.train_step(state, next(trainer.data_iter()))
    assert jnp.isfinite(m["loss"])


def test_eval_step(devices8):
    trainer = Trainer(tiny_resnet_cfg())
    state = trainer.init_state()
    m = trainer.eval_step(state, next(trainer.data_iter()))
    assert jnp.isfinite(m["loss"])


def test_config_rejects_unknown_keys():
    with pytest.raises(ValueError):
        TrainConfig.from_dict({"modell": "resnet50"})


def test_resnet_space_to_depth_stem_trains(devices8):
    # The MLPerf TPU stem variant must train the same as conv7.
    trainer = Trainer(tiny_resnet_cfg(
        model_kwargs={"stem": "space_to_depth"}, total_steps=3))
    state, summary = trainer.fit(steps=3)
    assert jnp.isfinite(summary["final"]["loss"])
    assert int(state.step) == 3


def test_space_to_depth_shape():
    import numpy as np

    from kubeflow_tpu.models.resnet import space_to_depth

    x = jnp.arange(2 * 4 * 4 * 3).reshape(2, 4, 4, 3).astype(jnp.float32)
    y = space_to_depth(x, 2)
    assert y.shape == (2, 2, 2, 12)
    # block (0,0) of image 0 = pixels (0,0),(0,1),(1,0),(1,1) channels-first
    np.testing.assert_array_equal(
        np.asarray(y[0, 0, 0]),
        np.concatenate([np.asarray(x[0, 0, 0]), np.asarray(x[0, 0, 1]),
                        np.asarray(x[0, 1, 0]), np.asarray(x[0, 1, 1])]))


def test_remat_dots_policy_trains_and_matches_no_remat(devices8):
    """remat_policy=dots (keep matmul outputs, recompute elementwise)
    computes the same loss as no-remat — it's a memory/compute trade,
    never a numerics change."""
    import jax
    import numpy as np

    from kubeflow_tpu.parallel.mesh import MeshSpec

    def cfg(**over):
        base = dict(
            model="transformer-test", task="lm", global_batch=8,
            seq_len=32, vocab_size=128, mesh=MeshSpec(data=8),
            optimizer="adamw", learning_rate=1e-3, total_steps=2,
            warmup_steps=1, log_every=10**9,
        )
        base.update(over)
        return TrainConfig.from_dict(base)

    t_plain = Trainer(cfg())
    t_dots = Trainer(cfg(remat=True, remat_policy="dots"))
    s1 = t_plain.init_state()
    s2 = t_dots.init_state()
    batch = next(t_plain.data_iter())
    _, m1 = t_plain.train_step(s1, batch)
    _, m2 = t_dots.train_step(s2, batch)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-5)
    # bad policy rejected at model level too
    import pytest as _pytest

    from kubeflow_tpu.models.transformer import TransformerConfig, _remat_policy
    with _pytest.raises(ValueError, match="remat_policy"):
        _remat_policy(TransformerConfig(remat_policy="bogus"))


def test_remat_slim_and_mlp_policies_match_no_remat(devices8):
    """The round-4 policies — slim (whitelist of named anchors) and the
    width-predicate mlp — are memory/compute trades only: same loss as
    no-remat on the same batch, and they must train under chunked CE
    (the production loss) too."""
    import numpy as np

    from kubeflow_tpu.parallel.mesh import MeshSpec

    def cfg(**over):
        base = dict(
            model="transformer-test", task="lm", global_batch=8,
            seq_len=32, vocab_size=128, mesh=MeshSpec(data=8),
            optimizer="adamw", learning_rate=1e-3, total_steps=2,
            warmup_steps=1, log_every=10**9, xent_chunks=4,
        )
        base.update(over)
        return TrainConfig.from_dict(base)

    t_plain = Trainer(cfg())
    batch = next(t_plain.data_iter())
    _, m_plain = t_plain.train_step(t_plain.init_state(), batch)
    for policy in ("slim", "mlp"):
        t_r = Trainer(cfg(remat=True, remat_policy=policy))
        _, m_r = t_r.train_step(t_r.init_state(), batch)
        np.testing.assert_allclose(
            float(m_plain["loss"]), float(m_r["loss"]), rtol=1e-5,
            err_msg=f"policy {policy}")


def test_periodic_eval_in_fit():
    """eval_every runs held-out eval during fit (train_and_evaluate
    parity): metrics land in the summary with LM perplexity = exp(loss),
    and the eval gauges reach the Prometheus registry."""
    import math

    from kubeflow_tpu.runtime import metrics as rt_metrics
    from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

    cfg = TrainConfig.from_dict(dict(
        model="transformer-test",
        task="lm",
        global_batch=8,
        seq_len=16,
        vocab_size=128,
        mesh=MeshSpec(data=8),
        optimizer="adafactor",
        learning_rate=1e-3,
        total_steps=4,
        warmup_steps=1,
        log_every=10**9,
        eval_every=2,
        eval_steps=2,
    ))
    _, summary = Trainer(cfg).fit()
    ev = summary["eval"]
    assert set(ev) >= {"loss", "accuracy", "perplexity"}
    assert math.isclose(ev["perplexity"], math.exp(ev["loss"]), rel_tol=1e-6)
    scrape = rt_metrics.REGISTRY.render()
    assert "jaxrt_eval_loss" in scrape and "jaxrt_eval_perplexity" in scrape


def test_flash_blocks_plumb_from_config(monkeypatch):
    """TrainConfig.flash_block_q/k must reach the flash kernel call —
    the measured-operating-point reproducibility guarantee (no env vars,
    no process-global state)."""
    import kubeflow_tpu.ops.flash_attention as fa
    from kubeflow_tpu.runtime.data import shard_batch
    from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

    seen = {}
    real = fa.flash_attention

    def spy(q, k, v, **kw):
        seen["block_q"] = kw.get("block_q")
        seen["block_k"] = kw.get("block_k")
        return real(q, k, v, **kw)

    monkeypatch.setattr(fa, "flash_attention", spy)
    cfg = TrainConfig.from_dict(dict(
        model="transformer-test",
        model_kwargs={"attention_impl": "flash"},
        task="lm",
        global_batch=8,
        seq_len=32,
        vocab_size=128,
        mesh=MeshSpec(data=8),
        optimizer="sgdm",
        learning_rate=1e-2,
        total_steps=1,
        warmup_steps=1,
        flash_block_q=32,
        flash_block_k=16,
    ))
    trainer = Trainer(cfg)
    state = trainer.init_state()
    batch = shard_batch(next(trainer.data_iter()),
                        next(iter(jax.tree.leaves(trainer.batch_shardings))))
    trainer.train_step(state, batch)
    assert seen == {"block_q": 32, "block_k": 16}
