"""https-redirect: HTTP->HTTPS 301 helper.

Mirrors components/https-redirect/main.py: any request is answered with
a permanent redirect to the same host+path over https (used in front of
ingresses that only terminate TLS on one port).
"""

from __future__ import annotations

from kubeflow_tpu.utils import httpd
from kubeflow_tpu.utils.httpd import HttpReq, HttpResp, Router


def _redirect(req: HttpReq):
    from urllib.parse import urlencode

    host = req.header("host", "localhost")
    # Strip a port: the https endpoint is the default 443. Bracketed IPv6
    # hosts contain ':' without a port — only strip after the bracket.
    if host.startswith("["):
        end = host.find("]")
        host = host[:end + 1] if end != -1 else host
    elif ":" in host:
        host = host.rsplit(":", 1)[0]
    qs = ""
    if req.query:
        # re-encode: parsed values are decoded, and raw interpolation
        # would corrupt values containing '&'/'='/'%'.
        pairs = [(k, v) for k, vs in req.query.items() for v in vs]
        qs = "?" + urlencode(pairs)
    return HttpResp(301, b"", "text/plain",
                    {"Location": f"https://{host}{req.path}{qs}"})


def router() -> Router:
    r = Router("https-redirect")
    httpd.add_health_routes(r)  # before the catch-all: first match wins
    for method in ("GET", "POST", "PUT", "DELETE"):
        r.route(method, "/", _redirect)
        r.route(method, "/{path*}", _redirect)
    return r


def serve(host: str = "0.0.0.0", port: int = 8080) -> httpd.HttpService:
    return httpd.HttpService(router(), host, port)
