"""StateRepo: deployment-state git persistence with rebase-retry push
(reference: sourceRepos_test.go / ksServer SaveAppToRepo semantics),
exercised against a local bare repo."""

import subprocess

import pytest

from kubeflow_tpu.tpctl.staterepo import GitError, StateRepo


@pytest.fixture()
def bare_remote(tmp_path):
    remote = tmp_path / "state.git"
    subprocess.run(["git", "init", "--bare", "-b", "main", str(remote)],
                   check=True, capture_output=True)
    return str(remote)


def test_save_load_roundtrip(bare_remote):
    with StateRepo(bare_remote) as repo:
        sha = repo.save_deployment("kf-prod", "name: kf-prod\n",
                                   manifests_yaml="kind: Namespace\n")
        assert len(sha) == 40
    # fresh clone (new object) sees the pushed state
    with StateRepo(bare_remote) as repo2:
        assert repo2.load_deployment("kf-prod") == "name: kf-prod\n"
        assert repo2.list_deployments() == ["kf-prod"]


def test_unchanged_save_is_noop(bare_remote):
    with StateRepo(bare_remote) as repo:
        sha1 = repo.save_deployment("a", "x: 1\n")
        sha2 = repo.save_deployment("a", "x: 1\n")
        assert sha1 == sha2


def test_concurrent_writer_rebase(bare_remote):
    # Writer B pushes between A's clone and A's push; A must rebase+retry.
    a = StateRepo(bare_remote)
    a.clone()
    with StateRepo(bare_remote) as b:
        b.save_deployment("from-b", "b: 1\n")
    sha = a.save_deployment("from-a", "a: 1\n", sleep=lambda *_: None)
    assert sha
    a.close()
    with StateRepo(bare_remote) as c:
        assert c.list_deployments() == ["from-a", "from-b"]


def test_missing_deployment_raises(bare_remote):
    with StateRepo(bare_remote) as repo:
        with pytest.raises(FileNotFoundError):
            repo.load_deployment("nope")


def test_delete_deployment(bare_remote):
    with StateRepo(bare_remote) as repo:
        repo.save_deployment("gone", "x: 1\n")
        assert repo.delete_deployment("gone") is True
        assert repo.delete_deployment("gone") is False
    with StateRepo(bare_remote) as repo2:
        assert repo2.list_deployments() == []
