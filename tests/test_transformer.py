"""Transformer + parallelism-matrix tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh
from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer


def lm_cfg(**over):
    cfg = dict(
        model="transformer-test",
        task="lm",
        global_batch=8,
        seq_len=64,
        vocab_size=256,
        mesh=MeshSpec(data=8),
        optimizer="adamw",
        learning_rate=1e-3,
        total_steps=4,
        warmup_steps=1,
        log_every=2,
    )
    cfg.update(over)
    return TrainConfig.from_dict(cfg)


def test_lm_dp_training(devices8):
    trainer = Trainer(lm_cfg())
    state, summary = trainer.fit(steps=3)
    assert np.isfinite(summary["final"]["loss"])


def test_lm_tensor_parallel(devices8):
    trainer = Trainer(lm_cfg(mesh=MeshSpec(data=2, model=4)))
    state = trainer.init_state()
    # TP actually shards attention/MLP kernels over `model`
    sharded = [
        p for p in jax.tree.leaves(state.params)
        if not p.sharding.is_fully_replicated
    ]
    assert sharded, "TP should shard transformer weights"
    state, m = trainer.train_step(state, next(trainer.data_iter()))
    assert np.isfinite(float(m["loss"]))


def test_lm_tp_matches_dp_loss(devices8):
    """Same seed => TP and DP compute the same loss (GSPMD correctness)."""
    t_dp = Trainer(lm_cfg(mesh=MeshSpec(data=8)))
    t_tp = Trainer(lm_cfg(mesh=MeshSpec(data=1, model=8)))
    s_dp, s_tp = t_dp.init_state(), t_tp.init_state()
    batch = next(t_dp.data_iter())
    _, m_dp = t_dp.train_step(s_dp, batch)
    _, m_tp = t_tp.train_step(s_tp, batch)
    np.testing.assert_allclose(float(m_dp["loss"]), float(m_tp["loss"]), rtol=2e-2)


def test_moe_block_runs(devices8):
    trainer = Trainer(lm_cfg(model="moe-test", mesh=MeshSpec(data=2, expert=4)))
    state = trainer.init_state()
    state, m = trainer.train_step(state, next(trainer.data_iter()))
    assert np.isfinite(float(m["loss"]))


def test_bert_forward(devices8):
    model = get_model("bert-test")
    tokens = jnp.ones((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens, train=False)
    from flax.core import meta

    logits = model.apply(meta.unbox(variables), tokens, train=False)
    assert logits.shape == (2, 2)
    assert np.isfinite(np.asarray(logits)).all()


def test_lm_adafactor_training(devices8):
    """adafactor (factored second moment) trains and keeps optimizer state
    sublinear in params — the memory-light path that fits llama-1b on one
    16 GB chip."""
    # dims must exceed adafactor's min_dim_size_to_factor (128) for the
    # second moment to actually factor into row+col stats
    kw = dict(model_kwargs={"d_model": 256, "d_ff": 512, "head_dim": 64})
    t_adam = Trainer(lm_cfg(optimizer="adamw", total_steps=3, **kw))
    t_af = Trainer(lm_cfg(optimizer="adafactor", total_steps=3, **kw))
    _, summary = t_af.fit(steps=3)
    assert np.isfinite(summary["final"]["loss"])

    def state_bytes(tr):
        st = tr.init_state()
        return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(st.opt_state))

    assert state_bytes(t_af) < 0.25 * state_bytes(t_adam)


def test_midsize_gpt_configs_build():
    """gpt-350m / gpt-760m registry entries produce consistent configs and
    analytic FLOPs (used by the bench MFU meter)."""
    for name, d in [("gpt-350m", 1024), ("gpt-760m", 1536)]:
        m = get_model(name, vocab_size=512, n_layers=2, max_seq_len=64)
        assert m.cfg.d_model == d
        assert m.flops_per_token(seq_len=64) > 6 * 2 * 3 * d * m.cfg.d_ff


def test_llama_1b_hd128_matches_llama_1b_budget():
    """The TPU-shaped head variant is the SAME model budget — identical
    param count and per-token FLOPs as llama-1b (16x128 GQA heads vs
    32x64) — so its bench numbers are apples-to-apples."""
    def n_params(name):
        m = get_model(name, vocab_size=32000)
        tok = jnp.ones((1, 32), jnp.int32)
        v = jax.eval_shape(
            lambda: m.init(jax.random.PRNGKey(0), tok, train=False))
        return sum(int(jnp.prod(jnp.asarray(x.shape)))
                   for x in jax.tree.leaves(v)), m.flops_per_token(2048)

    (n_a, f_a), (n_b, f_b) = n_params("llama-1b"), n_params("llama-1b-hd128")
    assert n_a == n_b
    assert f_a == f_b
    m = get_model("llama-1b-hd128", vocab_size=512)
    assert (m.cfg.head_dim, m.cfg.n_heads, m.cfg.n_kv_heads) == (128, 16, 4)


def test_bert_seq_classification_trains(devices8):
    """BERT fine-tune shape through the Trainer: task=seq_classification
    (tokens in, one label per sequence out), loss decreases on a fixed
    batch."""
    cfg = lm_cfg(model="bert-test", task="seq_classification",
                 num_classes=4, total_steps=6,
                 optimizer="adamw", learning_rate=5e-3)
    trainer = Trainer(cfg)
    state = trainer.init_state()
    batch = next(trainer.data_iter())
    losses = []
    for _ in range(6):
        state, m = trainer.train_step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0], losses


def test_lm_trains_with_sliding_window(devices8):
    """attention_window trains end to end and produces a DIFFERENT loss
    than full attention (the mask is live)."""
    from kubeflow_tpu.parallel.mesh import MeshSpec
    from kubeflow_tpu.runtime.data import shard_batch
    from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

    base = dict(
        model="transformer-test",
        task="lm",
        global_batch=8,
        seq_len=32,
        vocab_size=256,
        mesh=MeshSpec(data=8),
        optimizer="adafactor",
        learning_rate=1e-3,
        total_steps=1,
        warmup_steps=1,
        log_every=10**9,
    )
    losses = {}
    for name, kw in [("full", {}), ("window", {"attention_window": 8})]:
        cfg = TrainConfig.from_dict(
            dict(base, model_kwargs={"attention_impl": "flash", **kw}))
        trainer = Trainer(cfg)
        batch = shard_batch(
            next(trainer.data_iter()),
            next(iter(jax.tree.leaves(trainer.batch_shardings))))
        _, m = trainer.train_step(trainer.init_state(), batch)
        losses[name] = float(m["loss"])
    assert np.isfinite(losses["window"])
    assert losses["window"] != losses["full"]


class TestMixedRematPolicy:
    """'policy@K' — remat the first K blocks, save everything on the
    rest: the fractional rung between whole-model policies (r5 ledger:
    gpt-760m bs8 slim missed HBM by 50MB; slim@15 would fit)."""

    def _loss(self, policy, remat=True):
        cfg = lm_cfg(model="transformer-test",
                     model_kwargs={"dtype": "float32"},
                     total_steps=1, remat=remat, remat_policy=policy)
        trainer = Trainer(cfg)
        state = trainer.init_state()
        _, m = trainer.train_step(state, next(trainer.data_iter()))
        return float(m["loss"])

    def test_mixed_policy_is_value_preserving(self):
        # remat changes residuals, never values (up to compile-level
        # reassociation): slim, slim@1 and no-remat agree to f32 ulps
        base = self._loss("full", remat=False)
        np.testing.assert_allclose(self._loss("slim"), base, rtol=1e-6)
        np.testing.assert_allclose(self._loss("slim@1"), base, rtol=1e-6)

    def test_mixed_policy_bounds_validated(self):
        with pytest.raises(ValueError, match="1[.][.]"):
            self._loss("slim@0")
        with pytest.raises(ValueError, match="1[.][.]"):
            self._loss("slim@99")

    def test_mixed_policy_rejected_under_pipeline(self):
        from kubeflow_tpu.models import transformer as T

        pcfg = T.TransformerConfig(vocab_size=64, d_model=32, n_layers=2,
                                   n_heads=2, n_kv_heads=2, head_dim=16,
                                   d_ff=64, remat=True,
                                   remat_policy="slim@1",
                                   pipeline_stages=2)
        x = jnp.zeros((2, 8, 32), jnp.bfloat16)
        with pytest.raises(ValueError, match="pipeline"):
            T.Stage(pcfg).init(jax.random.PRNGKey(0), x,
                               jnp.arange(8, dtype=jnp.int32))

    def test_mixed_policy_saves_fewer_residuals_than_none_more_than_full(self):
        from tools import remat_plan as rp

        m = get_model("transformer-test", vocab_size=256, n_layers=4,
                      max_seq_len=64, remat=True, remat_policy="slim")
        tok = jnp.ones((2, 32), jnp.int32)
        full_slim, _ = rp.residual_bytes(m, tok, "slim")
        m2 = get_model("transformer-test", vocab_size=256, n_layers=4,
                       max_seq_len=64, remat=True, remat_policy="slim@2")
        mixed, _ = rp.residual_bytes(m2, tok, "slim@2")
        m3 = get_model("transformer-test", vocab_size=256, n_layers=4,
                       max_seq_len=64)
        none, _ = rp.residual_bytes(m3, tok, "none")
        assert full_slim < mixed < none
