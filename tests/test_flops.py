"""Analytic FLOPs accounting (the MFU meter's numerator).

Round-1 postmortem: the meter fed the literature "4.1 GFLOPs" resnet50
number into a peak that counts multiply and add separately — but that
number is MACs (fvcore convention), silently halving every reported MFU.
These tests pin the convention: model FLOPs = 2*MACs, cross-checked
against XLA's own HLO cost analysis.
"""

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models.resnet import RESNET50_FWD_FLOPS_224, fwd_flops


class TestResNetAnalytic:
    def test_resnet50_is_twice_the_mac_count(self):
        # 4.09 GMACs x 2 = ~8.2e9; the old constant was the MAC count
        got = fwd_flops("resnet50")
        assert got == pytest.approx(2 * RESNET50_FWD_FLOPS_224, rel=0.02)

    def test_variants_scale_sensibly(self):
        r18 = fwd_flops("resnet18")
        r50 = fwd_flops("resnet50")
        r101 = fwd_flops("resnet101")
        assert r18 < r50 < r101
        # literature MACs: r18=1.82G, r101=7.8G (x2 for FLOPs)
        assert r18 == pytest.approx(2 * 1.82e9, rel=0.03)
        assert r101 == pytest.approx(2 * 7.8e9, rel=0.03)

    def test_image_size_scaling(self):
        # conv FLOPs scale ~quadratically in image size
        ratio = fwd_flops("resnet50", image_size=448) / fwd_flops("resnet50")
        assert ratio == pytest.approx(4.0, rel=0.05)

    def test_s2d_stem_costs_slightly_more(self):
        # 4x4x12 contraction vs 7x7x3: more MACs for a better MXU shape
        assert fwd_flops("resnet50", stem="space_to_depth") > fwd_flops("resnet50")

    def test_matches_xla_cost_analysis(self):
        """XLA's HLO flop count for a fwd pass agrees within 15% (XLA
        also counts BN/pool elementwise, so it sits slightly above)."""
        from kubeflow_tpu.models.registry import get_model

        model = get_model("resnet50", num_classes=1000)
        x = jnp.zeros((2, 224, 224, 3), jnp.float32)
        variables = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0), x, train=False))
        lowered = jax.jit(
            lambda v, x: model.apply(v, x, train=False)).lower(variables, x)
        ca = lowered.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        xla = float(ca.get("flops", 0.0))
        if xla <= 0:
            pytest.skip("cost analysis unavailable on this backend")
        analytic = 2 * fwd_flops("resnet50")
        assert xla == pytest.approx(analytic, rel=0.15)

    def test_unknown_variant_rejected(self):
        with pytest.raises(ValueError):
            fwd_flops("resnet34")


class TestTransformerAnalytic:
    def test_attention_term_added_with_seq_len(self):
        from kubeflow_tpu.models.registry import get_model

        m = get_model("gpt-125m")
        base = m.flops_per_token()
        with_attn = m.flops_per_token(seq_len=2048)
        cfg = m.cfg
        want_attn = 12.0 * cfg.n_layers * cfg.n_heads * cfg.head_dim * 2048 / 2
        assert with_attn - base == pytest.approx(want_attn)

    def test_trainer_uses_seq_aware_flops(self):
        from kubeflow_tpu.parallel.mesh import MeshSpec
        from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

        from kubeflow_tpu.parallel.mesh import build_mesh

        cfg = TrainConfig.from_dict(dict(
            model="transformer-test", task="lm", global_batch=4, seq_len=32,
            vocab_size=256, mesh=MeshSpec(data=1), total_steps=1))
        tr = Trainer(cfg, mesh=build_mesh(cfg.mesh, devices=jax.devices()[:1]))
        per_token = tr.model.flops_per_token(seq_len=32)
        assert tr.flops_per_step() == pytest.approx(per_token * 4 * 32)

    def test_bert_flops_per_token(self):
        from kubeflow_tpu.models.registry import get_model

        m = get_model("bert-test")
        base = m.flops_per_token()
        with_attn = m.flops_per_token(seq_len=128)
        assert with_attn > base > 0


class TestMoEFlops:
    def test_moe_layers_count_topk_experts(self):
        from kubeflow_tpu.models.registry import get_model

        dense = get_model("transformer-test")
        moe = get_model("transformer-test", moe_every=2, n_experts=4,
                        expert_top_k=2)
        # half the layers run top_k=2 expert MLPs -> more FLOPs/token
        assert moe.flops_per_token() > dense.flops_per_token()
        cfg = moe.cfg
        mlp = 3 * cfg.d_model * cfg.d_ff
        extra = 6.0 * (cfg.n_layers // 2) * (
            (cfg.expert_top_k - 1) * mlp + cfg.d_model * cfg.n_experts)
        assert moe.flops_per_token() - dense.flops_per_token() == \
            pytest.approx(extra)
