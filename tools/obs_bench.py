#!/usr/bin/env python
"""obs_bench — deterministic fleet-observability-plane benchmark.

Builds a synthetic serving+control fleet (default 160 replica
registries x ~70 series each ≈ 11k live series), drives the ISSUE-10
plane over it on a VIRTUAL clock — ScrapeLoop cycles through the ONE
exposition parser, then the full default rule pack (recording rules +
multi-window SLO burn + 4 more alerts) — through a scripted incident
window (slow router latencies on one service, reconcile error spike,
KV-page exhaustion, checkpoint failures, two replica targets dying and
reviving). Measures:

- deterministic half: samples ingested per cycle, live series count,
  store op counts, and the full alert-transition log (fingerprinted) —
  these replay byte-for-byte per seed;
- machine half: scrape and rule-eval wall duration percentiles — the
  budget the bank records ("rule evaluation over >=10k series inside
  X ms").

    python tools/obs_bench.py                 # full + smoke, write JSON
    python tools/obs_bench.py --replicas 24 --cycles 24
    python tools/obs_bench.py --check         # CI gate: rerun the banked
        # smoke config; fail when the decision fingerprint or the exact
        # op counts drift, or the eval/scrape p99 regresses past 3x the
        # committed budget (floor 250 ms)
"""

from __future__ import annotations

import argparse
import hashlib
import json
import math
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.obs.plane import FleetPlane  # noqa: E402
from kubeflow_tpu.obs.tsdb import RegistryTarget  # noqa: E402
from kubeflow_tpu.obs.rules import default_rule_pack  # noqa: E402
from kubeflow_tpu.runtime.metrics import (  # noqa: E402
    DEFAULT_BUCKETS, MetricsRegistry,
)
from kubeflow_tpu.serving.router import REQUEST_BUCKETS  # noqa: E402

DEFAULT_OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_OBS_r01.json")

MODELS = ("llama-1b", "gemma-4b", "bert")
CONTROLLERS = ("jaxjob", "scheduler", "jaxservice", "notebook")
SCRAPE_INTERVAL_S = 15.0
LATENCY_TARGET_S = 0.5


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class SyntheticFleet:
    """Seeded workload generator over real MetricsRegistry objects —
    the plane scrapes EXACTLY what production registries render."""

    def __init__(self, replicas: int, seed: int):
        self.rng = random.Random(seed)
        self.replicas = [MetricsRegistry() for _ in range(replicas)]
        self.router = MetricsRegistry()
        self.control = MetricsRegistry()
        self.services = [f"svc-{i}" for i in range(4)]
        self.incident = False
        self.dead: set[int] = set()
        self._ckpt_failures = 0

    def targets(self) -> list[RegistryTarget]:
        out = [RegistryTarget("router", self.router,
                              labels={"job": "router"}),
               RegistryTarget("control", self.control,
                              labels={"job": "control"})]
        for i, reg in enumerate(self.replicas):
            t = RegistryTarget(f"replica-{i:03d}", reg,
                               labels={"job": "serving"})
            if i in self.dead:
                # a dead target: fetch raises, like a refused connection
                t.fetch = self._raise  # type: ignore[method-assign]
            out.append(t)
        return out

    @staticmethod
    def _raise() -> str:
        raise ConnectionError("replica gone")

    def step(self) -> None:
        """One interval of synthetic traffic."""
        rng = self.rng
        # router: per-service request latencies into the SLO histogram.
        # svc-0 degrades during the incident (the SLO-burn driver).
        for svc in self.services:
            n = rng.randint(40, 60)
            for _ in range(n):
                if self.incident and svc == "svc-0":
                    lat = rng.uniform(0.8, 2.5)
                else:
                    lat = rng.uniform(0.02, 0.3)
                self.router.histogram(
                    "router_request_seconds", lat,
                    buckets=REQUEST_BUCKETS,
                    namespace="default", service=svc)
            self.router.counter_inc(
                "router_tokens_total", by=float(n * 40),
                namespace="default", service=svc)
            self.router.gauge("router_queue_depth",
                              rng.randint(0, 8),
                              namespace="default", service=svc)
        # control plane: reconciles; jaxjob errors spike in the incident
        for ctl in CONTROLLERS:
            ok = rng.randint(20, 30)
            err = rng.randint(5, 8) if (self.incident
                                        and ctl == "jaxjob") else 0
            self.control.counter_inc("controller_reconcile_total",
                                     by=float(ok), controller=ctl,
                                     result="success")
            if err:
                self.control.counter_inc("controller_reconcile_total",
                                         by=float(err), controller=ctl,
                                         result="error")
        # scheduler pass durations: slow passes during the incident
        for _ in range(rng.randint(3, 5)):
            dur = rng.uniform(1.2, 3.0) if self.incident \
                else rng.uniform(0.004, 0.02)
            self.control.histogram("scheduler_pass_seconds", dur,
                                   buckets=DEFAULT_BUCKETS)
        if self.incident:
            self._ckpt_failures += 1
            self.control.counter_inc("checkpoint_failures_total",
                                     op="save")
        # replicas: the serving decode surface
        for i, reg in enumerate(self.replicas):
            if i in self.dead:
                continue
            for model in MODELS:
                # exhaustion lands on replica 2 — NOT one of the kill
                # drill's victims (0,1), whose series go stale and
                # could never hold an alert through the fault window
                free = 0 if (self.incident and i == 2
                             and model == MODELS[0]) \
                    else rng.randint(4, 128)
                reg.gauge("serving_kv_pages_free", free, model=model)
                reg.gauge("serving_kv_pages_used", 128 - min(free, 128),
                          model=model)
                reg.counter_inc("serving_prefix_cache_hits_total",
                                by=float(rng.randint(0, 30)), model=model)
                reg.counter_inc("serving_prefill_tokens_total",
                                by=float(rng.randint(100, 900)),
                                model=model)
                reg.counter_inc("serving_spec_rounds_total",
                                by=float(rng.randint(5, 25)), model=model)
                reg.counter_inc("serving_spec_tokens_accepted_total",
                                by=float(rng.randint(20, 100)),
                                model=model)
                reg.counter_inc("serving_tokens_generated_total",
                                by=float(rng.randint(200, 1200)),
                                model=model)
                reg.histogram("serving_predict_seconds",
                              rng.uniform(0.05, 0.8),
                              buckets=DEFAULT_BUCKETS, model=model)


def _percentile(samples: list[float], q: float) -> float:
    if not samples:
        return 0.0
    xs = sorted(samples)
    return xs[min(len(xs) - 1, int(math.ceil(q * len(xs))) - 1)]


def run_bench(replicas: int, cycles: int, seed: int = 0,
              incident: tuple[int, int] = (8, 18),
              kill: tuple[int, int] = (10, 16),
              short_window: str = "1m",
              long_window: str = "5m") -> dict:
    """One deterministic plane run; returns stats + the decision log.
    ``incident``/``kill`` are [start, end) cycle windows."""
    clock = ManualClock()
    fleet = SyntheticFleet(replicas, seed)
    registry = MetricsRegistry()  # the plane's own (not scraped)
    plane = FleetPlane(
        registry=registry, recorder=None,
        discover=fleet.targets,  # re-discovered per cycle (deaths move)
        rules=default_rule_pack(latency_target_s=LATENCY_TARGET_S,
                                short_window=short_window,
                                long_window=long_window),
        interval_s=SCRAPE_INTERVAL_S, clock=clock,
        max_points=128, max_series=100000)

    scrape_ms: list[float] = []
    eval_ms: list[float] = []
    transitions: list[dict] = []
    samples_per_cycle: list[int] = []
    for cycle in range(cycles):
        fleet.incident = incident[0] <= cycle < incident[1]
        fleet.dead = {0, 1} if kill[0] <= cycle < kill[1] else set()
        fleet.step()
        t0 = time.perf_counter()
        scrape = plane.scraper.scrape_once()
        t1 = time.perf_counter()
        trs = plane.engine.evaluate_once(at=clock.t)
        t2 = time.perf_counter()
        scrape_ms.append((t1 - t0) * 1e3)
        eval_ms.append((t2 - t1) * 1e3)
        samples_per_cycle.append(scrape["samples"])
        for tr in trs:
            transitions.append({"cycle": cycle, **tr})
        clock.advance(SCRAPE_INTERVAL_S)

    store_stats = plane.store.stats()
    decision_log = json.dumps(transitions, sort_keys=True)
    fired = sorted({t["alert"] for t in transitions
                    if t["to"] == "firing"})
    resolved = sorted({t["alert"] for t in transitions
                       if t["to"] == "resolved"})
    return {
        "config": {"replicas": replicas, "cycles": cycles, "seed": seed,
                   "incident": list(incident), "kill": list(kill),
                   "short_window": short_window,
                   "long_window": long_window},
        "series": store_stats["series"],
        "points": store_stats["points"],
        "appends": store_stats["appends"],
        "dropped": store_stats["dropped"],
        "samples_first_cycle": samples_per_cycle[0],
        "samples_total": sum(samples_per_cycle),
        "scrape_p50_ms": round(_percentile(scrape_ms, 0.50), 3),
        "scrape_p99_ms": round(_percentile(scrape_ms, 0.99), 3),
        "eval_p50_ms": round(_percentile(eval_ms, 0.50), 3),
        "eval_p99_ms": round(_percentile(eval_ms, 0.99), 3),
        "alerts_fired": fired,
        "alerts_resolved": resolved,
        "transitions": len(transitions),
        "decision_fingerprint": hashlib.sha256(
            decision_log.encode()).hexdigest(),
    }


FULL_CONFIG = {"replicas": 160, "cycles": 48, "seed": 0,
               "incident": (8, 18), "kill": (10, 16)}
SMOKE_CONFIG = {"replicas": 24, "cycles": 24, "seed": 0,
                "incident": (6, 12), "kill": (8, 11),
                "short_window": "30s", "long_window": "2m"}


def check_against(banked_path: str) -> int:
    """CI ratchet: rerun the banked smoke config. Fail (1) when the
    decision fingerprint or the exact op counts drift (the rules
    DECIDED differently / the scraper re-scanned — semantic
    regressions), or when scrape/eval p99 regresses past 3x the
    committed budget (floored at 250 ms so wall-clock contention on a
    busy CI machine cannot flake the gate)."""
    with open(banked_path) as fh:
        banked = json.load(fh)
    smoke = banked.get("smoke")
    if not smoke:
        print(f"check: no smoke section in {banked_path}", file=sys.stderr)
        return 2
    cfg = dict(smoke["config"])
    cfg["incident"] = tuple(cfg["incident"])
    cfg["kill"] = tuple(cfg["kill"])
    now = run_bench(**cfg)
    ok = True
    if now["decision_fingerprint"] != smoke["decision_fingerprint"]:
        print("check: decision fingerprint drifted "
              f"({now['decision_fingerprint'][:12]} != banked "
              f"{smoke['decision_fingerprint'][:12]}) — the rule engine "
              "made different alerting decisions on identical input",
              file=sys.stderr)
        ok = False
    for key in ("appends", "series", "samples_total"):
        if now[key] != smoke[key]:
            print(f"check: {key} {now[key]} != banked {smoke[key]} "
                  "(scrape op counts must replay exactly)",
                  file=sys.stderr)
            ok = False
    for key in ("scrape_p99_ms", "eval_p99_ms"):
        # 3x + an absolute floor: the wall gate exists to catch order-
        # of-magnitude regressions (an accidental O(series) rescan) and
        # must not flake when CI shares cores with a compile storm —
        # the DETERMINISTIC counters above are the tight gate, and a
        # real rescan also moves them
        budget = max(smoke[key] * 3.0, 250.0)
        if now[key] > budget:
            print(f"check: {key} {now[key]} exceeds budget {budget:.3f} "
                  f"(banked {smoke[key]})", file=sys.stderr)
            ok = False
    print(json.dumps({"check": "ok" if ok else "REGRESSED",
                      "eval_p99_ms": now["eval_p99_ms"],
                      "scrape_p99_ms": now["scrape_p99_ms"],
                      "fingerprint": now["decision_fingerprint"][:12]},
                     indent=2))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--replicas", type=int, default=None)
    ap.add_argument("--cycles", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=DEFAULT_OUT)
    ap.add_argument("--no-smoke", action="store_true")
    ap.add_argument("--check", action="store_true",
                    help="rerun the banked smoke config and gate on "
                         "fingerprint/op-count drift or a >3x p99 "
                         "budget regression")
    args = ap.parse_args(argv)
    if args.check:
        return check_against(args.out)

    config = dict(FULL_CONFIG, seed=args.seed)
    if args.replicas:
        config["replicas"] = args.replicas
    if args.cycles:
        config["cycles"] = args.cycles
    full = run_bench(**config)
    result = {"bench": "obs_bench", "round": "r01", "full": full}
    if not args.no_smoke:
        result["smoke"] = run_bench(**SMOKE_CONFIG)
    if full["series"] < 10000:
        print(f"WARNING: full config produced only {full['series']} "
              "series (<10k)", file=sys.stderr)
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps({
        "out": args.out,
        "series": full["series"],
        "eval_p99_ms": full["eval_p99_ms"],
        "scrape_p99_ms": full["scrape_p99_ms"],
        "alerts_fired": full["alerts_fired"],
        "alerts_resolved": full["alerts_resolved"]}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
