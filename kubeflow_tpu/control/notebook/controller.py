"""Notebook controller: CR -> StatefulSet + Service + VirtualService.

Reconcile mirrors notebook_controller.go:85-279; generators mirror
generateStatefulSet :282-348, generateService :349-380,
generateVirtualService :382-443. Env knobs kept: USE_ISTIO, ISTIO_GATEWAY,
CLUSTER_DOMAIN, ADD_FSGROUP. Status is derived from the pod's container
state (:200-231), and namespace Events involving the notebook's pod are
re-emitted onto the Notebook (:565-613) so JWA/dashboard can show them.
"""

from __future__ import annotations

import logging
import os

import prometheus_client as prom

from kubeflow_tpu.control import reconcilehelper as rh
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.notebook import culler
from kubeflow_tpu.control.notebook import types as T
from kubeflow_tpu.control.runtime import Controller, Reconciler, Request, Result

log = logging.getLogger("kubeflow_tpu.notebook")

def _metric(name, kind, doc):
    from kubeflow_tpu.runtime.metrics import prom_metric

    return prom_metric(name, kind, doc)


# metrics.go:27-61 names kept
def nb_created():
    return _metric("notebook_create_total", prom.Counter, "notebooks created")


def nb_culled():
    return _metric("notebook_culling_total", prom.Counter, "notebooks culled")


def nb_create_failed():
    return _metric("notebook_create_failed_total", prom.Counter,
                   "Total failure times of creating notebooks")


def nb_culling_timestamp():
    return _metric("last_notebook_culling_timestamp_seconds", prom.Gauge,
                   "Timestamp of the last notebook culling in seconds")


class RunningNotebooksCollector:
    """Live-state `notebook_running` gauge: scraped from the CURRENT
    StatefulSet inventory at every /metrics collection, not from
    controller event counters — restart-proof and drift-proof, exactly
    metrics.go:95-116's scrape(). An STS counts when its pod template
    carries notebook-name == its own name (the shape generate_statefulset
    produces)."""

    def __init__(self, client):
        self.client = client

    def collect(self):
        from prometheus_client.core import GaugeMetricFamily

        g = GaugeMetricFamily(
            "notebook_running", "Current running notebooks in the cluster",
            labels=["namespace"])
        try:
            # server-side filter: only notebook-owned STS (the controller
            # labels the STS object itself); template labels re-checked
            # below for metrics.go parity
            stss = self.client.list(
                "apps/v1", "StatefulSet",
                label_selector={"matchExpressions": [
                    {"key": T.LABEL_NOTEBOOK_NAME, "operator": "Exists"}]})
        except Exception as e:  # apiserver unreachable: emit nothing, not 0s
            log.warning("notebook_running scrape failed: %s", e)
            return [g]
        counts: dict[str, int] = {}
        for sts in stss:
            tmpl_labels = (((sts.get("spec") or {}).get("template") or {})
                           .get("metadata") or {}).get("labels") or {}
            if tmpl_labels.get(T.LABEL_NOTEBOOK_NAME) == ob.meta(sts)["name"]:
                ns = ob.meta(sts).get("namespace") or "default"
                counts[ns] = counts.get(ns, 0) + 1
        for ns, v in sorted(counts.items()):
            g.add_metric([ns], v)
        return [g]

    def register(self, registry=None) -> "RunningNotebooksCollector":
        import prometheus_client

        (registry or prometheus_client.REGISTRY).register(self)
        return self


def use_istio() -> bool:
    return os.environ.get("USE_ISTIO", "false").lower() == "true"


def istio_gateway() -> str:
    return os.environ.get("ISTIO_GATEWAY", "kubeflow/kubeflow-gateway")


def cluster_domain() -> str:
    return os.environ.get("CLUSTER_DOMAIN", "cluster.local")


class NotebookReconciler(Reconciler):
    def __init__(self, probe=culler.default_probe, cache=None):
        self.probe = probe
        # indexed ClusterCache (ROADMAP #3's remaining wiring): pod and
        # Event reads come from the snapshot instead of per-reconcile
        # list calls; None keeps the legacy relist shape.
        self.cache = cache

    def _nb_pods(self, client, namespace: str, name: str) -> list[dict]:
        if self.cache is not None:
            return self.cache.pods_by_label(
                T.LABEL_NOTEBOOK_NAME, namespace, name)
        return client.list(
            "v1", "Pod", namespace=namespace,
            label_selector={"matchLabels": {T.LABEL_NOTEBOOK_NAME: name}},
        )

    def _ns_events(self, client, namespace: str) -> list[dict]:
        if self.cache is not None:
            # O(namespace bucket): Events are the churniest,
            # highest-cardinality kind — a cluster-wide snapshot scan
            # per reconcile would defeat the indexed-cache wiring
            return self.cache.objects_ns("v1", "Event", namespace)
        return client.list("v1", "Event", namespace=namespace)

    # -- generators ---------------------------------------------------------

    def generate_statefulset(self, nb: dict) -> dict:
        m = ob.meta(nb)
        tmpl = ob.deep_copy((nb.get("spec") or {}).get("template") or {"spec": {}})
        pod_spec = tmpl.setdefault("spec", {})
        containers = pod_spec.setdefault("containers", [{}])
        c0 = containers[0]
        c0.setdefault("name", m["name"])
        c0.setdefault("workingDir", T.HOME_DIR)  # :318
        c0.setdefault("ports", [{"containerPort": T.CONTAINER_PORT, "name": "notebook-port",
                                 "protocol": "TCP"}])
        env = c0.setdefault("env", [])
        if not any(e.get("name") == T.ENV_NB_PREFIX for e in env):
            env.append({"name": T.ENV_NB_PREFIX,
                        "value": f"/notebook/{m['namespace']}/{m['name']}"})  # :329-332
        if os.environ.get("ADD_FSGROUP", "true").lower() == "true":
            pod_spec.setdefault("securityContext", {}).setdefault("fsGroup", 100)  # :338-345

        labels = tmpl.setdefault("metadata", {}).setdefault("labels", {})
        labels[T.LABEL_NOTEBOOK_NAME] = m["name"]
        labels["statefulset"] = m["name"]

        replicas = 0 if culler.is_stopped(nb) else 1  # :284-286 scale-to-zero
        return ob.new_object(
            "apps/v1", "StatefulSet", m["name"], m["namespace"],
            labels={T.LABEL_NOTEBOOK_NAME: m["name"]},
            spec={
                "serviceName": m["name"],
                "replicas": replicas,
                "selector": {"matchLabels": {"statefulset": m["name"]}},
                "template": tmpl,
            },
        )

    def generate_service(self, nb: dict) -> dict:
        m = ob.meta(nb)
        return ob.new_object(
            "v1", "Service", m["name"], m["namespace"],
            labels={T.LABEL_NOTEBOOK_NAME: m["name"]},
            spec={
                "type": "ClusterIP",
                "selector": {"statefulset": m["name"]},
                "ports": [{
                    # Istio needs the protocol-prefixed port name (:367)
                    "name": f"http-{m['name']}",
                    "port": T.SERVICE_PORT,
                    "targetPort": T.CONTAINER_PORT,
                    "protocol": "TCP",
                }],
            },
        )

    def generate_virtual_service(self, nb: dict) -> dict:
        """Route /notebook/<ns>/<name>/ through the mesh gateway (:382-443)."""
        m = ob.meta(nb)
        prefix = f"/notebook/{m['namespace']}/{m['name']}/"
        host = f"{m['name']}.{m['namespace']}.svc.{cluster_domain()}"
        return ob.new_object(
            "networking.istio.io/v1alpha3", "VirtualService",
            f"notebook-{m['namespace']}-{m['name']}", m["namespace"],
            spec={
                "hosts": ["*"],
                "gateways": [istio_gateway()],
                "http": [{
                    "match": [{"uri": {"prefix": prefix}}],
                    "rewrite": {"uri": prefix},
                    "route": [{"destination": {
                        "host": host, "port": {"number": T.SERVICE_PORT}}}],
                    "timeout": "300s",  # :433
                }],
            },
        )

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, client, req: Request) -> Result | None:
        if self.cache is not None:
            self.cache.refresh()
        nb = client.get_or_none(T.API_VERSION, T.KIND, req.name, req.namespace)
        if nb is None or ob.meta(nb).get("deletionTimestamp"):
            return None

        first_seen = not (nb.get("status") or {})
        if first_seen:
            nb_created().inc()

        try:
            rh.reconcile_child(client, nb, self.generate_statefulset(nb))
        except Exception:
            # metrics.go:41 notebook_create_failed_total; the reconcile
            # error still propagates so the workqueue retries with backoff
            nb_create_failed().inc()
            raise
        rh.reconcile_child(client, nb, self.generate_service(nb))
        if use_istio():
            rh.reconcile_child(client, nb, self.generate_virtual_service(nb))

        # -- status from pod container state (:200-231) --------------------
        pods = self._nb_pods(client, req.namespace, req.name)
        status = nb.setdefault("status", {})
        status["readyReplicas"] = sum(
            1 for p in pods
            if all(cs.get("ready") for cs in
                   (p.get("status") or {}).get("containerStatuses") or [{}])
            and (p.get("status") or {}).get("phase") == "Running"
        )
        if pods:
            cs = ((pods[0].get("status") or {}).get("containerStatuses") or [])
            if cs:
                status["containerState"] = cs[0].get("state", {})
        # re-emit pod events onto the Notebook (:565-613)
        self._forward_pod_events(client, nb, pods)

        ready = bool(status.get("readyReplicas"))
        changed = ob.cond_set(
            nb, "Ready",
            "True" if ready else "False",
            "NotebookReady" if ready else "NotebookNotReady",
        )
        client.update_status(nb)
        if changed:
            # readiness transitions are decision points worth an Event
            # (count-dedup in obs/events.py absorbs flapping pods)
            client.record_event(
                nb, "NotebookReady" if ready else "NotebookNotReady",
                f"readyReplicas={status.get('readyReplicas', 0)}",
                "Normal" if ready else "Warning")

        # -- culling (:250 -> culler.GetRequeueTime) ------------------------
        if culler.enabled() and not culler.is_stopped(nb):
            if culler.needs_culling(nb, probe=self.probe):
                fresh = client.get(T.API_VERSION, T.KIND, req.name, req.namespace)
                culler.set_stop_annotation(fresh)
                client.update(fresh)
                nb_culled().inc()
                nb_culling_timestamp().set_to_current_time()
                client.record_event(fresh, "Culling", "notebook idle; scaling to zero")
                return Result(requeue_after=0.0)
            return Result(requeue_after=culler.requeue_seconds())
        return None

    def _forward_pod_events(self, client, nb: dict, pods: list[dict]) -> None:
        nb_uid = ob.meta(nb).get("uid", "")
        pod_names = {ob.meta(p)["name"] for p in pods}
        if not pod_names:
            return
        events = self._ns_events(client, ob.meta(nb)["namespace"])
        # forwarded-marker set computed ONCE per reconcile (the legacy
        # shape re-listed the namespace's events per candidate)
        forwarded = {
            e.get("source", {}).get("component")
            for e in events
            if (e.get("involvedObject") or {}).get("uid") == nb_uid
        }
        for ev in events:
            inv = ev.get("involvedObject") or {}
            if inv.get("kind") != "Pod" or inv.get("name") not in pod_names:
                continue
            marker = f"nb-fwd-{ev['metadata']['name']}"
            if marker in forwarded:
                continue
            rec = client.record_event(nb, ev.get("reason", ""),
                                      ev.get("message", ""),
                                      ev.get("type", "Normal"),
                                      component=marker)
            if self.cache is not None and rec:
                # fold our own marker in (the note_write discipline): a
                # pumped snapshot lagging the watch would re-forward the
                # same pod event on the next reconcile
                self.cache.note_write(rec)


def build_controller(client, probe=culler.default_probe,
                     cache: bool = True) -> Controller:
    """``cache=True`` (default) serves the reconciler's pod and Event
    reads from an indexed ``ClusterCache`` — zero per-reconcile list
    calls (pinned in tests/test_cache.py); ``cache=False`` keeps the
    legacy relist shape."""
    cluster_cache = None
    if cache:
        from kubeflow_tpu.control.cache import ClusterCache

        cluster_cache = ClusterCache(
            client, kinds=(("v1", "Pod"), ("v1", "Event")),
            pod_labels=(T.LABEL_NOTEBOOK_NAME,)).connect()
    rec = NotebookReconciler(probe=probe, cache=cluster_cache)
    ctl = Controller("notebook", client, rec)
    if cluster_cache is not None:
        ctl.uses(cluster_cache)
    ctl.watches_primary(T.API_VERSION, T.KIND)
    ctl.owns("apps/v1", "StatefulSet").owns("v1", "Service")

    # map pods to notebooks via the notebook-name label (:541-563)
    def pod_to_nb(pod: dict):
        name = ob.labels_of(pod).get(T.LABEL_NOTEBOOK_NAME)
        if name:
            return [Request(ob.meta(pod).get("namespace") or "", name)]
        return []

    ctl.maps("v1", "Pod", pod_to_nb)
    return ctl
