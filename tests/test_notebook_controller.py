"""Notebook controller + culler semantics (reference:
notebook_controller_test.go, culler_test.go — SURVEY.md §4 tier 1)."""

import datetime

import pytest

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.notebook import culler
from kubeflow_tpu.control.notebook import types as T
from kubeflow_tpu.control.notebook.controller import build_controller
from kubeflow_tpu.control.runtime import seed_controller


@pytest.fixture()
def world(monkeypatch):
    monkeypatch.delenv("ENABLE_CULLING", raising=False)
    monkeypatch.delenv("USE_ISTIO", raising=False)
    cluster = FakeCluster()
    probe_state = {"last_activity": None}
    ctl = seed_controller(
        build_controller(cluster, probe=lambda nb: probe_state["last_activity"])
    )
    return cluster, ctl, probe_state


def drain(ctl):
    for _ in range(4):
        ctl.run_until_idle(advance_delayed=True)


class TestGenerate:
    def test_creates_statefulset_and_service(self, world):
        cluster, ctl, _ = world
        cluster.create(T.new_notebook("nb1", tpu_chips=4))
        drain(ctl)
        sts = cluster.get("apps/v1", "StatefulSet", "nb1", "default")
        assert sts["spec"]["replicas"] == 1
        c0 = sts["spec"]["template"]["spec"]["containers"][0]
        assert c0["workingDir"] == T.HOME_DIR
        env = {e["name"]: e["value"] for e in c0["env"]}
        assert env[T.ENV_NB_PREFIX] == "/notebook/default/nb1"
        assert c0["resources"]["limits"][T.RESOURCE_TPU] == 4
        assert sts["spec"]["template"]["spec"]["securityContext"]["fsGroup"] == 100
        svc = cluster.get("v1", "Service", "nb1", "default")
        port = svc["spec"]["ports"][0]
        assert (port["port"], port["targetPort"]) == (80, 8888)
        assert port["name"] == "http-nb1"  # istio port-name convention

    def test_virtual_service_only_with_istio(self, world, monkeypatch):
        cluster, ctl, _ = world
        cluster.create(T.new_notebook("nb1"))
        drain(ctl)
        assert not cluster.list("networking.istio.io/v1alpha3", "VirtualService")
        monkeypatch.setenv("USE_ISTIO", "true")
        cluster.create(T.new_notebook("nb2"))
        drain(ctl)
        vs = cluster.get(
            "networking.istio.io/v1alpha3", "VirtualService",
            "notebook-default-nb2", "default",
        )
        http = vs["spec"]["http"][0]
        assert http["match"][0]["uri"]["prefix"] == "/notebook/default/nb2/"
        assert http["timeout"] == "300s"
        assert vs["spec"]["gateways"] == ["kubeflow/kubeflow-gateway"]

    def test_status_tracks_pod_readiness(self, world):
        cluster, ctl, _ = world
        nb = cluster.create(T.new_notebook("nb1"))
        drain(ctl)
        pod = ob.new_object("v1", "Pod", "nb1-0", "default",
                            labels={T.LABEL_NOTEBOOK_NAME: "nb1"},
                            spec={"containers": [{"name": "nb1"}]})
        pod["status"] = {
            "phase": "Running",
            "containerStatuses": [
                {"name": "nb1", "ready": True,
                 "state": {"running": {"startedAt": ob.now_iso()}}}],
        }
        cluster.create(pod)
        drain(ctl)
        got = cluster.get(T.API_VERSION, T.KIND, "nb1", "default")
        assert got["status"]["readyReplicas"] == 1
        assert "running" in got["status"]["containerState"]
        assert ob.cond_is_true(got, "Ready")

    def test_pod_events_forwarded_to_notebook(self, world):
        cluster, ctl, _ = world
        cluster.create(T.new_notebook("nb1"))
        drain(ctl)
        pod = ob.new_object("v1", "Pod", "nb1-0", "default",
                            labels={T.LABEL_NOTEBOOK_NAME: "nb1"},
                            spec={"containers": [{"name": "nb1"}]})
        pod = cluster.create(pod)
        cluster.record_event(pod, "Pulled", "image pulled")
        drain(ctl)
        nb = cluster.get(T.API_VERSION, T.KIND, "nb1", "default")
        nb_events = [
            e for e in cluster.list("v1", "Event", namespace="default")
            if (e.get("involvedObject") or {}).get("uid") == ob.meta(nb)["uid"]
        ]
        assert any(e["reason"] == "Pulled" for e in nb_events)


class TestCuller:
    def test_disabled_by_default(self, world):
        _, _, _ = world
        assert not culler.enabled()
        assert not culler.needs_culling({}, probe=lambda nb: "2020-01-01T00:00:00Z")

    def test_is_idle_threshold(self, monkeypatch):
        monkeypatch.setenv("CULL_IDLE_TIME", "60")  # minutes
        now = datetime.datetime(2026, 1, 1, 12, 0, tzinfo=datetime.timezone.utc)
        assert culler.is_idle("2026-01-01T10:00:00Z", now=now)
        assert not culler.is_idle("2026-01-01T11:30:00Z", now=now)
        assert not culler.is_idle(None, now=now)
        assert not culler.is_idle("garbage", now=now)

    def test_culling_scales_to_zero(self, world, monkeypatch):
        cluster, ctl, probe_state = world
        monkeypatch.setenv("ENABLE_CULLING", "true")
        monkeypatch.setenv("CULL_IDLE_TIME", "60")
        cluster.create(T.new_notebook("nb1"))
        drain(ctl)
        assert cluster.get("apps/v1", "StatefulSet", "nb1", "default")["spec"]["replicas"] == 1
        # report ancient activity -> idle -> stop annotation -> replicas 0
        probe_state["last_activity"] = "2020-01-01T00:00:00Z"
        drain(ctl)
        nb = cluster.get(T.API_VERSION, T.KIND, "nb1", "default")
        assert T.STOP_ANNOTATION in ob.annotations_of(nb)
        drain(ctl)
        sts = cluster.get("apps/v1", "StatefulSet", "nb1", "default")
        assert sts["spec"]["replicas"] == 0

    def test_stopped_notebook_not_probed(self, world, monkeypatch):
        cluster, ctl, probe_state = world
        monkeypatch.setenv("ENABLE_CULLING", "true")
        nb = T.new_notebook("nb1")
        culler.set_stop_annotation(nb)
        cluster.create(nb)
        probe_state["last_activity"] = "2020-01-01T00:00:00Z"
        drain(ctl)
        sts = cluster.get("apps/v1", "StatefulSet", "nb1", "default")
        assert sts["spec"]["replicas"] == 0
        assert not culler.needs_culling(nb, probe=lambda n: "2020-01-01T00:00:00Z")

    def test_restart_by_removing_stop_annotation(self, world):
        cluster, ctl, _ = world
        nb = T.new_notebook("nb1")
        culler.set_stop_annotation(nb)
        cluster.create(nb)
        drain(ctl)
        assert cluster.get("apps/v1", "StatefulSet", "nb1", "default")["spec"]["replicas"] == 0
        fresh = cluster.get(T.API_VERSION, T.KIND, "nb1", "default")
        del ob.meta(fresh)["annotations"][T.STOP_ANNOTATION]
        cluster.update(fresh)
        drain(ctl)
        assert cluster.get("apps/v1", "StatefulSet", "nb1", "default")["spec"]["replicas"] == 1


class TestRunningNotebooksCollector:
    """Live-state notebook_running (metrics.go:95-116): the gauge reads
    CURRENT STS inventory at collection time — controller restarts and
    out-of-band deletions can't skew it."""

    def _scrape(self, cluster):
        from prometheus_client import CollectorRegistry, generate_latest

        from kubeflow_tpu.control.notebook.controller import (
            RunningNotebooksCollector)

        reg = CollectorRegistry()
        RunningNotebooksCollector(cluster).register(reg)
        return generate_latest(reg).decode()

    def test_counts_live_statefulsets_per_namespace(self):
        cluster = FakeCluster()
        ctl = seed_controller(build_controller(cluster))
        for ns, name in [("team-a", "nb1"), ("team-a", "nb2"),
                         ("team-b", "nb3")]:
            cluster.create(T.new_notebook(name, ns))
        ctl.run_until_idle(advance_delayed=True)
        out = self._scrape(cluster)
        assert 'notebook_running{namespace="team-a"} 2.0' in out
        assert 'notebook_running{namespace="team-b"} 1.0' in out
        # deletion reflects at the NEXT scrape with no controller help
        cluster.delete("apps/v1", "StatefulSet", "nb2", "team-a")
        out = self._scrape(cluster)
        assert 'notebook_running{namespace="team-a"} 1.0' in out

    def test_foreign_statefulsets_not_counted(self):
        cluster = FakeCluster()
        sts = ob.new_object("apps/v1", "StatefulSet", "other", "team-a")
        sts["spec"] = {"template": {"metadata": {"labels": {"app": "x"}}}}
        cluster.create(sts)
        # labeled like a notebook STS but template name mismatch: passes
        # the server-side selector, rejected by the metrics.go template
        # check (notebook-name == sts name)
        sts2 = ob.new_object("apps/v1", "StatefulSet", "liar", "team-a",
                             labels={"notebook-name": "somebody-else"})
        sts2["spec"] = {"template": {"metadata": {"labels": {
            "notebook-name": "somebody-else"}}}}
        cluster.create(sts2)
        out = self._scrape(cluster)
        assert "notebook_running{" not in out

    def test_culling_sets_timestamp_gauge(self, monkeypatch):
        import prometheus_client

        from kubeflow_tpu.control.notebook import culler
        from kubeflow_tpu.control.notebook.controller import (
            nb_culling_timestamp)

        cluster = FakeCluster()
        ctl = seed_controller(build_controller(cluster))
        monkeypatch.setenv("ENABLE_CULLING", "true")
        monkeypatch.setenv("CULL_IDLE_TIME", "0")
        monkeypatch.setattr(culler, "needs_culling",
                            lambda nb, probe=None: True)
        cluster.create(T.new_notebook("idle-nb", "default"))
        before = nb_culling_timestamp()._value.get()
        ctl.run_until_idle(advance_delayed=True)
        nb = cluster.get(T.API_VERSION, T.KIND, "idle-nb", "default")
        assert culler.is_stopped(nb)
        assert nb_culling_timestamp()._value.get() > before


def test_create_failure_counter_increments_and_error_propagates():
    from kubeflow_tpu.control.notebook.controller import nb_create_failed

    class _Refusing(FakeCluster):
        def create(self, obj):
            if obj.get("kind") == "StatefulSet":
                raise ob.ApiError("quota exceeded")
            return super().create(obj)

    cluster = _Refusing()
    ctl = seed_controller(build_controller(cluster))
    before = nb_create_failed()._value.get()
    cluster.create(T.new_notebook("doomed", "default"))
    ctl.run_until_idle(advance_delayed=True)
    assert nb_create_failed()._value.get() > before
    # the workqueue kept retrying (error propagated, not swallowed)
    assert cluster.get_or_none("apps/v1", "StatefulSet", "doomed",
                               "default") is None
