"""Alert-driven remediation: the detect->decide->act layer.

PR 10's rule engine detects every incident it stages; this module
closes the loop. A ``RemediationEngine`` consumes the transition list
each ``RuleEngine.evaluate_once`` pass returns and, when an
``AlertRule`` enters ``firing``, runs the registered ``Remediation``
for that alert. Acting only on the *firing* transition inherits the
rule engine's pending->firing damping wholesale: an alert that
oscillates pending->inactive across evaluation ticks produces no
firing transition, so it can never trigger an action or burn a
cooldown — flap protection is structural, not a timer.

Guardrails, in evaluation order per firing transition:

- label matchers scope a remediation to a subset of a rule's label
  sets (e.g. only ``namespace="prod"``);
- silences (the ``silenced`` hook, FleetPlane's silence store) mute
  the action the way they mute notification;
- per-action cooldown: after an action runs (live or dry-run), the
  same action stays quiet for ``cooldown_s`` — remediations act on
  control loops whose effect takes time to land;
- a global rate limit (``max_actions`` per ``rate_window_s``) bounds
  the blast radius of a correlated alert storm: a fleet-wide outage
  must page a human, not trigger a hundred automated mutations.

Every decision — executed, dry-run, suppressed, failed — is recorded
in a bounded audit ring, counted in
``obs_remediations_total{action,result}`` in BOTH metric sinks
(MetricsRegistry + prometheus_client), and executed/failed actions
additionally emit dedup'd k8s Events through the PR 4
``EventRecorder``. The audit ring is the deterministic decision log
``tools/heal_bench.py`` fingerprints.

Three actions ship, each wired through an existing control path (the
engine never invents a side channel into a controller):

- ``scale_up_nudge_action`` — KVPagesExhausted: annotate the
  JAXService with a one-shot floor (``ANNOTATION_SCALE_NUDGE``); the
  autoscaler honors it through its normal record-first target move.
- ``cache_relist_action`` — SchedulerPassSlow: mark the scheduler's
  ``ClusterCache`` kinds dirty so the next refresh re-lists them
  (repairing a cache poisoned by a missed watch event).
- ``cordon_drain_action`` — node-scoped SLO burn: set
  ``spec.unschedulable`` on the node and evict this scheduler's bound
  pods with the one-spelling ``eviction_status`` — elastic gangs then
  shrink to survivors through the PR 6 path instead of restarting.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

import prometheus_client as prom

from kubeflow_tpu.runtime.metrics import (
    REGISTRY,
    MetricsRegistry,
    prom_metric as _metric,
)

log = logging.getLogger("kubeflow_tpu.obs.remediate")

# Decision results (the `result` label of obs_remediations_total).
EXECUTED = "executed"
DRY_RUN = "dry_run"
COOLDOWN = "cooldown"
RATE_LIMITED = "rate_limited"
SILENCED = "silenced"
SKIPPED = "skipped"  # action declined (e.g. transition lacks a label)
ERROR = "error"


def remediations_total():
    return _metric("obs_remediations_total", prom.Counter,
                   "remediation decisions by action and result",
                   labelnames=("action", "result", "tenant"))


class SkipAction(Exception):
    """An action declining to act on this transition (not a failure):
    e.g. a node-scoped action on a transition with no node label."""


@dataclass
class Remediation:
    """One alert->action binding.

    ``action(transition)`` receives the firing transition dict
    (``{"alert", "to", "labels", "value", "at"}``) and returns a short
    human-readable detail string; it raises ``SkipAction`` to decline
    and any other exception to report failure. ``matchers`` restricts
    the binding to transitions whose labels carry every listed
    key=value."""

    name: str
    alert: str
    action: Callable[[dict], str]
    cooldown_s: float = 300.0
    matchers: dict = field(default_factory=dict)


class RemediationEngine:
    """Consumes alert transitions, executes matching remediations.

    ``observe(transitions, at=)`` is the only entry point — FleetPlane
    calls it from ``tick()`` with the pass's transition list. Returns
    the decision records made this call, in deterministic order (the
    transition order the rule engine produced, which is itself
    sorted)."""

    def __init__(self, remediations: list[Remediation] | None = None,
                 recorder=None,
                 registry: MetricsRegistry | None = None,
                 clock: Callable[[], float] = time.time,
                 dry_run: bool = False,
                 max_actions: int = 5,
                 rate_window_s: float = 600.0,
                 silenced: Callable[[str, dict, float], bool] | None = None,
                 audit_limit: int = 256):
        self.remediations: list[Remediation] = list(remediations or [])
        self.recorder = recorder
        self.registry = registry if registry is not None else REGISTRY
        self.clock = clock
        self.dry_run = dry_run
        self.max_actions = max_actions
        self.rate_window_s = rate_window_s
        self.silenced = silenced
        self._lock = threading.Lock()
        # action name -> last run time (live or dry-run both burn it)
        self._last_run: dict[str, float] = {}
        # run timestamps inside the rate window (live + dry-run)
        self._window: deque[float] = deque()
        self._audit: deque[dict] = deque(maxlen=audit_limit)

    def register(self, remediation: Remediation) -> None:
        with self._lock:
            self.remediations.append(remediation)

    # -- the decision pass ---------------------------------------------------

    def observe(self, transitions: list[dict],
                at: float | None = None) -> list[dict]:
        now = self.clock() if at is None else at
        decisions: list[dict] = []
        with self._lock:
            for tr in transitions:
                # ONLY firing triggers: pending and resolved never act,
                # and a pending->inactive flap produces neither — the
                # rule engine's for-duration damping is the gate.
                if tr.get("to") != "firing":
                    continue
                for rem in self.remediations:
                    if rem.alert != tr.get("alert"):
                        continue
                    labels = tr.get("labels") or {}
                    if any(labels.get(k) != v
                           for k, v in rem.matchers.items()):
                        continue
                    decisions.append(self._decide(rem, tr, labels, now))
        return decisions

    def _decide(self, rem: Remediation, tr: dict, labels: dict,
                now: float) -> dict:
        result, detail = self._guard(rem, labels, now)
        if result is None:
            # guards passed: burn the cooldown and the rate window for
            # BOTH live and dry-run, so a dry-run fleet produces the
            # byte-identical decision log a live fleet would
            self._last_run[rem.name] = now
            self._window.append(now)
            if self.dry_run:
                result, detail = DRY_RUN, "dry-run: action not executed"
            else:
                try:
                    detail = rem.action(tr) or ""
                    result = EXECUTED
                except SkipAction as e:
                    result, detail = SKIPPED, str(e)
                except Exception as e:  # an action must not kill the pass
                    log.exception("remediation %s failed", rem.name)
                    result, detail = ERROR, f"{type(e).__name__}: {e}"
        return self._record(rem, labels, result, detail, now)

    def _guard(self, rem: Remediation, labels: dict,
               now: float) -> tuple[str | None, str]:
        if self.silenced is not None:
            try:
                if self.silenced(rem.alert, labels, now):
                    return SILENCED, "alert is silenced"
            except Exception:
                log.exception("silence check failed")
        last = self._last_run.get(rem.name)
        if last is not None and now - last < rem.cooldown_s:
            return COOLDOWN, (f"action ran {now - last:.0f}s ago "
                              f"(cooldown {rem.cooldown_s:.0f}s)")
        while self._window and now - self._window[0] >= self.rate_window_s:
            self._window.popleft()
        if len(self._window) >= self.max_actions:
            return RATE_LIMITED, (
                f"{len(self._window)} actions in the last "
                f"{self.rate_window_s:.0f}s (limit {self.max_actions})")
        return None, ""

    def _record(self, rem: Remediation, labels: dict, result: str,
                detail: str, now: float) -> dict:
        # the namespace whose alert triggered this action IS the tenant
        # the decision bills to (chargeback attribution); an explicit
        # tenant label on the transition wins
        tenant = (labels.get("tenant") or labels.get("namespace")
                  or "default")
        decision = {
            "action": rem.name, "alert": rem.alert,
            "labels": dict(sorted(labels.items())),
            "tenant": tenant,
            "result": result, "detail": detail, "at": now,
        }
        self._audit.append(decision)
        try:
            self.registry.counter_inc(
                "obs_remediations_total",
                help_="remediation decisions by action and result",
                action=rem.name, result=result, tenant=tenant)
            remediations_total().labels(
                action=rem.name, result=result, tenant=tenant).inc()
        except Exception:  # telemetry must never break the pass
            log.exception("remediation metric emit failed")
        if self.recorder is not None and result in (EXECUTED, DRY_RUN,
                                                    ERROR):
            involved = {
                "apiVersion": "obs.kubeflow.org/v1",
                "kind": "Remediation",
                "metadata": {
                    "name": rem.name.lower(),
                    "namespace": labels.get("namespace", "default"),
                },
            }
            label_str = ",".join(f"{k}={v}"
                                 for k, v in sorted(labels.items()))
            try:
                if result == ERROR:
                    self.recorder.event(
                        involved, "RemediationFailed",
                        f"{rem.name} for {rem.alert} ({label_str}) "
                        f"failed: {detail}", etype="Warning")
                else:
                    self.recorder.event(
                        involved, "RemediationExecuted",
                        f"{rem.name} for {rem.alert} ({label_str}): "
                        f"{detail or result}")
            except Exception:
                log.exception("remediation event emit failed")
        return decision

    # -- introspection -------------------------------------------------------

    def audit(self) -> list[dict]:
        """The bounded decision history, oldest first."""
        with self._lock:
            return [dict(d) for d in self._audit]


# -- the shipped actions ------------------------------------------------------


def scale_up_nudge_action(client, namespace: str = "default"):
    """KVPagesExhausted -> nudge the JAXService autoscaler up one.

    Writes ``ANNOTATION_SCALE_NUDGE`` on the JAXService named by the
    transition's ``service`` label: a one-shot replica floor of
    (current target + 1) the autoscaler consumes — and clears — inside
    its normal reconcile, so the move flows through the record-first
    durable status write, hysteresis bookkeeping, and max-replica
    clamp like any other scale decision."""
    from kubeflow_tpu.control.jaxservice import types as T

    def act(tr: dict) -> str:
        labels = tr.get("labels") or {}
        svc = labels.get("service")
        if not svc:
            raise SkipAction("transition has no service label")
        ns = labels.get("namespace", namespace)
        cur = client.get(T.API_VERSION, T.KIND, svc, ns)
        target = int((cur.get("status") or {}).get(
            "targetReplicas",
            (cur.get("spec") or {}).get("minReplicas", 1)))
        nudge = target + 1
        client.patch(
            T.API_VERSION, T.KIND, svc,
            {"metadata": {"annotations": {
                T.ANNOTATION_SCALE_NUDGE: str(nudge)}}}, ns)
        return f"nudged {ns}/{svc} floor to {nudge} replicas"

    return act


def cache_relist_action(cache, kinds: tuple[tuple[str, str], ...] = ()):
    """SchedulerPassSlow -> mark the scheduler's ClusterCache dirty.

    A slow pass with a healthy node fleet usually means the cache has
    drifted (a dropped watch event leaving a stale index bucket); a
    relist of the dirty kinds rebuilds the indexes wholesale through
    the cache's own repair path."""

    def act(tr: dict) -> str:
        n = cache.mark_dirty(kinds or None)
        # complete the repair now rather than at the next scheduling
        # pass: refresh() relists exactly the dirty kinds (the cache's
        # own recovery path), so a quiet cluster still heals
        cache.refresh()
        return f"relisted {n} cached kind(s)"

    return act


def cordon_drain_action(client, scheduler_name: str | None = None):
    """Node-scoped SLO burn -> cordon the node and drain its pods.

    Cordons by setting ``spec.unschedulable`` (the scheduler's
    feasibility check excludes cordoned nodes, so nothing new lands),
    then evicts the gang scheduler's bound pods with the one-spelling
    ``eviction_status`` — phase Failed / reason Evicted, which the
    JAXJob controller classifies as preemption, so elastic gangs
    shrink to survivors through the PR 6 path (zero restart-budget
    burn) instead of whole-gang restarting."""
    from kubeflow_tpu.control.scheduler import SCHEDULER_NAME
    from kubeflow_tpu.control.scheduler import nodes as N

    sched = scheduler_name or SCHEDULER_NAME

    def act(tr: dict) -> str:
        labels = tr.get("labels") or {}
        node = labels.get("node")
        if not node:
            raise SkipAction("transition has no node label")
        client.patch("v1", "Node", node,
                     {"spec": {"unschedulable": True}})
        evicted = 0
        for pod in client.list("v1", "Pod"):
            spec = pod.get("spec") or {}
            if spec.get("nodeName") != node:
                continue
            if spec.get("schedulerName") != sched:
                continue
            phase = (pod.get("status") or {}).get("phase", "Pending")
            if phase in ("Succeeded", "Failed"):
                continue
            pod.setdefault("status", {})
            pod["status"].update(N.eviction_status(
                f"node {node} cordoned by remediation "
                f"({tr.get('alert')})"))
            client.update_status(pod)
            evicted += 1
        return f"cordoned {node}, evicted {evicted} pod(s)"

    return act


def default_remediations(client=None, cache=None,
                         namespace: str = "default") -> list[Remediation]:
    """The shipped alert->action bindings, wired to a kube client and
    (optionally) the scheduler's ClusterCache. Callers drop entries
    whose dependency is absent."""
    rems: list[Remediation] = []
    if client is not None:
        rems.append(Remediation(
            name="jaxservice-scale-up", alert="KVPagesExhausted",
            action=scale_up_nudge_action(client, namespace=namespace),
            cooldown_s=120.0))
        rems.append(Remediation(
            name="node-cordon-drain", alert="NodeSLOBurn",
            action=cordon_drain_action(client),
            cooldown_s=600.0))
    if cache is not None:
        rems.append(Remediation(
            name="cache-relist", alert="SchedulerPassSlow",
            action=cache_relist_action(cache),
            cooldown_s=300.0))
    return rems
