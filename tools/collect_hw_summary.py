"""Render a round's hardware ledger as markdown tables.

Reads the watcher's stage outputs (tools/r{N}_stages/*.out — each holds a
bench.py or serve_bench.py JSON line) plus the promoted
serve_table.json, and prints markdown ready for BASELINE.md: one LM
table (model / batch / policy / MFU / tok/s), one ResNet row set, one
serving table. Stages that never ran or failed are listed as such, so
the ledger distinguishes "didn't fit / didn't run" from "never
measured" — the same honesty rule as lm_sweep's failure records.

Usage: python tools/collect_hw_summary.py [STAGE_DIR]
"""

from __future__ import annotations

import glob
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def stage_records(stage_dir):
    for out in sorted(glob.glob(os.path.join(stage_dir, "*.out"))):
        name = os.path.basename(out)[:-4]
        doc = None
        for line in open(out, errors="replace"):
            line = line.strip()
            if line.startswith("{"):
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
        done = os.path.exists(os.path.join(stage_dir, name + ".done"))
        skip = os.path.exists(os.path.join(stage_dir, name + ".skip"))
        yield name, doc, done, skip


def _latest_stage_dir() -> str:
    """Newest r{N}_stages dir — defaulting to a hardcoded round would
    silently render a STALE ledger as if it were current."""
    import re

    dirs = glob.glob(os.path.join(HERE, "r*_stages"))
    dirs = [d for d in dirs if re.search(r"r(\d+)_stages$", d)]
    dirs.sort(key=lambda d: int(re.search(r"r(\d+)_stages$", d).group(1)))
    return dirs[-1] if dirs else os.path.join(HERE, "r4_stages")


def main() -> int:
    stage_dir = sys.argv[1] if len(sys.argv) > 1 else _latest_stage_dir()
    if not os.path.isdir(stage_dir):
        print(f"no stage dir at {stage_dir}; nothing measured yet")
        return 0

    lm_rows, rn_rows, serve_rows, pending = [], [], [], []
    for name, doc, done, skip in stage_records(stage_dir):
        if doc is None or not done:
            pending.append((name, "skipped (failed twice)" if skip
                            else "no parseable result"))
            continue
        lm = doc.get("lm") if isinstance(doc.get("lm"), dict) else None
        if lm and isinstance(lm.get("mfu"), (int, float)):
            lm_rows.append(
                (name, lm.get("model"), lm.get("global_batch"),
                 lm.get("seq_len"), lm.get("remat_policy")
                 if lm.get("remat") else "none",
                 lm.get("window") or "-", lm["mfu"],
                 lm.get("tokens_per_sec")))
        elif doc.get("metric", "").startswith("resnet") and doc.get("value"):
            rn_rows.append((name, doc.get("resnet_remat") or "none",
                            doc["value"], doc.get("images_per_sec"),
                            doc.get("fraction_of_roofline")))
        elif doc.get("mode") == "continuous":
            serve_rows.append(
                (name, doc.get("model"), doc.get("param_dtype"),
                 doc.get("kv_cache_dtype", "native"),
                 doc.get("attention_window", "-"),
                 "roll" if doc.get("rolling_kv_cache") else "full",
                 doc.get("tokens_per_sec"), doc.get("p50_ms"),
                 doc.get("p99_ms")))

    if lm_rows:
        print("### LM training (measured, 1x v5e)\n")
        print("| stage | model | bs | seq | remat | window | MFU | tok/s |")
        print("|---|---|---|---|---|---|---|---|")
        for r in sorted(lm_rows, key=lambda r: -r[6]):
            print("| " + " | ".join(str(x) for x in r) + " |")
        print()
    if rn_rows:
        print("### ResNet-50 (measured, 1x v5e)\n")
        print("| stage | remat | MFU | img/s | frac of roofline |")
        print("|---|---|---|---|---|")
        for r in rn_rows:
            print("| " + " | ".join(str(x) for x in r) + " |")
        print()
    if serve_rows:
        print("### Serving, continuous batching (measured, 1x v5e)\n")
        print("| stage | model | weights | kv | window | cache | tok/s "
              "| p50 ms | p99 ms |")
        print("|---|---|---|---|---|---|---|---|---|")
        for r in serve_rows:
            print("| " + " | ".join(str(x) for x in r) + " |")
        print()
    if pending:
        print("### Not measured\n")
        for name, why in pending:
            print(f"- {name}: {why}")
    if not (lm_rows or rn_rows or serve_rows or pending):
        print("stage dir empty; nothing measured yet")
    return 0


if __name__ == "__main__":
    sys.exit(main())
