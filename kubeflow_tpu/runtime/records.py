"""KFRecord shards: the real-data input pipeline.

tf_cnn_benchmarks reads TFRecord/ImageNet when --data_dir is set; the
reference's example jobs run synthetic (create_job_specs.py passes no
data flags), but the capability must exist. KFRecord is the TPU build's
shard format: fixed-size records (tensor-friendly: batch assembly is a
memcpy, random access is offset arithmetic) with per-record CRC32, read
by the native C++ loader (native/kfdata.cc) on a background thread —
checksums, shuffling and batching never touch the Python hot path. A
pure-Python reader with identical semantics serves as fallback and as a
differential test oracle for the native one.

Format:
    header : b"KFR1" | u32 version=1 | u64 record_bytes | u64 n_records
    records: n_records x (record_bytes payload | u32 crc32)
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, Sequence

import numpy as np

MAGIC = b"KFR1"
VERSION = 1
_HEADER = struct.Struct("<4sIQQ")  # magic, version, record_bytes, n_records


# ---------------------------------------------------------------------------
# writer (Python; writing shards is an offline/CI path, not the hot loop)


def write_records(path: str, records: np.ndarray | Sequence[bytes]) -> int:
    """Write a KFRecord shard. `records` is [n, record_bytes] uint8 (or a
    sequence of equal-length bytes). Returns number of records written."""
    if isinstance(records, np.ndarray):
        if records.ndim != 2 or records.dtype != np.uint8:
            raise ValueError(f"records must be [n, record_bytes] uint8, got "
                             f"{records.shape} {records.dtype}")
        rows = [r.tobytes() for r in records]
    else:
        rows = [bytes(r) for r in records]
    if not rows:
        raise ValueError("cannot write an empty shard")
    rb = len(rows[0])
    if any(len(r) != rb for r in rows):
        raise ValueError("all records must have equal length")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_HEADER.pack(MAGIC, VERSION, rb, len(rows)))
        for r in rows:
            f.write(r)
            f.write(struct.pack("<I", zlib.crc32(r) & 0xFFFFFFFF))
    os.replace(tmp, path)  # atomic: readers never see partial shards
    return len(rows)


def read_header(path: str) -> tuple[int, int]:
    """(record_bytes, n_records) of a shard."""
    with open(path, "rb") as f:
        magic, version, rb, n = _HEADER.unpack(f.read(_HEADER.size))
    if magic != MAGIC or version != VERSION:
        raise ValueError(f"{path}: not a KFRecord v{VERSION} file")
    return rb, n


# ---------------------------------------------------------------------------
# readers


def _iter_records_py(path: str, record_bytes: int) -> Iterator[bytes]:
    with open(path, "rb") as f:
        magic, version, rb, n = _HEADER.unpack(f.read(_HEADER.size))
        if magic != MAGIC or version != VERSION:
            raise ValueError(f"{path}: not a KFRecord v{VERSION} file")
        if rb != record_bytes:
            raise ValueError(f"{path}: record_bytes mismatch: file has {rb}, "
                             f"loader expects {record_bytes}")
        for i in range(n):
            payload = f.read(record_bytes)
            crc_raw = f.read(4)
            if len(payload) != record_bytes or len(crc_raw) != 4:
                raise ValueError(f"{path}: truncated record {i}")
            (crc,) = struct.unpack("<I", crc_raw)
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                raise ValueError(f"{path}: crc mismatch in record {i}")
            yield payload


class _PyLoader:
    """Pure-Python loader with the same shuffle/batch semantics as the
    native one (reservoir-swap pool, file order, end-of-data drain)."""

    def __init__(self, paths, record_bytes, batch, shuffle_buffer, seed,
                 loop, drop_remainder):
        self.paths = paths
        self.record_bytes = record_bytes
        self.batch = batch
        self.shuffle_buffer = shuffle_buffer
        self.loop = loop
        self.drop_remainder = drop_remainder
        self._rng = np.random.Generator(np.random.PCG64(seed))
        self._gen = self._batches()

    def _records(self) -> Iterator[bytes]:
        while True:
            for p in self.paths:
                yield from _iter_records_py(p, self.record_bytes)
            if not self.loop:
                return

    def _shuffled(self) -> Iterator[bytes]:
        if self.shuffle_buffer <= 1:
            yield from self._records()
            return
        pool: list[bytes] = []
        for rec in self._records():
            if len(pool) < self.shuffle_buffer:
                pool.append(rec)
                continue
            j = int(self._rng.integers(0, len(pool)))
            pool[j], rec = rec, pool[j]
            yield rec
        self._rng.shuffle(pool)  # end-of-data drain
        yield from pool

    def _batches(self) -> Iterator[np.ndarray]:
        cur: list[bytes] = []
        for rec in self._shuffled():
            cur.append(rec)
            if len(cur) == self.batch:
                yield np.frombuffer(b"".join(cur), np.uint8).reshape(
                    self.batch, self.record_bytes)
                cur = []
        if cur and not self.drop_remainder:
            yield np.frombuffer(b"".join(cur), np.uint8).reshape(
                len(cur), self.record_bytes)

    def next(self) -> np.ndarray | None:
        return next(self._gen, None)

    def close(self) -> None:
        pass


class _NativeLoader:
    def __init__(self, lib, paths, record_bytes, batch, shuffle_buffer, seed,
                 loop, drop_remainder, queue_capacity=4):
        import ctypes

        self._lib = lib
        self._ctypes = ctypes
        arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
        self._h = lib.kfdl_open(arr, len(paths), record_bytes, batch,
                                shuffle_buffer, seed, int(loop),
                                int(drop_remainder), queue_capacity)
        if not self._h:
            raise ValueError("kfdl_open failed (bad arguments)")
        self.record_bytes = record_bytes
        self.batch = batch

    def next(self) -> np.ndarray | None:
        if self._h is None:  # closed: NULL handle would segfault in C++
            return None
        cap = self.batch * self.record_bytes
        out = np.empty(cap, np.uint8)
        n = self._lib.kfdl_next(
            self._h,
            out.ctypes.data_as(self._ctypes.POINTER(self._ctypes.c_uint8)),
            cap,
        )
        if n < 0:
            err = self._lib.kfdl_error(self._h).decode()
            raise ValueError(err or "kfdata: unknown error")
        if n == 0:
            return None
        assert n % self.record_bytes == 0, (n, self.record_bytes)
        return out[:n].reshape(n // self.record_bytes, self.record_bytes)

    def close(self) -> None:
        if self._h:
            self._lib.kfdl_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class RecordDataset:
    """Iterator of [batch, record_bytes] uint8 batches over KFRecord
    shards; native C++ loader when built, Python fallback otherwise."""

    def __init__(self, paths: Sequence[str], batch: int, *,
                 record_bytes: int | None = None, shuffle_buffer: int = 0,
                 seed: int = 0, loop: bool = False,
                 drop_remainder: bool = True, native: bool | None = None):
        paths = list(paths)
        if not paths:
            raise ValueError("no shard paths given")
        rb = record_bytes if record_bytes is not None else read_header(paths[0])[0]
        lib = None
        if native is None or native:
            from kubeflow_tpu import native as native_pkg

            lib = native_pkg.load()
            if lib is None and native:
                raise RuntimeError("native kfdata library unavailable")
        args = (paths, rb, batch, shuffle_buffer, seed, loop, drop_remainder)
        self._impl = _NativeLoader(lib, *args) if lib else _PyLoader(*args)
        self.record_bytes = rb
        self.native = lib is not None

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        b = self._impl.next()
        if b is None:
            raise StopIteration
        return b

    def close(self) -> None:
        self._impl.close()


def token_batches(paths: Sequence[str], batch: int, seq_len: int, *,
                  shuffle_buffer: int = 0, seed: int = 0,
                  loop: bool = True, segmented: bool = False) -> Iterator[dict]:
    """LM batches from token shards: records are (seq_len+1) int32 tokens;
    yields {"tokens": [b, L], "targets": [b, L]} (next-token shift).

    segmented=True reads packed shards (write_packed_token_shard): each
    record carries tokens AND per-position segment ids, the batch gains
    "segment_ids", and targets at padding or document boundaries are -1
    (the loss-ignore convention the trainer's cross entropy applies)."""
    width = 2 if segmented else 1
    rb = width * (seq_len + 1) * 4
    ds = RecordDataset(paths, batch, record_bytes=rb,
                       shuffle_buffer=shuffle_buffer, seed=seed, loop=loop)
    try:
        for raw in ds:
            row = raw.view(np.int32).reshape(raw.shape[0], width, seq_len + 1)
            tok = row[:, 0]
            if not segmented:
                yield {"tokens": tok[:, :-1], "targets": tok[:, 1:]}
                continue
            seg = row[:, 1]
            # target t+1 trains only within one real document: padding
            # (seg 0) and the first token of the NEXT document are not
            # predictions of the current one
            valid = (seg[:, 1:] == seg[:, :-1]) & (seg[:, 1:] > 0)
            yield {"tokens": tok[:, :-1],
                   "targets": np.where(valid, tok[:, 1:], -1),
                   "segment_ids": seg[:, :-1]}
    finally:
        # Runs on generator close/GC too, so an abandoned iterator (e.g.
        # Prefetcher torn down mid-epoch) stops the native worker thread.
        ds.close()


def pack_documents(docs: Sequence[np.ndarray], seq_len: int,
                   pad_id: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Greedy best-fit packing of variable-length token documents into
    [n, seq_len+1] rows + matching 1-based segment ids (0 = padding).

    Documents longer than a row are split into row-size pieces (each
    piece its own segment occurrence); short documents share rows, the
    flash kernel's segment mask keeping their attention separate.
    Each piece goes to the open row with the SMALLEST remaining capacity
    that still fits (best-fit via a bisect on sorted remainders) —
    O(n log n) placement, so corpus-scale packing stays minutes, not the
    hours a linear scan over all open rows would take."""
    import bisect

    cap = seq_len + 1
    rows: list[list[np.ndarray]] = []
    remainders: list[tuple[int, int]] = []  # sorted (remaining, row_idx)
    for doc in docs:
        doc = np.asarray(doc, np.int32).ravel()
        if doc.size == 0:
            continue
        for piece_at in range(0, doc.size, cap):
            piece = doc[piece_at:piece_at + cap]
            i = bisect.bisect_left(remainders, (piece.size, -1))
            if i < len(remainders):
                remaining, r = remainders.pop(i)
                rows[r].append(piece)
                remaining -= piece.size
            else:
                rows.append([piece])
                r, remaining = len(rows) - 1, cap - piece.size
            if remaining:
                bisect.insort(remainders, (remaining, r))
    tokens = np.full((len(rows), cap), pad_id, np.int32)
    seg = np.zeros((len(rows), cap), np.int32)
    for r, pieces in enumerate(rows):
        at = 0
        for s, piece in enumerate(pieces, start=1):
            tokens[r, at:at + piece.size] = piece
            seg[r, at:at + piece.size] = s
            at += piece.size
    return tokens, seg


def write_token_shard(path: str, tokens: np.ndarray) -> int:
    """Write [n, seq_len+1] int32 token sequences as a KFRecord shard."""
    if tokens.ndim != 2 or tokens.dtype != np.int32:
        raise ValueError(f"tokens must be [n, seq_len+1] int32, got "
                         f"{tokens.shape} {tokens.dtype}")
    return write_records(path, tokens.view(np.uint8).reshape(tokens.shape[0], -1))


def write_packed_token_shard(path: str, tokens: np.ndarray,
                             segment_ids: np.ndarray) -> int:
    """Write packed rows (pack_documents output) as a KFRecord shard:
    each record is (seq_len+1) tokens followed by (seq_len+1) segment
    ids, both int32 — fixed-size, so the native loader needs no schema."""
    if tokens.shape != segment_ids.shape or tokens.ndim != 2:
        raise ValueError(f"tokens/segment_ids must be matching [n, L+1], "
                         f"got {tokens.shape} vs {segment_ids.shape}")
    recs = np.concatenate([tokens.astype(np.int32),
                           segment_ids.astype(np.int32)], axis=1)
    return write_records(path, recs.view(np.uint8).reshape(recs.shape[0], -1))


def write_image_shard(path: str, images: np.ndarray,
                      labels: np.ndarray) -> int:
    """Write [n, H, W, C] uint8 images + [n] int32 labels as one
    KFRecord shard; each record is 4 label bytes followed by the raw
    image bytes (fixed size, so the native loader needs no schema)."""
    if images.ndim != 4 or images.dtype != np.uint8:
        raise ValueError(f"images must be [n,H,W,C] uint8, got "
                         f"{images.shape} {images.dtype}")
    labels = np.asarray(labels, np.int32)
    if labels.shape != (images.shape[0],):
        raise ValueError(f"labels must be [n], got {labels.shape}")
    flat = images.reshape(images.shape[0], -1)
    recs = np.concatenate(
        [labels[:, None].view(np.uint8).reshape(labels.shape[0], 4), flat],
        axis=1)
    return write_records(path, recs)


def image_batches(paths: Sequence[str], batch: int, image_size: int, *,
                  channels: int = 3, shuffle_buffer: int = 0, seed: int = 0,
                  loop: bool = True) -> Iterator[dict]:
    """Classification batches from image shards: yields
    {"image": [b,H,W,C] float32 in [0,1), "label": [b] int32} — the
    tf.data-equivalent path for the resnet trainer (host decode is just
    a cast; heavy augmentation belongs upstream of the shard writer)."""
    rb = 4 + image_size * image_size * channels
    ds = RecordDataset(paths, batch, record_bytes=rb,
                       shuffle_buffer=shuffle_buffer, seed=seed, loop=loop)
    try:
        for raw in ds:
            labels = raw[:, :4].copy().view(np.int32).reshape(-1)
            imgs = raw[:, 4:].reshape(
                raw.shape[0], image_size, image_size, channels)
            yield {"image": imgs.astype(np.float32) / 255.0,
                   "label": labels}
    finally:
        ds.close()
