"""tpulint reconcile write-discipline rules (CTL5xx) for the control
plane.

The platform's hardest-won controller lessons existed only as prose in
CHANGES.md: record-FIRST durable writes (PR 5's gang restart bumps the
counter and writes the Restarting condition *before* deleting pods, so
a crash mid-restart resumes instead of double-restarting), status
no-op guards (the PR 5 status storm: an unconditional ``update_status``
per reconcile pass melts the apiserver), read-your-own-writes cache
folding (PRs 7-8: every write response folds back via
``note_write``/``note_delete`` or the next pass reads stale state), and
rv-preconditioned annotation mints (two controller replicas racing a
traceparent mint must conflict, not last-write-win). CTL5xx turns each
into a checkable property:

- **CTL501** record-first ordering: a destructive client call
  (``delete``/``evict``) that precedes the function's durable record
  write (``update_status``). Call-graph aware: a call into a helper
  that transitively deletes counts as a delete at the call site; a
  helper that both records and deletes (a self-contained transaction
  like ``_gang_restart``) is skipped. Only the wrong order fires — a
  function whose record write already precedes its deletes, or that
  never records (its caller does), stays clean.
- **CTL502** status-storm guard: an ``update_status`` with no
  conditional guard on any path from function entry. ``changed =
  cond_set(...); if changed: update_status(...)`` and the
  double-checked early-return idiom are clean; a private helper that
  writes unconditionally is clean when every resolved call site is
  itself guarded (one call-graph hop, like LOCK201's entry context).
- **CTL503** discarded write response in a ClusterCache-wired
  controller: a bare-statement ``client.create/patch/replace(...)``
  throws away the response instead of folding it
  (``self._note(client.patch(...))``, assignment, or ``return``), so
  the controller's next pass reads its own write stale.
- **CTL504** traceparent mints without a ``resourceVersion``
  precondition: an annotation patch carrying a traceparent key must
  include the observed rv so concurrent minters conflict (409) instead
  of silently overwriting each other's trace roots.

Scope is ``control/`` — the reconcile planes these disciplines were
paid for in.
"""

from __future__ import annotations

import ast
from typing import Iterator

from kubeflow_tpu.analysis.core import (
    Finding, Module, ProgramRule, Rule, call_name, register,
)

_SCOPES = ("control/",)

_DESTRUCTIVE = {"delete", "evict", "delete_collection"}
_RECORD = {"update_status", "replace_status"}
_WRITES = {"create", "patch", "replace"}
_NOTE_ATTRS = ("note_write", "note_delete")

_FIXPOINT_CAP = 32


def _in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(s in p for s in _SCOPES)


def _own_walk(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs — a
    closure's body runs at call time, not at this point in the
    reconcile, so its calls must not count toward CFG order."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _attr_of(node: ast.Call) -> str | None:
    return node.func.attr if isinstance(node.func, ast.Attribute) else None


def _direct_kind_closure(program, attrs: set[str]) -> set[str]:
    """Function quals that (transitively) make a call whose attribute
    is in ``attrs`` — the may-delete / may-record union fixpoint."""
    out: set[str] = set()
    for qual, fi in program.functions.items():
        for node in _own_walk(fi.node):
            if isinstance(node, ast.Call) and _attr_of(node) in attrs:
                out.add(qual)
                break
    for _ in range(_FIXPOINT_CAP):
        changed = False
        for site in program.calls:
            if site.callee in out and site.caller.qual not in out:
                out.add(site.caller.qual)
                changed = True
        if not changed:
            break
    return out


@register
class RecordFirstOrdering(ProgramRule):
    """CTL501: destructive call ordered before the durable record
    write. A crash between the delete and the (later) record write
    loses the fact that the action happened — record first, so the
    next pass resumes instead of repeating the destruction."""

    id = "CTL501"
    name = "record-first-ordering"
    short = "delete/evict before the reconcile's durable record write"

    def check_program(self, program) -> Iterator[Finding]:
        scoped = [fi for fi in program.functions.values()
                  if _in_scope(fi.module.path)]
        if not scoped:
            return
        may_del = _direct_kind_closure(program, _DESTRUCTIVE)
        may_rec = _direct_kind_closure(program, _RECORD)
        for fi in scoped:
            events: list[tuple[tuple[int, int], str, ast.Call, str]] = []
            for node in _own_walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                attr = _attr_of(node)
                kinds = set()
                label = attr or (call_name(node) or "call")
                if attr in _DESTRUCTIVE:
                    kinds.add("del")
                elif attr in _RECORD:
                    kinds.add("rec")
                else:
                    callee = program._resolve_call(node, fi)
                    if callee is not None:
                        if callee in may_del:
                            kinds.add("del")
                        if callee in may_rec:
                            kinds.add("rec")
                if len(kinds) != 1:
                    # both: a self-contained record+delete transaction
                    # (e.g. _gang_restart); neither: not interesting
                    continue
                events.append(((node.lineno, node.col_offset),
                               kinds.pop(), node, label))
            recs = [pos for pos, kind, _, _ in events if kind == "rec"]
            if not recs:
                continue  # the record write lives in a caller: no order
            first_rec = min(recs)
            for pos, kind, node, label in events:
                if kind == "del" and pos < first_rec:
                    yield self.finding(
                        fi.module, node,
                        f"destructive {label}() before this function's "
                        "durable record write (record-first): write the "
                        "status/record update ahead of the delete so a "
                        "crash in between resumes instead of repeating "
                        "the destruction")


@register
class StatusStormGuard(ProgramRule):
    """CTL502: unconditional status write on the reconcile path. Every
    pass that writes an unchanged status is an apiserver write, a
    resourceVersion bump, and a watch event fanned out to every
    informer — the PR 5 status storm."""

    id = "CTL502"
    name = "status-storm-guard"
    short = "status write without a prev-value comparison guard"

    def check_program(self, program) -> Iterator[Finding]:
        sites = getattr(program, "_sites_by_callee", {})
        for fi in program.functions.values():
            if not _in_scope(fi.module.path):
                continue
            for node in _own_walk(fi.node):
                if not (isinstance(node, ast.Call)
                        and _attr_of(node) in _RECORD):
                    continue
                if isinstance(fi.module.parents.get(node), ast.Return):
                    continue  # delegation: the caller owns the guard
                if self._guarded(fi, node):
                    continue
                callers = sites.get(fi.qual, [])
                if fi.is_private and callers and all(
                        self._guarded(s.caller, s.call) for s in callers):
                    continue  # every way in is guarded (one hop)
                yield self.finding(
                    fi.module, node,
                    "status write with no comparison guard on the path "
                    "from function entry: compute changed = "
                    "cond_set(...) (or compare prev/next) and write "
                    "only when it changed — unconditional writes per "
                    "pass are a status storm")

    @staticmethod
    def _guarded(fi, node: ast.AST) -> bool:
        # (a) conditional ancestor inside this function
        for anc in fi.module.ancestors(node):
            if isinstance(anc, (ast.If, ast.IfExp, ast.While,
                                ast.ExceptHandler, ast.Assert)):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        # (b) the double-checked idiom: an earlier early-exit branch
        # (``if prev == next: return``) guards everything after it
        for n in _own_walk(fi.node):
            if (isinstance(n, ast.If)
                    and n.lineno < getattr(node, "lineno", 0)
                    and any(isinstance(x, (ast.Return, ast.Raise,
                                           ast.Continue))
                            for b in n.body for x in ast.walk(b))):
                return True
        return False


@register
class DiscardedWriteResponse(Rule):
    """CTL503: a cache-wired controller throwing away a write response.
    The apiserver's reply carries the new resourceVersion; dropping it
    instead of folding via note_write means the next reconcile pass
    reads the controller's own write stale (PRs 7-8)."""

    id = "CTL503"
    name = "discarded-write-response"
    short = "write response not folded into the ClusterCache"

    def check(self, module: Module) -> Iterator[Finding]:
        if not _in_scope(module.path):
            return
        for cls in module.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            if not self._cache_wired(cls):
                continue
            for node in ast.walk(cls):
                if not isinstance(node, ast.Expr):
                    continue
                call = node.value
                if not (isinstance(call, ast.Call)
                        and _attr_of(call) in _WRITES):
                    continue
                recv = call_name(call) or ""
                if "client" not in recv.lower():
                    continue
                yield self.finding(
                    module, call,
                    f"{recv}() response discarded in a cache-wired "
                    "controller: fold it (self._note(client.patch(...))"
                    " / note_write) or the next pass reads this write "
                    "stale")

    @staticmethod
    def _cache_wired(cls: ast.ClassDef) -> bool:
        for node in ast.walk(cls):
            if isinstance(node, ast.Call):
                attr = _attr_of(node)
                if attr and (any(n in attr for n in _NOTE_ATTRS)
                             or attr in ("_note", "_note_gone")):
                    return True
        return False


@register
class TraceparentMintPrecondition(Rule):
    """CTL504: a traceparent annotation mint without an rv
    precondition. Two controller replicas racing the mint must get a
    409 conflict (one wins, one re-reads), not a silent last-write-wins
    that splits the object's trace across two roots."""

    id = "CTL504"
    name = "traceparent-mint-precondition"
    short = "traceparent annotation patch without resourceVersion"

    def check(self, module: Module) -> Iterator[Finding]:
        if not _in_scope(module.path):
            return
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and _attr_of(node) in ("patch", "replace")):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if not isinstance(arg, ast.Dict):
                    continue
                if (self._mints_traceparent(arg)
                        and not self._has_rv(arg)):
                    yield self.finding(
                        module, node,
                        "traceparent annotation mint without a "
                        "resourceVersion precondition: include the "
                        "observed metadata.resourceVersion so "
                        "concurrent minters conflict instead of "
                        "overwriting each other's trace roots")

    @classmethod
    def _mints_traceparent(cls, d: ast.Dict) -> bool:
        for key, value in zip(d.keys, d.values):
            if cls._is_traceparent_key(key):
                return True
            if isinstance(value, ast.Dict) and cls._mints_traceparent(value):
                return True
        return False

    @staticmethod
    def _is_traceparent_key(key: ast.expr | None) -> bool:
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            return "traceparent" in key.value.lower()
        if key is not None:
            name = ast.unparse(key) if hasattr(ast, "unparse") else ""
            return "traceparent" in name.lower()
        return False

    @staticmethod
    def _has_rv(d: ast.Dict) -> bool:
        for sub in ast.walk(d):
            if (isinstance(sub, ast.Constant)
                    and sub.value == "resourceVersion"):
                return True
        return False
