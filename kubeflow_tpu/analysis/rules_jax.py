"""tpulint JAX/TPU rules (TPU1xx) — the bug classes round 5 paid for.

All four rules hinge on knowing which functions are *traced*: decorated
with ``jax.jit``/``pjit`` (directly or via ``functools.partial``),
passed to ``jax.jit``/``pjit`` as a value, used as a ``jax.lax.scan``
body, or lexically nested inside any of those. ``_traced_functions``
computes that set once per module; each rule then walks only the traced
bodies (or, for TPU103, only the import-time surface).
"""

from __future__ import annotations

import ast
import builtins
from typing import Iterator

from kubeflow_tpu.analysis.core import (
    Finding, Module, Rule, call_name, dotted, register,
)

_JITS = {"jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit"}
_SCANS = {"jax.lax.scan", "lax.scan"}
_PARTIALS = {"functools.partial", "partial"}
_BUILTINS = frozenset(dir(builtins))

# module roots whose calls build arrays (device or host) when executed
_ARRAY_ROOTS = ("jnp.", "np.", "numpy.", "jax.numpy.")
# ...except pure metadata helpers, which return dtypes/scalars, not buffers
_META_TAILS = {"finfo", "iinfo", "dtype", "shape", "ndim", "result_type",
               "issubdtype", "promote_types"}
_ARRAY_EXACT = {"jax.device_put"}
_ARRAY_PREFIX = ("jax.random.",)

# enclosing-scope parameter names that conventionally hold weight trees
_PARAMISH = ("params", "variables", "weights", "state", "cache")


def _is_array_call(call: ast.Call) -> bool:
    name = call_name(call)
    if not name:
        return False
    if name in _ARRAY_EXACT or name.startswith(_ARRAY_PREFIX):
        return True
    if any(name.startswith(r) for r in _ARRAY_ROOTS):
        return name.rsplit(".", 1)[-1] not in _META_TAILS
    return False


def _paramish(name: str) -> bool:
    return name in _PARAMISH or name.endswith(
        ("_params", "_vars", "_variables", "_weights", "_state", "_cache"))


def _jit_decorator(fn: ast.FunctionDef) -> ast.expr | None:
    for dec in fn.decorator_list:
        if dotted(dec) in _JITS:
            return dec
        if isinstance(dec, ast.Call):
            name = call_name(dec)
            if name in _JITS:
                return dec
            if (name in _PARTIALS and dec.args
                    and dotted(dec.args[0]) in _JITS):
                return dec
    return None


def _scope_of(module: Module, node: ast.AST) -> ast.AST:
    for anc in module.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef, ast.Module)):
            return anc
    return module.tree


def _callable_args(call: ast.Call) -> list[ast.expr]:
    """First-positional-argument expressions that may name a function
    (unwrapping conditional selection like ``a if cond else b``)."""
    if not call.args:
        return []
    head = call.args[0]
    if isinstance(head, ast.IfExp):
        return [head.body, head.orelse]
    return [head]


def _static_names(fn: ast.FunctionDef, jit_node: ast.expr | None) -> set[str]:
    """Names the jit treats as static (static_argnames/static_argnums)."""
    if not isinstance(jit_node, ast.Call):
        return set()
    out: set[str] = set()
    pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in jit_node.keywords:
        val = kw.value
        items = val.elts if isinstance(val, (ast.Tuple, ast.List)) else [val]
        if kw.arg == "static_argnames":
            out |= {e.value for e in items
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)}
        elif kw.arg == "static_argnums":
            for e in items:
                if (isinstance(e, ast.Constant) and isinstance(e.value, int)
                        and e.value < len(pos)):
                    out.add(pos[e.value])
    return out


def _traced_functions(module: Module) -> dict[ast.FunctionDef, dict]:
    """Map every traced FunctionDef to {'jit': node|None, 'kind': str},
    computed once per module (memoized — TPU101 and TPU102 share it).

    kind is 'jit' (the jit root), 'scan' (a lax.scan body), or 'nested'
    (lexically inside another traced function, hence traced with it).
    """
    cached = getattr(module, "_tpulint_traced", None)
    if cached is not None:
        return cached
    defs: list[ast.FunctionDef] = [
        n for n in ast.walk(module.tree) if isinstance(n, ast.FunctionDef)]
    by_scope: dict[ast.AST, dict[str, ast.FunctionDef]] = {}
    for fn in defs:
        by_scope.setdefault(_scope_of(module, fn), {})[fn.name] = fn

    def resolve(call: ast.Call, name: str) -> ast.FunctionDef | None:
        scope: ast.AST | None = _scope_of(module, call)
        while scope is not None:
            fn = by_scope.get(scope, {}).get(name)
            if fn is not None:
                return fn
            scope = (None if isinstance(scope, ast.Module)
                     else _scope_of(module, scope))
        return None

    traced: dict[ast.FunctionDef, dict] = {}
    for fn in defs:
        dec = _jit_decorator(fn)
        if dec is not None:
            traced[fn] = {"jit": dec, "kind": "jit"}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name in _JITS or name in _SCANS:
            for arg in _callable_args(node):
                target = dotted(arg)
                fn = resolve(node, target) if target else None
                if fn is not None and fn not in traced:
                    traced[fn] = {
                        "jit": node if name in _JITS else None,
                        "kind": "jit" if name in _JITS else "scan"}
    # closure: nested defs trace with their parent
    for fn in defs:
        if fn in traced:
            continue
        for anc in module.ancestors(fn):
            if isinstance(anc, ast.FunctionDef) and anc in traced:
                traced[fn] = {"jit": traced[anc]["jit"], "kind": "nested"}
                break
    module._tpulint_traced = traced
    return traced


def _own_nodes(fn: ast.FunctionDef) -> Iterator[ast.AST]:
    """Walk fn's body without descending into nested function defs
    (those are traced entries of their own)."""
    stack: list[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _param_names(fn: ast.FunctionDef) -> set[str]:
    a = fn.args
    names = {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names


def _bound_names(fn: ast.FunctionDef) -> set[str]:
    """Names assigned anywhere inside fn (its locals)."""
    out = _param_names(fn)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            out.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)) and node is not fn:
            out.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                out.add((alias.asname or alias.name).split(".")[0])
    return out


def _module_globals(module: Module) -> set[str]:
    out: set[str] = set()
    for node in module.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                out.add((alias.asname or alias.name).split(".")[0])
        else:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                    out.add(sub.id)
    return out


@register
class ClosureCapturedArray(Rule):
    """TPU101: array built in an enclosing scope, captured by a traced
    function. The capture is serialized into the jitted program as an
    inline constant — the 700MB-MLIR / retrace-per-swap bug class
    (VERDICT.md r5). Arrays must flow through jit arguments."""

    id = "TPU101"
    name = "closure-captured-array"
    short = "traced function closes over an array built outside its jit root"

    def check(self, module: Module) -> Iterator[Finding]:
        traced = _traced_functions(module)
        g = _module_globals(module)
        for fn in traced:
            root = self._jit_root(module, traced, fn)
            if root is None:
                continue  # scan body with no jit boundary in this module:
                # captures stay inside whatever trace invokes it
            if module.enclosing_function(root) is None:
                continue  # module-level jit root: no function closure
            local = _bound_names(fn)
            reported: set[str] = set()
            for node in _own_nodes(fn):
                if not (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)):
                    continue
                name = node.id
                if (name in local or name in g or name in _BUILTINS
                        or name in reported):
                    continue
                verdict = self._classify(module, traced, fn, root, name)
                if verdict:
                    reported.add(name)
                    yield self.finding(module, node, verdict)

    @staticmethod
    def _jit_root(module: Module, traced: dict,
                  fn: ast.FunctionDef) -> ast.FunctionDef | None:
        """Outermost enclosing-or-self traced function entered via
        jax.jit/pjit. Bindings inside it are tracers (same trace);
        bindings *outside* it are host values a capture would bake in."""
        root = fn if traced[fn]["kind"] == "jit" else None
        for anc in module.ancestors(fn):
            if (isinstance(anc, ast.FunctionDef) and anc in traced
                    and traced[anc]["kind"] == "jit"):
                root = anc
        return root

    def _classify(self, module: Module, traced: dict, fn: ast.FunctionDef,
                  root: ast.FunctionDef, name: str) -> str | None:
        """Walk enclosing function scopes for name's binding; report iff
        the binding is array-valued evidence AND lives outside the jit
        root (a host value serialized into the program)."""
        host_scopes = {anc for anc in module.ancestors(root)
                       if isinstance(anc, ast.FunctionDef)}
        scope = module.enclosing_function(fn)
        while scope is not None:
            if scope not in host_scopes:
                # scopes at or inside the jit root are part of the same
                # trace — captures there are tracers, not constants
                if name in _param_names(scope) or any(
                        isinstance(t, ast.Name) and t.id == name
                        for sub in ast.walk(scope)
                        if isinstance(sub, ast.Assign) for t in sub.targets):
                    return None
                scope = module.enclosing_function(scope)
                continue
            if name in _param_names(scope):
                if scope not in traced and _paramish(name):
                    return (f"traced function '{fn.name}' closes over "
                            f"'{name}', a parameter of '{scope.name}' that "
                            "by name holds arrays; the tree is inlined into "
                            "the jitted program as constants — pass it as a "
                            "jit argument")
                return None
            for sub in ast.walk(scope):
                if (isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and sub is not scope):
                    continue
                if isinstance(sub, ast.Assign):
                    targets = sub.targets
                elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                    targets = [sub.target]
                else:
                    continue
                for t in targets:
                    if (isinstance(t, ast.Name) and t.id == name
                            and isinstance(sub.value, ast.Call)
                            and _is_array_call(sub.value)):
                        return (f"traced function '{fn.name}' closes over "
                                f"array '{name}' built at line "
                                f"{sub.value.lineno} "
                                f"({call_name(sub.value)}); it is baked into "
                                "the jitted program as a constant — pass it "
                                "as a jit argument instead")
                    if isinstance(t, ast.Name) and t.id == name:
                        return None  # bound, but not to array evidence
            scope = module.enclosing_function(scope)
        return None


@register
class HostSyncInJit(Rule):
    """TPU102: host-synchronizing call inside a traced function. These
    either fail at trace time (``.item``/``float`` on tracers) or, via
    callbacks, serialize device and host per step — the dispatch-bound
    decode-loop class (VERDICT.md r5, ~235 ms/tick through the tunnel)."""

    id = "TPU102"
    name = "host-sync-in-jit"
    short = "host-synchronizing call inside a traced function"

    _SYNC_DOTTED = {"jax.device_get", "np.asarray", "np.array",
                    "numpy.asarray", "numpy.array"}

    def check(self, module: Module) -> Iterator[Finding]:
        traced = _traced_functions(module)
        for fn, info in traced.items():
            static = _static_names(fn, info.get("jit"))
            params = _param_names(fn)
            for node in _own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "item" and not node.args:
                    yield self.finding(
                        module, node,
                        f".item() inside traced '{fn.name}' forces a "
                        "device->host sync (or a tracer error); return the "
                        "array and read it outside the jit")
                elif name in self._SYNC_DOTTED:
                    yield self.finding(
                        module, node,
                        f"{name}() inside traced '{fn.name}' pulls the value "
                        "to host; use jnp ops (or move the conversion "
                        "outside the jit)")
                elif name == "print":
                    yield self.finding(
                        module, node,
                        f"print() inside traced '{fn.name}' runs at trace "
                        "time only; use jax.debug.print for runtime values")
                elif name in ("float", "int") and len(node.args) == 1:
                    arg = node.args[0]
                    if (isinstance(arg, ast.Name) and arg.id in params
                            and arg.id not in static):
                        yield self.finding(
                            module, node,
                            f"{name}() on traced argument '{arg.id}' in "
                            f"'{fn.name}' concretizes a tracer (host sync "
                            "or trace error); keep it as an array or mark "
                            "it static")


@register
class JnpAtImport(Rule):
    """TPU103: jnp/jax array construction at import time. Import-time
    device work breaks JAX_PLATFORMS selection, initializes the backend
    before the mesh exists, and runs on every process that so much as
    imports the module (controllers included)."""

    id = "TPU103"
    name = "jnp-at-import"
    short = "jnp/jax array construction executed at module import"

    def check(self, module: Module) -> Iterator[Finding]:
        for call in self._import_time_calls(module.tree.body):
            yield self.finding(
                module, call,
                f"{call_name(call)}() runs at module import; build the "
                "array lazily (inside the function that uses it) so "
                "importing never touches the backend")

    def _import_time_calls(self, stmts) -> Iterator[ast.Call]:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # bodies are lazy, but decorators and defaults evaluate now
                eager = (stmt.decorator_list + stmt.args.defaults
                         + [d for d in stmt.args.kw_defaults if d])
                for expr in eager:
                    yield from self._calls_in(expr)
            elif isinstance(stmt, ast.ClassDef):
                yield from self._import_time_calls(stmt.body)
                for expr in stmt.decorator_list:
                    yield from self._calls_in(expr)
            else:
                yield from self._calls_in(stmt)

    def _calls_in(self, node: ast.AST) -> Iterator[ast.Call]:
        stack = [node]
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue  # lazy bodies
            if isinstance(cur, ast.Call) and _is_array_call(cur) \
                    and call_name(cur).split(".")[0] not in ("np", "numpy"):
                yield cur  # host numpy at import is cheap: allowed
            stack.extend(ast.iter_child_nodes(cur))


@register
class MissingDonate(Rule):
    """TPU104: a train/update-step jit without buffer donation. The
    threaded state (params+opt) is then copied every step — 2x HBM for
    the largest live tree and measurable step-time tax at scale."""

    id = "TPU104"
    name = "missing-donate"
    short = "train-step jit without donate_argnums"

    _STEPPISH = ("train_step", "update_step")

    def _steppish(self, name: str | None) -> bool:
        return bool(name) and any(s in name for s in self._STEPPISH)

    def _has_donate(self, call: ast.Call) -> bool:
        return any(kw.arg in ("donate_argnums", "donate_argnames")
                   for kw in call.keywords)

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.FunctionDef) and self._steppish(node.name):
                dec = _jit_decorator(node)
                if isinstance(dec, ast.Call) and not self._has_donate(dec):
                    yield self._emit(module, dec, node.name)
                elif dec is not None and not isinstance(dec, ast.Call):
                    yield self._emit(module, dec, node.name)  # bare @jax.jit
            elif isinstance(node, ast.Call) and call_name(node) in _JITS \
                    and not self._has_donate(node):
                for arg in _callable_args(node):
                    target = dotted(arg)
                    if self._steppish(target):
                        yield self._emit(module, node, target)
                        break

    def _emit(self, module: Module, node: ast.AST, name: str) -> Finding:
        return self.finding(
            module, node,
            f"jit of '{name}' without donate_argnums/donate_argnames: the "
            "threaded train state is copied instead of donated, doubling "
            "its HBM footprint every step")
