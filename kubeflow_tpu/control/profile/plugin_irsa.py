"""AWS IRSA profile plugin: IAM Roles for Service Accounts.

Mirrors the capability of the reference's AwsIAMForServiceAccount plugin
(profile-controller/controllers/plugin_iam.go:27-284):

- ``apply`` annotates the namespace's default-editor ServiceAccount with
  ``eks.amazonaws.com/role-arn`` (plugin_iam.go:110-117) and adds the
  ``system:serviceaccount:<ns>:<sa>`` web-identity subject to the IAM
  role's trust (assume-role) policy (:127-177).
- ``revoke`` removes both again (:42-50, :179-238).

The trust-policy JSON surgery is pure-Python here (the reference uses
gjson): it operates on Statement[0] only, reads the OIDC provider from
``Statement.0.Principal.Federated``, rebuilds the condition with the
default audience plus the updated subject list, and omits the ``:sub``
key entirely when the list empties (plugin_iam.go:213-227 — an empty
JSON array would break AWS policy validation).

AWS API access goes through an injectable backend (the reference holds a
live aws-sdk session, untestable offline); the policy functions are the
meat and fully covered by tests/test_profile_irsa.py at the fidelity of
plugin_iam_test.go.
"""

from __future__ import annotations

import json
import logging
import urllib.parse
from typing import Protocol

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.profile import types as T

log = logging.getLogger("kubeflow_tpu.profile.irsa")

KIND = "AwsIamForServiceAccount"
ANNOTATION = "eks.amazonaws.com/role-arn"            # plugin_iam.go:22
TRUST_IDENTITY_SUBJECT = "system:serviceaccount:{ns}:{sa}"  # :23
DEFAULT_AUDIENCE = "sts.amazonaws.com"               # :24


class ConditionExistsError(Exception):
    """The subject is already in the trust policy (plugin_iam.go:278)."""


class IamBackend(Protocol):
    """The slice of the AWS IAM API the plugin needs.

    ``get_role`` returns the role's assume-role policy document as the
    AWS API does: URL-quoted JSON (plugin_iam.go:85 notes the encoding).
    """

    def get_assume_role_policy(self, role_name: str) -> str: ...

    def update_assume_role_policy(self, role_name: str, policy_json: str) -> None: ...


def issuer_url_from_provider_arn(arn: str) -> str:
    """arn:aws:iam::<acct>:oidc-provider/<issuerUrl> -> issuerUrl (:241-243)."""
    return arn[arn.index("/") + 1:] if "/" in arn else arn


def role_name_from_arn(arn: str) -> str:
    """arn:aws:iam::<acct>:role/<name> -> name (:245-247)."""
    return arn[arn.rindex("/") + 1:] if "/" in arn else arn


def make_assume_role_with_web_identity_policy_document(
        provider_arn: str, condition: dict) -> dict:
    """Trust-policy statement for a web-identity provider (:250-259)."""
    return {
        "Effect": "Allow",
        "Action": "sts:AssumeRoleWithWebIdentity",
        "Principal": {"Federated": provider_arn},
        "Condition": condition,
    }


def make_policy_document(*statements: dict) -> dict:
    """Wrap statements in a policy document (:262-267)."""
    return {"Version": "2012-10-17", "Statement": list(statements)}


def _parse(policy_document: str):
    """Load the doc and locate Statement[0]'s web-identity condition.

    Unlike the reference — which rebuilds a single-statement document
    from scratch, deleting sibling statements, non-StringEquals
    operators, extra condition keys, and any custom audience
    (plugin_iam.go:163-175) — we edit the document in place: only the
    ``<issuer>:sub`` list (and a defaulted ``<issuer>:aud``) of the
    first statement changes; everything else round-trips untouched.
    """
    doc = json.loads(policy_document)
    statements = doc.get("Statement") or []
    if not statements:
        raise ValueError("trust policy has no statements")
    # Like the reference, the subject list lives on the first statement
    # (:147 comment) — but the rest of the document is preserved.
    stmt = statements[0]
    provider_arn = ((stmt.get("Principal") or {}).get("Federated")) or ""
    issuer = issuer_url_from_provider_arn(provider_arn)
    equals = stmt.setdefault("Condition", {}).setdefault("StringEquals", {})
    subjects = equals.get(f"{issuer}:sub") or []
    if isinstance(subjects, str):
        subjects = [subjects]
    return doc, issuer, equals, list(subjects)


def add_service_account_in_assume_role_policy(
        policy_document: str, ns: str, sa: str) -> str:
    """Add <ns>/<sa>'s web-identity subject to the trust policy (:127-177).

    Raises ConditionExistsError when the subject is already present, so
    the caller can skip the (non-idempotent-priced) AWS update call.
    """
    doc, issuer, equals, subjects = _parse(policy_document)
    trust_identity = TRUST_IDENTITY_SUBJECT.format(ns=ns, sa=sa)
    if trust_identity in subjects:
        raise ConditionExistsError(trust_identity)
    subjects.append(trust_identity)
    equals.setdefault(f"{issuer}:aud", [DEFAULT_AUDIENCE])
    equals[f"{issuer}:sub"] = subjects
    return json.dumps(doc)


def remove_service_account_in_assume_role_policy(
        policy_document: str, ns: str, sa: str) -> str:
    """Remove <ns>/<sa>'s subject; drop the :sub key when empty (:179-238
    — an empty JSON array breaks AWS policy validation).

    Raises ConditionExistsError when the subject is absent (nothing to
    remove), so revoke can skip the AWS write — the short-circuit the
    reference's remove path lacks.
    """
    doc, issuer, equals, subjects = _parse(policy_document)
    trust_identity = TRUST_IDENTITY_SUBJECT.format(ns=ns, sa=sa)
    if trust_identity not in subjects:
        raise ConditionExistsError(trust_identity)
    remaining = [s for s in subjects if s != trust_identity]
    if remaining:
        equals[f"{issuer}:sub"] = remaining
    else:
        equals.pop(f"{issuer}:sub", None)
    equals.setdefault(f"{issuer}:aud", [DEFAULT_AUDIENCE])
    return json.dumps(doc)


class IrsaPlugin:
    """Profile plugin: pairs the namespace's editor SA with an IAM role."""

    KIND = KIND

    def __init__(self, iam_backend: IamBackend | None = None):
        self.iam = iam_backend

    def _role_arn(self, profile: dict) -> str | None:
        from kubeflow_tpu.control.profile.controller import plugin_spec_field

        return plugin_spec_field(profile, self.KIND, "awsIamRole")

    def _patch_annotation(self, client, ns: str, arn: str | None) -> None:
        sa = client.get_or_none("v1", "ServiceAccount", T.SA_EDITOR, ns)
        if sa is None:
            return
        if arn is not None:
            ob.set_annotation(sa, ANNOTATION, arn)
        else:
            annos = ob.annotations_of(sa)
            annos.pop(ANNOTATION, None)
        client.update(sa)

    def _update_trust_policy(self, arn: str, ns: str, update_fn) -> None:
        if not self.iam:
            log.warning(
                "IRSA plugin has no IAM backend configured: %s annotated on "
                "%s/%s but the role trust policy was NOT updated — "
                "AssumeRoleWithWebIdentity will fail until it is", arn, ns,
                T.SA_EDITOR)
            return
        role = role_name_from_arn(arn)
        encoded = self.iam.get_assume_role_policy(role)
        decoded = urllib.parse.unquote(encoded)  # AWS URL-quotes the doc (:85)
        try:
            updated = update_fn(decoded, ns, T.SA_EDITOR)
        except ConditionExistsError:
            return  # already present: skip the update (:93-96)
        self.iam.update_assume_role_policy(role, updated)

    def apply(self, client, profile: dict) -> None:
        arn = self._role_arn(profile)
        if not arn:
            return
        ns = ob.meta(profile)["name"]
        self._patch_annotation(client, ns, arn)
        self._update_trust_policy(arn, ns, add_service_account_in_assume_role_policy)

    def revoke(self, client, profile: dict) -> None:
        arn = self._role_arn(profile)
        if not arn:
            return
        ns = ob.meta(profile)["name"]
        self._patch_annotation(client, ns, None)
        self._update_trust_policy(arn, ns, remove_service_account_in_assume_role_policy)
