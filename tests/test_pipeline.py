"""Pipeline-parallelism tests on the 8-device CPU mesh.

The key correctness property: the GPipe-scheduled SPMD pipeline computes
exactly the same function as the sequential layer stack — only the
parameter layout (stage-stacked, pipe-sharded) and schedule differ.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.core import meta

from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh
from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer


MODEL_KW = dict(
    vocab_size=128, d_model=32, n_layers=4, n_heads=2, n_kv_heads=2,
    head_dim=16, d_ff=64, max_seq_len=64, attention_impl="reference",
)


def _restack_params(seq_params: dict, pp: int, n_layers: int) -> dict:
    """Map sequential params {layer_i: ...} onto the pipelined layout
    {pipeline: {ticks: {stages: {block_p: stacked-over-stage}}}}."""
    lps = n_layers // pp
    out = {k: v for k, v in seq_params.items() if not k.startswith("layer_")}
    stages = {}
    for p in range(lps):
        per_stage = [seq_params[f"layer_{s * lps + p}"] for s in range(pp)]
        stages[f"block_{p}"] = jax.tree.map(
            lambda *leaves: jnp.stack(leaves, axis=0), *per_stage
        )
    out["pipeline"] = {"ticks": {"stages": stages}}
    return out


@pytest.mark.parametrize("n_mb", [1, 2, 4])
def test_pipeline_matches_sequential(devices8, n_mb):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128)

    seq_model = get_model("transformer-test", **MODEL_KW)
    variables = meta.unbox(seq_model.init(jax.random.PRNGKey(0), tokens, train=False))
    ref = seq_model.apply(variables, tokens, train=False)

    pp_model = get_model(
        "transformer-test", pipeline_stages=2, pp_microbatches=n_mb, **MODEL_KW
    )
    pp_params = {"params": _restack_params(variables["params"], pp=2, n_layers=4)}
    # Shape agreement with a fresh init of the pipelined model
    fresh = meta.unbox(pp_model.init(jax.random.PRNGKey(0), tokens, train=False))
    jax.tree.map(
        lambda a, b: np.testing.assert_equal(a.shape, b.shape), fresh, pp_params
    )
    got = pp_model.apply(pp_params, tokens, train=False)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got), atol=5e-3, rtol=5e-2)


def test_pipeline_grads_match_sequential(devices8):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128)
    targets = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 128)

    seq_model = get_model("transformer-test", **MODEL_KW)
    variables = meta.unbox(seq_model.init(jax.random.PRNGKey(0), tokens, train=False))
    pp_model = get_model(
        "transformer-test", pipeline_stages=2, pp_microbatches=2, **MODEL_KW
    )
    pp_params = {"params": _restack_params(variables["params"], pp=2, n_layers=4)}

    def loss(model, params):
        import optax

        logits = model.apply(params, tokens, train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), targets
        ).mean()

    g_seq = jax.grad(lambda p: loss(seq_model, p))(variables)
    g_pp = jax.grad(lambda p: loss(pp_model, p))(pp_params)
    # Compare the embedding grad (touched by every microbatch) and the
    # restacked layer grads.
    np.testing.assert_allclose(
        np.asarray(g_seq["params"]["embedding"]),
        np.asarray(g_pp["params"]["embedding"]),
        atol=5e-3, rtol=5e-2,
    )
    g_seq_stacked = _restack_params(g_seq["params"], pp=2, n_layers=4)
    for name in ("block_0", "block_1"):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=5e-3, rtol=5e-2),
            g_seq_stacked["pipeline"]["ticks"]["stages"][name],
            g_pp["params"]["pipeline"]["ticks"]["stages"][name],
        )


def test_pipeline_training_on_pipe_mesh(devices8):
    """End-to-end: Trainer over a dp=2 x pipe=2 x model=2 mesh."""
    cfg = TrainConfig.from_dict(dict(
        model="transformer-test",
        model_kwargs=dict(attention_impl="reference"),
        task="lm",
        global_batch=8,
        seq_len=32,
        vocab_size=256,
        mesh=MeshSpec(data=2, pipe=2, model=2),
        optimizer="adamw",
        learning_rate=1e-3,
        total_steps=2,
        warmup_steps=1,
        pp_microbatches=2,
    ))
    trainer = Trainer(cfg)
    state = trainer.init_state()
    # stage-stacked weights must actually shard over the pipe axis
    from kubeflow_tpu.parallel.mesh import AXIS_PIPELINE

    stage_leaf = jax.tree.leaves(
        state.params["pipeline"]["ticks"]["stages"]
    )[0]
    spec = stage_leaf.sharding.spec
    assert spec and spec[0] == AXIS_PIPELINE, f"stage dim not pipe-sharded: {spec}"
    batch = next(trainer.data_iter())
    state, m = trainer.train_step(state, batch)
    state, m = trainer.train_step(state, batch)
    assert np.isfinite(float(m["loss"]))


def test_pipeline_rejects_bad_config(devices8):
    with pytest.raises(ValueError, match="not divisible"):
        m = get_model("transformer-test", pipeline_stages=3, **MODEL_KW)
        m.init(jax.random.PRNGKey(0), jnp.ones((3, 8), jnp.int32), train=False)
