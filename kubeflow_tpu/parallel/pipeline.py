"""SPMD pipeline parallelism (GPipe schedule under GSPMD).

The reference has no pipeline parallelism at all — its parallelism ceiling
is PS data-parallel / MPI allreduce (SURVEY.md §2.5). This module adds the
`pipe` mesh axis the TPU-native way: instead of per-stage processes and
point-to-point sends (the GPU/NCCL idiom), the pipeline is ONE jitted SPMD
program:

- every stage's parameters are stacked on a leading stage dim and sharded
  over the `pipe` mesh axis (`nn.vmap` + flax partitioning metadata), so
  each pipeline group holds exactly its own stage weights;
- one schedule tick applies ALL stages at once (`nn.vmap` over the stage
  dim — each mesh group computes only its slice);
- between ticks the activation buffer shifts one stage forward. The shift
  is written as concat(feed, state[:-1]) on the stage-sharded dim, which
  XLA lowers to a collective-permute over the ICI ring — the TPU
  equivalent of the NCCL send/recv pair, but fused into the step program
  with zero host involvement;
- `nn.scan` runs the n_microbatches + n_stages - 1 ticks with parameters
  broadcast (not re-stacked per tick), keeping compile time and HBM flat
  in the number of ticks.

The GPipe bubble is (pp-1)/(ticks) — amortized by raising
`n_microbatches`. Backward runs through the scan transpose automatically;
activations for the backward pass can be rematerialized per-tick with the
model's usual remat flag.

Multi-slice placement: on a multislice deployment the `pipe` axis may be
laid OVER the DCN boundary so each slice holds whole pipeline stages and
only the per-tick stage handoff (one activation shift) crosses DCN — the
classic stages-across-pods shape. That is purely a mesh-construction
concern: pass ``dcn_pipeline_levels()`` (or set JAXJOB_MESH_DCN_AXES=pipe)
to the backend's mesh builder (``parallel/backends.build_level_mesh``)
and this module runs unchanged — the axes→levels map IS the placement
policy, there is no second pipeline code path.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.parallel.mesh import (
    AXIS_PIPELINE,
    AXIS_SEQ,
    BATCH_AXES,
    shard_constraint as _shard,
)

# Activation-buffer layout: [stage, microbatch, seq, features]
STATE_SPEC = P(AXIS_PIPELINE, BATCH_AXES, AXIS_SEQ, None)


def dcn_pipeline_levels() -> dict[str, str]:
    """The mesh-axes→levels map for stages-across-slices: `pipe` rides
    DCN (stage handoff is the only cross-slice traffic), everything
    else stays ICI. Feed to CollectivesBackend.mesh(levels=...)."""
    from kubeflow_tpu.parallel import backends as B
    from kubeflow_tpu.parallel.mesh import AXIS_DCN

    return {AXIS_DCN: B.LEVEL_DCN, AXIS_PIPELINE: B.LEVEL_DCN}


class SPMDPipeline(nn.Module):
    """Runs `n_stages` copies of `stage_cls(*stage_args)` as a pipeline.

    The stage module must have signature ``__call__(x, *broadcast)`` where
    ``x`` is [mb, seq, d] and ``broadcast`` inputs are shared verbatim by
    every stage and every microbatch — they must NOT carry a batch
    dimension (pass e.g. 1-D rope positions and broadcast inside the
    stage). Parameters of the wrapped stage gain a leading ``pipe``-sharded
    stage dimension.
    """

    stage_cls: Any
    stage_args: tuple = ()
    n_stages: int = 1
    n_microbatches: int = 1

    @nn.compact
    def __call__(self, x: jax.Array, *broadcast: Any) -> jax.Array:
        pp = self.n_stages
        batch = x.shape[0]
        n_mb = self.n_microbatches
        if n_mb <= 0 or batch % n_mb != 0:
            # Only shape-only paths (init/eval_shape with a tiny batch) may
            # degrade; a real batch that doesn't divide is a config error
            # that would otherwise silently run with a (pp-1)/pp bubble.
            if batch >= n_mb:
                raise ValueError(
                    f"batch {batch} not divisible by n_microbatches {n_mb}"
                )
            n_mb = 1
        mb = batch // n_mb
        ticks = n_mb + pp - 1

        x_mb = x.reshape(n_mb, mb, *x.shape[1:])
        # Stage-0 feed for every tick; the tail of the schedule (drain
        # ticks) re-feeds the last microbatch — its output is discarded.
        feed = x_mb[jnp.minimum(jnp.arange(ticks), n_mb - 1)]
        # Anchor the stacked scan input: without it the partitioner
        # propagates an arbitrary sharding onto `feed`, and the per-tick
        # dynamic-slice then needs a reshard it can only do as
        # replicate-then-repartition (caught by the dryrun warning gate
        # at n=16, dcn x dp x pp x tp).
        feed = _shard(feed, P(None, BATCH_AXES, AXIS_SEQ, None))
        # Broadcast inputs are shared across microbatches by API contract
        # (they are passed unsplit to every tick); no shape heuristic here —
        # a leading dim that merely *equals* batch (e.g. positions when
        # seq_len == global_batch) is legitimate.
        bcast = tuple(broadcast)

        vstage = nn.vmap(
            self.stage_cls,
            in_axes=(0,) + tuple(None for _ in bcast),
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            metadata_params={nn.meta.PARTITION_NAME: AXIS_PIPELINE},
        )

        outer = self

        class Tick(nn.Module):
            @nn.compact
            def __call__(self, state, feed_t):
                # state[s] = last output of stage s; stage s>0 consumes
                # stage s-1's output, stage 0 consumes the fresh feed.
                # The concat of a fresh row with state[:-1] on the
                # pipe-sharded dim IS the inter-stage transfer: XLA lowers
                # it to collective-permute over ICI.
                feed_t = _shard(feed_t, P(BATCH_AXES, AXIS_SEQ, None))
                stages_in = jnp.concatenate([feed_t[None], state[:-1]], axis=0)
                stages_in = _shard(stages_in, STATE_SPEC)
                out = vstage(*outer.stage_args, name="stages")(stages_in, *bcast)
                out = _shard(out, STATE_SPEC)
                return out, out[-1]

        scanned = nn.scan(
            Tick,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0,
            out_axes=0,
            length=ticks,
        )
        state0 = _shard(jnp.zeros((pp, mb) + x.shape[1:], x.dtype), STATE_SPEC)
        _, drained = scanned(name="ticks")(state0, feed)
        # First pp-1 drained rows are bubble output of the cold pipeline.
        out = drained[pp - 1 :]
        return out.reshape(batch, *x.shape[1:])
