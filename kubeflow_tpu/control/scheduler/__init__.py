"""kubeflow_tpu.control.scheduler — TPU-topology-aware gang scheduler.

The reference delegates placement entirely to kube-scheduler; its only
topology notion is "N pods each asking for nvidia.com/gpu: 1"
(tf-controller-examples/tf-cnn/create_job_specs.py:165-170). That
collapses on TPU, where a job needs a *contiguous slice* and partial
placement is worthless: a jax.distributed world missing one worker never
forms a mesh. This package is the kube-scheduler/Kueue analogue rebuilt
TPU-slice-native:

- ``topology``  — the ONE parser for ``"2x4"``/``"4x4x4"`` slice strings
  (shared with tpctl and JAXJob validation; AST-pinned in tests).
- ``nodes``     — the node/TPU-pool model: accelerator + topology labels,
  chips-per-node allocatable, taints, readiness.
- ``queue``     — per-namespace gang queue: priority + FIFO order,
  exponential requeue backoff, injectable clock.
- ``scheduler`` — the Reconciler: all-or-nothing gang admission
  (reserve -> bind every pod via spec.nodeName, or release and requeue)
  and priority preemption (evict a lower-priority gang as
  Failed/Evicted so the JAXJob controller's gang-restart path fires).

A JAXJob opts in by setting ``spec.schedulerName`` (see
``jaxjob.types.new_jaxjob(gang_schedule=True)``); its generated pods
carry a scheduling gate that only admission lifts, so no kubelet runs a
partially placed gang.
"""

from __future__ import annotations

# Pod-facing contract, consumed by the JAXJob controller when a job opts
# into gang scheduling. Constants live here (import-light) so jaxjob can
# import them without pulling the scheduler runtime in.
SCHEDULER_NAME = "kubeflow-tpu-scheduler"
GATE_GANG = "scheduler.kubeflow.org/gang"
ANNOTATION_GANG_SIZE = "scheduler.kubeflow.org/gang-size"
ANNOTATION_PRIORITY = "scheduler.kubeflow.org/priority"
# Elastic floor: present on a gang's pods => the gang may be admitted
# PARTIALLY, down to this many workers (rigid gangs — no annotation —
# keep the all-or-nothing law). Stamped by the JAXJob controller from
# spec.elastic.minReplicas.
ANNOTATION_ELASTIC_MIN = "scheduler.kubeflow.org/elastic-min"
# Spot/preemptible pool surface (the GKE spot label): spot nodes carry
# this label plus a matching NoSchedule taint, so only workloads that
# explicitly tolerate reclaim — elastic gangs — may land there. The
# scheduler PREFERS spot nodes for elastic workers (keeping on-demand
# capacity for rigid gangs) but falls back to on-demand when the spot
# pool is full: preferred, never required.
LABEL_SPOT = "cloud.google.com/gke-spot"


def __getattr__(name):
    # lazy: the runtime imports jaxjob types/controller, which import the
    # constants above — eager re-export here would be a cycle
    if name in ("build_scheduler", "GangScheduler"):
        from kubeflow_tpu.control.scheduler import scheduler as _s

        return getattr(_s, name)
    raise AttributeError(name)
