"""Device mesh construction for TPU slices.

The reference platform's only notion of topology is "N replica pods, each
asking for `nvidia.com/gpu: 1`" (tf-controller-examples/tf-cnn/
create_job_specs.py:165-170). On TPU the topology is first-class: a slice
is a 2D/3D torus of chips wired by ICI, and XLA lowers collectives onto
that torus. This module owns the mapping from a logical parallelism spec
(dp/fsdp/tp/pp/sp/ep axis sizes) to a physical `jax.sharding.Mesh`.

Axis vocabulary (used by models, trainer, and kernels throughout):

- ``dcn``      — the cross-slice axis: data parallelism over the
                 data-center network on multislice deployments (one
                 gradient all-reduce per step; the only collective slow
                 enough for DCN).
- ``data``     — pure data parallelism (gradient all-reduce).
- ``fsdp``     — data parallelism with parameter/optimizer sharding
                 (all-gather params, reduce-scatter grads).
- ``model``    — tensor parallelism (Megatron-style row/col sharding).
- ``pipe``     — pipeline stages.
- ``seq``      — sequence/context parallelism (ring attention axis).
- ``expert``   — expert parallelism for MoE (all-to-all dispatch).

Collectives for `dcn`/`data`/`fsdp` are cheap and tolerate DCN;
`model`/`seq` collectives are per-layer and must ride ICI. `build_mesh`
therefore puts the fastest-varying (innermost, ICI-adjacent) device
dimension on `model`/`seq` and the outermost on `dcn` then `data`,
matching the scaling-book recipe of "model-parallel inner, data-parallel
outer, slices outermost". On real multislice hardware the ``dcn`` axis is
placed with `mesh_utils.create_hybrid_device_mesh` so each slice's
devices stay ICI-contiguous.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DCN = "dcn"
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_PIPELINE = "pipe"
AXIS_EXPERT = "expert"
AXIS_SEQ = "seq"
AXIS_MODEL = "model"

# Outer-to-inner physical placement order. Inner axes get ICI-adjacent
# devices; the outermost (dcn) spans slices on multi-slice deployments.
_AXIS_ORDER = (AXIS_DCN, AXIS_DATA, AXIS_FSDP, AXIS_PIPELINE, AXIS_EXPERT,
               AXIS_SEQ, AXIS_MODEL)

# The canonical axis vocabulary, public. tpulint's sharding-consistency
# rules (TPU105/TPU106, kubeflow_tpu/analysis/rules_sharding.py) resolve
# every PartitionSpec axis name against this tuple — a new axis must be
# added here (the lint's mirror is AST-pinned to _AXIS_ORDER in
# tests/test_tpulint.py) before any spec may name it.
AXIS_NAMES: tuple[str, ...] = _AXIS_ORDER

# Every batch-sharded PartitionSpec uses this tuple; size-1 axes are free,
# so single-slice meshes pay nothing for carrying the dcn name.
# `expert` is a batch axis too (GShard-style): outside MoE layers the
# expert dimension has nothing to shard, and leaving tokens replicated
# across it would duplicate every dense block's compute ep-fold. Inside
# an MoE layer the token<->expert regrouping is exactly the all-to-all
# over this axis (ops/moe.py).
BATCH_AXES = (AXIS_DCN, AXIS_DATA, AXIS_FSDP, AXIS_EXPERT)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism specification.

    Any axis set to 1 is still present in the mesh (size-1 axes are free),
    so a single `PartitionSpec` vocabulary works for every configuration.
    ``data = -1`` means "whatever is left over" and is resolved against the
    device count at mesh-build time.
    """

    dcn: int = 1
    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    expert: int = 1
    seq: int = 1
    model: int = 1

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Resolve data=-1 against the device count; validate divisibility."""
        fixed = (self.dcn * self.fsdp * self.pipe * self.expert * self.seq
                 * self.model)
        data = self.data
        if data == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"device count {n_devices} not divisible by non-data axes "
                    f"product {fixed} (spec={self})"
                )
            data = n_devices // fixed
        total = data * fixed
        if total != n_devices:
            raise ValueError(
                f"mesh spec {self} needs {total} devices, have {n_devices}"
            )
        return dataclasses.replace(self, data=data)

    def axis_sizes(self) -> dict[str, int]:
        return {
            AXIS_DCN: self.dcn,
            AXIS_DATA: self.data,
            AXIS_FSDP: self.fsdp,
            AXIS_PIPELINE: self.pipe,
            AXIS_EXPERT: self.expert,
            AXIS_SEQ: self.seq,
            AXIS_MODEL: self.model,
        }

    @property
    def batch_axes(self) -> tuple[str, ...]:
        """Axes the global batch is sharded over."""
        return BATCH_AXES

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "MeshSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}; known: {sorted(known)}")
        return cls(**{k: int(v) for k, v in d.items()})


def build_mesh(
    spec: MeshSpec | Mapping[str, Any] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a `jax.sharding.Mesh` from a logical spec.

    Uses `mesh_utils.create_device_mesh` so the physical assignment follows
    the slice's ICI topology (it understands TPU coords); falls back to a
    plain reshape for CPU/interpreter devices.
    """
    if devices is None:
        devices = jax.devices()
    if spec is None:
        spec = MeshSpec()
    if not isinstance(spec, MeshSpec):
        spec = MeshSpec.from_dict(spec)
    spec = spec.resolve(len(devices))
    sizes = spec.axis_sizes()
    shape = tuple(sizes[a] for a in _AXIS_ORDER)
    dev_np = np.asarray(devices, dtype=object)
    if spec.dcn > 1 and all(
            getattr(d, "slice_index", None) is not None for d in devices):
        # real multislice hardware: the dcn axis must fall on slice
        # boundaries so inner axes stay ICI-contiguous. Errors here (dcn
        # not matching the actual slice count, per-slice shape mismatch)
        # MUST propagate — a silent reshape would put per-layer
        # collectives on DCN, an order-of-magnitude slowdown.
        ici_shape = (1,) + shape[1:]
        dcn_shape = (spec.dcn,) + (1,) * (len(shape) - 1)
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=dev_np)
        return Mesh(dev_array, _AXIS_ORDER)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=dev_np)
    except (ValueError, AssertionError, NotImplementedError):
        # CPU/interpreter devices (no slice topology): plain reshape keeps
        # the dcn axis outermost, which is exactly the contiguous-rank
        # layout the JAXJob controller assigns slices by
        dev_array = dev_np.reshape(shape)
    return Mesh(dev_array, _AXIS_ORDER)


def batch_spec(mesh: Mesh, extra_dims: int = 0) -> P:
    """PartitionSpec for a batch-major array: shard dim 0 over data axes."""
    del mesh
    return P(BATCH_AXES, *([None] * extra_dims))


def batch_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(mesh, extra_dims))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_batch_size(mesh: Mesh, global_batch: int) -> int:
    n = 1
    for a in BATCH_AXES:
        n *= mesh.shape[a]
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by dp={n}")
    return global_batch // n


def mesh_summary(mesh: Mesh) -> str:
    axes = ", ".join(f"{k}={v}" for k, v in mesh.shape.items() if v > 1) or "single-device"
    kinds = {d.device_kind for d in mesh.devices.flat}
    return f"Mesh({axes}) on {mesh.devices.size}x {'/'.join(sorted(kinds))}"


def pad_to_multiple(x: int, m: int) -> int:
    return int(math.ceil(x / m) * m)


def current_mesh() -> Mesh | None:
    """The mesh installed by the ambient `with mesh:` context, if any."""
    env = jax._src.mesh.thread_resources.env
    m = env.physical_mesh
    return None if m.empty else m


def shard_constraint(x: jax.Array, spec: P) -> jax.Array:
    """with_sharding_constraint that is a no-op outside a mesh context.

    Mesh presence is checked explicitly (rather than try/except) so real
    sharding errors — rank mismatch, indivisible dims — still propagate."""
    if current_mesh() is None:
        return x
    return jax.lax.with_sharding_constraint(x, spec)
