"""Ring attention: exact causal attention over sequence-sharded inputs.

Long-context is first-class in the TPU build (the reference has nothing —
SURVEY.md §5 "Long-context / sequence parallelism: Absent"). Sequences are
sharded over the mesh's `seq` axis; each device holds one block of Q/K/V.
K/V blocks rotate around the ring with `lax.ppermute` (nearest-neighbor
ICI hops, no all-gather), and each device maintains a streaming-softmax
accumulator (running max / sum / output), so memory stays O(L/ring) and
the math is exactly softmax(QK^T)V.

Implementation is `shard_map` over the ambient mesh: inside, arrays are
the local blocks and collectives are explicit. Per ring step the K/V
transfer overlaps the block matmul (XLA schedules ppermute async).

References (public technique literature): Liu et al., "Ring Attention
with Blockwise Transformers for Near-Infinite Context" (2023);
flash-attention streaming softmax (Dao et al. 2022).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import AXIS_MODEL, AXIS_SEQ, BATCH_AXES

NEG_INF = -1e30


from kubeflow_tpu.parallel.mesh import current_mesh as _current_mesh


def _ring_perm(n: int) -> list[tuple[int, int]]:
    # send block to the next device; receive from the previous
    return [(i, (i + 1) % n) for i in range(n)]


def _block_attn(q, k, v, row_ids, col_ids, scale, causal,
                qseg=None, kseg=None, window=0):
    """One block pair: returns (unnormalized out, row max, row sum).
    qseg/kseg: optional [b, lq]/[b, lk] packing ids — cross-document
    pairs are masked like causal violations. window > 0 masks keys
    further than window-1 positions in the past (global indices, so the
    bound holds across ring hops)."""
    h = q.shape[2]
    if k.shape[2] != h:
        k = jnp.repeat(k, h // k.shape[2], axis=2)
        v = jnp.repeat(v, h // v.shape[2], axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    mask = None                                        # [b, q, k] or None
    if causal:
        mask = jnp.broadcast_to(
            row_ids[:, None] >= col_ids[None, :],      # global indices
            (q.shape[0],) + (row_ids.shape[0], col_ids.shape[0]))
    if window > 0:
        near = jnp.broadcast_to(
            (row_ids[:, None] - col_ids[None, :]) < window,
            (q.shape[0],) + (row_ids.shape[0], col_ids.shape[0]))
        mask = near if mask is None else mask & near
    if qseg is not None:
        seg = qseg[:, :, None] == kseg[:, None, :]
        mask = seg if mask is None else mask & seg
    if mask is not None:
        logits = jnp.where(mask[:, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                       # [b,h,q]
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(logits - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask[:, None], p, 0.0)
    l = jnp.sum(p, axis=-1)                            # [b,h,q]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m_safe, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = AXIS_SEQ,
    mesh: Mesh | None = None,
    causal: bool = True,
    segment_ids: jax.Array | None = None,
    window: int = 0,
) -> jax.Array:
    """Exact attention over seq-sharded [B, L, H, D] arrays.

    ``causal=False`` gives the bidirectional (BERT-style) long-context
    path: same ring rotation and streaming softmax, no block masking.
    ``segment_ids`` ([B, L], sharded over `seq` like Q/K/V) mask packed
    documents apart; the K-side ids rotate around the ring with K/V.
    Falls back to single-block reference attention when the mesh has no
    `seq` axis (so the same model code runs on any mesh spec).
    """
    mesh = mesh or _current_mesh()
    if mesh is None or axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        from kubeflow_tpu.ops.attention import reference_attention

        return reference_attention(q, k, v, causal=causal,
                                   segment_ids=segment_ids, window=window)

    n_ring = mesh.shape[axis_name]
    scale = q.shape[-1] ** -0.5
    l_total = q.shape[1]
    l_block = l_total // n_ring
    assert l_block * n_ring == l_total, (l_total, n_ring)

    # GQA: repeat KV heads up to Q heads *before* sharding so the head dim
    # of all three operands shards identically over `model`. Without this,
    # n_kv_heads < model-axis size crashes shard_map (the weight-sharding
    # heuristic in parallel/shardings.py deliberately replicates such KV
    # weights, so the activations really do arrive with few heads).
    h = q.shape[2]
    if k.shape[2] != h:
        assert h % k.shape[2] == 0, (h, k.shape[2])
        k = jnp.repeat(k, h // k.shape[2], axis=2)
        v = jnp.repeat(v, h // v.shape[2], axis=2)
    model_size = mesh.shape.get(AXIS_MODEL, 1) if AXIS_MODEL in mesh.axis_names else 1
    head_axis = AXIS_MODEL if h % max(model_size, 1) == 0 and model_size > 1 else None
    qkv_spec = P(BATCH_AXES, axis_name, head_axis, None)
    seg_spec = P(BATCH_AXES, axis_name)
    has_seg = segment_ids is not None

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec)
        + ((seg_spec,) if has_seg else ()),
        out_specs=qkv_spec,
        check_vma=False,
    )
    def _ring(q_blk, k_blk, v_blk, *maybe_seg):
        seg_blk = maybe_seg[0] if has_seg else None
        seq_idx = jax.lax.axis_index(axis_name)
        b, lq, h, d = q_blk.shape
        row_ids = seq_idx * l_block + jnp.arange(lq)
        perm = _ring_perm(n_ring)

        def accumulate(o, m, l, k_cur, v_cur, kseg_cur, i):
            src = (seq_idx - i) % n_ring           # owner of current K/V block
            col_ids = src * l_block + jnp.arange(k_cur.shape[1])
            o_i, m_i, l_i = _block_attn(q_blk, k_cur, v_cur, row_ids, col_ids,
                                        scale, causal,
                                        qseg=seg_blk, kseg=kseg_cur,
                                        window=window)
            m_new = jnp.maximum(m, m_i)
            alpha = jnp.exp(m - m_new)             # rescale old accumulator
            beta = jnp.exp(m_i - m_new)
            l_new = l * alpha + l_i * beta
            o_new = o * alpha[..., None].transpose(0, 2, 1, 3) + \
                o_i * beta[..., None].transpose(0, 2, 1, 3)
            return o_new, m_new, l_new

        def step(carry, i):
            o, m, l, k_cur, v_cur, kseg_cur = carry
            o, m, l = accumulate(o, m, l, k_cur, v_cur, kseg_cur, i)
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            # the K-side packing ids travel WITH their K/V block
            kseg_nxt = (jax.lax.ppermute(kseg_cur, axis_name, perm)
                        if has_seg else kseg_cur)
            return (o, m, l, k_nxt, v_nxt, kseg_nxt), None

        o0 = jnp.zeros((b, lq, h, d), jnp.float32)
        m0 = jnp.full((b, h, lq), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, lq), jnp.float32)
        kseg0 = seg_blk if has_seg else jnp.zeros((b, 1), jnp.int32)
        # causal + window: hop i's closest (q, k) pair sits (i-1)*l_block+1
        # positions apart, so blocks past ceil((window-1)/l_block) hops are
        # entirely outside the window — skip their compute AND their
        # ppermute traffic (static cap: window/l_block are Python ints).
        n_hops = n_ring
        if causal and window > 0:
            n_hops = min(n_ring, max(1, (window - 2) // l_block + 2))
        # scan the first n_hops-1 rotations; peel the last block so its
        # K/V are not ppermuted onward (that transfer is never read).
        (o, m, l, k_last, v_last, kseg_last), _ = jax.lax.scan(
            step, (o0, m0, l0, k_blk, v_blk, kseg0), jnp.arange(n_hops - 1)
        )
        o, m, l = accumulate(o, m, l, k_last, v_last, kseg_last, n_hops - 1)
        l = jnp.maximum(l, 1e-20)
        out = o / l[..., None].transpose(0, 2, 1, 3)
        return out.astype(q_blk.dtype)

    args = (q, k, v) + ((segment_ids,) if has_seg else ())
    return _ring(*args)
