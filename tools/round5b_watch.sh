#!/usr/bin/env bash
# Round-5 phase 2: refine the measured frontier.
#
# Phase 1 (round5_watch.sh) found the round's winning operating point —
# llama-1b bs8 slim-remat, 0.5132 MFU — but the flash block sizes it
# ran with (Q=512, K=1024) were swept at gpt-350m WITHOUT remat back in
# round 3. VERDICT r4 #2 asks for a block re-sweep at the winning
# policy: the slim backward replays gate/up matmuls, shifting the
# VMEM-residency tradeoff, and llama-1b's head_dim/kv geometry differs
# from 350m's. Also: gpt-760m bs8 slim (phase 1 queued 760m dots/mlp
# but never slim — dots OOMed; slim saves strictly less).
#
# Same ledger + chip-yield protocol as phase 1 (tools/watch_lib.sh);
# run AFTER phase 1 exits (tools/watch_chain.sh supervises the
# handoff).
set -u
cd "$(dirname "$0")/.."
LOG=tools/round5_watch.log
LEDGER=tools/r5_stages
WATCH_TAG=" [p2]"
. tools/watch_lib.sh

lm1b() {  # NAME Q K — one llama-1b bs8 slim point at given flash blocks
  run_stage "$1" 1500 env KFTPU_FLASH_BLOCK_Q="$2" KFTPU_FLASH_BLOCK_K="$3" \
    python bench.py --workload lm --lm-model llama-1b --lm-batch 8 \
    --lm-optimizer adafactor --lm-remat --lm-remat-policy slim \
    --lm-xent-chunks 8
}

while true; do
  if extern_active; then
    note "external bench holds the chip — idling"
    sleep 20
    continue
  fi
  if probe; then
    note "tunnel UP — phase-2 ledger"
    # the missing slim point at 760m (dots OOMed; slim saves less)
    run_stage lm_760m_bs8_slim 1500 python bench.py --workload lm \
      --lm-model gpt-760m --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy slim --lm-xent-chunks 8
    # slim BEAT no-remat at 1b bs8 (0.513 vs r3's 0.475): in the
    # byte-bound regime saved activation traffic outweighs recompute —
    # so measure one step further down the memory ladder too
    run_stage lm_1b_bs8_full 1500 python bench.py --workload lm \
      --lm-model llama-1b --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy full --lm-xent-chunks 8
    # TPU-shaped head geometry: the microbench puts flash fwd+bwd at
    # ~0.10 util vs 0.66 for MLP because head_dim 64 uses half the MXU
    # contraction lanes; llama-1b-hd128 is the same 1.1B params / same
    # FLOPs with 16x128 GQA heads
    run_stage lm_1b_hd128_bs8_slim 1500 python bench.py --workload lm \
      --lm-model llama-1b-hd128 --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy slim --lm-xent-chunks 8
    run_stage lm_1b_hd128_bs8 1500 python bench.py --workload lm \
      --lm-model llama-1b-hd128 --lm-batch 8 --lm-optimizer adafactor \
      --lm-xent-chunks 8
    # flash-block sweep at the winning point (default 512/1024 already
    # measured as lm_1b_bs8_slim = 0.5132)
    lm1b lm_1b_slim_q256_k512   256  512
    lm1b lm_1b_slim_q512_k512   512  512
    lm1b lm_1b_slim_q1024_k512  1024 512
    lm1b lm_1b_slim_q256_k1024  256  1024
    lm1b lm_1b_slim_q1024_k1024 1024 1024
    lm1b lm_1b_slim_q512_k2048  512  2048
    # fused-decode serving re-measurement: the same commands as phase
    # 1's serve_cont_int8 / serve_kv_int8 rows, now running the
    # FUSE=8 tick fusion (amortizes the per-dispatch tunnel round-trip
    # that made decode latency-bound)
    run_stage serve_cont_int8_fused 1800 python tools/serve_bench.py \
      --modes continuous --requests 32 --param-dtype int8
    run_stage serve_kv_int8_fused 1800 python tools/serve_bench.py \
      --modes continuous --requests 16 --model llama-1b \
      --prompt-len 1024 --max-new-tokens 32 --slots 8 \
      --param-dtype int8 --kv-cache-dtype int8
    # head_dim 64-vs-128 flash utilization, measured directly
    run_stage microbench_hd128 1500 python tools/op_microbench.py \
      --batch 8 --seq 2048
    # mixed remat (policy@K): slim@15 rescues gpt-760m bs8's 50MB miss;
    # slim@12 probes whether 4 save-everything layers beat full slim at
    # the 1b frontier (slim already beat no-remat, so the optimum may
    # sit between)
    run_stage lm_760m_bs8_slim15 1500 python bench.py --workload lm \
      --lm-model gpt-760m --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy slim@15 --lm-xent-chunks 8
    run_stage lm_1b_bs8_slim12 1500 python bench.py --workload lm \
      --lm-model llama-1b --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy slim@12 --lm-xent-chunks 8
    # promote anything that beats the banked floor
    cat "$LEDGER"/*.out > tools/lm_sweep_r05.jsonl 2>/dev/null || true
    python tools/promote_best.py tools/lm_sweep_r05.jsonl \
      >> "$LOG" 2>&1 || true
    python tools/promote_serve_best.py "$LEDGER"/serve_*.out \
      >> "$LOG" 2>&1 || true
    settled=$(ls "$LEDGER"/lm_1b_slim_*.done "$LEDGER"/lm_1b_slim_*.skip \
      "$LEDGER"/lm_760m_bs8_slim.done "$LEDGER"/lm_760m_bs8_slim.skip \
      "$LEDGER"/lm_1b_bs8_full.done "$LEDGER"/lm_1b_bs8_full.skip \
      "$LEDGER"/lm_1b_hd128_*.done "$LEDGER"/lm_1b_hd128_*.skip \
      "$LEDGER"/serve_*_fused.done "$LEDGER"/serve_*_fused.skip \
      "$LEDGER"/microbench_hd128.done "$LEDGER"/microbench_hd128.skip \
      "$LEDGER"/lm_760m_bs8_slim15.done "$LEDGER"/lm_760m_bs8_slim15.skip \
      "$LEDGER"/lm_1b_bs8_slim12.done "$LEDGER"/lm_1b_bs8_slim12.skip \
      2>/dev/null | wc -l)
    if [ "$settled" -ge 15 ]; then
      note "phase-2 settled ($settled)"
      exit 0
    fi
  else
    note "tunnel down"
  fi
  sleep 230
done
