"""Repro: which config emits the SPMD involuntary-remat warning, and on
which tensor. Run: python tools/repro_accum_warn.py '{"grad_accum_steps": 2, ...}'"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh  # noqa: E402
from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer  # noqa: E402

over = json.loads(sys.argv[1]) if len(sys.argv) > 1 else {}
mesh_kw = over.pop("mesh", dict(dcn=2, data=2, fsdp=2))
base = dict(
    model="transformer-test",
    model_kwargs={"attention_impl": "reference"},
    task="lm", global_batch=16, seq_len=16, vocab_size=256,
    mesh=MeshSpec(**mesh_kw),
    optimizer="adafactor", learning_rate=1e-3, total_steps=1,
    warmup_steps=1, grad_accum_steps=2, xent_chunks=4,
)
base.update(over)
cfg = TrainConfig.from_dict(base)

mesh = build_mesh(cfg.mesh, devices=jax.devices()[:8])
trainer = Trainer(cfg, mesh=mesh)
state = trainer.init_state()
state, m = trainer.train_step(state, next(trainer.data_iter()))
print("loss", float(m["loss"]))
