"""Jupyter web app backend: the notebook spawner REST API.

Mirrors jupyter-web-app/backend (SURVEY.md §2.3):
- GETs for namespaces / notebooks / PVCs / PodDefaults / storageclasses /
  events (common/base_app.py:23-131),
- POST notebook: form -> Notebook CR from a template
  (default/app.py:13, common/yaml/notebook.yaml:1-25),
- POST pvc (:140), DELETE notebook (:164), health probes (:170-175).

The GPU swap point: where the reference inserts `nvidia.com/gpu` /
`amd.com/gpu` limits from the form (common/utils.py:262-277), this
backend inserts `google.com/tpu` chips plus the GKE accelerator/topology
node selectors.
"""

from __future__ import annotations

import logging

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.notebook import types as NT
from kubeflow_tpu.utils import httpd
from kubeflow_tpu.utils.httpd import ApiHttpError, HttpReq, Router

log = logging.getLogger("kubeflow_tpu.jwa")

USER_HEADER = "kubeflow-userid"

# spawner_ui_config.yaml analogue: what the form offers
DEFAULT_CONFIG = {
    "image": {
        "value": "kubeflow-tpu/jax-notebook:latest",
        "options": [
            "kubeflow-tpu/jax-notebook:latest",
            "kubeflow-tpu/jax-notebook-tpu:latest",
        ],
    },
    "cpu": {"value": "0.5"},
    "memory": {"value": "1Gi"},
    "tpu": {
        "value": 0,
        "options": [0, 1, 4, 8],
        "accelerators": ["tpu-v5-lite-podslice", "tpu-v4-podslice"],
    },
    "workspaceVolume": {"value": {"size": "10Gi", "mountPath": NT.HOME_DIR}},
}


def process_tpu(container: dict, pod_spec: dict, form: dict) -> None:
    """utils.py:262-277 equivalent: insert accelerator resources from the
    form — google.com/tpu instead of nvidia.com/gpu."""
    tpu = form.get("tpu") or 0
    if isinstance(tpu, dict):
        chips = int(tpu.get("count", 0) or 0)
    else:
        chips, tpu = int(tpu), {}
    if not chips:
        return
    limits = container.setdefault("resources", {}).setdefault("limits", {})
    limits[NT.RESOURCE_TPU] = chips
    accel = tpu.get("accelerator")
    if accel:
        sel = pod_spec.setdefault("nodeSelector", {})
        sel["cloud.google.com/gke-tpu-accelerator"] = accel
        if tpu.get("topology"):
            sel["cloud.google.com/gke-tpu-topology"] = tpu["topology"]


# server-side name validation (found by the jsdom UI harness: the spawner
# accepted 'Invalid Name!'): the browser form is advisory; a real
# apiserver rejects non-RFC1123 metadata.name opaquely, so 400 up front
from kubeflow_tpu.utils.names import require_dns1123 as _require_dns1123


def notebook_from_form(namespace: str, form: dict,
                       config: dict | None = None) -> dict:
    """The yaml template + form fill (notebook.yaml:1-25 + app.py:13)."""
    name = form.get("name")
    if not name:
        raise ApiHttpError(400, "notebook form requires 'name'")
    cfg = config or DEFAULT_CONFIG
    nb = NT.new_notebook(
        name, namespace,
        image=form.get("image", cfg["image"]["value"]),
        cpu=str(form.get("cpu", cfg["cpu"]["value"])),
        memory=form.get("memory", cfg["memory"]["value"]),
    )
    pod_spec = nb["spec"]["template"]["spec"]
    container = pod_spec["containers"][0]
    process_tpu(container, pod_spec, form)
    ws = form.get("workspaceVolume")
    if ws:
        claim = ws.get("name", f"workspace-{name}")
        container["volumeMounts"] = [
            {"name": "workspace", "mountPath": ws.get("mountPath", NT.HOME_DIR)}]
        pod_spec["volumes"] = [
            {"name": "workspace", "persistentVolumeClaim": {"claimName": claim}}]
    # Form labels go on the CR *and* the pod template: PodDefault
    # "configurations" match pod labels (filter_poddefaults), so a label
    # only on the Notebook metadata would make the feature a silent no-op.
    pod_labels = (nb["spec"]["template"].setdefault("metadata", {})
                  .setdefault("labels", {}))
    for k, v in (form.get("labels") or {}).items():
        ob.set_label(nb, k, v)
        pod_labels[k] = v
    return nb


def notebook_status(nb: dict, events: list[dict]) -> dict:
    """The row JWA's UI renders (status + last event message)."""
    m = ob.meta(nb)
    ready = bool((nb.get("status") or {}).get("readyReplicas"))
    stopped = NT.STOP_ANNOTATION in ob.annotations_of(nb)
    phase = "stopped" if stopped else ("ready" if ready else "waiting")
    own = [e for e in events
           if (e.get("involvedObject") or {}).get("uid") == m.get("uid")]
    return {
        "name": m["name"],
        "namespace": m["namespace"],
        "image": nb["spec"]["template"]["spec"]["containers"][0].get("image"),
        "status": {"phase": phase, "ready": ready},
        "events": [{"reason": e.get("reason"), "message": e.get("message"),
                    "type": e.get("type")} for e in own[-5:]],
    }


def load_spawner_config(path: str | None = None) -> dict:
    """Admin-editable spawner options (spawner_ui_config.yaml contract:
    the form's defaults/options come from a YAML file the platform
    mounts, jupyter-web-app/backend main.py). `path` or $JWA_CONFIG
    points at the YAML; keys deep-merge over the built-in default so a
    config can override just one field."""
    import os

    path = path or os.environ.get("JWA_CONFIG")
    if not path:
        return DEFAULT_CONFIG
    import copy

    import yaml

    with open(path) as f:
        loaded = yaml.safe_load(f) or {}
    # spawner_ui_config.yaml nests under spawnerFormDefaults
    loaded = loaded.get("spawnerFormDefaults", loaded)

    def merge(base, over):
        out = copy.deepcopy(base)
        for k, v in over.items():
            out[k] = merge(out[k], v) if (
                isinstance(v, dict) and isinstance(out.get(k), dict)) else v
        return out

    return merge(DEFAULT_CONFIG, loaded)


class JupyterWebApp:
    def __init__(self, client, config: dict | None = None,
                 flavor: str | None = None):
        from kubeflow_tpu.webapps.jwa_flavors import (
            SnapshotFlavor, select_flavor)

        self.client = client
        self.config = config if config is not None else load_spawner_config()
        # UI-flavor dispatch (reference main.py:12-29 UI=default|rok);
        # the TPU build's non-default flavor is object-store snapshots.
        # Explicit args validate through the same gate as $UI: an unknown
        # flavor fails loudly, never silently degrades to default.
        self.flavor_name = select_flavor(
            {"UI": flavor} if flavor is not None else None)
        self.flavor = (SnapshotFlavor(self)
                       if self.flavor_name == "snapshot" else None)

    def _user(self, req: HttpReq) -> str:
        return req.header(USER_HEADER, "anonymous@kubeflow.org")

    # -- GET surfaces -------------------------------------------------------

    def get_config(self, req: HttpReq):
        return {"config": self.config}

    def get_namespaces(self, req: HttpReq):
        return {"namespaces": [
            ob.meta(ns)["name"] for ns in self.client.list("v1", "Namespace")]}

    def get_notebooks(self, req: HttpReq):
        ns = req.params["ns"]
        events = self.client.list("v1", "Event", namespace=ns)
        return {"notebooks": [
            notebook_status(nb, events)
            for nb in self.client.list(NT.API_VERSION, NT.KIND, namespace=ns)]}

    def get_pvcs(self, req: HttpReq):
        ns = req.params["ns"]
        return {"pvcs": [
            {"name": ob.meta(p)["name"],
             "size": ((p.get("spec") or {}).get("resources") or {})
             .get("requests", {}).get("storage"),
             "mode": ((p.get("spec") or {}).get("accessModes") or [""])[0]}
            for p in self.client.list("v1", "PersistentVolumeClaim", namespace=ns)]}

    def get_poddefaults(self, req: HttpReq):
        ns = req.params["ns"]
        items = self.client.list("kubeflow.org/v1alpha1", "PodDefault", namespace=ns)
        return {"poddefaults": [
            {"name": ob.meta(p)["name"],
             "desc": (p.get("spec") or {}).get("desc", ob.meta(p)["name"]),
             # the labels a pod needs to match this PodDefault's selector —
             # the spawner's "configurations" control applies them
             "matchLabels": (((p.get("spec") or {}).get("selector") or {})
                             .get("matchLabels") or {})}
            for p in items]}

    def get_storageclasses(self, req: HttpReq):
        return {"storageclasses": [
            ob.meta(s)["name"]
            for s in self.client.list("storage.k8s.io/v1", "StorageClass")]}

    def get_events(self, req: HttpReq):
        ns, name = req.params["ns"], req.params["name"]
        nb = self.client.get_or_none(NT.API_VERSION, NT.KIND, name, ns)
        if nb is None:
            raise ApiHttpError(404, f"notebook {name} not found")
        uid = ob.meta(nb).get("uid")
        evs = [e for e in self.client.list("v1", "Event", namespace=ns)
               if (e.get("involvedObject") or {}).get("uid") == uid]
        return {"events": evs}

    # -- mutations ----------------------------------------------------------

    def post_notebook(self, req: HttpReq):
        ns = req.params["ns"]
        form = req.json() or {}
        _require_dns1123(form.get("name", ""))
        nb = notebook_from_form(ns, form, self.config)
        if self.flavor is not None:  # flavor POST override (rok/app.py:56)
            nb = self.flavor.mutate_notebook(nb, form)
        try:
            self.client.create(nb)
        except ob.Conflict:
            raise ApiHttpError(409, f"notebook {ob.meta(nb)['name']} exists")
        log.info("user %s created notebook %s/%s", self._user(req), ns,
                 ob.meta(nb)["name"])
        return 200, {"status": "ok", "name": ob.meta(nb)["name"]}

    def post_pvc(self, req: HttpReq):
        ns = req.params["ns"]
        form = req.json() or {}
        _require_dns1123(form.get("name", "workspace"))
        pvc = ob.new_object(
            "v1", "PersistentVolumeClaim", form.get("name", "workspace"), ns,
            spec={
                "accessModes": [form.get("mode", "ReadWriteOnce")],
                "resources": {"requests": {"storage": form.get("size", "10Gi")}},
                **({"storageClassName": form["class"]} if form.get("class") else {}),
            },
        )
        try:
            self.client.create(pvc)
        except ob.Conflict:
            raise ApiHttpError(409, f"pvc {ob.meta(pvc)['name']} exists")
        return 200, {"status": "ok"}

    def delete_notebook(self, req: HttpReq):
        ns, name = req.params["ns"], req.params["name"]
        try:
            self.client.delete(NT.API_VERSION, NT.KIND, name, ns)
        except ob.NotFound:
            raise ApiHttpError(404, f"notebook {name} not found")
        return 200, {"status": "ok"}

    def patch_notebook(self, req: HttpReq):
        """start/stop (the stop-annotation toggle the culler honors)."""
        ns, name = req.params["ns"], req.params["name"]
        body = req.json() or {}
        nb = self.client.get_or_none(NT.API_VERSION, NT.KIND, name, ns)
        if nb is None:
            raise ApiHttpError(404, f"notebook {name} not found")
        if body.get("stopped"):
            ob.set_annotation(nb, NT.STOP_ANNOTATION, ob.now_iso())
        else:
            ob.annotations_of(nb).pop(NT.STOP_ANNOTATION, None)
        self.client.update(nb)
        return 200, {"status": "ok"}

    # -- wiring -------------------------------------------------------------

    def router(self) -> Router:
        r = Router("jwa")
        r.route("GET", "/api/config", self.get_config)
        r.route("GET", "/api/namespaces", self.get_namespaces)
        r.route("GET", "/api/namespaces/{ns}/notebooks", self.get_notebooks)
        r.route("POST", "/api/namespaces/{ns}/notebooks", self.post_notebook)
        r.route("GET", "/api/namespaces/{ns}/notebooks/{name}/events", self.get_events)
        r.route("PATCH", "/api/namespaces/{ns}/notebooks/{name}", self.patch_notebook)
        r.route("DELETE", "/api/namespaces/{ns}/notebooks/{name}", self.delete_notebook)
        r.route("GET", "/api/namespaces/{ns}/pvcs", self.get_pvcs)
        r.route("POST", "/api/namespaces/{ns}/pvcs", self.post_pvc)
        r.route("GET", "/api/namespaces/{ns}/poddefaults", self.get_poddefaults)
        r.route("GET", "/api/storageclasses", self.get_storageclasses)
        # browser spawner UI (the JWA frontend equivalent, webapps/jwa_ui.py)
        from kubeflow_tpu.webapps.jwa_ui import add_ui_routes

        add_ui_routes(r)
        if self.flavor is not None:
            self.flavor.add_routes(r)
        httpd.add_health_routes(r)
        httpd.add_metrics_route(r)
        return r

    def serve(self, host: str = "0.0.0.0", port: int = 5000) -> httpd.HttpService:
        return httpd.HttpService(self.router(), host, port)
