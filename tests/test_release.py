"""Release tooling tests: image inventory, command rendering, and the
build->push->manifest DAG run hermetically with a recording runner."""

import json
import os

from kubeflow_tpu.release import IMAGES, ImageSpec, build_commands, release_workflow
from kubeflow_tpu.release.releaser import image_ref, push_commands


def test_image_inventory_files_exist():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for spec in IMAGES:
        assert os.path.exists(os.path.join(repo, spec.context, spec.dockerfile)), spec


def test_build_command_rendering():
    spec = ImageSpec("jax-notebook-tpu", ".", "images/notebook/Dockerfile",
                     (("JAX_EXTRA", "tpu"),))
    [cmd] = build_commands(spec, "gcr.io/kf-tpu", "v1")
    assert cmd[:4] == ["docker", "build", "-t", "gcr.io/kf-tpu/jax-notebook-tpu:v1"]
    assert "--build-arg" in cmd and "JAX_EXTRA=tpu" in cmd
    assert cmd[-1] == "."
    [push] = push_commands(spec, "gcr.io/kf-tpu", "v1")
    assert push == ["docker", "push", "gcr.io/kf-tpu/jax-notebook-tpu:v1"]


def test_release_workflow_dag(tmp_path):
    ran = []
    wf = release_workflow("reg.local/kf", "v0", runner=ran.append,
                          artifacts_dir=str(tmp_path))
    res = wf.run()
    assert res.succeeded, {k: s.error for k, s in res.steps.items()}
    builds = [c for c in ran if c[1] == "build"]
    pushes = [c for c in ran if c[1] == "push"]
    assert len(builds) == len(IMAGES) and len(pushes) == len(IMAGES)
    # every push happens after its build (ran list is append-ordered)
    for spec in IMAGES:
        ref = image_ref(spec, "reg.local/kf", "v0")
        b = next(i for i, c in enumerate(ran) if c[1] == "build" and ref in c)
        p = next(i for i, c in enumerate(ran) if c[1] == "push" and ref in c)
        assert b < p
    manifest = json.load(open(tmp_path / "release-v0.json"))
    assert len(manifest["images"]) == len(IMAGES)


def test_release_workflow_build_failure_skips_push(tmp_path):
    def runner(cmd):
        if cmd[1] == "build" and "jaxrt" in cmd[3]:
            raise RuntimeError("build broke")

    wf = release_workflow("reg.local/kf", "v0", runner=runner,
                          artifacts_dir=str(tmp_path))
    res = wf.run()
    assert not res.succeeded
    assert res.steps["build-jaxrt"].status == "Failed"
    assert res.steps["push-jaxrt"].status == "Skipped"
    assert res.steps["release-manifest"].status == "Skipped"
    assert res.steps["push-platform"].status == "Succeeded"
