"""YAML loading shim.

Wraps PyYAML's safe loader (present in the baked image). Kept behind one
module so every config consumer (launcher, tpctl, controllers) shares one
entry point and the dependency stays swappable.
"""

from __future__ import annotations

from typing import Any

import yaml


def loads(text: str) -> Any:
    return yaml.safe_load(text)


def load(path: str) -> Any:
    with open(path) as f:
        return yaml.safe_load(f)


def dumps(obj: Any) -> str:
    return yaml.safe_dump(obj, sort_keys=False)


def load_all(text: str) -> list[Any]:
    """Multi-document YAML (kustomize-style manifest bundles)."""
    return [d for d in yaml.safe_load_all(text) if d is not None]
