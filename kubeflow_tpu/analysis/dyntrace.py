"""Dynamic happens-before validator: confirm LOCK201's static lockset
map against what instrumented control-plane classes actually do.

Static analysis says "`Controller._queue` is guarded by `_cv`". This
module checks that claim at runtime, Eraser-style, while the race tier
(tests/test_race.py, under ``TPU_RACE_TRACE=1``) hammers the control
plane in its production threaded mode:

- ``Tracer.instrument(cls)`` wraps the class's ``__setattr__`` to see
  attribute rebinds, registers every ``threading.Lock``/``RLock``/
  ``Condition`` assigned to an instance attribute (that is how a lock
  object gets its *name*), and transparently replaces dict/list values
  with recording proxies so container mutations — the writes the
  control plane actually performs (``self._queue[req] = None``) — are
  observed too.
- A ``sys.setprofile`` / ``threading.setprofile`` hook watches C-level
  ``acquire``/``release``/``__enter__``/``__exit__`` (and Condition's
  ``_release_save``/``_acquire_restore`` around ``wait``) on the
  registered lock objects, maintaining a per-thread held-lock multiset.
- Each write is fed to the per-(instance, attr) Eraser state machine:
  writes stay *exclusive* while a single thread owns the location
  (creation/``__init__`` happens-before publication, no lock needed);
  the first write from a second thread moves it to *shared* and from
  then on the candidate lockset is the intersection of locks held at
  every write.

``divergences(static_map)`` then compares: for every attribute the
static map claims is guarded, a shared (multi-thread-written) location
whose observed lockset misses the claimed lock is a divergence — either
the static map is wrong or the code has a real race the lint's
suppression/fixpoint reasoning papered over. Locations never contended
are vacuously consistent.

Opt-in only: tracing costs a profile hook on every thread; nothing here
activates unless a Tracer is entered.

Known limit: container proxying replaces an assigned dict/list with a
recording *copy*, so instrument only classes that assign fresh
containers (``self._queue = {}``) — mutating a pre-existing alias after
assigning it to an instrumented attribute would bypass both the
instance and the recorder. The control-plane classes this validator
targets follow the fresh-container idiom throughout.
"""

from __future__ import annotations

import sys
import threading
from typing import Iterable

from kubeflow_tpu.analysis.callgraph import Program, module_name_for
from kubeflow_tpu.analysis.core import Module

_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))
_ACQUIRE = {"acquire", "__enter__", "_acquire_restore"}
_RELEASE = {"release", "__exit__", "_release_save"}


def static_guarded_map(paths: Iterable[str]) -> dict[str, dict[str, set[str]]]:
    """LOCK201's guarded-attribute map for the given source files:
    ``{ClassName: {attr: {lock attrs}}}`` — the static half of the
    comparison, built on the same Program the lint rules use."""
    modules: dict[str, Module] = {}
    for p in paths:
        with open(p, "r", encoding="utf-8") as fh:
            modules[module_name_for(p)] = Module(str(p), fh.read())
    program = Program(modules)
    out: dict[str, dict[str, set[str]]] = {}
    for cqual, per in program.guarded_map().items():
        name = cqual.split(":")[-1]
        out.setdefault(name, {}).update(
            {attr: set(locks) for attr, (_p, _l, locks) in per.items()})
    return out


class _TracedLock:
    """Delegating Lock/RLock proxy. CPython's ``with`` statement invokes
    C-level ``__enter__`` without emitting a ``c_call`` profile event
    (only ``__exit__`` is visible), so bare locks are proxied with
    Python-level enter/exit that record directly; Condition objects need
    no proxy because their Python-level methods call the inner RLock's C
    methods through normal CALLs, which the profile hook does see."""

    def __init__(self, inner, tracer: "Tracer"):
        self._kftr_inner = inner
        self._kftr_tracer = tracer

    def acquire(self, *a, **kw):
        got = self._kftr_inner.acquire(*a, **kw)
        if got:
            self._kftr_tracer._bump(id(self), +1)
        return got

    def release(self):
        self._kftr_tracer._bump(id(self), -1)
        self._kftr_inner.release()

    def __enter__(self):
        self._kftr_inner.acquire()
        self._kftr_tracer._bump(id(self), +1)
        return self

    def __exit__(self, *exc):
        self._kftr_tracer._bump(id(self), -1)
        self._kftr_inner.release()
        return False

    def locked(self):
        return self._kftr_inner.locked()

    def __getattr__(self, name):
        return getattr(self._kftr_inner, name)


class _AttrState:
    """Eraser state machine for one (instance, attr) location."""

    __slots__ = ("owner_thread", "shared", "lockset", "writes")

    def __init__(self, thread_id: int):
        self.owner_thread = thread_id
        self.shared = False
        self.lockset: frozenset | None = None  # None = top (unrefined)
        self.writes = 0

    def record(self, thread_id: int, held: frozenset) -> None:
        self.writes += 1
        if not self.shared:
            if thread_id == self.owner_thread:
                return  # exclusive: creation happens-before publication
            self.shared = True
        self.lockset = held if self.lockset is None else self.lockset & held


class _TracedDict(dict):
    """dict recording every mutation against its owning (class, attr)."""

    def _note(self):
        self._kftr_tracer._record(self._kftr_cls, self._kftr_owner,
                                  self._kftr_attr)

    def __setitem__(self, k, v):
        self._note()
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._note()
        super().__delitem__(k)

    def update(self, *a, **kw):
        self._note()
        super().update(*a, **kw)

    def pop(self, *a):
        self._note()
        return super().pop(*a)

    def popitem(self):
        self._note()
        return super().popitem()

    def clear(self):
        self._note()
        super().clear()

    def setdefault(self, *a):
        self._note()
        return super().setdefault(*a)


class _TracedList(list):
    def _note(self):
        self._kftr_tracer._record(self._kftr_cls, self._kftr_owner,
                                  self._kftr_attr)

    def append(self, x):
        self._note()
        super().append(x)

    def extend(self, it):
        self._note()
        super().extend(it)

    def insert(self, i, x):
        self._note()
        super().insert(i, x)

    def remove(self, x):
        self._note()
        super().remove(x)

    def pop(self, *a):
        self._note()
        return super().pop(*a)

    def clear(self):
        self._note()
        super().clear()

    def __setitem__(self, i, v):
        self._note()
        super().__setitem__(i, v)

    def __delitem__(self, i):
        self._note()
        super().__delitem__(i)

    def __iadd__(self, other):
        self._note()
        return super().__iadd__(other)

    def sort(self, **kw):
        self._note()
        super().sort(**kw)

    def reverse(self):
        self._note()
        super().reverse()


class Tracer:
    """Record lock acquire/release and attribute writes on instrumented
    classes; compare the observed locksets with the static map."""

    def __init__(self):
        self._locks: dict[int, tuple[str, str]] = {}   # id -> (cls, attr)
        self._states: dict[tuple[int, str, str], _AttrState] = {}
        self._tls = threading.local()
        self._saved_setattr: list[tuple[type, object | None]] = []
        self._mu = threading.Lock()
        self._prev_profile = None
        self._active = False

    # -- instrumentation -----------------------------------------------------

    def instrument(self, cls: type) -> None:
        """Wrap cls.__setattr__ to observe rebinds, discover locks, and
        proxy container values. Idempotent per Tracer."""
        if any(c is cls for c, _ in self._saved_setattr):
            return
        own = cls.__dict__.get("__setattr__")
        self._saved_setattr.append((cls, own))
        orig = cls.__setattr__
        tracer = self

        def traced_setattr(obj, name, value):
            value = tracer._on_setattr(cls, obj, name, value)
            orig(obj, name, value)

        cls.__setattr__ = traced_setattr

    def uninstrument_all(self) -> None:
        for cls, own in self._saved_setattr:
            if own is None:
                try:
                    del cls.__setattr__
                except AttributeError:
                    pass
            else:
                cls.__setattr__ = own
        self._saved_setattr.clear()

    def _on_setattr(self, cls: type, obj, name: str, value):
        if isinstance(value, _LOCK_TYPES):
            proxy = _TracedLock(value, self)
            self._locks[id(proxy)] = (cls.__name__, name)
            return proxy
        if isinstance(value, threading.Condition):
            self._locks[id(value._lock)] = (cls.__name__, name)
            self._locks[id(value)] = (cls.__name__, name)
            return value
        if isinstance(value, (threading.Event, _TracedLock)):
            return value  # Event.set() is internally synchronized
        self._record(cls, obj, name)
        if type(value) is dict:
            value = self._proxy(_TracedDict(value), cls, obj, name)
        elif type(value) is list:
            value = self._proxy(_TracedList(value), cls, obj, name)
        return value

    def _proxy(self, proxied, cls: type, obj, name: str):
        # plain attributes (not slots): proxies carry their identity
        proxied._kftr_tracer = self
        proxied._kftr_cls = cls
        proxied._kftr_owner = obj
        proxied._kftr_attr = name
        return proxied

    # -- the write stream ----------------------------------------------------

    def _bump(self, key: int, delta: int) -> None:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = {}
        held[key] = max(held.get(key, 0) + delta, 0)

    def _held_tokens(self) -> frozenset:
        held = getattr(self._tls, "held", None)
        if not held:
            return frozenset()
        return frozenset(self._locks[k] for k, n in held.items()
                         if n > 0 and k in self._locks)

    def _record(self, cls: type, obj, attr: str) -> None:
        if not self._active:
            return
        key = (id(obj), cls.__name__, attr)
        tid = threading.get_ident()
        held = self._held_tokens()
        # locks of *this* class guard its attrs; a foreign lock held by
        # coincidence must not count as protection
        held_here = frozenset(a for (c, a) in held if c == cls.__name__)
        with self._mu:
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = _AttrState(tid)
            st.record(tid, held_here)

    # -- lock event stream (sys.setprofile) ----------------------------------

    def _profile(self, frame, event, arg):
        if event not in ("c_call", "c_return"):
            return
        try:
            sobj = getattr(arg, "__self__", None)
            if sobj is None or id(sobj) not in self._locks:
                return
            name = getattr(arg, "__name__", "")
            held = getattr(self._tls, "held", None)
            if held is None:
                held = self._tls.held = {}
            key = id(sobj)
            if name in _ACQUIRE and event == "c_return":
                if name == "_acquire_restore":
                    held[key] = getattr(self._tls, "saved", {}).pop(key, 1)
                else:
                    held[key] = held.get(key, 0) + 1
            elif name in _RELEASE and event == "c_call":
                if name == "_release_save":
                    saved = getattr(self._tls, "saved", None)
                    if saved is None:
                        saved = self._tls.saved = {}
                    saved[key] = held.get(key, 0)
                    held[key] = 0
                else:
                    held[key] = max(held.get(key, 0) - 1, 0)
        except Exception:  # a raising profile hook silently uninstalls
            pass

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self) -> "Tracer":
        self._prev_profile = sys.getprofile()
        self._prev_thread_profile = threading.getprofile()
        self._active = True
        threading.setprofile(self._profile)  # new threads
        sys.setprofile(self._profile)        # this thread
        return self

    def __exit__(self, *exc) -> None:
        self._active = False
        sys.setprofile(self._prev_profile)
        threading.setprofile(self._prev_thread_profile)
        self.uninstrument_all()

    # -- results -------------------------------------------------------------

    def observed(self) -> dict[tuple[str, str], dict]:
        """Aggregate per (class, attr): shared?, final lockset (the
        intersection across all shared instances), write count."""
        out: dict[tuple[str, str], dict] = {}
        with self._mu:
            states = dict(self._states)
        for (_oid, cls, attr), st in states.items():
            agg = out.setdefault((cls, attr), {
                "shared": False, "lockset": None, "writes": 0})
            agg["writes"] += st.writes
            if st.shared:
                agg["shared"] = True
                ls = st.lockset if st.lockset is not None else frozenset()
                agg["lockset"] = (ls if agg["lockset"] is None
                                  else agg["lockset"] & ls)
        return out

    def divergences(self, static_map: dict[str, dict[str, set[str]]]
                    ) -> list[str]:
        """Statically-guarded attrs whose observed (shared) lockset does
        not contain the claimed lock. Empty = static and dynamic agree."""
        out = []
        for (cls, attr), rec in sorted(self.observed().items()):
            want = static_map.get(cls, {}).get(attr)
            if not want or not rec["shared"]:
                continue
            got = set(rec["lockset"] or frozenset())
            if not (want & got):
                out.append(
                    f"{cls}.{attr}: static map says guarded by "
                    f"{sorted(want)}, but {rec['writes']} observed writes "
                    f"hold only {sorted(got)}")
        return out

    def confirmed(self, static_map: dict[str, dict[str, set[str]]]
                  ) -> list[str]:
        """Statically-guarded attrs the dynamic run actually contended
        and confirmed — the positive half of the cross-check."""
        out = []
        for (cls, attr), rec in sorted(self.observed().items()):
            want = static_map.get(cls, {}).get(attr)
            if not want or not rec["shared"]:
                continue
            got = set(rec["lockset"] or frozenset())
            if want & got:
                out.append(f"{cls}.{attr}")
        return out
