"""Coordinator: Apply(PLATFORM) -> Apply(K8S) with retry + conditions.

Mirrors kfctlServer.handleDeployment (kfctlServer.go:105-327): write the
config, apply the platform (cloud infra), build cluster credentials, then
apply K8S manifests with x3 constant backoff (:290-294), appending
KfAvailable/KfDegraded status conditions (:320-327). Second apply is a
no-op on an unchanged config (kfctl_second_apply.py contract).

Platform providers are pluggable; `existing` targets a cluster that is
already up (the common GKE TPU case — node pools carry the TPU chips),
`gke-tpu` shells out to gcloud to create TPU node pools and is exercised
only when gcloud is available.
"""

from __future__ import annotations

import logging
import subprocess
import time

import prometheus_client as prom

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.tpctl import manifests
from kubeflow_tpu.tpctl.tpudef import COND_AVAILABLE, COND_DEGRADED, TpuDef

log = logging.getLogger("kubeflow_tpu.tpctl")

_METRICS: dict[str, object] = {}


def _metric(name, kind, doc, **kw):
    # deploy metrics of bootstrap/cmd/bootstrap/app/server.go:68-132
    if name not in _METRICS:
        _METRICS[name] = kind(name, doc, **kw)
    return _METRICS[name]


def deploy_requests():
    return _metric("tpctl_deploy_requests_total", prom.Counter, "deploy requests")


def deploy_failures():
    return _metric("tpctl_deployments_failure_total", prom.Counter, "failed deploys")


def deploy_duration():
    return _metric(
        "tpctl_dep_duration_seconds", prom.Histogram, "deployment wall time",
        buckets=tuple(30 * i for i in range(1, 16)),  # 30s linear x15 (:112)
    )


class PlatformProvider:
    def apply(self, cfg: TpuDef) -> None: ...

    def delete(self, cfg: TpuDef) -> None: ...


class ExistingCluster(PlatformProvider):
    def apply(self, cfg: TpuDef) -> None:
        log.info("platform=existing: nothing to provision")

    def delete(self, cfg: TpuDef) -> None:
        pass


class GkeTpuPlatform(PlatformProvider):
    """TPU node-pool provisioning via gcloud (the DM/kfctl-gcp analogue).
    Command construction is testable; execution requires gcloud."""

    def __init__(self, runner=subprocess.run):
        self.runner = runner

    def commands(self, cfg: TpuDef) -> list[list[str]]:
        return [[
            "gcloud", "container", "node-pools", "create", f"{cfg.name}-tpu",
            f"--project={cfg.project}", f"--zone={cfg.zone}",
            f"--cluster={cfg.name}",
            f"--machine-type=ct5lp-hightpu-4t",
            "--num-nodes=1",
            f"--node-labels=cloud.google.com/gke-tpu-accelerator={cfg.accelerator},"
            f"cloud.google.com/gke-tpu-topology={cfg.topology}",
        ]]

    def apply(self, cfg: TpuDef) -> None:
        for cmd in self.commands(cfg):
            log.info("platform exec: %s", " ".join(cmd))
            self.runner(cmd, check=True)

    def delete(self, cfg: TpuDef) -> None:
        self.runner([
            "gcloud", "container", "node-pools", "delete", f"{cfg.name}-tpu",
            f"--project={cfg.project}", f"--zone={cfg.zone}",
            f"--cluster={cfg.name}", "--quiet",
        ], check=True)


PROVIDERS = {"existing": ExistingCluster, "gke-tpu": GkeTpuPlatform}


class Coordinator:
    K8S_RETRIES = 3  # kfctlServer.go:290-294

    def __init__(self, client, provider: PlatformProvider | None = None):
        self.client = client
        self.provider = provider

    def _provider_for(self, cfg: TpuDef) -> PlatformProvider:
        if self.provider is not None:
            return self.provider
        cls = PROVIDERS.get(cfg.platform)
        if cls is None:
            raise ValueError(f"unknown platform {cfg.platform!r}; "
                             f"valid: {sorted(PROVIDERS)}")
        return cls()

    def apply(self, cfg: TpuDef) -> dict:
        """Full deployment; returns the stored TpuDef object with
        conditions. Idempotent: identical spec re-applies cleanly."""
        deploy_requests().inc()
        t0 = time.monotonic()
        stored = self._store_tpudef(cfg)
        try:
            self._provider_for(cfg).apply(cfg)
            self._apply_k8s(cfg)
        except Exception as e:
            deploy_failures().inc()
            ob.cond_set(stored, COND_DEGRADED, "True", "ApplyFailed", str(e)[:500])
            self._update_status(stored)
            raise
        deploy_duration().observe(time.monotonic() - t0)
        ob.cond_set(stored, COND_AVAILABLE, "True", "ApplySucceeded",
                    f"{len(cfg.applications)} applications applied")
        ob.cond_set(stored, COND_DEGRADED, "False", "ApplySucceeded", "")
        return self._update_status(stored)

    def _store_tpudef(self, cfg: TpuDef) -> dict:
        obj = cfg.to_object()
        existing = self.client.get_or_none(obj["apiVersion"], obj["kind"],
                                           ob.meta(obj)["name"])
        if existing is None:
            return self.client.create(obj)
        if existing.get("spec") != obj.get("spec"):
            existing["spec"] = obj["spec"]
            return self.client.update(existing)
        return existing

    def _update_status(self, obj: dict) -> dict:
        fresh = self.client.get(obj["apiVersion"], obj["kind"], ob.meta(obj)["name"])
        fresh["status"] = obj.get("status", {})
        return self.client.update_status(fresh)

    def _apply_k8s(self, cfg: TpuDef) -> None:
        objs = manifests.render(cfg)
        last_err: Exception | None = None
        for attempt in range(self.K8S_RETRIES):
            try:
                for o in objs:
                    self._apply_one(o)
                return
            except ob.ApiError as e:
                last_err = e
                log.warning("k8s apply attempt %d failed: %s", attempt + 1, e)
                time.sleep(0.01 * (attempt + 1))
        raise last_err  # type: ignore[misc]

    def _apply_one(self, desired: dict) -> None:
        """Server-side-apply-ish create-or-update keyed on spec equality."""
        m = ob.meta(desired)
        found = self.client.get_or_none(
            desired["apiVersion"], desired["kind"], m["name"], m.get("namespace"))
        if found is None:
            self.client.create(desired)
            return
        merged = ob.merge_patch(found, {k: v for k, v in desired.items()
                                        if k not in ("metadata", "status")})
        # labels are additive, like the reconcilehelper policy
        want_labels = {**(ob.labels_of(found)), **(ob.labels_of(desired))}
        if merged != found or want_labels != ob.labels_of(found):
            ob.meta(merged).setdefault("labels", {}).update(want_labels)
            self.client.update(merged)

    def delete(self, cfg: TpuDef) -> None:
        """Teardown: platform resources + the TpuDef (children GC)."""
        self._provider_for(cfg).delete(cfg)
        for o in reversed(manifests.render(cfg)):
            m = ob.meta(o)
            try:
                self.client.delete(o["apiVersion"], o["kind"], m["name"],
                                   m.get("namespace"))
            except ob.NotFound:
                pass
        try:
            self.client.delete(API_VERSION_KIND[0], API_VERSION_KIND[1], cfg.name)
        except ob.NotFound:
            pass

    def status(self, name: str) -> dict | None:
        return self.client.get_or_none(API_VERSION_KIND[0], API_VERSION_KIND[1], name)


from kubeflow_tpu.tpctl.tpudef import API_VERSION as _AV, KIND as _K  # noqa: E402

API_VERSION_KIND = (_AV, _K)
