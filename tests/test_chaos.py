"""Chaos tier: seeded fault injection from apiserver to checkpoint.

Three layers of coverage:

1. The chaos engine itself (``control/k8s/chaos.py``): deterministic
   replay, pass-through at rate 0, verb/kind targeting, watch drops
   through the resume and 410-relist paths, cluster primitives.
2. The hardening this PR adds, pinned in isolation: RestClient's
   retry/backoff schedule against a scripted fake session, the
   controller runtime's jittered conflict delay, the scheduler's
   node-death eviction through the JAXJob gang-restart path, lease
   retention across transient renew errors, PreemptionNotice handler
   hygiene, and corruption-tolerant checkpoint resume.
3. Convergence under chaos: the EXISTING jaxjob-controller and
   scheduler happy-path suites re-run with faults armed across
   CHAOS_SEEDS (same assertions, faults on), plus the full-platform
   soak (jaxjob controller + gang scheduler + fake kubelet + leased
   standby replica) marked slow.

Knobs (tests/conftest.py): TPU_CHAOS_RATE, TPU_CHAOS_SEED.
"""

import json
import os
import random
import signal

import pytest

import test_jaxjob_controller as J
import test_scheduler as S
from conftest import CHAOS_RATE, CHAOS_SEEDS

from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.jaxjob.controller import build_controller, worker_name
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.chaos import (
    ChaosClient, ChaosPolicy, arm_controller,
)
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.k8s.kubelet import FakeKubelet
from kubeflow_tpu.control.k8s.rest import RestClient
from kubeflow_tpu.control.leases import LeaderElector
from kubeflow_tpu.control.runtime import (
    Controller, Reconciler, Request, seed_controller,
)
from kubeflow_tpu.control.scheduler.nodes import eviction_status, new_tpu_node
from kubeflow_tpu.control.scheduler.scheduler import build_scheduler
from kubeflow_tpu.obs import trace as tr
from kubeflow_tpu.obs.events import EventRecorder
from kubeflow_tpu.runtime.metrics import MetricsRegistry
from kubeflow_tpu.runtime.preemption import PreemptionNotice

pytestmark = pytest.mark.chaos


def _cm(name, ns="default"):
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": ns}}


def _policy(seed, **over):
    base = dict(seed=seed, rate=CHAOS_RATE, watch_drop_every=25)
    base.update(over)
    return ChaosPolicy(**base)


# -- the chaos engine --------------------------------------------------------


class TestChaosClient:
    def test_rate_zero_is_pass_through(self):
        inner = FakeCluster()
        c = ChaosClient(inner, ChaosPolicy(seed=7, rate=0.0))
        c.create(_cm("a"))
        assert c.get("v1", "ConfigMap", "a", "default")["metadata"]["name"] == "a"
        assert c.list("v1", "ConfigMap") == inner.list("v1", "ConfigMap")
        c.delete("v1", "ConfigMap", "a", "default")
        assert c.fault_log() == []
        # rate 0 + watch_drop_every 0: the very stream the fake returns
        stream = c.watch("v1", "ConfigMap")
        assert hasattr(stream, "poll")
        assert type(stream).__name__ == "FakeWatchStream"

    def test_same_seed_same_faults(self):
        def run(seed):
            c = ChaosClient(FakeCluster(), ChaosPolicy(seed=seed, rate=0.5))
            for i in range(60):
                try:
                    c.create(_cm(f"x{i}"))
                except ob.ApiError:
                    pass
            return c.fault_log()

        assert run(3) == run(3)
        assert run(3) != run(4)
        assert len(run(3)) > 5

    def test_conflicts_only_on_mutating_verbs(self):
        c = ChaosClient(FakeCluster(),
                        ChaosPolicy(seed=1, rate=1.0, error_weight=0.0,
                                    conflict_weight=1.0))
        with pytest.raises(ob.Conflict):
            c.create(_cm("a"))
        # conflict-only policy leaves reads alone entirely
        assert c.list("v1", "ConfigMap") == []
        assert all(f.fault == "conflict" for f in c.fault_log())
        assert {f.verb for f in c.fault_log()} == {"create"}

    def test_server_errors_carry_code_and_retry_after(self):
        c = ChaosClient(FakeCluster(),
                        ChaosPolicy(seed=2, rate=1.0, conflict_weight=0.0,
                                    retry_after=0.25))
        codes = set()
        for i in range(30):
            try:
                c.list("v1", "ConfigMap")
            except ob.ApiError as e:
                codes.add(e.code)
                if e.code in (429, 503):
                    assert e.retry_after == 0.25
        assert codes == {429, 500, 503}

    def test_verb_and_kind_filters(self):
        c = ChaosClient(FakeCluster(),
                        ChaosPolicy(seed=1, rate=1.0,
                                    verbs=frozenset({"update"}),
                                    kinds=frozenset({"Pod"})))
        c.create(_cm("a"))                       # wrong verb: clean
        pod = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "p", "namespace": "default"}}
        c.create(pod)                            # wrong verb: clean
        got = c.get("v1", "ConfigMap", "a", "default")
        c.update(got)                            # wrong kind: clean
        with pytest.raises(ob.ApiError):
            c.update(c.get("v1", "Pod", "p", "default"))
        assert [(f.verb, f.kind) for f in c.fault_log()] == [("update", "Pod")]

    def test_armed_gating(self):
        c = ChaosClient(FakeCluster(), ChaosPolicy(seed=1, rate=1.0),
                        always_on=False)
        c.create(_cm("a"))       # disarmed: clean
        assert c.fault_log() == []
        with c.armed():
            with pytest.raises(ob.ApiError):
                c.create(_cm("b"))
        c.create(_cm("b"))       # disarmed again
        assert len(c.fault_log()) == 1

    def test_latency_injection_uses_sleeper(self):
        slept = []
        c = ChaosClient(FakeCluster(),
                        ChaosPolicy(seed=1, rate=1.0, error_weight=0.0,
                                    conflict_weight=0.0, latency=0.02),
                        sleeper=slept.append)
        c.create(_cm("a"))       # latency fault: delayed, not failed
        assert slept == [0.02]
        assert [f.fault for f in c.fault_log()] == ["latency"]

    def test_events_are_never_faulted(self):
        inner = FakeCluster()
        c = ChaosClient(inner, ChaosPolicy(seed=1, rate=1.0))
        pod = inner.create({"apiVersion": "v1", "kind": "Pod",
                            "metadata": {"name": "p", "namespace": "default"}})
        c.record_event(pod, "Tested", "fire-and-forget stays clean")
        assert len(inner.list("v1", "Event", namespace="default")) == 1

    def test_cluster_primitives(self):
        inner = FakeCluster()
        c = ChaosClient(inner, ChaosPolicy(seed=1, rate=0.0))
        inner.create(new_tpu_node("n0"))
        c.fail_node("n0")
        conds = inner.get("v1", "Node", "n0")["status"]["conditions"]
        assert {"type": "Ready", "status": "False"} in conds
        c.heal_node("n0")
        conds = inner.get("v1", "Node", "n0")["status"]["conditions"]
        assert {"type": "Ready", "status": "True"} in conds
        pod = inner.create({"apiVersion": "v1", "kind": "Pod",
                            "metadata": {"name": "p", "namespace": "default"},
                            "spec": {"nodeName": "n0"}})
        c.evict_pod("p")
        st = inner.get("v1", "Pod", "p", "default")["status"]
        assert (st["phase"], st["reason"]) == ("Failed", "Evicted")
        c.kill_pod("p")
        assert inner.get_or_none("v1", "Pod", "p", "default") is None
        c.kill_pod("p")  # idempotent
        c.delete_node("n0")
        assert inner.get_or_none("v1", "Node", "n0") is None

    def test_backend_surface_passes_through(self):
        inner = FakeCluster()
        c = ChaosClient(inner, ChaosPolicy(seed=1, rate=1.0))
        c.create  # faulted verb, defined on wrapper
        assert c.dump() == []          # FakeCluster-only helper delegates
        assert c.current_rv == inner.current_rv


class TestChaosWatch:
    def test_drop_and_resume_loses_no_object(self):
        inner = FakeCluster()
        c = ChaosClient(inner, ChaosPolicy(seed=3, watch_drop_every=3))
        stream = c.watch("v1", "ConfigMap")
        for i in range(12):
            inner.create(_cm(f"c{i}"))
        seen = set()
        while True:
            ev = stream.poll()
            if ev is None:
                break
            seen.add(ob.meta(ev.object)["name"])
        assert stream.drops >= 1, "policy should have dropped mid-stream"
        assert seen == {f"c{i}" for i in range(12)}

    def test_expired_resume_relists(self):
        # tiny watch cache: the resume point falls out of history, the
        # 410 path fires and the relist re-yields every live object
        inner = FakeCluster(history_limit=4)
        c = ChaosClient(inner, ChaosPolicy(seed=1, watch_drop_every=1))
        stream = c.watch("v1", "ConfigMap")
        inner.create(_cm("c0"))
        inner.create(_cm("c1"))
        first = stream.poll()
        assert first is not None
        for i in range(2, 10):   # push c0/c1's events out of history
            inner.create(_cm(f"c{i}"))
        seen = set()
        while True:
            ev = stream.poll()
            if ev is None:
                break
            seen.add(ob.meta(ev.object)["name"])
        assert stream.drops >= 1
        assert seen == {f"c{i}" for i in range(10)} - {ob.meta(first.object)["name"]} \
            or seen == {f"c{i}" for i in range(10)}

    def test_relist_synthesizes_deleted(self):
        inner = FakeCluster(history_limit=2)
        c = ChaosClient(inner, ChaosPolicy(seed=1, watch_drop_every=1))
        stream = c.watch("v1", "ConfigMap")
        inner.create(_cm("doomed"))
        ev = stream.poll()
        assert ev is not None and ob.meta(ev.object)["name"] == "doomed"
        # the object dies AND its deletion event ages out of the cache
        inner.delete("v1", "ConfigMap", "doomed", "default")
        for i in range(4):
            inner.create(_cm(f"filler{i}"))
        events = []
        while True:
            ev = stream.poll()
            if ev is None:
                break
            events.append((ev.type, ob.meta(ev.object)["name"]))
        assert ("DELETED", "doomed") in events, events
        assert {n for t, n in events if t == "MODIFIED"} >= \
            {f"filler{i}" for i in range(4)}


# -- RestClient retry/backoff (fake session, pinned schedule) ---------------


class _Resp:
    def __init__(self, code, headers=None, body=None):
        self.status_code = code
        self.headers = headers or {}
        doc = body if body is not None else {}
        self.content = json.dumps(doc).encode()
        self.text = self.content.decode()

    def json(self):
        return json.loads(self.content)

    def close(self):
        pass


class _Session:
    """Scripted responses; an Exception entry raises (connection error)."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = []

    def request(self, method, url, timeout=None, **kw):
        self.calls.append(method)
        item = self.script.pop(0)
        if isinstance(item, Exception):
            raise item
        return item


class _FixedRng:
    def uniform(self, a, b):
        return 1.0  # jitter factor pinned to 1x for exact schedule pins


def _rest(script, **kw):
    client = RestClient("http://chaos.invalid", token="t", ca_cert=False,
                        **kw)
    client._s = _Session(script)
    client._rng = _FixedRng()
    sleeps = []
    client._sleep = sleeps.append
    return client, client._s, sleeps


class TestRestClientBackoff:
    def test_refused_statuses_retry_with_exponential_schedule(self):
        client, sess, sleeps = _rest(
            [_Resp(503), _Resp(503), _Resp(200, body={"ok": True})])
        assert client._req("GET", "/api/v1/pods") == {"ok": True}
        assert sess.calls == ["GET"] * 3
        assert sleeps == [0.1, 0.2]  # retry_base * 2^attempt, jitter 1x

    def test_retry_after_raises_the_floor(self):
        client, _, sleeps = _rest(
            [_Resp(429, headers={"Retry-After": "0.7"}), _Resp(200)])
        client._req("GET", "/api/v1/pods")
        assert sleeps == [0.7]

    def test_mutating_verbs_retry_on_explicit_refusal(self):
        # 429/503 mean "not applied": POST retries safely
        client, sess, sleeps = _rest([_Resp(429), _Resp(201, body={})])
        client._req("POST", "/api/v1/pods", json={})
        assert sess.calls == ["POST", "POST"]
        assert sleeps == [0.1]

    def test_post_never_retries_ambiguous_500(self):
        client, sess, sleeps = _rest([_Resp(500)])
        with pytest.raises(ob.ApiError) as ei:
            client._req("POST", "/api/v1/pods", json={})
        assert ei.value.code == 500
        assert sess.calls == ["POST"]
        assert sleeps == []

    def test_get_retries_ambiguous_500(self):
        client, sess, sleeps = _rest([_Resp(500), _Resp(200)])
        client._req("GET", "/api/v1/pods")
        assert sess.calls == ["GET", "GET"]
        assert sleeps == [0.1]

    def test_connection_error_retries_only_replay_safe_verbs(self):
        client, sess, _ = _rest([OSError("conn reset"), _Resp(200)])
        client._req("GET", "/api/v1/pods")
        assert sess.calls == ["GET", "GET"]
        client2, sess2, sleeps2 = _rest([OSError("conn reset")])
        with pytest.raises(OSError):
            client2._req("POST", "/api/v1/pods", json={})
        assert sess2.calls == ["POST"]
        assert sleeps2 == []

    def test_exhaustion_surfaces_the_last_error(self):
        client, sess, sleeps = _rest([_Resp(503)] * 5)  # max_retries=4
        with pytest.raises(ob.ApiError) as ei:
            client._req("GET", "/api/v1/pods")
        assert ei.value.code == 503
        assert sess.calls == ["GET"] * 5
        assert sleeps == [0.1, 0.2, 0.4, 0.8]  # capped schedule, jitter 1x

    def test_cap_bounds_the_schedule(self):
        client, _, sleeps = _rest(
            [_Resp(503)] * 5, retry_base=1.0, retry_cap=2.0)
        with pytest.raises(ob.ApiError):
            client._req("GET", "/api/v1/pods")
        assert sleeps == [1.0, 2.0, 2.0, 2.0]

    def test_status_mapping_unchanged_after_retry_plumbing(self):
        client, _, _ = _rest([_Resp(404, body={"message": "gone"})])
        with pytest.raises(ob.NotFound):
            client._req("GET", "/api/v1/pods/x")
        client, _, _ = _rest([_Resp(409, body={"message": "rv"})])
        with pytest.raises(ob.Conflict):
            client._req("PUT", "/api/v1/pods/x", json={})


# -- controller runtime: conflict delay -------------------------------------


class _ConflictOnce(Reconciler):
    def __init__(self):
        self.calls = 0

    def reconcile(self, client, req):
        self.calls += 1
        if self.calls == 1:
            raise ob.Conflict("injected")
        return None


class TestConflictBackoff:
    def test_conflict_requeues_with_jittered_delay_not_hot_spin(self):
        reg = MetricsRegistry()
        ctl = Controller("t", FakeCluster(), _ConflictOnce(), registry=reg)
        req = Request("ns", "x")
        ctl._process_one(req)
        # the retry went to the DELAYED queue, inside the jitter window
        assert req not in ctl._queue
        assert len(ctl._delayed) == 1
        due, r = ctl._delayed[0]
        assert r == req
        import time as _time
        lo, hi = Controller.CONFLICT_RETRY
        remaining = due - _time.monotonic()
        assert 0.0 < remaining <= hi + 0.001
        assert 'result="conflict"' in reg.render()

    def test_zeroed_window_restores_immediate_retry(self):
        ctl = Controller("t", FakeCluster(), _ConflictOnce(),
                         registry=MetricsRegistry())
        ctl.CONFLICT_RETRY = (0, 0)
        req = Request("ns", "x")
        ctl._process_one(req)
        assert req in ctl._queue
        assert ctl._delayed == []

    def test_drain_completes_the_conflicted_reconcile(self):
        rec = _ConflictOnce()
        ctl = Controller("t", FakeCluster(), rec, registry=MetricsRegistry())
        ctl.enqueue(Request("ns", "x"))
        for _ in range(3):
            ctl.run_until_idle(advance_delayed=True)
        assert rec.calls == 2  # conflict, then the successful retry


# -- scheduler: node death under a bound gang -------------------------------


class TestNodeHealthEviction:
    def _running_world(self):
        fc = S.FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = S.sched_world(fc)
        cluster.create(new_tpu_node("n0"))
        cluster.create(new_tpu_node("n1"))
        cluster.create(S.gang_job("gang", replicas=2))
        S.pump([jax_ctl, sched_ctl], fc, kubelet)
        job = cluster.get(JT.API_VERSION, JT.KIND, "gang", "default")
        assert ob.cond_is_true(job, JT.COND_RUNNING)
        return fc, cluster, jax_ctl, sched_ctl, kubelet, reg

    def test_health_pass_evicts_pods_on_dead_node(self):
        """Scheduler-only view: the node dies and ONLY the scheduler
        runs — its health pass must evict the bound pods through the
        kubelet-eviction shape (preemption, not crash)."""
        fc, cluster, jax_ctl, sched_ctl, kubelet, reg = self._running_world()
        ChaosClient(cluster, ChaosPolicy()).fail_node("n0")
        for _ in range(4):
            sched_ctl.run_until_idle(advance_delayed=True)
        evicted = [p for p in cluster.list("v1", "Pod", namespace="default")
                   if (p.get("status") or {}).get("reason") == "Evicted"]
        assert len(evicted) >= 1
        assert any("NotReady under gang" in p["status"].get("message", "")
                   for p in evicted)
        assert "scheduler_node_evictions_total" in reg.render()

    def test_node_not_ready_gang_restarts_on_preemption_budget(self):
        fc, cluster, jax_ctl, sched_ctl, kubelet, reg = self._running_world()
        chaos = ChaosClient(cluster, ChaosPolicy())
        chaos.fail_node("n0")
        S.pump([jax_ctl, sched_ctl], fc, kubelet)
        job = cluster.get(JT.API_VERSION, JT.KIND, "gang", "default")
        assert job["status"].get("preemptions", 0) >= 1
        assert job["status"].get("restarts", 0) == 0
        assert not ob.cond_is_true(job, JT.COND_FAILED)
        # half the pool is gone: the recreated gang waits in the queue
        assert all(n is None for n in S.bindings(cluster).values())
        chaos.heal_node("n0")
        S.pump([jax_ctl, sched_ctl], fc, kubelet)
        job = cluster.get(JT.API_VERSION, JT.KIND, "gang", "default")
        assert ob.cond_is_true(job, JT.COND_RUNNING)

    def test_node_deleted_gang_restarts_and_requeues(self):
        fc, cluster, jax_ctl, sched_ctl, kubelet, reg = self._running_world()
        cluster.delete("v1", "Node", "n0")
        S.pump([jax_ctl, sched_ctl], fc, kubelet)
        job = cluster.get(JT.API_VERSION, JT.KIND, "gang", "default")
        assert job["status"].get("preemptions", 0) >= 1
        assert job["status"].get("restarts", 0) == 0
        assert all(n is None for n in S.bindings(cluster).values())
        cluster.create(new_tpu_node("n2"))   # replacement capacity
        S.pump([jax_ctl, sched_ctl], fc, kubelet)
        job = cluster.get(JT.API_VERSION, JT.KIND, "gang", "default")
        assert ob.cond_is_true(job, JT.COND_RUNNING)
        assert sorted(S.bindings(cluster).values()) == ["n1", "n2"]


# -- leases: transient-error retention --------------------------------------


class _LeaseClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class TestLeaseRetention:
    def test_transient_renew_error_does_not_flap_leadership(self):
        inner = FakeCluster()
        clock = _LeaseClock()
        faulty = ChaosClient(
            inner, ChaosPolicy(seed=1, rate=1.0, conflict_weight=0.0,
                               kinds=frozenset({"Lease"})),
            always_on=False)
        a = LeaderElector(faulty, "ctl", identity="a", lease_seconds=15.0,
                          clock=clock)
        assert a.try_acquire() is True       # clean bootstrap
        faulty.always_on = True              # apiserver starts erroring
        clock.t += 6                         # past the lease/3 cache
        assert a.try_acquire() is True       # retained: lease still ours
        assert a.is_leader
        clock.t += 10                        # 16s since last REAL renew
        assert a.try_acquire() is False      # guard ends at lease expiry
        faulty.always_on = False             # apiserver healthy again
        assert a.try_acquire() is True       # lease still names us: renew

    def test_standby_takeover_still_works_after_retention_window(self):
        inner = FakeCluster()
        clock = _LeaseClock()
        faulty = ChaosClient(
            inner, ChaosPolicy(seed=2, rate=1.0, conflict_weight=0.0,
                               kinds=frozenset({"Lease"})),
            always_on=False)
        a = LeaderElector(faulty, "ctl", identity="a", lease_seconds=15.0,
                          clock=clock)
        b = LeaderElector(inner, "ctl", identity="b", lease_seconds=15.0,
                          clock=clock)
        assert a.try_acquire()
        faulty.always_on = True              # a can no longer renew
        clock.t += 16                        # lease expires for everyone
        assert b.try_acquire() is True       # healthy standby takes over
        assert a.try_acquire() is False


# -- preemption notice hygiene ----------------------------------------------


class TestPreemptionNoticeHygiene:
    SIG = signal.SIGUSR1

    def test_uninstall_restores_previous_handler(self):
        hits = []

        def prev_handler(sig, frame):
            hits.append(sig)

        old = signal.signal(self.SIG, prev_handler)
        try:
            notice = PreemptionNotice().install(self.SIG)
            assert notice.installed
            # chained: our handler fires AND the previous one still runs
            os.kill(os.getpid(), self.SIG)
            assert notice() and hits == [self.SIG]
            notice.uninstall()
            assert not notice.installed
            assert signal.getsignal(self.SIG) is prev_handler
            os.kill(os.getpid(), self.SIG)
            assert hits == [self.SIG, self.SIG]
        finally:
            signal.signal(self.SIG, old)

    def test_double_install_is_idempotent(self):
        old = signal.getsignal(self.SIG)
        try:
            notice = PreemptionNotice().install(self.SIG)
            handler = signal.getsignal(self.SIG)
            assert notice.install(self.SIG) is notice
            # no re-chain: the active handler is the SAME object, so a
            # signal cannot fire it twice (and uninstall still reaches
            # the true previous handler)
            assert signal.getsignal(self.SIG) is handler
            notice.uninstall()
            notice.uninstall()  # idempotent
        finally:
            signal.signal(self.SIG, old)

    def test_install_on_second_signal_requires_uninstall(self):
        old = signal.getsignal(self.SIG)
        try:
            notice = PreemptionNotice().install(self.SIG)
            with pytest.raises(ValueError):
                notice.install(signal.SIGUSR2)
            notice.uninstall()
        finally:
            signal.signal(self.SIG, old)


# -- checkpoint: corruption-tolerant resume + atomic writes -----------------


class _State:
    def __init__(self, **kw):
        self.__dict__.update(kw)

    def replace(self, **kw):
        d = dict(self.__dict__)
        d.update(kw)
        return _State(**d)


class _StubMgr:
    def __init__(self, steps, bad=()):
        self._steps = list(steps)
        self.bad = set(bad)
        self.restore_attempts = []

    def all_steps(self):
        return list(self._steps)

    def wait_until_finished(self):
        pass

    def close(self):
        pass

    def latest_step(self):
        return max(self._steps) if self._steps else None

    def restore(self, step, args=None):
        self.restore_attempts.append(step)
        if step in self.bad:
            raise ValueError("truncated checkpoint payload")
        return {"step": step, "params": {"w": float(step)},
                "batch_stats": {}, "opt_state": {}}


def _stub_checkpointer(mgr):
    from types import SimpleNamespace

    from kubeflow_tpu.runtime.checkpoint import Checkpointer

    ck = Checkpointer.__new__(Checkpointer)
    ck._mgr = mgr
    ck._ocp = SimpleNamespace(
        args=SimpleNamespace(StandardRestore=lambda tree: tree))
    ck.directory = "/stub"
    return ck


class TestCheckpointResilience:
    def _template(self):
        return _State(step=0, params={"w": 0.0}, batch_stats={},
                      opt_state={})

    def test_restore_latest_skips_corrupt_and_falls_back(self):
        mgr = _StubMgr([1, 2, 3], bad={3})
        st = _stub_checkpointer(mgr).restore_latest(self._template())
        assert st is not None and st.step == 2
        assert mgr.restore_attempts == [3, 2]  # newest first, one fallback

    def test_restore_latest_all_steps_failing_raises_systematic_error(self):
        # every step failing is a volume outage / template mismatch, not
        # three independent corruptions: crash-and-retry (the gang
        # restart loop) beats silently discarding all progress
        mgr = _StubMgr([1, 2, 3], bad={1, 2, 3})
        with pytest.raises(ValueError):
            _stub_checkpointer(mgr).restore_latest(self._template())
        assert mgr.restore_attempts == [3, 2, 1]

    def test_restore_latest_empty_dir_is_fresh_start(self):
        assert _stub_checkpointer(_StubMgr([])).restore_latest(
            self._template()) is None

    def test_wait_writes_resume_manifest_atomically(self, tmp_path):
        mgr = _StubMgr([1, 2, 5])
        ck = _stub_checkpointer(mgr)
        ck.directory = str(tmp_path)
        ck.wait()
        manifest = json.loads((tmp_path / "manifest.json").read_text())
        assert manifest == {"latest_step": 5, "steps": [1, 2, 5],
                            "world_sizes": {}, "slice_counts": {}}
        # remote URIs skip the local manifest (orbax owns metadata there)
        ck.directory = "gs://bucket/ckpt"
        ck.close()

    def test_atomic_write_text(self, tmp_path):
        from kubeflow_tpu.runtime.checkpoint import atomic_write_text

        path = tmp_path / "manifest.json"
        atomic_write_text(str(path), '{"step": 1}')
        assert path.read_text() == '{"step": 1}'
        atomic_write_text(str(path), '{"step": 2}')  # overwrite in place
        assert path.read_text() == '{"step": 2}'
        # no temp residue after successful replaces
        assert [p.name for p in tmp_path.iterdir()] == ["manifest.json"]

    def test_trace_dump_is_atomic_and_loadable(self, tmp_path):
        t = tr.Tracer(tr.TraceCollector())
        with t.span("unit"):
            pass
        path = tmp_path / "trace.jsonl"
        tr.write_jsonl(str(path), t.collector.spans())
        assert [s.name for s in tr.read_jsonl(str(path))] == ["unit"]
        assert [p.name for p in tmp_path.iterdir()] == ["trace.jsonl"]


# -- events: fire-and-forget under apiserver errors -------------------------


class TestEventBestEffort:
    def test_recorder_drops_instead_of_raising(self):
        inner = FakeCluster()
        faulty = ChaosClient(
            inner, ChaosPolicy(seed=1, rate=1.0, conflict_weight=0.0,
                               verbs=frozenset({"create"})))
        rec = EventRecorder(faulty)
        pod = inner.create({"apiVersion": "v1", "kind": "Pod",
                            "metadata": {"name": "p", "namespace": "default"}})
        out = rec.event(pod, "Chaos", "event create failed upstream")
        assert out["reason"] == "Chaos"  # returned unsent, no raise
        assert inner.list("v1", "Event", namespace="default") == []

    def test_recorder_recovers_when_apiserver_does(self):
        inner = FakeCluster()
        faulty = ChaosClient(
            inner, ChaosPolicy(seed=1, rate=1.0, conflict_weight=0.0,
                               verbs=frozenset({"create"})),
            always_on=False)
        rec = EventRecorder(faulty)
        pod = inner.create({"apiVersion": "v1", "kind": "Pod",
                            "metadata": {"name": "p", "namespace": "default"}})
        with faulty.armed():
            rec.event(pod, "Chaos", "dropped")
        rec.event(pod, "Chaos", "dropped")  # healthy: lands this time
        evs = inner.list("v1", "Event", namespace="default")
        assert len(evs) == 1 and evs[0]["count"] == 1


# -- chaos-parameterized reruns of the happy-path suites --------------------


def _jaxjob_chaos_world(seed):
    """The J.world fixture, chaos edition: one FakeCluster, faults armed
    ONLY during reconciles (the tests' own setup/asserts stay clean)."""
    inner = FakeCluster()
    chaos = ChaosClient(inner, _policy(seed), always_on=False)
    ctl = arm_controller(
        seed_controller(build_controller(chaos, record_events=True)), chaos)
    # zero the retry delays: error/conflict retries then complete inside
    # the SAME drain the original tests budget for, so their assertions
    # hold with faults on (wall-clock pacing is pinned separately in
    # TestConflictBackoff / TestRestClientBackoff)
    ctl.CONFLICT_RETRY = (0, 0)
    ctl.RETRY_BASE = 0.0
    kubelet = FakeKubelet(inner)
    return chaos, ctl, kubelet


def _sched_chaos_world(seed):
    def factory(clock):
        inner = FakeCluster()
        chaos = ChaosClient(inner, _policy(seed), always_on=False)
        registry = MetricsRegistry()
        jax_ctl = arm_controller(seed_controller(
            build_controller(chaos, record_events=False)), chaos)
        sched_ctl = arm_controller(seed_controller(
            build_scheduler(chaos, registry=registry, record_events=False,
                            clock=clock)), chaos)
        for ctl in (jax_ctl, sched_ctl):
            ctl.CONFLICT_RETRY = (0, 0)
            ctl.RETRY_BASE = 0.0
        kubelet = FakeKubelet(inner, auto_bind=False)
        return chaos, jax_ctl, sched_ctl, kubelet, registry

    return factory


def _methods(cls):
    return [(cls, n) for n in sorted(dir(cls)) if n.startswith("test_")]


# Every jaxjob-controller suite whose tests drive ONLY through the world
# tuple (TestIdempotency calls the reconciler directly — with chaos
# armed its no-op contract cannot hold, so it stays chaos-free).
JAXJOB_HAPPY = [case for cls in (
    J.TestGangCreation, J.TestLifecycle, J.TestGangRestart,
    J.TestPreemptionAwareRestart, J.TestSliceHealth,
    J.TestSliceHealthOrdering, J.TestPreemptionClassification,
    J.TestMultislice, J.TestTopologyValidation,
) for case in _methods(cls)]

# Scheduler happy paths whose assertions are chaos-stable (final
# placement / never-happens properties — not exact retry counts,
# fake-clock-pinned backoff schedules, or queue-ARRIVAL order, all of
# which chaos legitimately shifts: e.g. strict-FIFO orders gangs once
# queued, but a faulted gang creation can reach the queue second).
SCHED_HAPPY = [
    (S.TestAllOrNothingAdmission, "test_capacity_for_n_minus_one_binds_zero"),
    (S.TestAllOrNothingAdmission, "test_admits_when_capacity_appears"),
    (S.TestAllOrNothingAdmission, "test_head_blocking_is_per_namespace"),
    (S.TestAllOrNothingAdmission,
     "test_topology_spelling_is_normalized_for_placement"),
    (S.TestAllOrNothingAdmission, "test_non_gang_jobs_ignore_the_scheduler"),
    (S.TestPriorityPreemption, "test_victims_in_other_pools_are_never_evicted"),
    (S.TestPriorityPreemption, "test_equal_priority_never_preempts"),
]


@pytest.mark.parametrize("seed", CHAOS_SEEDS)
@pytest.mark.parametrize(
    "case", JAXJOB_HAPPY,
    ids=[f"{cls.__name__}.{name}" for cls, name in JAXJOB_HAPPY])
def test_jaxjob_happy_paths_survive_chaos(case, seed):
    cls, name = case
    getattr(cls(), name)(_jaxjob_chaos_world(seed))


@pytest.mark.parametrize("seed", CHAOS_SEEDS)
@pytest.mark.parametrize(
    "case", SCHED_HAPPY,
    ids=[f"{cls.__name__}.{name}" for cls, name in SCHED_HAPPY])
def test_scheduler_happy_paths_survive_chaos(case, seed, monkeypatch):
    monkeypatch.setattr(S, "sched_world", _sched_chaos_world(seed))
    cls, name = case
    getattr(cls(), name)()


# -- deterministic replay through real controllers --------------------------


def _replay_run(seed):
    """A full jaxjob lifecycle under conflict-only chaos with every
    retry delay zeroed: control flow depends on nothing but the seed, so
    two runs must inject the IDENTICAL fault sequence and converge to
    the identical terminal state."""
    inner = FakeCluster()
    chaos = ChaosClient(
        inner, ChaosPolicy(seed=seed, rate=0.3, error_weight=0.0,
                           conflict_weight=1.0, watch_drop_every=7),
        always_on=False)
    ctl = arm_controller(
        seed_controller(build_controller(chaos, record_events=True)), chaos)
    ctl.CONFLICT_RETRY = (0, 0)
    ctl.RETRY_BASE = 0.0
    kubelet = FakeKubelet(inner)
    inner.create(JT.new_jaxjob("replay", replicas=2,
                               accelerator="tpu-v5-lite-podslice",
                               topology="2x4", chips_per_worker=4))
    for _ in range(6):
        ctl.run_until_idle(advance_delayed=True)
        kubelet.step()
    for i in range(2):
        kubelet.succeed(worker_name("replay", i))
    for _ in range(6):
        ctl.run_until_idle(advance_delayed=True)
    job = inner.get(JT.API_VERSION, JT.KIND, "replay", "default")
    return chaos.fault_log(), ob.cond_is_true(job, JT.COND_SUCCEEDED)


@pytest.mark.parametrize("seed", CHAOS_SEEDS)
def test_fault_sequence_replays_exactly(seed):
    log1, ok1 = _replay_run(seed)
    log2, ok2 = _replay_run(seed)
    assert ok1 and ok2, "chaos run must still converge to Succeeded"
    assert log1 == log2, "same seed must inject the same fault sequence"
    assert log1, "the run should actually have seen faults"


# -- the full-platform chaos soak -------------------------------------------


def _assert_capacity_respected(inner):
    """No node oversubscribed by bound, non-terminal pods — the
    all-or-nothing + eviction accounting invariant, checked every
    round."""
    from kubeflow_tpu.control.scheduler import nodes as N

    alloc = {}
    for node in inner.list("v1", "Node"):
        v = N.node_view(node)
        alloc[v.name] = v.allocatable_chips
    used: dict[str, int] = {}
    for p in inner.list("v1", "Pod"):
        node = (p.get("spec") or {}).get("nodeName")
        if not node:
            continue
        if (p.get("status") or {}).get("phase") in N.TERMINAL_PHASES:
            continue
        used[node] = used.get(node, 0) + N.pod_tpu_request(p)
    for node, n in used.items():
        if node in alloc:
            assert n <= alloc[node], (
                f"node {node} oversubscribed: {n} > {alloc[node]}")


def _soak(seed, rounds=200):
    """One seeded soak: 3 gang jobs contending for 4 TPU hosts while the
    apiserver errors, watches drop, a node dies and heals, pods are
    evicted and hard-killed, the lease plane misbehaves, and the leader
    crashes mid-run. Returns (fault logs, failover duration)."""
    tr.COLLECTOR.clear()
    inner = FakeCluster(history_limit=64)
    chaos = ChaosClient(
        inner, ChaosPolicy(seed=seed, rate=0.06, watch_drop_every=18),
        always_on=False)
    lease_chaos = ChaosClient(
        inner, ChaosPolicy(seed=seed + 1000, rate=0.15, conflict_weight=0.0,
                           kinds=frozenset({"Lease"})))
    clock = S.FakeClock()
    registry = MetricsRegistry()
    lease_seconds = 15.0

    el_a = LeaderElector(lease_chaos, "jaxjob-soak", identity="a",
                         lease_seconds=lease_seconds, clock=clock)
    el_b = LeaderElector(lease_chaos, "jaxjob-soak", identity="b",
                         lease_seconds=lease_seconds, clock=clock)
    ctl_a = arm_controller(seed_controller(build_controller(
        chaos, record_events=True, registry=registry)),
        chaos).with_leader_election(el_a)
    ctl_b = arm_controller(seed_controller(build_controller(
        chaos, record_events=True, registry=registry)),
        chaos).with_leader_election(el_b)
    sched_ctl = arm_controller(seed_controller(build_scheduler(
        chaos, registry=registry, record_events=True, clock=clock)), chaos)
    for ctl in (ctl_a, ctl_b, sched_ctl):
        ctl.CONFLICT_RETRY = (0, 0)  # timing-free: replay-exact runs
        ctl.RETRY_BASE = 0.0
    kubelet = FakeKubelet(inner, auto_bind=False)

    for i in range(4):
        inner.create(new_tpu_node(f"n{i}"))
    jobs = ["j0", "j1", "j2"]
    for i, name in enumerate(jobs):
        job = JT.new_jaxjob(name, replicas=2,
                            accelerator="tpu-v5-lite-podslice",
                            topology="2x4", chips_per_worker=4,
                            gang_schedule=True, priority=i % 2)
        # chaos budget: transient faults must never exhaust a job
        job["spec"]["maxRestarts"] = 100
        job["spec"]["maxPreemptions"] = 100
        inner.create(job)

    rng = random.Random(seed)
    run_age: dict[str, int] = {}
    controllers = [ctl_a, ctl_b]
    failover_took = None

    def drain():
        for c in controllers + [sched_ctl]:
            c.run_until_idle(advance_delayed=True)

    for r in range(rounds):
        drain()
        kubelet.step()
        _assert_capacity_respected(inner)

        # simulated workload: a pod that stays Running 6 rounds succeeds
        # (long enough that every drill below lands on LIVE gangs)
        for p in sorted(inner.list("v1", "Pod"),
                        key=lambda p: ob.meta(p)["name"]):
            if (p.get("status") or {}).get("phase") != "Running":
                continue
            uid = ob.meta(p)["uid"]
            run_age[uid] = run_age.get(uid, 0) + 1
            if run_age[uid] >= 6:
                try:
                    kubelet.succeed(ob.meta(p)["name"],
                                    ob.meta(p).get("namespace") or "default")
                except ob.NotFound:
                    pass

        # scripted chaos drills (deterministic per seed)
        if r == 8:
            chaos.fail_node("n0")
        if r == 16:
            chaos.heal_node("n0")
        if r in (12, 20):
            running = sorted(
                (p for p in inner.list("v1", "Pod")
                 if (p.get("status") or {}).get("phase") == "Running"
                 and (p.get("spec") or {}).get("nodeName")),
                key=lambda p: ob.meta(p)["name"])
            if running:
                victim = running[rng.randrange(len(running))]
                m = ob.meta(victim)
                if r == 12:
                    chaos.evict_pod(m["name"], m.get("namespace") or "default")
                else:
                    chaos.kill_pod(m["name"], m.get("namespace") or "default")
        if r == 26 and failover_took is None:
            # crash whichever replica holds the lease RIGHT NOW (lease-
            # plane chaos means it is not always "a"): stop driving it,
            # and the survivor must take over within one lease duration
            # of the leader's last successful renew (+ slack for fault-
            # injected renew attempts of its own)
            if el_b.is_leader:
                survivor_ctl, survivor_el = ctl_a, el_a
            else:  # a leads (or neither mid-fault: crash a, keep b)
                survivor_ctl, survivor_el = ctl_b, el_b
            controllers = [survivor_ctl]
            crash_t = clock.t
            while not survivor_el.try_acquire():
                clock.advance(1.0)
                survivor_ctl.run_until_idle(advance_delayed=True)
                assert clock.t - crash_t <= lease_seconds + 5.0, \
                    "standby failed to take over within one lease duration"
            assert survivor_el.is_leader
            failover_took = clock.t - crash_t

        clock.advance(1.0)
        done = all(ob.cond_is_true(
            inner.get(JT.API_VERSION, JT.KIND, name, "default"),
            JT.COND_SUCCEEDED) for name in jobs)
        if done and failover_took is not None:
            break

    # -- convergence ---------------------------------------------------------
    for name in jobs:
        job = inner.get(JT.API_VERSION, JT.KIND, name, "default")
        assert ob.cond_is_true(job, JT.COND_SUCCEEDED), (
            name, job.get("status"))
        assert not ob.cond_is_true(job, JT.COND_FAILED)
        # no gang lost or duplicated: exactly the declared worker set
        pods = inner.list("v1", "Pod", namespace="default",
                          label_selector={"matchLabels": {
                              JT.LABEL_JOB_NAME: name}})
        assert sorted(ob.meta(p)["name"] for p in pods) == \
            [worker_name(name, i) for i in range(2)]

    # -- leader failover happened, inside one lease duration (+ slack) -------
    assert failover_took is not None
    assert failover_took <= lease_seconds + 5.0

    # -- the trace tree stays connected under chaos --------------------------
    for name in jobs:
        job = inner.get(JT.API_VERSION, JT.KIND, name, "default")
        header = (ob.meta(job).get("annotations") or {}).get(
            tr.TRACEPARENT_ANNOTATION)
        assert header, f"{name} lost its traceparent"
        ctx = tr.parse_traceparent(header)
        spans = tr.COLLECTOR.trace(ctx.trace_id)
        assert spans, f"{name} produced no spans"
        reach = tr.reachable(spans, ctx.span_id)
        assert reach >= {s.span_id for s in spans}, (
            f"{name}: disconnected spans "
            f"{[s.name for s in spans if s.span_id not in reach]}")

        # -- goodput ledger conservation (ISSUE 10) --------------------------
        # however chaotic the run, the ledger's buckets must sum to the
        # job's wall window EXACTLY — check() raises on any double-
        # counted or dropped time (2 workers x 4 chips = 8 chips)
        from kubeflow_tpu.obs import goodput as gp

        report = gp.job_report(spans, chips=8)
        report.check()
        assert report.wall_s > 0
        assert all(v >= 0 for v in report.buckets.values())
        # restarts the drills forced show up as ACCOUNTED rebuild time:
        # any provision beyond the first must land in restart_rebuild,
        # never vanish into unclassified loss
        provisions = [s for s in spans if s.name == "jaxjob.provision"
                      and s.end is not None]
        if len(provisions) > 1:
            assert report.buckets[gp.RESTART] > 0, report.buckets

    return chaos.fault_log(), lease_chaos.fault_log(), failover_took


@pytest.mark.slow
@pytest.mark.parametrize("seed", CHAOS_SEEDS)
def test_chaos_soak_converges_and_replays(seed):
    faults1, lease_faults1, took1 = _soak(seed)
    assert faults1, "soak should actually have injected faults"
    faults2, lease_faults2, took2 = _soak(seed)
    assert faults1 == faults2, "soak fault sequence must replay exactly"
    assert lease_faults1 == lease_faults2
    assert took1 == took2


# -- elastic: the scripted spot-reclaim drill (ISSUE 6) ----------------------


def _spot_world(seed, rate):
    """Full control plane over 2 spot + 2 on-demand hosts with a
    4-worker elastic gang (floor 2); chaos primitives drive the reclaim
    drill, API faults armed at ``rate`` (0.0 = scripted pass-through)."""
    tr.COLLECTOR.clear()
    inner = FakeCluster()
    chaos = ChaosClient(inner, ChaosPolicy(seed=seed, rate=rate),
                        always_on=False)
    clock = S.FakeClock()
    registry = MetricsRegistry()
    jax_ctl = arm_controller(seed_controller(build_controller(
        chaos, record_events=True)), chaos)
    sched_ctl = arm_controller(seed_controller(build_scheduler(
        chaos, registry=registry, record_events=True, clock=clock)), chaos)
    for ctl in (jax_ctl, sched_ctl):
        ctl.CONFLICT_RETRY = (0, 0)
        ctl.RETRY_BASE = 0.0
    kubelet = FakeKubelet(inner, auto_bind=False)
    for i in range(2):
        inner.create(new_tpu_node(f"spot{i}", topology="4x4", spot=True))
    for i in range(2):
        inner.create(new_tpu_node(f"ond{i}", topology="4x4"))
    inner.create(JT.new_jaxjob(
        "el", replicas=4, accelerator="tpu-v5-lite-podslice",
        topology="4x4", chips_per_worker=4, gang_schedule=True,
        elastic_min=2))

    def pump(rounds=10):
        for _ in range(rounds):
            jax_ctl.run_until_idle(advance_delayed=True)
            sched_ctl.run_until_idle(advance_delayed=True)
            kubelet.step()
            clock.advance(1.0)

    return inner, chaos, kubelet, pump


def _spot_drill(inner, chaos, kubelet, pump):
    """kill K (spot) nodes -> shrunken gang continues -> heal -> grow
    back -> finish. Returns the job's final status."""
    pump()
    job = inner.get(JT.API_VERSION, JT.KIND, "el", "default")
    assert ob.cond_is_true(job, JT.COND_RUNNING)
    # spot reclaim: both spot hosts die (workers 0,1 live there — the
    # scheduler preferred the spot pool for this elastic gang)
    chaos.fail_node("spot0")
    chaos.fail_node("spot1")
    pump()
    mid = inner.get(JT.API_VERSION, JT.KIND, "el", "default")["status"]
    assert mid["activeReplicas"] == 2, mid
    assert {*mid["world"]["members"]} == {worker_name("el", 2),
                                          worker_name("el", 3)}
    # the reclaimed capacity returns
    chaos.heal_node("spot0")
    chaos.heal_node("spot1")
    pump()
    grown = inner.get(JT.API_VERSION, JT.KIND, "el", "default")["status"]
    assert grown["activeReplicas"] == 4, grown
    for i in range(4):
        kubelet.succeed(worker_name("el", i))
    pump()
    job = inner.get(JT.API_VERSION, JT.KIND, "el", "default")
    assert ob.cond_is_true(job, JT.COND_SUCCEEDED)
    return job


def test_spot_reclaim_drill_keeps_budgets_and_trace_connected():
    inner, chaos, kubelet, pump = _spot_world(seed=CHAOS_SEEDS[0], rate=0.0)
    job = _spot_drill(inner, chaos, kubelet, pump)
    st = job["status"]
    # THE budget assertion: a full reclaim/heal cycle costs ZERO of the
    # restart AND preemption budgets — resizes carry it all
    assert st.get("restarts", 0) == 0
    assert st.get("preemptions", 0) == 0
    assert st["resizes"] == 2  # scripted drill: one shrink, one grow
    # the trace tree stays connected across both resizes
    header = (ob.meta(job).get("annotations") or {}).get(
        tr.TRACEPARENT_ANNOTATION)
    assert header
    ctx = tr.parse_traceparent(header)
    spans = tr.COLLECTOR.trace(ctx.trace_id)
    assert spans
    reach = tr.reachable(spans, ctx.span_id)
    assert reach >= {s.span_id for s in spans}, (
        [s.name for s in spans if s.span_id not in reach])
    # goodput ledger conservation across the resize drill (ISSUE 10):
    # shrink + grow re-provisions are ACCOUNTED (restart_rebuild /
    # admission buckets), and everything sums to the wall window
    from kubeflow_tpu.obs import goodput as gp

    report = gp.job_report(spans, chips=16)  # 4 workers x 4 chips
    report.check()
    assert report.wall_s > 0
    assert all(v >= 0 for v in report.buckets.values())
    # the drill re-provisioned replacements after the first provision:
    # that time must land in restart_rebuild, not vanish
    provisions = [s for s in spans if s.name == "jaxjob.provision"
                  and s.end is not None]
    if len(provisions) > 1:
        assert report.buckets[gp.RESTART] > 0


@pytest.mark.parametrize("seed", CHAOS_SEEDS[:2])
def test_spot_reclaim_drill_survives_api_faults(seed):
    """The same drill with apiserver faults armed: evictions may land
    in separate waves (more than one shrink resize), but the budget and
    convergence invariants must hold fault-schedule-independently."""
    inner, chaos, kubelet, pump = _spot_world(seed=seed, rate=CHAOS_RATE)
    job = _spot_drill(inner, chaos, kubelet, pump)
    st = job["status"]
    assert st.get("restarts", 0) == 0
    assert st.get("preemptions", 0) == 0
    assert st["resizes"] >= 2
    assert chaos.fault_log(), "faults should actually have been injected"


# -- eviction-status single spelling ----------------------------------------


def test_eviction_status_is_the_preemption_shape():
    from kubeflow_tpu.control.jaxjob.controller import JAXJobReconciler

    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "p", "namespace": "default"},
           "spec": {"containers": [{"name": "jax"}]},
           "status": eviction_status("drill")}
    assert JAXJobReconciler._pod_preempted(pod)
