from kubeflow_tpu.metric_collector.prober import main

main()
