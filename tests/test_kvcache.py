"""Paged KV cache (runtime/kvcache.py + the paged/speculative
SlotDecoder modes): allocator invariants under random transitions,
prefix-reuse COW correctness, and the pinned token-for-token
equalities — paged == dense and speculative == plain greedy."""

import random
import threading

import numpy as np
import pytest

from kubeflow_tpu.runtime.kvcache import (
    TRASH_PAGE,
    PageAllocator,
    pages_for,
)


@pytest.fixture(scope="module")
def lm():
    import jax

    from kubeflow_tpu.models.registry import get_model

    model = get_model("transformer-test", vocab_size=64, max_seq_len=24)
    tok = np.zeros((1, 1), np.int32)
    variables = model.init(jax.random.PRNGKey(0), tok, train=False)
    return model, variables


def paged_model(**kw):
    from kubeflow_tpu.models.registry import get_model

    base = dict(vocab_size=64, max_seq_len=24)
    base.update(kw)
    return get_model("transformer-test", **base)


def reference_generate(model, variables, tokens, prompt_len=8, max_new=4):
    import jax.numpy as jnp

    from kubeflow_tpu.runtime.generate import generate

    row = [int(t) for t in tokens][-prompt_len:]
    pad = prompt_len - len(row)
    prompt = jnp.asarray([[0] * pad + row], jnp.int32)
    out = generate(model, variables, prompt, max_new_tokens=max_new,
                   pad_len=jnp.asarray([pad], jnp.int32))
    return [int(t) for t in np.asarray(out)[0, prompt_len:]]


class TestPageAllocator:
    def test_admit_shares_prefix_and_cows_the_full_hit(self):
        a = PageAllocator(num_pages=24, page_size=8, slots=4,
                          max_pages_per_slot=6)
        row = list(range(1, 33))                    # 4 full pages
        p0 = a.admit(0, row, 0, 40)
        assert p0.shared_pages == 0 and p0.compute_start == 0
        a.check()
        # identical prompt: every full page hits; the final position is
        # recomputed for logits, so the last shared page COW-clones
        need, cached = a.plan(row, 0, 40)
        assert cached == 32
        p1 = a.admit(1, row, 0, 40)
        assert p1.shared_pages == 4 and p1.compute_start == 31
        assert len(p1.copies) == 1 and a.cow_clones == 1
        a.check()
        # page-aligned divergence: 3 shared pages, no COW
        p2 = a.admit(2, row[:24] + [9] * 8, 0, 40)
        assert p2.shared_pages == 3 and p2.compute_start == 24
        assert not p2.copies
        a.check()
        # mid-page divergence: the divergent page hash misses entirely
        p3 = a.admit(3, row[:28] + [9] * 4, 0, 40)
        assert p3.shared_pages == 3 and p3.compute_start == 24
        a.check()

    def test_plan_accounts_for_the_cow_extra_page(self):
        a = PageAllocator(num_pages=8, page_size=4, slots=2,
                          max_pages_per_slot=3)
        row = list(range(1, 9))                     # 2 full pages
        a.admit(0, row, 0, 8)
        need, cached = a.plan(row, 0, 8)
        assert cached == 8
        assert need == 1                            # 0 fresh + 1 COW clone
        a.check()

    def test_free_returns_pages_and_zeroes_the_table_row(self):
        a = PageAllocator(num_pages=16, page_size=4, slots=2,
                          max_pages_per_slot=4, prefix_cache=False)
        a.admit(0, list(range(1, 9)), 0, 16)
        a.append(0, 16)
        assert a.used_pages == 4
        a.free(0)
        a.check()
        assert a.used_pages == 0
        assert (a.table[0] == TRASH_PAGE).all()

    def test_pool_exhaustion_is_an_error_not_corruption(self):
        a = PageAllocator(num_pages=4, page_size=4, slots=2,
                          max_pages_per_slot=3, prefix_cache=False)
        a.admit(0, list(range(1, 9)), 0, 12)        # 3 of 3 usable pages
        with pytest.raises(RuntimeError, match="exhausted"):
            a.admit(1, list(range(10, 18)), 0, 12)

    def test_property_random_transitions_hold_invariants(self):
        """Random admit/append/write_barrier/free sequences never
        double-allocate or leak a page: refcounts, freelist, table and
        prefix-index invariants checked after EVERY transition."""
        rng = random.Random(20260804)
        a = PageAllocator(num_pages=48, page_size=4, slots=8,
                          max_pages_per_slot=12)
        live: dict[int, tuple] = {}    # slot -> (total_len, cur_len)
        admits = 0
        for _step in range(6000):
            op = rng.random()
            if op < 0.40 and len(live) < a.slots:
                slot = next(s for s in range(a.slots) if s not in live)
                plen = rng.randrange(1, 25)
                row = [rng.randrange(0, 4) for _ in range(plen)]
                total = plen + rng.randrange(0, 16)
                if pages_for(total, a.page_size) > a.max_pages_per_slot:
                    continue
                pad = rng.randrange(0, 2)
                if a.can_admit(row, pad, total):
                    a.admit(slot, row, pad, total)
                    live[slot] = (total, plen)
                    admits += 1
            elif op < 0.80 and live:
                slot = rng.choice(sorted(live))
                total, cur = live[slot]
                if cur < total:
                    step = min(total - cur, rng.randrange(1, 4))
                    a.append(slot, cur + step)
                    a.write_barrier(slot, cur, cur + step)
                    live[slot] = (total, cur + step)
            elif live:
                slot = rng.choice(sorted(live))
                a.free(slot)
                del live[slot]
            a.check()
        assert admits > 100   # the run actually exercised admission
        for slot in sorted(live):
            a.free(slot)
            a.check()
        # nothing leaked: only prefix-index pages may remain resident
        assert a.used_pages == len(a._prefix)

    def test_can_admit_never_counts_its_own_hits_as_evictable(self):
        """The admission gate must not plan on evicting the very prefix
        pages the admission is about to claim: with 2 free pages and a
        4-token budget left only via this prompt's own cached pages,
        admission must WAIT, or append() exhausts the pool mid-decode
        and fails every in-flight request."""
        a = PageAllocator(num_pages=7, page_size=4, slots=2,
                          max_pages_per_slot=7)
        a.admit(0, list(range(1, 9)), 0, 8)    # chain A: 2 prefix pages
        a.admit(1, list(range(20, 28)), 0, 8)  # chain B: 2 prefix pages
        a.free(0)
        a.free(1)
        a.check()
        assert a.free_pages == 2               # 4 pages live in the index
        row = list(range(1, 9))
        # total_len 24 needs 6 pages - 2 hits + 1 COW = 5, obtainable =
        # free(2) + NON-HIT evictables(2) = 4: the naive
        # `need <= free + all evictables(4+2)` gate would admit and
        # starve; the correct gate refuses. 20 (need 4) fits exactly.
        assert a.can_admit(row, 0, 20) is True
        assert a.can_admit(row, 0, 24) is False
        a.admit(0, row, 0, 20)
        a.append(0, 20)                          # never raises
        a.check()

    def test_reset_forgets_everything(self):
        a = PageAllocator(num_pages=16, page_size=4, slots=2,
                          max_pages_per_slot=4)
        a.admit(0, list(range(1, 9)), 0, 12)
        a.reset()
        a.check()
        assert a.free_pages == 15 and a.used_pages == 0


class TestPagedDecode:
    """The paged SlotDecoder against its dense twin: same weights, same
    tokens, byte for byte."""

    def test_paged_matches_dense_exactly(self, lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        pm = paged_model(kv_pages=17, kv_page_size=4)
        dec = SlotDecoder(pm, variables, slots=4, prompt_len=8,
                          max_new_tokens=4)
        try:
            prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9], [10, 11]]
            want = [reference_generate(model, variables, p)
                    for p in prompts]
            assert [dec.submit(p) for p in prompts] == want
            st = dec.stats()
            assert st["mode"] == "paged" and st["completed"] == 4
            assert st["kv_pages_free"] + st["kv_pages_used"] == 16
        finally:
            dec.close()

    def test_concurrent_staggered_paged_stays_exact(self, lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        pm = paged_model(kv_pages=25, kv_page_size=4)
        dec = SlotDecoder(pm, variables, slots=3, prompt_len=8,
                          max_new_tokens=6)
        try:
            prompts = [[i + 1, i + 2, i + 3] for i in range(7)]
            want = {tuple(p): reference_generate(
                model, variables, p, max_new=6) for p in prompts}
            results: dict = {}
            errs: list = []

            def go(p):
                try:
                    results[tuple(p)] = dec.submit(p)
                except Exception as e:  # pragma: no cover
                    errs.append(e)

            threads = [threading.Thread(target=go, args=(p,))
                       for p in prompts]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errs, errs
            assert results == want
        finally:
            dec.close()

    def test_prefix_reuse_cow_does_not_corrupt_the_sharer(self, lm):
        """Three live slots share prompt pages; the full-hit admissions
        COW-clone the page they must rewrite. Every decode must still
        equal the no-sharing reference — a clone that mutated the
        shared original would corrupt its sharers' tokens."""
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        pm = paged_model(kv_pages=25, kv_page_size=4)
        dec = SlotDecoder(pm, variables, slots=4, prompt_len=8,
                          max_new_tokens=6)
        try:
            prompt = [3, 1, 4, 1, 5, 9, 2, 6]    # full 8 = 2 whole pages
            want = reference_generate(model, variables, prompt, max_new=6)
            held, dec._free = dec._free, []      # admit as one burst
            results: list = [None] * 3
            threads = [threading.Thread(
                target=lambda i=i: results.__setitem__(
                    i, dec.submit(prompt))) for i in range(3)]
            for t in threads:
                t.start()
            import time as _time

            _time.sleep(0.3)
            dec._free = held
            dec._wake.set()
            for t in threads:
                t.join(timeout=120)
            assert results == [want] * 3
            st = dec.stats()
            assert st["prefix_hit_pages"] >= 2   # sharing really happened
            assert st["cow_clones"] >= 1         # and the COW path ran
        finally:
            dec.close()

    def test_admission_gates_on_pages_not_slots(self, lm):
        """A pool sized for ~2 live sequences with 6 slots: requests
        queue on page availability and all complete as pages free."""
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        pm = paged_model(kv_pages=8, kv_page_size=4)  # 7 usable pages
        dec = SlotDecoder(pm, variables, slots=6, prompt_len=8,
                          max_new_tokens=4, prefix_cache=False)
        try:
            prompts = [[i + 1, i + 2] for i in range(6)]
            want = [reference_generate(model, variables, p)
                    for p in prompts]
            results: list = [None] * 6
            threads = [threading.Thread(
                target=lambda i=i: results.__setitem__(
                    i, dec.submit(prompts[i]))) for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert results == want
            # 7 usable pages / 3 pages per sequence -> never 3 at once
            assert dec.stats()["peak_active"] <= 2
        finally:
            dec.close()

    def test_per_request_budget_frees_pages_early(self, lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        pm = paged_model(kv_pages=17, kv_page_size=4)
        dec = SlotDecoder(pm, variables, slots=4, prompt_len=8,
                          max_new_tokens=6)
        try:
            p = [1, 2, 3]
            full = reference_generate(model, variables, p, max_new=6)
            assert dec.submit(p, max_new=2) == full[:2]
            assert dec.submit(p, max_new=6) == full
            with pytest.raises(ValueError, match="max_new"):
                dec.submit(p, max_new=7)
            st = dec.stats()
            assert st["completed"] == 2   # the out-of-range cap never ran
            # completed sequences hold nothing; only prefix-index pages
            # stay resident for future reuse
            assert st["kv_pages_used"] < st["kv_pages_total"]
        finally:
            dec.close()

    def test_pool_too_small_for_one_sequence_refused(self, lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        pm = paged_model(kv_pages=3, kv_page_size=4)
        with pytest.raises(ValueError, match="kv_pages"):
            SlotDecoder(pm, variables, slots=2, prompt_len=8,
                        max_new_tokens=4)


class TestSpeculativeLockstep:
    """speculative_generate's propose/verify round generalized to
    [S, k] inside SlotDecoder._tick: output must be token-for-token
    equal to plain greedy decode, accept or reject."""

    def test_disagreeing_draft_stays_exact(self, lm):
        """A randomly-initialized draft rejects constantly — the
        rejection/resync path must still emit exactly greedy tokens."""
        import jax

        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        draft_vars = model.init(jax.random.PRNGKey(99),
                                np.zeros((1, 1), np.int32), train=False)
        dec = SlotDecoder(model, variables, slots=3, prompt_len=8,
                          max_new_tokens=6, draft_model=model,
                          draft_variables=draft_vars, draft_k=3)
        try:
            prompts = [[i + 1, i + 2, i + 3] for i in range(7)]
            want = {tuple(p): reference_generate(
                model, variables, p, max_new=6) for p in prompts}
            results: dict = {}
            threads = [threading.Thread(
                target=lambda p=p: results.__setitem__(
                    tuple(p), dec.submit(p))) for p in prompts]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert results == want
        finally:
            dec.close()

    def test_agreeing_draft_emits_multiple_tokens_per_forward(self, lm):
        """Draft == target weights: every proposal is accepted, so each
        verify forward emits k+1 tokens (the counter-based speedup
        claim; the bench banks the same number)."""
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        dec = SlotDecoder(model, variables, slots=2, prompt_len=8,
                          max_new_tokens=6, draft_model=model,
                          draft_variables=variables, draft_k=3)
        try:
            prompts = [[1, 2, 3], [4, 5]]
            want = [reference_generate(model, variables, p, max_new=6)
                    for p in prompts]
            assert [dec.submit(p) for p in prompts] == want
            st = dec.stats()
            assert st["spec_tokens_emitted"] / st["spec_rounds"] > 1.0
            assert st["spec_tokens_accepted"] > 0
        finally:
            dec.close()

    def test_spec_composes_with_paged_and_prefix_reuse(self, lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        pm = paged_model(kv_pages=33, kv_page_size=4)
        dec = SlotDecoder(pm, variables, slots=3, prompt_len=8,
                          max_new_tokens=4, draft_model=model,
                          draft_variables=variables, draft_k=3)
        try:
            p = [2, 7, 1, 8, 2, 8, 1, 8]
            want = reference_generate(model, variables, p)
            assert dec.submit(p) == want
            assert dec.submit(p) == want      # prefix-cache hit path
            st = dec.stats()
            assert st["prefix_hit_pages"] >= 2 and st["cow_clones"] >= 1
            assert st["spec_tokens_emitted"] / st["spec_rounds"] > 1.0
        finally:
            dec.close()

    def test_spec_round_failure_recovers_instead_of_zombie(self, lm):
        """A failed donated verify poisons in-flight requests ONCE and
        the decoder rebuilds both caches + the allocator."""
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        dec = SlotDecoder(model, variables, slots=2, prompt_len=8,
                          max_new_tokens=4, draft_model=model,
                          draft_variables=variables, draft_k=2)
        try:
            real_admit = dec._spec_admit_dense
            blew = []

            def exploding(*a, **kw):
                if not blew:
                    blew.append(1)
                    raise RuntimeError("RESOURCE_EXHAUSTED (simulated)")
                return real_admit(*a, **kw)

            dec._spec_admit_dense = exploding
            with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
                dec.submit([1, 2, 3])
            assert dec.submit([1, 2, 3]) == reference_generate(
                model, variables, [1, 2, 3])
        finally:
            dec.close()

    def test_greedy_only(self, lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = lm
        with pytest.raises(ValueError, match="greedy"):
            SlotDecoder(model, variables, slots=2, prompt_len=8,
                        max_new_tokens=4, temperature=0.7,
                        draft_model=model, draft_variables=variables)


class TestDecodeBenchContract:
    @staticmethod
    def _bench():
        import os
        import sys

        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, os.path.join(here, "tools"))
        try:
            import serve_bench as sb
        finally:
            sys.path.pop(0)
        return sb

    # a CI-speed miniature of DECODE_CONFIG: same invariants, smaller
    # model geometry (the banked run uses the full config)
    SMALL = {
        "seed": 5, "model": "transformer-test", "vocab_size": 64,
        "prompt_len": 8, "max_new_tokens": 4, "req_new": 2,
        "page_size": 2, "dense_slots": 2, "paged_slots": 4,
        "requests": 4, "shared_prefix": 6, "draft_k": 2,
        "spec_requests": 2,
    }

    def test_banked_results_satisfy_acceptance(self):
        """BENCH_SERVE_r02.json is the PR's acceptance artifact: >= 2x
        admitted sequences at the same cache bytes, >= 40% prefill
        tokens saved by the prefix cache, > 1 token per target forward
        — all token-identical across arms."""
        import json
        import os

        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(here, "BENCH_SERVE_r02.json")) as fh:
            banked = json.load(fh)
        d = banked["decode"]
        assert d["density"]["identical_tokens"] is True
        assert d["density"]["same_cache_bytes"] is True
        assert d["density"]["concurrency_x"] >= 2.0
        assert d["prefix"]["identical_tokens"] is True
        assert d["prefix"]["saving_pct"] >= 40.0
        assert d["speculative"]["identical_tokens"] is True
        assert d["speculative"]["tokens_per_forward"] > 1.0
        assert d["density"]["paged"]["peak_active"] == \
            d["config"]["requests"]

    def test_check_gate_round_trip(self, tmp_path):
        """``--check`` passes against a just-banked run of the same
        config and fails loudly (exit 1) against a poisoned bank —
        the sched_bench ratchet discipline over the new bank."""
        import json

        sb = self._bench()
        result = sb.run_decode_bench(dict(self.SMALL))
        assert result["density"]["identical_tokens"]
        assert result["density"]["concurrency_x"] >= 2.0
        assert result["prefix"]["saving_pct"] >= 40.0
        assert result["speculative"]["tokens_per_forward"] > 1.0
        ok = tmp_path / "bank_ok.json"
        ok.write_text(json.dumps({"decode": result}))
        assert sb.check_decode_bench(str(ok)) == 0
        bad = json.loads(ok.read_text())
        bad["decode"]["fingerprint"] = "poisoned"
        bad_path = tmp_path / "bank_bad.json"
        bad_path.write_text(json.dumps(bad))
        assert sb.check_decode_bench(str(bad_path)) == 1
