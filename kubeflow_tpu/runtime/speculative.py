"""Greedy speculative decoding: a small draft model proposes, the
target verifies k tokens in ONE forward.

Decode is HBM-bandwidth-bound — each emitted token streams the target's
full weights. Speculative decoding amortizes that stream over several
tokens: the draft (e.g. gpt-125m against a llama-1b target) runs k
cheap autoregressive steps, then the target consumes the whole proposal
chunk through its KV cache in one multi-position forward
(models/transformer.py chunked decode_index) and greedily accepts the
longest matching prefix plus one bonus token from its own logits. With
greedy acceptance the output is EXACTLY the target's own greedy
decode — the tests pin token-for-token equality — so speedup is free of
quality change; acceptance rate only affects throughput.

Cache correctness without rollback: a rejected proposal leaves stale
KV entries beyond the accept point, but the next round's chunk write
covers exactly that range before any read (write-then-attend inside one
apply), and the causal mask hides positions beyond the chunk. So both
caches self-heal — no rollback bookkeeping, no recompilation (round
geometry is static; positions are traced scalars).

Reference analogue: none — the reference's serving is TF-Serving
SavedModels (testing/test_tf_serving.py); this is TPU-native headroom.
Technique: Leviathan et al., "Fast Inference from Transformers via
Speculative Decoding" (2023), specialized to greedy acceptance.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.runtime.generate import init_cache, prefill_scan


def _split(variables):
    params = {k: v for k, v in variables.items() if k != "cache"}
    return params


@functools.partial(jax.jit, static_argnames=("model", "k"))
def _draft_propose(model, params, cache, cur, n, *, k, pad_len=None):
    """k greedy draft steps from token `cur` at position `n`.
    Returns (cache', proposals [B, k])."""

    def tick(carry, _):
        cache, tok, idx = carry
        logits, mut = model.apply(
            params | {"cache": cache}, tok, train=False,
            decode_index=idx, mutable=["cache"],
            **({"pad_len": pad_len} if pad_len is not None else {}))
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
        return (mut["cache"], nxt, idx + 1), nxt[:, 0]

    (cache, _, _), toks = jax.lax.scan(
        tick, (cache, cur, n), None, length=k)
    return cache, toks.T  # [B, k]


@functools.partial(jax.jit, static_argnames=("model",))
def _verify_chunk(model, params, cache, chunk, n, pad_len=None):
    """Target forward over the [B, C] chunk at positions n..n+C-1.
    Returns (cache', logits [B, C, V])."""
    logits, mut = model.apply(
        params | {"cache": cache}, chunk, train=False,
        decode_index=n, mutable=["cache"],
        **({"pad_len": pad_len} if pad_len is not None else {}))
    return mut["cache"], logits


def greedy_accept(drafted, targets, k: int) -> int:
    """Longest prefix of the k proposals the target's own greedy
    argmaxes agree with — the ONE acceptance rule, shared between the
    batch-1 loop and the lockstep slot decoder so they can never
    drift."""
    a = 0
    while a < k and drafted[a] == targets[a]:
        a += 1
    return a


# ---------------------------------------------------------------------------
# Lockstep generalization: the same propose/verify round over [S, k]
# slots at per-slot positions — what SlotDecoder._tick drives. Accept
# lengths are data-dependent PER SLOT, so the host resyncs each slot's
# draft cache by re-feeding the tokens emitted last round (a fixed
# [S, k+1] buffer with a per-slot valid length) before proposing again.
# Pad rows of that buffer write garbage K/V at future positions; the
# write-then-attend discipline (every position is rewritten by a later
# chunk before any query at or beyond it attends) makes the caches
# self-heal — the same argument that already covers rejected proposals.


@functools.partial(jax.jit, static_argnames=("model", "k"),
                   donate_argnums=(2,))
def lockstep_propose(model, params, cache, emitted, start, elen, *, k,
                     pad_len=None):
    """Resync + propose for S slots in lockstep.

    emitted: [S, k+1] tokens emitted last round (right-padded),
    start: [S] position of each row 0, elen: [S] valid lengths (the
    last valid token of slot s sits at start[s] + elen[s] - 1).
    Returns (cache', proposals [S, k]): one chunk apply (resync +
    first proposal from the last valid row's logits) plus k-1 fused
    single steps — k draft forwards per round, same as batch-1."""
    pad_kw = {"pad_len": pad_len} if pad_len is not None else {}
    logits, mut = model.apply(
        params | {"cache": cache}, emitted, train=False,
        decode_index=start, mutable=["cache"], **pad_kw)
    cache = mut["cache"]
    last = jnp.take_along_axis(
        logits, (elen - 1)[:, None, None], axis=1)[:, 0]      # [S, V]
    cur = jnp.argmax(last, axis=-1).astype(jnp.int32)         # d_1

    def tick(carry, _):
        cache, tok, idx = carry
        lg, mut = model.apply(
            params | {"cache": cache}, tok[:, None], train=False,
            decode_index=idx, mutable=["cache"], **pad_kw)
        nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
        return (mut["cache"], nxt, idx + 1), tok

    if k > 1:
        (cache, last_tok, _), fed = jax.lax.scan(
            tick, (cache, cur, start + elen), None, length=k - 1)
        props = jnp.concatenate([fed.T, last_tok[:, None]], axis=1)
    else:
        props = cur[:, None]
    return cache, props


@functools.partial(jax.jit, static_argnames=("model",),
                   donate_argnums=(2,))
def lockstep_verify(model, params, cache, chunk, n, pad_len=None,
                    page_table=None):
    """Target forward over [S, C] chunks at per-slot positions n[s]
    (dense or paged cache). Returns (cache', argmax ids [S, C]) — the
    greedy targets the host's accept rule compares against."""
    kw = {"pad_len": pad_len} if pad_len is not None else {}
    if page_table is not None:
        kw["page_table"] = page_table
    logits, mut = model.apply(
        params | {"cache": cache}, chunk, train=False,
        decode_index=n, mutable=["cache"], **kw)
    return mut["cache"], jnp.argmax(logits, axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("model",))
def _prefill(model, params, cache, prompt, pad_len=None):
    """Jitted prompt prefill (prefill_scan re-traces eagerly; a served
    request must not pay Python tracing per call)."""
    return prefill_scan(model, params, cache, prompt, pad_len)


def speculative_generate(target, target_vars, draft, draft_vars,
                         prompt: jax.Array, *, max_new_tokens: int,
                         k: int = 4, pad_len=None) -> tuple:
    """Greedy decode of `target` accelerated by `draft`.

    prompt: [1, P] int32 (batch 1: accept lengths are data-dependent, so
    rows cannot share a round; serve concurrency comes from slots/
    micro-batching above this). Returns (tokens [1, P+max_new_tokens],
    stats dict with rounds/accept counts).
    """
    if prompt.shape[0] != 1:
        raise ValueError("speculative_generate is batch-1 "
                         f"(got batch {prompt.shape[0]}); batch via the "
                         "serving layer")
    for name, m in (("target", target), ("draft", draft)):
        if getattr(m.cfg, "rolling_kv_cache", False):
            # rejection rewinds the decode index: a slot then holds a
            # REJECTED newer position while the rolling mask dates it as
            # the older same-residue position — silently wrong attention.
            # The full cache masks stale future entries out via
            # pos <= qpos, so only it composes with speculation.
            raise ValueError(
                f"speculative decoding requires the full KV cache; "
                f"{name} has rolling_kv_cache=True")
    p_len = prompt.shape[1]
    for name, m in (("target", target), ("draft", draft)):
        need = p_len + max_new_tokens + k
        if m.cfg.max_seq_len < need:
            raise ValueError(
                f"{name} max_seq_len {m.cfg.max_seq_len} < prompt + "
                f"max_new_tokens + k = {need} (the verify chunk may "
                "write up to k positions past the last emitted token)")
    t_params = _split(target_vars)
    d_params = _split(draft_vars)
    t_cache, t_logits = _prefill(
        target, t_params, init_cache(target, 1), prompt, pad_len)
    d_cache, _ = _prefill(
        draft, d_params, init_cache(draft, 1), prompt, pad_len)

    # first generated token comes straight from the target's prefill
    cur = int(np.asarray(jnp.argmax(t_logits, axis=-1))[0])
    out = [cur]
    n = p_len  # next write position: `cur` sits at position p_len
    rounds = 0
    accepted_total = 0
    while len(out) < max_new_tokens:
        d_cache, props = _draft_propose(
            draft, d_params, d_cache, jnp.full((1, 1), cur, jnp.int32),
            jnp.int32(n), k=k, pad_len=pad_len)
        # verify chunk = [cur, d_1 .. d_k] at positions n .. n+k: ALL k
        # proposals are judged (y_1..y_{k+1}), so a perfect round emits
        # k+1 tokens from k draft forwards + one verify
        chunk = jnp.concatenate(
            [jnp.full((1, 1), cur, jnp.int32), props], axis=1)
        t_cache, logits = _verify_chunk(
            target, t_params, t_cache, chunk, jnp.int32(n), pad_len=pad_len)
        y = np.asarray(jnp.argmax(logits, axis=-1))[0]      # [k+1] targets
        d = np.asarray(props)[0]                            # [k] proposals
        a = greedy_accept(d, y, k)
        emitted = list(d[:a]) + [y[a]]                      # a + 1 tokens
        if a == k:
            # full accept: the draft never consumed d_k, so its cache
            # lacks position n+k — heal it with one tick (proposal
            # discarded) or the hole degrades every later draft round
            d_cache, _ = _draft_propose(
                draft, d_params, d_cache,
                jnp.full((1, 1), int(d[k - 1]), jnp.int32),
                jnp.int32(n + k), k=1, pad_len=pad_len)
        out.extend(int(t) for t in emitted)
        cur = int(emitted[-1])
        n += a + 1
        rounds += 1
        accepted_total += a
    out = out[:max_new_tokens]
    tokens = jnp.concatenate(
        [prompt, jnp.asarray(out, jnp.int32)[None, :]], axis=1)
    return tokens, {"rounds": rounds, "drafted": rounds * k,
                    "accepted": accepted_total,
                    "tokens": len(out)}
