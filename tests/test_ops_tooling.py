"""Ops tooling bucket (reference: tools/gcb/template.libsonnet,
scripts/gke/iam_patch.py)."""

import pytest

from kubeflow_tpu.release.releaser import IMAGES, cloudbuild_manifest
from kubeflow_tpu.tpctl.iam_patch import load_bindings, patch_iam_policy


class FlakyCrm:
    """set_iam_policy fails `fail` times (concurrent-editor conflicts)."""

    def __init__(self, fail: int = 0):
        self.fail = fail
        self.policy = {"bindings": [], "etag": "e0"}
        self.sets = 0

    def test_iam_permissions(self, project, token, permissions):
        return list(permissions)

    def get_iam_policy(self, project, token):
        import copy
        return copy.deepcopy(self.policy)

    def set_iam_policy(self, project, token, policy):
        self.sets += 1
        if self.fail > 0:
            self.fail -= 1
            raise ConnectionError("409 concurrent policy change")
        self.policy = policy


BINDINGS = [{"members": ["set-kubeflow-iap-account"],
             "roles": ["roles/iap.httpsResourceAccessor"]}]


class TestIamPatch:
    def test_auth_rejection_not_retried(self):
        class Denied(FlakyCrm):
            def set_iam_policy(self, project, token, policy):
                err = ConnectionError("403 forbidden")
                err.code = 403
                raise err
        with pytest.raises(ConnectionError):
            patch_iam_policy("p", "tok", BINDINGS, Denied(), action="add",
                             email="a@b.co", sleep=lambda s: None)

    def test_zero_retries_rejected(self):
        with pytest.raises(ValueError):
            patch_iam_policy("p", "tok", BINDINGS, FlakyCrm(), retries=0,
                             email="a@b.co")

    def test_add_then_remove_roundtrip(self):
        crm = FlakyCrm()
        out = patch_iam_policy("p", "tok", BINDINGS, crm, action="add",
                               email="a@b.co")
        assert out["bindings"] == [{
            "role": "roles/iap.httpsResourceAccessor",
            "members": ["user:a@b.co"]}]
        out = patch_iam_policy("p", "tok", BINDINGS, crm, action="remove",
                               email="a@b.co")
        assert out["bindings"] == []

    def test_retries_on_set_conflict(self):
        # iam_patch.py's retry loop: re-read + re-merge on conflict
        crm = FlakyCrm(fail=2)
        sleeps = []
        patch_iam_policy("p", "tok", BINDINGS, crm, action="add",
                         email="a@b.co", sleep=sleeps.append)
        assert crm.sets == 3 and len(sleeps) == 2

    def test_retries_exhausted_reraises(self):
        crm = FlakyCrm(fail=99)
        with pytest.raises(ConnectionError):
            patch_iam_policy("p", "tok", BINDINGS, crm, action="add",
                             email="a@b.co", retries=2, sleep=lambda s: None)

    def test_invalid_action_rejected(self):
        with pytest.raises(ValueError):
            patch_iam_policy("p", "tok", BINDINGS, FlakyCrm(),
                             action="replace")

    def test_load_bindings(self, tmp_path):
        f = tmp_path / "b.yaml"
        f.write_text(
            "bindings:\n"
            "  - members: [user:x@y.co]\n"
            "    roles: [roles/viewer]\n")
        assert load_bindings(str(f)) == [
            {"members": ["user:x@y.co"], "roles": ["roles/viewer"]}]
        bad = tmp_path / "bad.yaml"
        bad.write_text("nope: 1\n")
        with pytest.raises(ValueError):
            load_bindings(str(bad))


class TestCloudBuildManifest:
    def test_steps_and_images_per_spec(self):
        doc = cloudbuild_manifest(IMAGES, "gcr.io/kf", "v1")
        build_ids = [s["id"] for s in doc["steps"]]
        assert build_ids == [f"build-{s.name}" for s in IMAGES]
        # independent images parallelize: no step waits for all-previous
        assert all(s["waitFor"] == ["-"] for s in doc["steps"])
        assert f"gcr.io/kf/{IMAGES[0].name}:v1" in doc["images"]
        assert f"gcr.io/kf/{IMAGES[0].name}:latest" in doc["images"]

    def test_image_cache_adds_pull_steps(self):
        # template.libsonnet pullStep: waitFor '-' so pulls parallelize
        doc = cloudbuild_manifest(IMAGES[:1], "gcr.io/kf", "v1",
                                  use_image_cache=True)
        pull, build = doc["steps"]
        assert pull["id"] == f"pull-{IMAGES[0].name}"
        assert pull["waitFor"] == ["-"]
        assert "--cache-from" in build["args"]
        assert build["waitFor"] == [pull["id"]]

    def test_build_args_propagate(self):
        [nb] = [s for s in IMAGES if s.name == "jax-notebook-tpu"]
        doc = cloudbuild_manifest((nb,), "gcr.io/kf", "v1")
        assert "JAX_EXTRA=tpu" in doc["steps"][0]["args"][-2]  # --build-arg v
