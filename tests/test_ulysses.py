"""Ulysses attention == reference attention on a seq-sharded mesh, and an
end-to-end trainer step with attention_impl="ulysses"."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.attention import reference_attention
from kubeflow_tpu.ops.ulysses import ulysses_attention
from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh


def make_qkv(b=2, l=32, h=8, hk=8, d=16, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, l, h, d), dtype)
    k = jax.random.normal(ks[1], (b, l, hk, d), dtype)
    v = jax.random.normal(ks[2], (b, l, hk, d), dtype)
    return q, k, v


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ulysses_matches_reference(devices8, sp):
    mesh = build_mesh(MeshSpec(data=1, seq=sp), devices=jax.devices()[:sp])
    q, k, v = make_qkv()
    want = reference_attention(q, k, v, causal=True)
    with mesh:
        got = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_ulysses_with_gqa(devices8):
    mesh = build_mesh(MeshSpec(data=1, seq=4), devices=jax.devices()[:4])
    q, k, v = make_qkv(h=8, hk=2)
    want = reference_attention(q, k, v, causal=True)
    with mesh:
        got = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_ulysses_with_data_and_model_parallel(devices8):
    mesh = build_mesh(MeshSpec(data=2, seq=2, model=2))
    q, k, v = make_qkv(b=4, h=8)
    want = reference_attention(q, k, v, causal=True)
    with mesh:
        got = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_ulysses_falls_back_without_seq_axis(devices8):
    mesh = build_mesh(MeshSpec(data=8))
    q, k, v = make_qkv()
    want = reference_attention(q, k, v, causal=True)
    with mesh:
        got = ulysses_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_indivisible_heads(devices8):
    mesh = build_mesh(MeshSpec(data=2, seq=4))
    q, k, v = make_qkv(h=2, hk=2)
    with pytest.raises(ValueError, match="divisible"):
        with mesh:
            ulysses_attention(q, k, v, mesh=mesh)


def test_trainer_step_with_ulysses(devices8):
    from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

    cfg = TrainConfig.from_dict(dict(
        model="transformer-test",
        model_kwargs={"attention_impl": "ulysses"},
        task="lm",
        global_batch=4,
        seq_len=64,
        vocab_size=256,
        mesh=MeshSpec(data=2, seq=2, model=2),
        total_steps=2,
        warmup_steps=1,
        log_every=1,
        learning_rate=0.01,
    ))
    state, summary = Trainer(cfg).fit(steps=2)
    assert np.isfinite(summary["final"]["loss"])
    assert int(state.step) == 2


def test_ulysses_with_segments_matches_reference(devices8):
    """Packed sequences under Ulysses: seg ids all-gather to full length
    and the local attention masks cross-document pairs."""
    from kubeflow_tpu.ops.attention import reference_attention

    from conftest import make_segments

    seg = make_segments(2, 32, 3)
    mesh = build_mesh(MeshSpec(data=1, seq=4), devices=jax.devices()[:4])
    q, k, v = make_qkv()
    want = reference_attention(q, k, v, causal=True, segment_ids=seg)
    with mesh:
        got = jax.jit(lambda q, k, v, s: ulysses_attention(
            q, k, v, mesh=mesh, segment_ids=s))(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_with_window_matches_reference(devices8):
    from kubeflow_tpu.ops.attention import reference_attention

    mesh = build_mesh(MeshSpec(data=1, seq=4), devices=jax.devices()[:4])
    q, k, v = make_qkv()
    want = reference_attention(q, k, v, causal=True, window=10)
    with mesh:
        got = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, mesh=mesh, window=10))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_window_gradients(devices8):
    from kubeflow_tpu.ops.attention import reference_attention

    mesh = build_mesh(MeshSpec(data=1, seq=4), devices=jax.devices()[:4])
    q, k, v = make_qkv(b=1)

    def f_uly(q, k, v):
        with mesh:
            return (ulysses_attention(q, k, v, mesh=mesh, window=12)
                    .astype(jnp.float32) ** 2).sum()

    def f_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True, window=12)
                .astype(jnp.float32) ** 2).sum()

    g_uly = jax.grad(f_uly, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)
