#!/usr/bin/env bash
# Round-5 persistent hardware watcher (phase 1).
#
# Protocol fixes over round 4 (VERDICT r4 weak #1 + ADVICE r4 #1) live
# in tools/watch_lib.sh (shared with phase 2): bidirectional chip-yield
# via bench.py's atomic pid lockfile (checked between stages AND every
# 5s while a stage is in flight), bounded 90s probes that never run
# under the lock, and a 2-strike skip that only counts deterministic
# failures (rc not a timeout kill, post-failure probe up).
#
# Run from the repo root: nohup bash tools/round5_watch.sh &
set -u
cd "$(dirname "$0")/.."
LOG=tools/round5_watch.log
LEDGER=tools/r5_stages
. tools/watch_lib.sh

while true; do
  if extern_active; then
    note "external bench holds the chip — idling"
    sleep 20
    continue
  fi
  if probe; then
    note "tunnel UP — resuming ledger"
    # 1. Headline validation: the exact command the driver runs.
    run_stage validate_bench 2400 python bench.py
    # 2. MoE hardware point (first gpt-moe-8e measurement).
    run_stage moe_point 1800 python bench.py --workload lm \
      --lm-model gpt-moe-8e --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    # dots OOMs on MoE by 577M (r5 ledger: it pins the [e,cap,d_ff]
    # expert outputs — the one tensor class MoE needs dropped); slim's
    # whitelist recomputes them, bs4 halves them
    run_stage moe_point_slim 1800 python bench.py --workload lm \
      --lm-model gpt-moe-8e --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy slim --lm-xent-chunks 8
    run_stage moe_point_bs4 1800 python bench.py --workload lm \
      --lm-model gpt-moe-8e --lm-batch 4 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    # 3. Serving ledger: prefill chunking, int8 weights, int8 KV,
    #    rolling-cache A/B on a GQA model with a real cache.
    run_stage serve_prefill_per_token 1800 env KFTPU_PREFILL_CHUNK=1 \
      python tools/serve_bench.py --modes micro --requests 16 \
      --param-dtype bfloat16
    run_stage serve_prefill_chunked 1800 python tools/serve_bench.py \
      --modes micro --requests 16 --param-dtype bfloat16
    run_stage serve_cont_bf16 1800 python tools/serve_bench.py \
      --modes continuous --requests 32 --param-dtype bfloat16
    run_stage serve_cont_int8 1800 python tools/serve_bench.py \
      --modes continuous --requests 32 --param-dtype int8
    run_stage serve_kv_bf16 1800 python tools/serve_bench.py \
      --modes continuous --requests 16 --model llama-1b \
      --prompt-len 1024 --max-new-tokens 32 --slots 8 --param-dtype int8
    run_stage serve_kv_int8 1800 python tools/serve_bench.py \
      --modes continuous --requests 16 --model llama-1b \
      --prompt-len 1024 --max-new-tokens 32 --slots 8 \
      --param-dtype int8 --kv-cache-dtype int8
    run_stage serve_win_full 1800 python tools/serve_bench.py \
      --modes continuous --requests 16 --model llama-1b \
      --prompt-len 1024 --max-new-tokens 32 --slots 8 \
      --param-dtype int8 --attention-window 512
    run_stage serve_win_rolling 1800 python tools/serve_bench.py \
      --modes continuous --requests 16 --model llama-1b \
      --prompt-len 1024 --max-new-tokens 32 --slots 8 \
      --param-dtype int8 --attention-window 512 --rolling-kv-cache
    # 4. ResNet byte-wall A/B: whole-forward remat trades the HBM
    #    activation round-trip for VMEM-fused recompute.
    run_stage resnet_remat_full 1800 python bench.py --workload resnet \
      --resnet-remat full
    run_stage resnet_remat_dots 1800 python bench.py --workload resnet \
      --resnet-remat dots
    # 5. Remat-policy frontier (the route toward >=0.55 at 700M+).
    #    tools/remat_plan.py upper bounds (llama-1b bs16): dots = 23.6
    #    GiB saved at 6.5% replay; slim = 11.6 GiB at 58%; full = 2.6
    #    GiB at 100%. bs8 halves activation bytes: dots@bs8 is the
    #    highest-MFU candidate IF it fits.
    run_stage lm_1b_bs8_dots 1800 python bench.py --workload lm \
      --lm-model llama-1b --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    run_stage lm_760m_bs8_dots 1800 python bench.py --workload lm \
      --lm-model gpt-760m --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    run_stage lm_1b_bs8_slim 1800 python bench.py --workload lm \
      --lm-model llama-1b --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy slim --lm-xent-chunks 8
    run_stage lm_1b_bs16_slim 1800 python bench.py --workload lm \
      --lm-model llama-1b --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy slim --lm-xent-chunks 8
    run_stage lm_760m_bs16_slim 1800 python bench.py --workload lm \
      --lm-model gpt-760m --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy slim --lm-xent-chunks 8
    run_stage lm_350m_bs16_dots 1800 python bench.py --workload lm \
      --lm-model gpt-350m --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    run_stage lm_1b_bs16_dots 1800 python bench.py --workload lm \
      --lm-model llama-1b --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    run_stage lm_760m_bs16_dots 1800 python bench.py --workload lm \
      --lm-model gpt-760m --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    run_stage lm_760m_bs8_mlp 1800 python bench.py --workload lm \
      --lm-model gpt-760m --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy mlp --lm-xent-chunks 8
    run_stage lm_760m_bs16_full 1800 python bench.py --workload lm \
      --lm-model gpt-760m --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy full --lm-xent-chunks 8
    run_stage lm_1b_bs16_full 1800 python bench.py --workload lm \
      --lm-model llama-1b --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy full --lm-xent-chunks 8
    run_stage lm_350m_bs16_full 1800 python bench.py --workload lm \
      --lm-model gpt-350m --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy full --lm-xent-chunks 8
    # 6. Op microbenchmark (attributes the remaining MFU gap).
    run_stage microbench 2400 python tools/op_microbench.py \
      --batch 8 --seq 2048
    # 7. Feature-cost A/Bs (sliding window; 8k long-context pair —
    #    windowed points are never promoted).
    run_stage lm_350m_win512 1500 python bench.py --workload lm \
      --lm-model gpt-350m --lm-batch 8 --lm-optimizer adafactor \
      --lm-xent-chunks 8 --lm-window 512
    run_stage lm_350m_8k_full 1800 python bench.py --workload lm \
      --lm-model gpt-350m --lm-batch 2 --seq-len 8192 \
      --lm-optimizer adafactor --lm-remat --lm-remat-policy dots \
      --lm-xent-chunks 16
    run_stage lm_350m_8k_win512 1800 python bench.py --workload lm \
      --lm-model gpt-350m --lm-batch 2 --seq-len 8192 \
      --lm-optimizer adafactor --lm-remat --lm-remat-policy dots \
      --lm-xent-chunks 16 --lm-window 512
    # Promote any measured LM/serving point that beats the ledger floor.
    cat "$LEDGER"/*.out > tools/lm_sweep_r05.jsonl 2>/dev/null || true
    python tools/promote_best.py tools/lm_sweep_r05.jsonl \
      >> "$LOG" 2>&1 || true
    python tools/promote_serve_best.py "$LEDGER"/serve_*.out \
      >> "$LOG" 2>&1 || true
    settled=$(ls "$LEDGER"/*.done "$LEDGER"/*.skip 2>/dev/null | wc -l)
    if [ "$settled" -ge 30 ]; then
      note "all stages settled ($settled done+skip)"
      exit 0
    fi
  else
    note "tunnel down"
  fi
  sleep 230
done
