"""Sidecar lifecycle protocol (reference: openmpi-controller
controller.py) + availability prober."""

import threading
import time

import pytest

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.metric_collector.prober import AvailabilityProber, availability_gauge
from kubeflow_tpu.sidecar.controller import (
    PHASE_FAILED,
    PHASE_SUCCEEDED,
    SIGCONT_FILE,
    SIGTERM_FILE,
    SIGNAL_DIR,
    SidecarController,
)


def make_master(cluster, phase="Running"):
    pod = ob.new_object("v1", "Pod", "job-worker-0", "default",
                        spec={"containers": [{"name": "jax"}]})
    pod["status"] = {"phase": phase}
    return cluster.create(pod)


class TestSidecar:
    def test_ready_handshake_writes_sigcont(self, tmp_path):
        cluster = FakeCluster()
        make_master(cluster)
        copies = []
        ctl = SidecarController(
            tmp_path, master_pod="job-worker-0", client=cluster,
            download=("file://src", "file://dst"),
            copier=lambda s, d: copies.append((s, d)),
            device_check=lambda: True, timeout_s=5, poll_s=0.01,
        )
        with ctl:
            ctl.wait_ready()
            assert (tmp_path / SIGNAL_DIR / SIGCONT_FILE).exists()
            assert copies == [("file://src", "file://dst")]
        # __exit__ always signals termination (:51)
        assert (tmp_path / SIGNAL_DIR / SIGTERM_FILE).exists()

    def test_device_gate_blocks_until_present(self, tmp_path):
        cluster = FakeCluster()
        make_master(cluster)
        state = {"present": False}
        ctl = SidecarController(
            tmp_path, master_pod="job-worker-0", client=cluster,
            device_check=lambda: state["present"], timeout_s=5, poll_s=0.01,
        )

        def flip():
            time.sleep(0.05)
            state["present"] = True

        threading.Thread(target=flip).start()
        with ctl:
            t0 = time.monotonic()
            ctl.wait_ready()
            assert time.monotonic() - t0 >= 0.04
            assert ctl.is_ready()

    def test_device_gate_timeout(self, tmp_path):
        ctl = SidecarController(
            tmp_path, master_pod="m", client=FakeCluster(),
            device_check=lambda: False, timeout_s=0.05, poll_s=0.01,
        )
        with pytest.raises(TimeoutError):
            with ctl:
                ctl.wait_ready()

    def test_wait_done_polls_master_to_terminal(self, tmp_path):
        cluster = FakeCluster()
        master = make_master(cluster, phase="Running")
        uploads = []
        ctl = SidecarController(
            tmp_path, master_pod="job-worker-0", client=cluster,
            upload=("file://out", "gs://bucket/out"),
            copier=lambda s, d: uploads.append((s, d)),
            device_check=lambda: True, timeout_s=5, poll_s=0.01,
        )

        def finish():
            time.sleep(0.05)
            master["status"]["phase"] = PHASE_SUCCEEDED
            cluster.update_status(master)

        threading.Thread(target=finish).start()
        with ctl:
            assert ctl.wait_done() == PHASE_SUCCEEDED
        assert uploads == [("file://out", "gs://bucket/out")]

    def test_master_disappearance_is_failure(self, tmp_path):
        """The reference treats a vanished master as job death (:92-102)."""
        ctl = SidecarController(tmp_path, master_pod="gone", client=FakeCluster(),
                                device_check=lambda: True, timeout_s=1, poll_s=0.01)
        with ctl:
            assert ctl.wait_done() == PHASE_FAILED

    def test_file_copier_local(self, tmp_path):
        from kubeflow_tpu.sidecar.controller import default_copier

        src = tmp_path / "a.txt"
        src.write_text("artifacts")
        default_copier(str(src), str(tmp_path / "out" / "a.txt"))
        assert (tmp_path / "out" / "a.txt").read_text() == "artifacts"


class TestProber:
    def test_probe_sets_gauge(self):
        up = {"dashboard": True, "kfam": False}
        prober = AvailabilityProber(
            {"dashboard": "http://d/healthz", "kfam": "http://k/healthz"},
            checker=lambda url: up["dashboard" if "d/" in url else "kfam"],
        )
        results = prober.probe_once()
        assert results == up
        g = availability_gauge()
        assert g.labels(target="dashboard")._value.get() == 1.0
        assert g.labels(target="kfam")._value.get() == 0.0

    def test_probe_live_http(self):
        from kubeflow_tpu.utils.httpd import HttpService, Router, add_health_routes

        r = Router("t")
        add_health_routes(r)
        svc = HttpService(r, host="127.0.0.1").serve_background()
        try:
            prober = AvailabilityProber(
                {"svc": f"http://127.0.0.1:{svc.port}/healthz",
                 "down": "http://127.0.0.1:1/healthz"})
            out = prober.probe_once()
            assert out == {"svc": True, "down": False}
        finally:
            svc.shutdown()
