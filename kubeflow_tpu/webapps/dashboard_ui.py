"""Central-dashboard frontend: the browser UI over the dashboard API.

The reference ships a Polymer 3 SPA (centraldashboard/public/components/
dashboard-view.js, namespace-selector.js, notebooks-card.js,
resource-chart.js, manage-users-view.js, registration-page.js) behind an
Express server. Here the same views are one dependency-free page served
by the dashboard backend itself: namespace selector, registration flow
(workgroup exists/create), activity feed, contributor management and a
resource chart, all driven by the `/api/workgroup/*`, `/api/activities`
and `/api/metrics` endpoints (webapps/dashboard.py).
"""

from __future__ import annotations

from kubeflow_tpu.utils.httpd import HttpReq, HttpResp

PAGE = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>kubeflow-tpu</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 0; background: #f5f6f8; }
  header { background: #1a73e8; color: #fff; padding: 10px 20px;
           display: flex; align-items: center; gap: 16px; }
  header h1 { font-size: 18px; margin: 0; flex: 1; }
  select, button, input { font-size: 14px; padding: 6px 10px;
                          border-radius: 4px; border: 1px solid #ccc; }
  button { background: #fff; cursor: pointer; }
  main { display: grid; grid-template-columns: 1fr 1fr; gap: 16px;
         padding: 20px; max-width: 1100px; margin: auto; }
  .card { background: #fff; border-radius: 8px; padding: 16px;
          box-shadow: 0 1px 3px rgba(0,0,0,.15); }
  .card h2 { margin: 0 0 10px; font-size: 15px; color: #333; }
  ul { margin: 0; padding-left: 18px; }
  li { margin: 3px 0; font-size: 13px; }
  #register { grid-column: 1 / -1; display: none; }
  .muted { color: #777; font-size: 12px; }
  svg { width: 100%; height: 120px; }
</style>
</head>
<body>
<header>
  <h1>kubeflow-tpu</h1>
  <span class="muted" id="user"></span>
  <select id="ns" title="namespace"></select>
</header>
<main>
  <div class="card" id="register">
    <h2>Welcome — create your workspace</h2>
    <p class="muted">No namespace is registered for your account yet.</p>
    <input id="reg-ns" placeholder="namespace name">
    <button id="reg-btn">Create namespace</button>
    <p id="reg-msg" class="muted"></p>
  </div>
  <div class="card">
    <h2>Activity</h2>
    <ul id="activities"><li class="muted">select a namespace</li></ul>
  </div>
  <div class="card">
    <h2>Contributors</h2>
    <ul id="contributors"></ul>
    <p class="muted">Managed via the access-management (KFAM) API.</p>
  </div>
  <div class="card">
    <h2>Cluster TPU utilization</h2>
    <svg id="chart" viewBox="0 0 300 100" preserveAspectRatio="none"></svg>
    <p class="muted" id="chart-note"></p>
  </div>
  <div class="card">
    <h2>Platform</h2>
    <ul id="envinfo"></ul>
  </div>
</main>
<script>
const $ = (id) => document.getElementById(id);
const api = (p) => fetch(p).then(r => { if (!r.ok) throw r; return r.json(); });

async function loadEnv() {
  const info = await api('/api/workgroup/env-info');
  $('user').textContent = info.user || '';
  const ul = $('envinfo');
  ul.innerHTML = '';
  for (const [k, v] of Object.entries(info.platform || {})) {
    const li = document.createElement('li');
    li.textContent = k + ': ' + v;
    ul.appendChild(li);
  }
  const sel = $('ns');
  sel.innerHTML = '';
  for (const ns of info.namespaces || []) {
    const o = document.createElement('option');
    o.value = o.textContent = typeof ns === 'string' ? ns : ns.namespace;
    sel.appendChild(o);
  }
  if (!(info.namespaces || []).length) {
    $('register').style.display = 'block';
  } else {
    await loadNamespace(sel.value);
  }
}

async function loadNamespace(ns) {
  const acts = await api('/api/activities/' + ns).catch(() => ({events: []}));
  const ul = $('activities');
  ul.innerHTML = '';
  for (const a of (acts.events || []).slice(0, 12)) {
    const li = document.createElement('li');
    li.textContent = (a.lastTimestamp || '') + ' ' + (a.reason || '') + ': ' + (a.message || '');
    ul.appendChild(li);
  }
  if (!ul.children.length) ul.innerHTML = '<li class="muted">no events</li>';
  const contribs = await api('/api/workgroup/get-contributors/' + ns)
    .catch(() => ({contributors: []}));
  const cl = $('contributors');
  cl.innerHTML = '';
  for (const c of contribs.contributors || []) {
    const li = document.createElement('li');
    li.textContent = typeof c === 'string' ? c : (c.user + ' (' + c.role + ')');
    cl.appendChild(li);
  }
  if (!cl.children.length) cl.innerHTML = '<li class="muted">owner only</li>';
}

async function loadChart() {
  try {
    const m = await api('/api/metrics/tpu-chips');
    const pts = (m.values || []).map(p =>
      (typeof p === 'object' ? Number(p.chips ?? p.value ?? 0) : Number(p)));
    if (!pts.length) { $('chart-note').textContent = 'no samples'; return; }
    const max = Math.max(...pts, 1);
    const step = 300 / Math.max(pts.length - 1, 1);
    const d = pts.map((v, i) =>
      (i ? 'L' : 'M') + (i * step).toFixed(1) + ',' +
      (100 - v / max * 90).toFixed(1)).join(' ');
    $('chart').innerHTML =
      '<path d="' + d + '" fill="none" stroke="#1a73e8" stroke-width="2"/>';
    $('chart-note').textContent = m.note || '';
  } catch (e) { $('chart-note').textContent = 'metrics unavailable'; }
}

$('ns').addEventListener('change', (e) => loadNamespace(e.target.value));
$('reg-btn').addEventListener('click', async () => {
  const ns = $('reg-ns').value.trim();
  if (!ns) return;
  const r = await fetch('/api/workgroup/create', {
    method: 'POST',
    headers: {'Content-Type': 'application/json'},
    body: JSON.stringify({namespace: ns}),
  });
  $('reg-msg').textContent = r.ok ? 'created — reloading…' : 'failed: ' + r.status;
  if (r.ok) setTimeout(() => location.reload(), 800);
});

loadEnv().catch(e => { $('user').textContent = 'not signed in'; });
loadChart();
</script>
</body>
</html>
"""


def page(req: HttpReq) -> HttpResp:
    return HttpResp(200, PAGE.encode(), "text/html")


def add_ui_routes(router) -> None:
    router.route("GET", "/", page)
    router.route("GET", "/dashboard", page)
