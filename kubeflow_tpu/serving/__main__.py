from kubeflow_tpu.serving.server import main

main()
