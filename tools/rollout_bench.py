#!/usr/bin/env python
"""rollout_bench — deterministic virtual-time rollout drill.

Builds the REAL rollout plane on one virtual clock — the JAXService
controller (surge -> canary-analyze -> promote | rollback state
machine) over a FakeCluster, a revision-aware TokenRouter fed from the
controller's endpoints annotation, and a FleetPlane scraping the shared
registry with the default + canary rule packs — then runs two drills:

- **good**: a spec edit rolls out a healthy revision. The canary walks
  the weight ladder, every analysis window passes, the base fleet is
  replaced surge-by-surge, and the rollout PROMOTES — with zero request
  drops in any band.
- **bad**: the new revision serves at 10x latency. The store-backed
  ``CanaryAnalysis`` gate (canary latency-quantile vs baseline,
  multi-window) flunks it inside the FIRST analysis window; the
  controller auto-rolls back, the fleet converges on the previous
  revision, and critical-band goodput is held (zero drops).

Both drills log every decision — rollout phase transitions, Rollout*
events, ``jaxservice_rollouts_total`` outcomes, final pod revisions,
per-band drop counts — and the bench fingerprints the combined log.
Correctness is asserted, not eyeballed: a promote that drops requests,
a bad canary that reaches Promote, or a rollback that leaves a pod on
the bad revision raises.

    python tools/rollout_bench.py          # full + smoke, write JSON
    python tools/rollout_bench.py --check  # CI gate: rerun the banked
        # smoke config; fail when the decision fingerprint, outcomes or
        # final revisions drift, or control p99 regresses past 3x budget
"""

from __future__ import annotations

import argparse
import hashlib
import json
import math
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.control.jaxservice import types as T  # noqa: E402
from kubeflow_tpu.control.jaxservice.controller import (  # noqa: E402
    build_controller,
)
from kubeflow_tpu.control.k8s.fake import FakeCluster  # noqa: E402
from kubeflow_tpu.control.k8s.kubelet import FakeKubelet  # noqa: E402
from kubeflow_tpu.control.runtime import seed_controller  # noqa: E402
from kubeflow_tpu.obs.plane import FleetPlane  # noqa: E402
from kubeflow_tpu.obs.rules import (  # noqa: E402
    CanaryAnalysis, canary_rule_pack, default_rule_pack,
)
from kubeflow_tpu.obs.tsdb import RegistryTarget  # noqa: E402
from kubeflow_tpu.runtime.metrics import MetricsRegistry  # noqa: E402
from kubeflow_tpu.serving.router import (  # noqa: E402
    BAND_CRITICAL, BAND_DEFAULT, RegistrySignals, TokenRouter,
    parse_endpoints,
)

DEFAULT_OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_ROLLOUT_r01.json")

CYCLE_S = 5.0
SERVICE = "chat"
NAMESPACE = "default"
REPLICAS = 3
# the rollout knobs under test: one surge slot, capacity never dips,
# a two-step ladder, and a window short enough that the FULL drill
# walks the whole machine inside its cycle budget
ROLLOUT_SPEC = {"maxSurge": 1, "maxUnavailable": 0,
                "canarySteps": [0.3, 1.0],
                "analysisWindowSeconds": 15.0, "autoRollback": True}
# traffic per cycle: enough canary volume at weight 0.3 that the
# analysis gate's min-request floor is conclusive by the second cycle
TRAFFIC = ((BAND_CRITICAL, 5), (BAND_DEFAULT, 15))
BAD_LATENCY_X = 10.0


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _percentile(samples: list[float], q: float) -> float:
    if not samples:
        return 0.0
    xs = sorted(samples)
    return xs[min(len(xs) - 1, int(math.ceil(q * len(xs))) - 1)]


def build_world(clock: ManualClock) -> dict:
    cluster = FakeCluster(history_limit=65536)
    registry = MetricsRegistry()
    signals = RegistrySignals(registry)
    plane = FleetPlane(
        registry=MetricsRegistry(),
        targets=[RegistryTarget("fleet", registry,
                                labels={"job": "serving"})],
        rules=default_rule_pack() + canary_rule_pack(),
        interval_s=CYCLE_S, clock=clock,
        max_points=512, max_series=20000)
    # the SLO gate reads canary-vs-baseline straight from the plane's
    # store; windows sized to the scrape cadence so the short window
    # holds two samples and the long one the whole canary history
    analysis = CanaryAnalysis(
        plane.store, windows_s=(10.0, 25.0), min_requests=4.0,
        max_latency_ratio=3.0)
    ctl = seed_controller(build_controller(
        cluster, record_events=True, registry=registry, signals=signals,
        clock=clock, rollout_analysis=analysis))
    kubelet = FakeKubelet(cluster)
    router = TokenRouter(
        service=SERVICE, namespace=NAMESPACE, clock=clock,
        registry=registry, prom_sink=False,
        max_queue=4096, replica_token_budget=100000)
    svc = T.new_jaxservice(SERVICE, model="gpt-125m",
                           min_replicas=REPLICAS, max_replicas=REPLICAS)
    svc["spec"]["rollout"] = dict(ROLLOUT_SPEC)
    cluster.create(svc)
    return {"cluster": cluster, "ctl": ctl, "kubelet": kubelet,
            "router": router, "registry": registry, "plane": plane}


def control_tick(world: dict, rounds: int = 4) -> None:
    for _ in range(rounds):
        if world["ctl"].run_until_idle(max_rounds=1000,
                                       advance_delayed=True) == 0:
            break
        world["kubelet"].step()


def _service(world: dict) -> dict:
    return world["cluster"].get(T.API_VERSION, T.KIND, SERVICE, NAMESPACE)


def _sync_router(world: dict) -> None:
    world["router"].sync_endpoints(parse_endpoints(_service(world)))


def _stage_traffic(world: dict, clock: ManualClock, rng: random.Random,
                   bad_rev: str, bands: dict) -> None:
    """One cycle of synchronous traffic. Latency is drawn per request
    and multiplied when the serving replica runs the bad revision —
    tickets complete in latency order on the shared clock, so the
    histogram sees exactly the per-revision distributions the analysis
    gate must tell apart."""
    router: TokenRouter = world["router"]
    plan: list[tuple[float, int, object, str]] = []
    for band, count in TRAFFIC:
        for _ in range(count):
            base = rng.uniform(0.05, 0.12)
            # the plan list owns every ticket from submit to complete
            plan.append((base, len(plan), router.submit(40, band=band),
                         band))
            bands[band]["submitted"] += 1
    scored = [(base * BAD_LATENCY_X
               if bad_rev and t.revision == bad_rev else base, seq, t, band)
              for base, seq, t, band in plan]
    elapsed = 0.0
    for lat, _seq, t, band in sorted(scored, key=lambda p: (p[0], p[1])):
        clock.advance(lat - elapsed)
        elapsed = lat
        router.complete(t)
        bands[band]["completed"] += 1


def _pod_revisions(world: dict) -> list[list[str]]:
    out = []
    for pod in world["cluster"].list(
            "v1", "Pod", namespace=NAMESPACE,
            label_selector={T.LABEL_SERVICE_NAME: SERVICE}):
        out.append([pod["metadata"]["name"],
                    (pod["metadata"].get("labels") or {})
                    .get(T.LABEL_REVISION, "")])
    return sorted(out)


def _rollout_events(world: dict) -> list[list]:
    out = []
    for e in world["cluster"].list("v1", "Event", namespace=NAMESPACE):
        reason = e.get("reason", "")
        if reason.startswith("Rollout") or reason == "ReplicaCordoned" \
                or reason == "ReplicaRemoved":
            out.append([reason, e.get("message", ""),
                        int(e.get("count", 1))])
    return sorted(out)


def _outcomes(world: dict) -> dict:
    out = {o: 0.0 for o in T.ROLLOUT_OUTCOMES}
    for labels, value in world["registry"].series(
            "jaxservice_rollouts_total"):
        if labels.get("service") == SERVICE:
            out[labels["outcome"]] = out.get(labels["outcome"], 0) + value
    return {k: round(v, 6) for k, v in sorted(out.items())}


def run_drill(kind: str, cycles: int, seed: int,
              rollout_at: int) -> dict:
    """One drill on a fresh world: ``kind`` is "good" (healthy new
    revision -> promote) or "bad" (10x-latency canary -> auto
    rollback)."""
    clock = ManualClock()
    rng = random.Random(seed)
    world = build_world(clock)
    control_tick(world, rounds=6)  # settle: provision the base fleet
    old_rev = T.revisions_status(_service(world))["current"]

    bands = {band: {"submitted": 0, "completed": 0}
             for band, _ in TRAFFIC}
    phase_log: list[list] = []
    control_ms: list[float] = []
    max_pods = 0
    new_rev = ""
    analyze_at = abort_at = None
    for cycle in range(cycles):
        cycle_start = clock.t
        if cycle == rollout_at:
            svc = _service(world)
            svc["spec"]["model"]["ref"] = "gpt-125m-v2"
            world["cluster"].update(svc)
            new_rev = T.revision_hash(svc["spec"])
        _sync_router(world)
        bad_rev = new_rev if kind == "bad" else ""
        _stage_traffic(world, clock, rng, bad_rev, bands)
        world["plane"].tick(at=clock.t)
        t0 = time.perf_counter()
        control_tick(world)
        control_ms.append((time.perf_counter() - t0) * 1e3)
        rev = T.revisions_status(_service(world))
        entry = [cycle, rev["phase"], rev["step"], rev["target"]]
        if not phase_log or phase_log[-1][1:] != entry[1:]:
            phase_log.append(entry)
            if rev["phase"] == T.PHASE_ANALYZE and analyze_at is None:
                analyze_at = clock.t
        # Rollback drains instantly here (in-flight is zero between
        # cycles), so the phase flashes through inside one control tick
        # — the abort moment is read off the outcome counter instead
        if abort_at is None and _outcomes(world)["aborted"] >= 1:
            abort_at = clock.t
        max_pods = max(max_pods, len(_pod_revisions(world)))
        clock.advance(CYCLE_S - (clock.t - cycle_start))

    rev = T.revisions_status(_service(world))
    pods = _pod_revisions(world)
    outcomes = _outcomes(world)
    drops = {band: c["submitted"] - c["completed"]
             for band, c in sorted(bands.items())}

    # -- the drill's reason to exist: assert, don't eyeball ------------------
    assert new_rev and new_rev != old_rev, "spec edit did not re-hash"
    assert max_pods <= REPLICAS + ROLLOUT_SPEC["maxSurge"], \
        f"capacity oversubscribed: {max_pods} pods"
    assert drops[BAND_CRITICAL] == 0, \
        f"critical-band drops: {drops[BAND_CRITICAL]}"
    if kind == "good":
        assert outcomes == {"aborted": 0.0, "promoted": 1.0,
                            "rolled_back": 0.0}, outcomes
        assert rev["current"] == new_rev and rev["phase"] == T.PHASE_IDLE
        assert all(r == new_rev for _, r in pods), pods
        assert all(d == 0 for d in drops.values()), drops
    else:
        assert outcomes == {"aborted": 1.0, "promoted": 0.0,
                            "rolled_back": 1.0}, outcomes
        assert rev["current"] == old_rev and rev["aborted"] == new_rev
        assert rev["phase"] == T.PHASE_IDLE
        assert not any(r == new_rev for _, r in pods), pods
        assert all(d == 0 for d in drops.values()), drops
        # "inside the analysis window": the gate flunked the canary
        # before the ladder ever advanced past its first step
        assert abort_at is not None and analyze_at is not None
        window = ROLLOUT_SPEC["analysisWindowSeconds"]
        assert abort_at - analyze_at <= window + CYCLE_S, \
            f"rollback {abort_at - analyze_at:.1f}s after analyze " \
            f"opened (window {window}s)"
        assert not any(p[1] == T.PHASE_PROMOTE for p in phase_log) \
            and max((p[2] for p in phase_log
                     if p[1] == T.PHASE_ANALYZE), default=0) == 0, \
            "bad canary advanced the ladder before the gate caught it"
    assert len(pods) == REPLICAS, pods

    return {
        "kind": kind,
        "old_rev": old_rev,
        "new_rev": new_rev,
        "phases": phase_log,
        "events": _rollout_events(world),
        "outcomes": outcomes,
        "final": {"current": rev["current"], "previous": rev["previous"],
                  "aborted": rev["aborted"], "phase": rev["phase"]},
        "pods": pods,
        "bands": {b: dict(sorted(c.items()))
                  for b, c in sorted(bands.items())},
        "drops": drops,
        "max_pods": max_pods,
        "control_ms": control_ms,
    }


def run_bench(cycles: int, seed: int = 0, rollout_at: int = 4) -> dict:
    good = run_drill("good", cycles, seed, rollout_at)
    bad = run_drill("bad", cycles, seed, rollout_at)
    control_ms = good.pop("control_ms") + bad.pop("control_ms")
    decision_log = json.dumps({"good": good, "bad": bad}, sort_keys=True)
    return {
        "config": {"cycles": cycles, "seed": seed,
                   "rollout_at": rollout_at},
        "good": good,
        "bad": bad,
        "decision_fingerprint": hashlib.sha256(
            decision_log.encode()).hexdigest(),
        # wall-clock timings live apart from the deterministic body so
        # a double-run byte-compares everything else
        "machine": {
            "control_p50_ms": round(_percentile(control_ms, 0.50), 3),
            "control_p99_ms": round(_percentile(control_ms, 0.99), 3),
        },
    }


# FULL walks the whole good-rollout ladder with idle margin on both
# sides; SMOKE is the CI-gate config — the minimum cycles that still
# promote the good revision and roll back the bad one.
FULL_CONFIG = {"cycles": 24, "seed": 0, "rollout_at": 4}
SMOKE_CONFIG = {"cycles": 16, "seed": 0, "rollout_at": 3}


def check_against(banked_path: str) -> int:
    """CI ratchet: rerun the banked smoke config. Fail (1) when the
    decision fingerprint, the rollout outcomes, the final revision
    state or the drop counts drift (the machine DECIDED differently on
    identical input), or when control p99 regresses past 3x the
    committed budget (floored at 250 ms so CI contention cannot flake
    the gate)."""
    with open(banked_path) as fh:
        banked = json.load(fh)
    smoke = banked.get("smoke")
    if not smoke:
        print(f"check: no smoke section in {banked_path}",
              file=sys.stderr)
        return 2
    now = run_bench(**smoke["config"])
    ok = True
    if now["decision_fingerprint"] != smoke["decision_fingerprint"]:
        print("check: decision fingerprint drifted "
              f"({now['decision_fingerprint'][:12]} != banked "
              f"{smoke['decision_fingerprint'][:12]}) — the rollout "
              "machine decided differently on identical input",
              file=sys.stderr)
        ok = False
    for drill in ("good", "bad"):
        for key in ("outcomes", "final", "pods", "drops", "phases"):
            if now[drill][key] != smoke[drill][key]:
                print(f"check: {drill}.{key} {now[drill][key]!r} != "
                      f"banked {smoke[drill][key]!r}", file=sys.stderr)
                ok = False
    budget = max(smoke["machine"]["control_p99_ms"] * 3.0, 250.0)
    if now["machine"]["control_p99_ms"] > budget:
        print(f"check: control_p99_ms {now['machine']['control_p99_ms']}"
              f" exceeds budget {budget:.3f} (banked "
              f"{smoke['machine']['control_p99_ms']})", file=sys.stderr)
        ok = False
    print(json.dumps({"check": "ok" if ok else "REGRESSED",
                      "control_p99_ms": now["machine"]["control_p99_ms"],
                      "fingerprint": now["decision_fingerprint"][:12]},
                     indent=2))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cycles", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=DEFAULT_OUT)
    ap.add_argument("--no-smoke", action="store_true")
    ap.add_argument("--check", action="store_true",
                    help="rerun the banked smoke config and gate on "
                         "fingerprint/outcome/revision drift or a "
                         ">3x p99 budget regression")
    args = ap.parse_args(argv)
    if args.check:
        return check_against(args.out)

    config = dict(FULL_CONFIG, seed=args.seed)
    if args.cycles:
        config["cycles"] = args.cycles
    full = run_bench(**config)
    result = {"bench": "rollout_bench", "round": "r01", "full": full}
    if not args.no_smoke:
        result["smoke"] = run_bench(**SMOKE_CONFIG)
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps({
        "out": args.out,
        "good": full["good"]["outcomes"],
        "bad": full["bad"]["outcomes"],
        "bad_final": full["bad"]["final"],
        "control_p99_ms": full["machine"]["control_p99_ms"]}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
