"""FleetPlane — the assembled observability plane, one handle.

Bundles the ISSUE-10 layers (``tsdb`` scrape plane, ``rules`` engine,
``goodput`` accounting) plus the ISSUE-13 closing-the-loop layers —
the alert-driven ``RemediationEngine``, per-alert routing, and
silences — behind the object the dashboard routes (``GET /api/alerts``
/ ``/api/query`` / ``/api/goodput`` / ``/api/silences``) and
``run_controller``-style mains wire up. Hermetic harnesses build their
own with fake clocks; a process that just wants "the plane" uses the
module-level ``default_plane()`` singleton (the REGISTRY/COLLECTOR/
TRACER convention from runtime/metrics.py and obs/trace.py).

Routing and silences follow Alertmanager's split: a *route* maps an
alert (by severity and label matchers) to a receiver name — operators
read it off ``route_for``; a *silence* (matchers + expiry) mutes
notification and remediation for matching alerts WITHOUT touching the
alert state machine, so un-silencing reveals true current state.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from kubeflow_tpu.obs import goodput as gp
from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.obs.rules import RuleEngine, default_rule_pack
from kubeflow_tpu.obs.tsdb import ScrapeLoop, Target, TimeSeriesStore


@dataclass
class Route:
    """severity + label matchers -> receiver. First match wins; a
    ``severity`` of "" matches every severity."""

    receiver: str
    severity: str = ""
    matchers: dict = field(default_factory=dict)


DEFAULT_ROUTES = (
    Route(receiver="page", severity="critical"),
    Route(receiver="ticket", severity="warning"),
    Route(receiver="log"),
)


class SilenceStore:
    """Bounded set of active silences (id, matchers, until, comment).

    ``silenced(alertname, labels, at)`` is the predicate both the rule
    engine (Events) and the remediation engine (actions) consult; a
    matcher key of ``alertname`` matches the rule name, every other
    key matches the alert's labels. Expired silences are pruned on
    every read."""

    def __init__(self, clock: Callable[[], float] = time.time,
                 limit: int = 256):
        self.clock = clock
        self.limit = limit
        self._lock = threading.Lock()
        self._silences: dict[str, dict] = {}
        self._ids = itertools.count(1)

    def add(self, matchers: dict, until: float,
            comment: str = "", created_by: str = "") -> dict:
        if not matchers:
            raise ValueError("a silence needs at least one matcher")
        with self._lock:
            self._prune(self.clock())
            if len(self._silences) >= self.limit:
                raise ValueError("silence store full")
            sid = f"s{next(self._ids)}"
            entry = {"id": sid,
                     "matchers": {str(k): str(v)
                                  for k, v in matchers.items()},
                     "until": float(until), "comment": comment,
                     "createdBy": created_by,
                     "createdAt": self.clock()}
            self._silences[sid] = entry
            return dict(entry)

    def delete(self, sid: str) -> bool:
        with self._lock:
            return self._silences.pop(sid, None) is not None

    def list(self, at: float | None = None) -> list[dict]:
        now = self.clock() if at is None else at
        with self._lock:
            self._prune(now)
            return [dict(s) for _, s in sorted(self._silences.items())]

    def silenced(self, alertname: str, labels: dict,
                 at: float | None = None) -> bool:
        now = self.clock() if at is None else at
        with self._lock:
            self._prune(now)
            for s in self._silences.values():
                if all(alertname == v if k == "alertname"
                       else (labels or {}).get(k) == v
                       for k, v in s["matchers"].items()):
                    return True
        return False

    def _prune(self, now: float) -> None:
        dead = [sid for sid, s in self._silences.items()
                if s["until"] <= now]
        for sid in dead:
            del self._silences[sid]


class FleetPlane:
    """store + scraper + rule engine + goodput reads + remediation,
    one lifecycle.

    ``tick()`` is the deterministic unit (one scrape cycle + one rule
    pass + one remediation pass at the shared clock) — drills, tests
    and the bench drive it on virtual time; ``start()``/``stop()`` run
    it on wall time."""

    def __init__(self, registry=None, recorder=None,
                 targets: list[Target] = (),
                 discover: Callable[[], list[Target]] | None = None,
                 rules: list | None = None,
                 interval_s: float = 15.0,
                 clock: Callable[[], float] = time.time,
                 collector: "obs_trace.TraceCollector | None" = None,
                 max_points: int = 512, max_series: int = 50000,
                 lookback_s: float | None = None,
                 remediator=None,
                 routes: tuple = DEFAULT_ROUTES):
        from kubeflow_tpu.runtime.metrics import REGISTRY

        self.registry = registry if registry is not None else REGISTRY
        self.clock = clock
        self.collector = collector if collector is not None \
            else obs_trace.COLLECTOR
        self.store = TimeSeriesStore(max_points=max_points,
                                     max_series=max_series)
        self.scraper = ScrapeLoop(
            self.store, targets=targets, discover=discover,
            interval_s=interval_s, clock=clock, registry=self.registry)
        self.silences = SilenceStore(clock=clock)
        # instant-selector lookback tracks the scrape interval: a
        # series is "current" while it misses fewer than ~4 scrapes
        self.engine = RuleEngine(
            self.store,
            rules=default_rule_pack() if rules is None else rules,
            recorder=recorder, registry=self.registry, clock=clock,
            lookback_s=(lookback_s if lookback_s is not None
                        else max(interval_s * 4, 60.0)),
            silenced=self.silences.silenced)
        # alert-driven remediation (obs/remediate.py). The plane owns
        # the silence hookup so an operator's POST /api/silences mutes
        # both notification AND action in one move.
        self.remediator = remediator
        if remediator is not None and remediator.silenced is None:
            remediator.silenced = self.silences.silenced
        self.routes: tuple = tuple(routes)
        self.slos = [gp.ServingSLO()]
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- deterministic core --------------------------------------------------

    def tick(self, at: float | None = None) -> dict:
        """One scrape + rule pass + remediation pass; returns
        {'scrape': ..., 'transitions': [...], 'remediations': [...]} —
        the unit the benches fingerprint."""
        scrape = self.scraper.scrape_once()
        transitions = self.engine.evaluate_once(at=at)
        remediations: list = []
        if self.remediator is not None:
            remediations = self.remediator.observe(transitions, at=at)
        return {"scrape": scrape, "transitions": transitions,
                "remediations": remediations}

    # -- routing -------------------------------------------------------------

    def route_for(self, alertname: str, severity: str,
                  labels: dict | None = None) -> str:
        """First-match routing: the receiver this alert notifies."""
        for r in self.routes:
            if r.severity and r.severity != severity:
                continue
            if any((labels or {}).get(k) != v
                   for k, v in r.matchers.items()):
                continue
            return r.receiver
        return "log"

    # -- dashboard reads -----------------------------------------------------

    def alerts(self) -> dict:
        out = self.engine.active_alerts()
        by_name = {r.name: r for r in self.engine.rules
                   if hasattr(r, "severity")}
        now = self.clock()
        for a in out:
            rule = by_name.get(a["alert"])
            a["severity"] = rule.severity if rule else "warning"
            a["receiver"] = self.route_for(
                a["alert"], a["severity"], a["labels"])
            a["silenced"] = self.silences.silenced(
                a["alert"], a["labels"], now)
        return {"alerts": out}

    def query(self, text: str, at: float | None = None) -> dict:
        result = self.engine.query(text, at=at)
        return {"query": text,
                "result": [{"labels": labels, "value": value}
                           for labels, value in result]}

    def goodput(self, chips: int = 1, window_s: float | None = None,
                at: float | None = None) -> dict:
        """Training goodput from the span stream + serving SLO status
        from the TSDB — the /api/goodput body."""
        spans = self.collector.spans()
        report = gp.job_report(spans, chips=chips)
        now = self.clock() if at is None else at
        slos = [slo.from_store(self.store, now,
                               window_s=window_s or 300.0)
                for slo in self.slos]
        return {"training": report.check().to_dict(), "serving": slos}

    def remediation_audit(self) -> dict:
        if self.remediator is None:
            return {"audit": []}
        return {"audit": self.remediator.audit()}

    def chargeback(self, window_s: float = 300.0,
                   at: float | None = None,
                   chips_by_tenant: dict | None = None,
                   default_chips: int = 1) -> dict:
        """The per-tenant bill over the trailing window — the
        /api/chargeback body. For every tenant seen in the span stream,
        the TSDB's tenant-labeled router series, or the remediation
        audit: goodput %, chip-seconds lost by cause (the conservation-
        checked ledger cut — ``TenantLedger.check`` raises rather than
        publish an invoice that doesn't add up to the fleet ledger),
        SLO attainment, and the remediation actions its alerts
        triggered."""
        now = self.clock() if at is None else at
        start = max(now - max(window_s, 0.0), 0.0)
        ledger = gp.tenant_report(
            self.collector.spans(), start, now,
            chips_by_tenant=chips_by_tenant,
            default_chips=default_chips).check()
        tenants = set(ledger.reports)
        for labels, _v in self.engine.query(
                "sum by (tenant) (router_requests_total)", at=now):
            if labels.get("tenant"):
                tenants.add(labels["tenant"])
        audit = (self.remediator.audit()
                 if self.remediator is not None else [])
        remediations: dict[str, int] = {}
        for decision in audit:
            if decision.get("at") is not None \
                    and not (start <= decision["at"] <= now):
                continue
            tenant = decision.get("tenant") or "default"
            remediations[tenant] = remediations.get(tenant, 0) + 1
        tenants.update(remediations)
        out: dict = {
            "window_s": round(max(window_s, 0.0), 6),
            "at": round(now, 6),
            "chips": ledger.chips,
            "tenants": {},
        }
        for tenant in sorted(tenants):
            report = ledger.reports.get(tenant)
            slos = [slo.from_store(self.store, now,
                                   window_s=max(window_s, 1.0),
                                   tenant=tenant)
                    for slo in self.slos]
            out["tenants"][tenant] = {
                "goodput": (report.check().to_dict()
                            if report is not None else None),
                "slo": slos,
                "remediations": remediations.get(tenant, 0),
            }
        return out

    # -- thread shell --------------------------------------------------------

    def start(self) -> "FleetPlane":  # pragma: no cover - thread shell
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="obs-plane", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:  # pragma: no cover - thread shell
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.scraper.interval_s + 5)
            self._thread = None

    def _run(self) -> None:  # pragma: no cover - thread shell
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # the plane must outlive a bad pass
                import logging

                logging.getLogger("kubeflow_tpu.obs.plane").exception(
                    "plane tick failed")
            self._stop.wait(self.scraper.interval_s)


_default: FleetPlane | None = None
_default_lock = threading.Lock()


def default_plane() -> FleetPlane:
    """The process-wide plane (lazily built, self-scraping the global
    MetricsRegistry). The dashboard serves this one unless handed
    another. STARTED on first build — a plane that is never ticked
    would serve a permanently empty store and a silent alert surface,
    which is worse than no plane at all."""
    global _default
    with _default_lock:
        if _default is None:
            from kubeflow_tpu.obs.tsdb import RegistryTarget
            from kubeflow_tpu.runtime.metrics import REGISTRY

            _default = FleetPlane(
                targets=[RegistryTarget("self", REGISTRY)]).start()
        return _default
