"""Crash-consistent small-file writes — ONE spelling of temp + fsync +
rename, shared by every layer that persists state it may be killed while
writing (the preemption steady state): the checkpoint resume manifest
(runtime/checkpoint.py) and the launcher's exit-time trace dump
(obs/trace.py). jax-free on purpose: obs/ must stay importable without
the training runtime.
"""

from __future__ import annotations

import os


def atomic_write_text(path: str, text: str) -> None:
    """Write ``text`` to ``path`` so a reader never observes a truncated
    file — it sees the old content or the new, nothing between. The temp
    file lives in the SAME directory (os.replace must not cross
    filesystems); a mid-write kill leaves at worst a stale ``.tmp``
    sibling, never a corrupt live file. The directory fd is fsynced
    after the rename (best-effort: not all filesystems allow it) so the
    rename itself is durable, not just the data."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    tmp = os.path.join(directory,
                       f".{os.path.basename(path)}.{os.getpid()}.tmp")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        with os.fdopen(fd, "w") as fh:
            fh.write(text)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        dir_fd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
    except OSError:
        pass
