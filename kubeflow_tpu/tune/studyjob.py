"""StudyJob controller: HPO sweeps where every trial is a JAXJob.

Contract preserved from the reference's consumer
(testing/katib_studyjob_test.py): `status.conditions[].type` reaches
"Running" while trials execute and "Succeeded"/"Failed" terminally; the
E2E polls exactly that (:128-194). Spec shape follows the katib
v1alpha1 StudyJob the test submits: objective + parameter space +
suggestion algorithm + trial template.

Search algorithms: grid and random (the two the reference example used),
plus the two the katib of that era shipped beyond them: bayesian
optimization (here a TPE-flavored exploit/explore sampler over completed
trials — no GP dependency) and hyperband-style successive halving
(budget rungs with top-1/eta promotion; the trial template receives the
rung budget through ``${budget}``).
Trial metrics: trials publish their objective through the
``studyjob.kubeflow.org/objective-value`` annotation on their JAXJob
(written by jaxrt's launcher via its summary line, or by the test); an
injectable collector lets other transports plug in.
"""

from __future__ import annotations

import itertools
import json
import logging
import random as _random
from typing import Any, Callable

from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.runtime import Controller, Reconciler, Request, Result

log = logging.getLogger("kubeflow_tpu.studyjob")

GROUP = "kubeflow.org"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "StudyJob"

ANNO_OBJECTIVE = "studyjob.kubeflow.org/objective-value"
ANNO_PARAMETERS = "studyjob.kubeflow.org/parameters"
LABEL_STUDY = "studyjob.kubeflow.org/study-name"
LABEL_TRIAL = "studyjob.kubeflow.org/trial-id"

COND_RUNNING = "Running"
COND_SUCCEEDED = "Succeeded"
COND_FAILED = "Failed"


def new_studyjob(
    name: str,
    namespace: str = "default",
    *,
    objective: str = "loss",
    goal: str = "minimize",
    algorithm: str = "grid",
    parameters: list[dict] | None = None,
    trial_template: dict | None = None,
    max_trials: int = 4,
    parallel_trials: int = 2,
    seed: int = 0,
) -> dict:
    return ob.new_object(
        API_VERSION, KIND, name, namespace,
        spec={
            "objective": {"objectiveMetricName": objective, "type": goal},
            "algorithm": {"algorithmName": algorithm, "seed": seed},
            "parameters": parameters or [],
            "trialTemplate": trial_template or {},
            "maxTrialCount": max_trials,
            "parallelTrialCount": parallel_trials,
        },
    )


def crd_manifest() -> dict:
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"studyjobs.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {"kind": KIND, "listKind": "StudyJobList",
                      "plural": "studyjobs", "singular": "studyjob"},
            "scope": "Namespaced",
            "versions": [{
                "name": VERSION, "served": True, "storage": True,
                "subresources": {"status": {}},
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "x-kubernetes-preserve-unknown-fields": True}},
            }],
        },
    }


# ---------------------------------------------------------------------------
# suggestion algorithms


def _param_values(p: dict) -> list[Any]:
    ptype = p.get("parameterType", p.get("type", "categorical"))
    feas = p.get("feasible") or {}
    if ptype in ("categorical", "discrete"):
        return list(feas.get("list") or p.get("list") or [])
    lo, hi = float(feas.get("min", 0)), float(feas.get("max", 1))
    steps = int(feas.get("steps", 3))
    if ptype == "int":
        vals = sorted({round(lo + (hi - lo) * i / max(steps - 1, 1))
                       for i in range(steps)})
        return [int(v) for v in vals]
    return [lo + (hi - lo) * i / max(steps - 1, 1) for i in range(steps)]


def grid_suggestions(parameters: list[dict], max_trials: int) -> list[dict]:
    names = [p["name"] for p in parameters]
    spaces = [_param_values(p) for p in parameters]
    combos = itertools.product(*spaces) if spaces else iter([()])
    return [dict(zip(names, c)) for c in itertools.islice(combos, max_trials)]


def random_suggestions(parameters: list[dict], max_trials: int, seed: int = 0) -> list[dict]:
    rng = _random.Random(seed)
    out = []
    for _ in range(max_trials):
        pick = {}
        for p in parameters:
            ptype = p.get("parameterType", p.get("type", "categorical"))
            feas = p.get("feasible") or {}
            if ptype in ("categorical", "discrete"):
                pick[p["name"]] = rng.choice(list(feas.get("list") or p.get("list")))
            elif ptype == "int":
                pick[p["name"]] = rng.randint(int(feas.get("min", 0)),
                                              int(feas.get("max", 1)))
            else:
                pick[p["name"]] = rng.uniform(float(feas.get("min", 0.0)),
                                              float(feas.get("max", 1.0)))
        out.append(pick)
    return out


def bayes_suggestions(parameters: list[dict], max_trials: int, seed: int,
                      observations: list[dict] | None = None,
                      goal: str = "minimize") -> list[dict]:
    """Sequential model-based search, TPE-flavored: the first quarter of
    the budget explores uniformly; afterwards each suggestion either
    perturbs a random top-quartile observed config (exploit, sigma =
    1/8 of the range, decaying with trial index) or samples uniformly
    (explore, 20%). Launched trials pin their params in an annotation,
    so re-deriving the tail as observations arrive is safe."""
    out = random_suggestions(parameters, max_trials, seed)
    obs = [o for o in (observations or []) if o.get("objective") is not None]
    if len(obs) < 2:
        return out
    obs = sorted(obs, key=lambda o: o["objective"],
                 reverse=(goal == "maximize"))
    top = [o["parameters"] for o in obs[:max(1, len(obs) // 4)]]
    n_init = max(2, max_trials // 4)
    for i in range(max(n_init, len(obs)), max_trials):
        rng_i = _random.Random((seed, i, len(obs)).__hash__())
        if rng_i.random() < 0.2:
            continue  # keep the uniform-explore sample from the skeleton
        base = rng_i.choice(top)
        pick = {}
        for p in parameters:
            name = p["name"]
            ptype = p.get("parameterType", p.get("type", "categorical"))
            feas = p.get("feasible") or {}
            anchor = base.get(name)
            if ptype in ("categorical", "discrete"):
                choices = list(feas.get("list") or p.get("list"))
                pick[name] = anchor if (anchor in choices
                                        and rng_i.random() < 0.8) \
                    else rng_i.choice(choices)
                continue
            lo, hi = float(feas.get("min", 0.0)), float(feas.get("max", 1.0))
            sigma = (hi - lo) / 8.0 / (1.0 + i / max(max_trials, 1))
            try:
                center = float(anchor)
            except (TypeError, ValueError):
                center = (lo + hi) / 2
            val = min(hi, max(lo, rng_i.gauss(center, sigma)))
            pick[name] = int(round(val)) if ptype == "int" else val
        out[i] = pick
    return out


def sha_rungs(algo: dict) -> tuple[list[int], int]:
    """Budget ladder for successive halving: minBudget * eta^r up to
    maxBudget; returns (rungs, eta)."""
    eta = max(2, int(algo.get("reduction", 2)))
    bmin = max(1, int(algo.get("minBudget", 1)))
    bmax = max(bmin, int(algo.get("maxBudget", bmin * eta ** 2)))
    rungs = [bmin]
    while rungs[-1] * eta <= bmax:
        rungs.append(rungs[-1] * eta)
    return rungs, eta


def sha_bracket(max_trials: int, rungs: list[int], eta: int) -> int:
    """Initial rung size n0 so the WHOLE bracket (rung r runs
    max(1, n0 // eta^r) trials — the promotion rule below) stays within
    maxTrialCount: katib's cap is total trials, not initial configs."""
    for n0 in range(max(1, max_trials), 1, -1):
        total, n = 0, n0
        for _ in rungs:
            total += n
            n = max(1, n // eta)
        if total <= max_trials:
            return n0
    return 1


def sha_suggestions(parameters: list[dict], max_trials: int, seed: int,
                    observations: list[dict] | None = None,
                    goal: str = "minimize",
                    algo: dict | None = None) -> list[dict]:
    """Hyperband-style successive halving (single bracket): the largest
    n0 whose full bracket fits maxTrialCount runs at the smallest
    budget; when a rung completes, the top 1/eta configs re-run at
    eta x the budget. Every suggestion carries a ``budget`` param for
    the trial template's ``${budget}`` token."""
    rungs, eta = sha_rungs(algo or {})
    # a ladder longer than the trial budget can't fit even at n0=1 (one
    # trial per rung): drop the top rungs so the cap always holds
    rungs = rungs[:max(1, max_trials)]
    n0 = sha_bracket(max_trials, rungs, eta)
    out = [dict(c, budget=rungs[0])
           for c in random_suggestions(parameters, n0, seed)]
    for r in range(1, len(rungs)):
        prev_budget = rungs[r - 1]
        expected = len([s for s in out if s["budget"] == prev_budget])
        done_prev = [o for o in (observations or [])
                     if int(o["parameters"].get("budget", -1)) == prev_budget]
        if len(done_prev) < expected:
            break  # rung still running; promotions appear when it drains
        # failed / metric-less trials count toward the drain above but are
        # never promoted: promote the top 1/eta of the *survivors*
        survivors = [o for o in done_prev if o.get("objective") is not None]
        keep = min(max(1, expected // eta), len(survivors))
        survivors.sort(key=lambda o: o["objective"],
                       reverse=(goal == "maximize"))
        for o in survivors[:keep]:
            cfg = {k: v for k, v in o["parameters"].items() if k != "budget"}
            out.append(dict(cfg, budget=rungs[r]))
    return out


def _substitute(obj: Any, params: dict) -> Any:
    """${param} substitution in the trial template (katib's
    go-template analogue). Full-string matches keep native types."""
    if isinstance(obj, dict):
        return {k: _substitute(v, params) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_substitute(v, params) for v in obj]
    if isinstance(obj, str):
        for k, v in params.items():
            token = "${" + k + "}"
            if obj == token:
                return v
            if token in obj:
                obj = obj.replace(token, str(v))
        return obj
    return obj


def default_collector(job: dict) -> float | None:
    """Read the objective off the trial JAXJob's annotation."""
    val = ob.annotations_of(job).get(ANNO_OBJECTIVE)
    if val is None:
        return None
    try:
        return float(val)
    except ValueError:
        return None


class StudyJobReconciler(Reconciler):
    def __init__(self, collector: Callable[[dict], float | None] = default_collector):
        self.collector = collector

    def _suggestions(self, study: dict,
                     observations: list[dict] | None = None) -> list[dict]:
        spec = study["spec"]
        algo_spec = spec.get("algorithm") or {}
        algo = algo_spec.get("algorithmName", "grid")
        max_trials = spec.get("maxTrialCount", 4)
        params = spec.get("parameters") or []
        seed = algo_spec.get("seed", 0)
        goal = (spec.get("objective") or {}).get("type", "minimize")
        if algo == "random":
            return random_suggestions(params, max_trials, seed)
        if algo == "grid":
            return grid_suggestions(params, max_trials)
        if algo in ("bayesianoptimization", "bayes"):
            return bayes_suggestions(params, max_trials, seed,
                                     observations, goal)
        if algo in ("hyperband", "successivehalving"):
            return sha_suggestions(params, max_trials, seed,
                                   observations, goal, algo_spec)
        raise ValueError(f"unknown algorithmName {algo!r} "
                         "(grid|random|bayesianoptimization|hyperband)")

    def trial_name(self, study: dict, idx: int) -> str:
        return f"{ob.meta(study)['name']}-trial-{idx}"

    def generate_trial(self, study: dict, idx: int, params: dict) -> dict:
        m = ob.meta(study)
        tmpl = ob.deep_copy((study["spec"].get("trialTemplate") or {}))
        tmpl = _substitute(tmpl, params)
        job = {
            "apiVersion": JT.API_VERSION,
            "kind": JT.KIND,
            "metadata": {
                "name": self.trial_name(study, idx),
                "namespace": m["namespace"],
                "labels": {LABEL_STUDY: m["name"], LABEL_TRIAL: str(idx)},
                "annotations": {
                    ANNO_PARAMETERS: json.dumps(params)},
            },
            "spec": tmpl.get("spec", tmpl) or {
                "replicas": 1,
                "template": {"spec": {"containers": [
                    {"name": "jax", "image": "kubeflow-tpu/jaxrt:latest"}]}},
            },
        }
        return job

    def reconcile(self, client, req: Request) -> Result | None:
        study = client.get_or_none(API_VERSION, KIND, req.name, req.namespace)
        if study is None or ob.meta(study).get("deletionTimestamp"):
            return None
        if ob.cond_is_true(study, COND_SUCCEEDED) or ob.cond_is_true(study, COND_FAILED):
            return None

        spec = study["spec"]
        parallel = spec.get("parallelTrialCount", 2)

        trials = client.list(
            JT.API_VERSION, JT.KIND, namespace=req.namespace,
            label_selector={"matchLabels": {LABEL_STUDY: req.name}},
        )
        by_idx = {int(ob.labels_of(t)[LABEL_TRIAL]): t for t in trials}

        n_done = n_failed = n_active = 0
        results: list[dict] = []
        for idx, t in by_idx.items():
            succeeded = ob.cond_is_true(t, JT.COND_SUCCEEDED)
            if succeeded or ob.cond_is_true(t, JT.COND_FAILED):
                # failed trials observe objective None: they count toward
                # rung drain in successive halving but are never promoted
                n_done, n_failed = n_done + succeeded, n_failed + (not succeeded)
                results.append({
                    "trial": ob.meta(t)["name"],
                    "parameters": json.loads(
                        ob.annotations_of(t).get(ANNO_PARAMETERS, "{}")),
                    "objective": self.collector(t) if succeeded else None,
                })
            else:
                n_active += 1

        # sequential algorithms (bayes, successive halving) grow/refine the
        # suggestion list from completed-trial observations
        try:
            suggestions = self._suggestions(study, results)
        except ValueError as e:
            ob.cond_set(study, COND_FAILED, "True", "BadAlgorithm", str(e))
            client.update_status(study)
            return None

        # launch next trials up to parallelism
        next_idx = max(by_idx) + 1 if by_idx else 0
        while n_active < parallel and next_idx < len(suggestions):
            trial = self.generate_trial(study, next_idx, suggestions[next_idx])
            ob.set_owner(trial, study)
            client.create(trial)
            n_active += 1
            next_idx += 1

        status = study.setdefault("status", {})
        status["trials"] = {"completed": n_done, "failed": n_failed,
                            "active": n_active, "total": len(suggestions)}
        done = n_done + n_failed >= len(suggestions) and n_active == 0

        # best trial so far (objective direction from spec)
        goal = (spec.get("objective") or {}).get("type", "minimize")
        scored = [r for r in results if r["objective"] is not None]
        if scored:
            best = (min if goal == "minimize" else max)(
                scored, key=lambda r: r["objective"])
            status["bestTrial"] = best

        if done:
            ob.cond_set(study, COND_RUNNING, "False", "SweepComplete", "")
            if n_done > 0:
                ob.cond_set(study, COND_SUCCEEDED, "True", "SweepComplete",
                            f"{n_done}/{len(suggestions)} trials succeeded")
            else:
                ob.cond_set(study, COND_FAILED, "True", "AllTrialsFailed",
                            f"{n_failed} trials failed")
            client.update_status(study)
            return None

        ob.cond_set(study, COND_RUNNING, "True", "TrialsRunning",
                    f"{n_active} active / {n_done} done")
        client.update_status(study)
        return Result(requeue_after=2.0)


def build_controller(client, collector=default_collector) -> Controller:
    rec = StudyJobReconciler(collector=collector)
    ctl = Controller("studyjob", client, rec)
    ctl.watches_primary(API_VERSION, KIND).owns(JT.API_VERSION, JT.KIND)
    return ctl
