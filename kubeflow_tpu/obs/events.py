"""EventRecorder — the client-go ``record.EventRecorder`` analogue.

Writes real corev1 ``Event`` objects through the k8s client (so
``run_until_idle`` tests can assert them and the dashboard activities
feed surfaces them) with the aggregator's count-dedup: re-recording an
identical event bumps ``count`` and ``lastTimestamp`` on the existing
object instead of minting a new one per occurrence — a gang backing
off every 0.5s must not write a fresh Event per retry.

The dedup key is (involvedObject identity, reason, message, type,
component); the key→name map is a bounded LRU per recorder, so a
long-lived controller process cannot grow it forever. Both
``FakeCluster.record_event`` and ``RestClient.record_event`` route
through this class — controllers keep calling ``client.record_event``
and get dedup for free on either backend.
"""

from __future__ import annotations

import logging
import threading
import uuid
from collections import OrderedDict

from kubeflow_tpu.control.k8s import objects as ob

log = logging.getLogger("kubeflow_tpu.events")


class EventRecorder:
    def __init__(self, client, component: str = "kubeflow-tpu",
                 max_keys: int = 1024):
        self.client = client
        self.component = component
        self._max_keys = max_keys
        self._lock = threading.Lock()
        self._seen: OrderedDict[tuple, tuple[str, str]] = OrderedDict()

    def event(self, involved: dict, reason: str, message: str,
              etype: str = "Normal", component: str | None = None) -> dict:
        """Record one occurrence; returns the created/updated Event.

        The whole lookup→create/bump→remember sequence runs under the
        recorder lock: releasing it mid-flight lets two threads both
        miss the key and create duplicate Events, or both read count=N
        and lose an increment — the exact dedup this class exists for.
        Event recording is low-rate; serializing it is the same trade
        client-go's single recorder goroutine makes. (Lock order is
        recorder→client only — never taken the other way around.)

        Fire-and-forget: a transient apiserver error here DROPS the
        occurrence (returned unsent, logged) rather than raising —
        client-go's recorder makes the same call, because failing a
        reconcile over its own telemetry inverts the priority of the
        two writes."""
        comp = component or self.component
        m = ob.meta(involved)
        ns = m.get("namespace") or "default"
        key = (involved.get("apiVersion"), involved.get("kind"), ns,
               m["name"], m.get("uid", ""), reason, message, etype, comp)
        with self._lock:
            hit = self._seen.get(key)
            if hit is not None:
                self._seen.move_to_end(key)
                try:
                    bumped = self._bump(hit[0], hit[1])
                except ob.ApiError as e:
                    log.warning("event %s/%s %s dropped (count bump "
                                "failed): %s", ns, m["name"], reason, e)
                    return {"reason": reason, "message": message,
                            "type": etype, "count": 0}
                if bumped is not None:
                    return bumped
                self._seen.pop(key, None)  # Event GC'd/expired: recreate
            ev = {
                "apiVersion": "v1",
                "kind": "Event",
                "metadata": {
                    "name": f"{m['name']}.{uuid.uuid4().hex[:10]}",  # tpulint: disable=DET604  apiserver object-name suffix (client-go idiom), never a decision input
                    "namespace": ns,
                },
                "involvedObject": {
                    "apiVersion": involved.get("apiVersion"),
                    "kind": involved.get("kind"),
                    "name": m["name"],
                    "namespace": ns,
                    "uid": m.get("uid", ""),
                },
                "reason": reason,
                "message": message,
                "type": etype,
                "source": {"component": comp},
                "firstTimestamp": ob.now_iso(),  # tpulint: disable=DET601  Event timestamps are apiserver metadata, excluded from decision fingerprints
                "lastTimestamp": ob.now_iso(),  # tpulint: disable=DET601  Event timestamps are apiserver metadata, excluded from decision fingerprints
                "count": 1,
            }
            try:
                created = self.client.create(ev)
            except ob.ApiError as e:
                log.warning("event %s/%s %s dropped (create failed): %s",
                            ns, m["name"], reason, e)
                return ev
            self._seen[key] = (ob.meta(created)["name"], ns)
            while len(self._seen) > self._max_keys:
                self._seen.popitem(last=False)
            return created

    def _bump(self, name: str, namespace: str) -> dict | None:
        """count+1 on the existing Event; None when it no longer exists
        (apiserver Events expire — the caller recreates)."""
        cur = self.client.get_or_none("v1", "Event", name, namespace)
        if cur is None:
            return None
        try:
            return self.client.patch(
                "v1", "Event", name,
                {"count": cur.get("count", 1) + 1,
                 "lastTimestamp": ob.now_iso()},  # tpulint: disable=DET601  Event timestamps are apiserver metadata, excluded from decision fingerprints
                namespace)
        except ob.NotFound:
            return None
