from kubeflow_tpu.control.mains import run_controller
from kubeflow_tpu.control.notebook.controller import (
    RunningNotebooksCollector,
    build_controller,
)


def _build(client, args):
    # live-state notebook_running gauge: scraped at /metrics collection
    # time from the current STS inventory (metrics.go:95-116)
    RunningNotebooksCollector(client).register()
    return build_controller(client)


run_controller("notebook-controller", _build)
