#!/usr/bin/env bash
# One-shot follow-up for the next tunnel-up window: run the op
# microbenchmark (attributes the remaining MFU gap) and then a full
# validation bench.py (ResNet + the promoted LM operating point) so the
# round closes with a driver-reproducible headline even if nobody is
# watching. Probes every ~5 min; exits after one successful pass.
set -u
cd "$(dirname "$0")/.."
LOG=tools/tunnel_followup.log
while true; do
  if timeout 180 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "tunnel UP $(date -u +%H:%M:%S) — phase4 sweep, microbench, bench" >> "$LOG"
    timeout 14400 python tools/lm_sweep.py --phase4 >> "$LOG" 2>&1
    echo "--- phase5 feature-cost sweep $(date -u +%H:%M:%S)" >> "$LOG"
    timeout 5400 python tools/lm_sweep.py --phase5 --skip-blocks >> "$LOG" 2>&1
    echo "--- microbench $(date -u +%H:%M:%S)" >> "$LOG"
    timeout 2400 python tools/op_microbench.py --batch 8 --seq 2048 \
      >> "$LOG" 2>&1
    echo "--- validation bench $(date -u +%H:%M:%S)" >> "$LOG"
    timeout 2400 python bench.py >> "$LOG" 2>&1
    echo "--- serving bf16 vs int8 $(date -u +%H:%M:%S)" >> "$LOG"
    # prefill A/B: per-token (old behavior) vs 128-wide chunks
    echo "--- prefill A/B: KFTPU_PREFILL_CHUNK=1 (per-token)" >> "$LOG"
    KFTPU_PREFILL_CHUNK=1 timeout 1800 python tools/serve_bench.py \
      --modes micro --requests 16 --param-dtype bfloat16 >> "$LOG" 2>&1
    echo "--- prefill A/B: default 128-wide chunks" >> "$LOG"
    timeout 1800 python tools/serve_bench.py \
      --modes micro --requests 16 --param-dtype bfloat16 >> "$LOG" 2>&1
    timeout 1800 python tools/serve_bench.py --modes continuous \
      --requests 32 --param-dtype bfloat16 >> "$LOG" 2>&1
    timeout 1800 python tools/serve_bench.py --modes continuous \
      --requests 32 --param-dtype int8 >> "$LOG" 2>&1
    # kv-cache A/B on a GQA model with a real cache (llama-1b, 1k
    # prompts): gpt-350m's cache is too small to show the effect
    timeout 1800 python tools/serve_bench.py --modes continuous \
      --requests 16 --model llama-1b --prompt-len 1024 \
      --max-new-tokens 32 --slots 8 --param-dtype int8 >> "$LOG" 2>&1
    timeout 1800 python tools/serve_bench.py --modes continuous \
      --requests 16 --model llama-1b --prompt-len 1024 \
      --max-new-tokens 32 --slots 8 --param-dtype int8 \
      --kv-cache-dtype int8 >> "$LOG" 2>&1
    echo "done $(date -u +%H:%M:%S)" >> "$LOG"
    exit 0
  fi
  echo "tunnel down $(date -u +%H:%M:%S)" >> "$LOG"
  sleep 290
done
