"""Create-or-update reconcile helpers with field-copy policies.

The reference factors its "desired vs found" diff policy into
components/common/reconcilehelper/util.go and reuses it across the
notebook/profile/tensorboard controllers:

- Deployment  (util.go:18-44): create if absent, else copy selected fields
  and update when changed.
- Service     (util.go:46-72): same, but PRESERVE the allocated ClusterIP
  (CopyServiceFields, util.go:166-197).
- StatefulSet (CopyStatefulSetFields, util.go:107-137): only replicas and
  pod template are controller-owned; everything else the cluster owns.
- VirtualService (util.go:74-105, CopyVirtualService :199-230): spec only.

Same policies here, expressed over unstructured dicts and generalized by a
``copy_fields`` registry keyed by kind.
"""

from __future__ import annotations

import logging
from typing import Callable

from kubeflow_tpu.control.k8s import objects as ob

log = logging.getLogger("kubeflow_tpu.reconcilehelper")


def _copy_meta(desired: dict, found: dict) -> bool:
    """Labels and annotations are controller-owned (additive)."""
    changed = False
    fm, dm = ob.meta(found), ob.meta(desired)
    for field in ("labels", "annotations"):
        want = dm.get(field) or {}
        have = fm.get(field) or {}
        merged = {**have, **want}
        if merged != have:
            fm[field] = merged
            changed = True
    return changed


def copy_statefulset_fields(desired: dict, found: dict) -> bool:
    """Only spec.replicas + spec.template (CopyStatefulSetFields,
    util.go:107-137 — replica changes drive culling scale-to-zero)."""
    changed = _copy_meta(desired, found)
    dspec, fspec = desired.get("spec") or {}, found.setdefault("spec", {})
    if fspec.get("replicas") != dspec.get("replicas"):
        fspec["replicas"] = dspec.get("replicas")
        changed = True
    if fspec.get("template") != dspec.get("template"):
        fspec["template"] = dspec.get("template")
        changed = True
    return changed


def copy_deployment_fields(desired: dict, found: dict) -> bool:
    changed = _copy_meta(desired, found)
    dspec = desired.get("spec") or {}
    fspec = found.setdefault("spec", {})
    for f in ("replicas", "template", "selector"):
        if f in dspec and fspec.get(f) != dspec[f]:
            fspec[f] = dspec[f]
            changed = True
    return changed


def copy_service_fields(desired: dict, found: dict) -> bool:
    """Spec is copied except the cluster-allocated ClusterIP
    (CopyServiceFields, util.go:166-197)."""
    changed = _copy_meta(desired, found)
    dspec = dict(desired.get("spec") or {})
    fspec = found.setdefault("spec", {})
    cluster_ip = fspec.get("clusterIP")
    dspec.pop("clusterIP", None)
    compare_found = {k: v for k, v in fspec.items() if k != "clusterIP"}
    if compare_found != dspec:
        new_spec = dict(dspec)
        if cluster_ip is not None:
            new_spec["clusterIP"] = cluster_ip
        found["spec"] = new_spec
        changed = True
    return changed


def copy_spec_only(desired: dict, found: dict) -> bool:
    """Whole-spec ownership (CopyVirtualService, util.go:199-230)."""
    changed = _copy_meta(desired, found)
    if found.get("spec") != desired.get("spec"):
        found["spec"] = desired.get("spec")
        changed = True
    return changed


COPIERS: dict[str, Callable[[dict, dict], bool]] = {
    "StatefulSet": copy_statefulset_fields,
    "Deployment": copy_deployment_fields,
    "Service": copy_service_fields,
}


def reconcile_child(client, owner: dict, desired: dict) -> dict:
    """Create-or-update one generated child with owner reference.

    The per-kind create/get/copy/update dance every reference controller
    repeats (e.g. notebook_controller.go:126-180) — done once.
    """
    ob.set_owner(desired, owner)
    m = ob.meta(desired)
    found = client.get_or_none(
        desired["apiVersion"], desired["kind"], m["name"], m.get("namespace")
    )
    if found is None:
        log.info("creating %s %s/%s", desired["kind"], m.get("namespace"), m["name"])
        return client.create(desired)
    copier = COPIERS.get(desired["kind"], copy_spec_only)
    if copier(desired, found):
        log.info("updating %s %s/%s", desired["kind"], m.get("namespace"), m["name"])
        return client.update(found)
    return found
