"""Jupyter-web-app frontend: the notebook spawner UI.

The reference JWA ships an Angular/JS frontend (jupyter-web-app/frontend)
over its Flask backend; this is the same spawner as one dependency-free
page served by the backend itself:

- create form: name / image / cpu / memory / TPU chips (the utils.py:262
  GPU swap point, surfaced in the UI)
- workspace volume section: none | create new | attach existing PVC
  (PVC list from /api/namespaces/{ns}/pvcs, like the reference's
  volume form)
- configurations: PodDefault multi-select; selected entries' selector
  matchLabels are applied to the notebook so the admission webhook
  injects them (spawner_ui_config.yaml "configurations" analogue)
- notebook table: status, image, connect link, stop/start toggle
  (the culler's stop annotation) and delete, plus last event per row
"""

from __future__ import annotations

from kubeflow_tpu.utils.httpd import HttpReq, HttpResp

PAGE = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>Notebooks — kubeflow-tpu</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 0; background: #f5f6f8; }
  header { background: #1a73e8; color: #fff; padding: 10px 20px;
           display: flex; gap: 16px; align-items: center; }
  header h1 { font-size: 18px; margin: 0; flex: 1; }
  main { max-width: 1000px; margin: 20px auto; display: grid; gap: 16px; }
  .card { background: #fff; border-radius: 8px; padding: 16px;
          box-shadow: 0 1px 3px rgba(0,0,0,.15); }
  table { width: 100%; border-collapse: collapse; font-size: 14px; }
  th, td { text-align: left; padding: 6px 8px; border-bottom: 1px solid #eee; }
  select, input, button { font-size: 14px; padding: 6px 8px; margin: 2px 0;
                          border: 1px solid #ccc; border-radius: 4px; }
  button { cursor: pointer; background: #fff; }
  .primary { background: #1a73e8; color: #fff; border: none; }
  .muted { color: #777; font-size: 12px; }
  form { display: grid; grid-template-columns: repeat(3, 1fr); gap: 8px; }
  form label { display: flex; flex-direction: column; font-size: 12px;
               color: #555; }
  fieldset { grid-column: 1 / -1; border: 1px solid #eee; border-radius: 6px;
             display: grid; grid-template-columns: repeat(3, 1fr); gap: 8px; }
  fieldset legend { font-size: 12px; color: #555; padding: 0 4px; }
  .cfg { display: flex; gap: 6px; align-items: center; font-size: 13px; }
  .ev { font-size: 11px; color: #777; }
</style>
</head>
<body>
<header>
  <h1>Notebooks</h1>
  <select id="ns"></select>
</header>
<main>
  <div class="card">
    <h2>New notebook</h2>
    <form id="spawn">
      <label>Name <input name="name" required></label>
      <label>Image <select name="image" id="images"></select></label>
      <label>TPU chips <select name="tpu" id="tpus"></select></label>
      <label>CPU <input name="cpu" value="0.5"></label>
      <label>Memory <input name="memory" value="1Gi"></label>
      <label>&nbsp;</label>
      <fieldset>
        <legend>Workspace volume</legend>
        <label>Mode
          <select id="vol-mode">
            <option value="none">none</option>
            <option value="new">create new</option>
            <option value="existing">attach existing</option>
          </select>
        </label>
        <label id="vol-new" style="display:none">Size
          <input id="vol-size" value="10Gi"></label>
        <label id="vol-existing" style="display:none">PVC
          <select id="pvcs"></select></label>
        <label>Mount path <input id="vol-mount" value="/home/jovyan"></label>
      </fieldset>
      <fieldset>
        <legend>Configurations (PodDefaults)</legend>
        <div id="poddefaults" class="cfg muted" style="grid-column:1/-1">
          none available in this namespace</div>
      </fieldset>
      <label style="grid-column:1/-1">
        <button class="primary" type="submit">Launch</button></label>
    </form>
    <p class="muted" id="msg"></p>
  </div>
  <div class="card">
    <h2>Running</h2>
    <table>
      <thead><tr><th>Name</th><th>Status</th><th>Image</th>
        <th>Last event</th><th></th></tr></thead>
      <tbody id="list"><tr><td class="muted" colspan="5">loading</td></tr></tbody>
    </table>
  </div>
</main>
<script>
const $ = (id) => document.getElementById(id);
const api = (p, opt) => fetch(p, opt).then(r => {
  if (!r.ok) throw new Error('HTTP ' + r.status);
  return r.json();
});

let config = {};
let podDefaults = [];

async function init() {
  config = (await api('api/config')).config || {};
  for (const img of (config.image?.options || [])) {
    const o = document.createElement('option');
    o.value = o.textContent = img;
    $('images').appendChild(o);
  }
  for (const n of (config.tpu?.options || [0])) {
    const o = document.createElement('option');
    o.value = o.textContent = n;
    $('tpus').appendChild(o);
  }
  const nss = (await api('api/namespaces')).namespaces || [];
  for (const ns of nss) {
    const o = document.createElement('option');
    o.value = o.textContent = ns;
    $('ns').appendChild(o);
  }
  if (nss.length) await nsChanged();
}

async function nsChanged() {
  const ns = $('ns').value;
  await Promise.all([refresh(), loadPvcs(ns), loadPodDefaults(ns)]);
}

async function loadPvcs(ns) {
  const out = await api('api/namespaces/' + ns + '/pvcs').catch(() => ({pvcs: []}));
  const sel = $('pvcs');
  sel.innerHTML = '';
  for (const p of out.pvcs || []) {
    const o = document.createElement('option');
    o.value = p.name;
    o.textContent = p.name + (p.size ? ' (' + p.size + ')' : '');
    sel.appendChild(o);
  }
}

async function loadPodDefaults(ns) {
  const out = await api('api/namespaces/' + ns + '/poddefaults')
    .catch(() => ({poddefaults: []}));
  podDefaults = out.poddefaults || [];
  const box = $('poddefaults');
  box.innerHTML = '';
  for (const pd of podDefaults) {
    const row = document.createElement('label');
    row.className = 'cfg';
    const cb = document.createElement('input');
    cb.type = 'checkbox';
    cb.value = pd.name;
    row.appendChild(cb);
    row.appendChild(document.createTextNode(pd.desc || pd.name));
    box.appendChild(row);
  }
  if (!podDefaults.length) {
    box.textContent = 'none available in this namespace';
    box.className = 'cfg muted';
  } else {
    box.className = 'cfg';
  }
}

$('vol-mode').addEventListener('change', () => {
  const m = $('vol-mode').value;
  $('vol-new').style.display = m === 'new' ? '' : 'none';
  $('vol-existing').style.display = m === 'existing' ? '' : 'none';
});

async function refresh() {
  const ns = $('ns').value;
  const out = await api('api/namespaces/' + ns + '/notebooks');
  const tb = $('list');
  tb.innerHTML = '';
  for (const nb of out.notebooks || []) {
    // DOM-built rows: names/images are never interpolated into HTML
    const tr = document.createElement('tr');
    const lastEv = (nb.events || []).slice(-1)[0];
    for (const text of [nb.name, (nb.status && nb.status.phase) || 'unknown',
                        nb.image || '']) {
      const td = document.createElement('td');
      td.textContent = text;
      tr.appendChild(td);
    }
    const ev = document.createElement('td');
    ev.className = 'ev';
    ev.textContent = lastEv ? (lastEv.reason + ': ' + lastEv.message) : '';
    tr.appendChild(ev);
    const td = document.createElement('td');
    const a = document.createElement('a');
    a.href = '/notebook/' + encodeURIComponent(ns) + '/' +
             encodeURIComponent(nb.name) + '/';
    a.textContent = 'connect';
    const stopped = nb.status && nb.status.phase === 'stopped';
    const toggle = document.createElement('button');
    toggle.textContent = stopped ? 'start' : 'stop';
    toggle.addEventListener('click', async () => {
      await fetch('api/namespaces/' + encodeURIComponent(ns) +
                  '/notebooks/' + encodeURIComponent(nb.name), {
        method: 'PATCH',
        headers: {'Content-Type': 'application/json'},
        body: JSON.stringify({stopped: !stopped}),
      });
      refresh();
    });
    const del = document.createElement('button');
    del.textContent = 'delete';
    del.addEventListener('click', async () => {
      await fetch('api/namespaces/' + encodeURIComponent(ns) +
                  '/notebooks/' + encodeURIComponent(nb.name),
                  {method: 'DELETE'});
      refresh();
    });
    td.append(a, ' ', toggle, ' ', del);
    tr.appendChild(td);
    tb.appendChild(tr);
  }
  if (!tb.children.length)
    tb.innerHTML = '<tr><td class="muted" colspan="5">none</td></tr>';
}

$('ns').addEventListener('change', nsChanged);
$('spawn').addEventListener('submit', async (e) => {
  e.preventDefault();
  const ns = $('ns').value;
  const form = Object.fromEntries(new FormData(e.target).entries());
  form.tpu = parseInt(form.tpu || '0', 10);
  const mode = $('vol-mode').value;
  if (mode === 'new') {
    // create the PVC first, then attach (reference post_pvc flow);
    // 409 = claim already exists from an earlier attempt -> reuse it,
    // any other failure aborts so the notebook never mounts a missing
    // claim
    const claim = 'workspace-' + form.name;
    const pr = await fetch('api/namespaces/' + ns + '/pvcs', {
      method: 'POST', headers: {'Content-Type': 'application/json'},
      body: JSON.stringify({name: claim, size: $('vol-size').value}),
    });
    if (!pr.ok && pr.status !== 409) {
      $('msg').textContent = 'volume create failed: HTTP ' + pr.status;
      return;
    }
    form.workspaceVolume = {name: claim, mountPath: $('vol-mount').value};
  } else if (mode === 'existing') {
    if (!$('pvcs').value) {
      $('msg').textContent = 'no existing volume to attach in this namespace';
      return;
    }
    form.workspaceVolume = {name: $('pvcs').value,
                            mountPath: $('vol-mount').value};
  }
  // configurations -> labels matching the PodDefault selectors
  const labels = {};
  document.querySelectorAll('#poddefaults input:checked').forEach(cb => {
    const pd = podDefaults.find(p => p.name === cb.value);
    Object.assign(labels, (pd && pd.matchLabels) || {});
  });
  if (Object.keys(labels).length) form.labels = labels;
  const r = await fetch('api/namespaces/' + ns + '/notebooks', {
    method: 'POST',
    headers: {'Content-Type': 'application/json'},
    body: JSON.stringify(form),
  });
  $('msg').textContent = r.ok ? 'created' : 'failed: HTTP ' + r.status;
  if (r.ok) refresh();
});

init().catch(e => { $('msg').textContent = String(e); });
setInterval(() => refresh().catch(() => {}), 10000);
</script>
</body>
</html>
"""


def page(req: HttpReq) -> HttpResp:
    return HttpResp(200, PAGE.encode(), "text/html")


def add_ui_routes(router) -> None:
    router.route("GET", "/", page)
    router.route("GET", "/spawner", page)
