"""Graceful TPU preemption/maintenance handling.

SURVEY.md §5 lists slice preemption as a hard part with no reference
precedent (the reference's failure story is per-replica restartPolicy).
The TPU-native answer: when the platform warns a worker (SIGTERM from
the kubelet on pod eviction; GKE sends it ahead of TPU maintenance),
the trainer finishes the in-flight step, force-saves a checkpoint, and
exits EX_TEMPFAIL — the JAXJob controller then gang-restarts the job,
which resumes from that checkpoint instead of losing the interval since
the last periodic save.

Usage (wired by the launcher):
    notice = PreemptionNotice().install()
    state, summary = trainer.fit(stop=notice)
    if summary.get("preempted"):
        sys.exit(EX_TEMPFAIL)
"""

from __future__ import annotations

import logging
import signal
import threading

log = logging.getLogger("kubeflow_tpu.preemption")

# A preempted worker must NOT exit 0 (the controller would count it
# Succeeded) nor look like a crash-only failure: EX_TEMPFAIL is the
# conventional "transient, retry me" exit status.
EX_TEMPFAIL = 75


class PreemptionNotice:
    """Callable flag set by SIGTERM (and available for tests/manual
    triggering via .trigger())."""

    def __init__(self):
        self._event = threading.Event()
        self._prev_handler = None

    def install(self, signum: int = signal.SIGTERM) -> "PreemptionNotice":
        """Install the signal handler (main thread only — launcher entry).
        Chains to any previously installed handler."""
        prev = signal.getsignal(signum)

        def handler(sig, frame):
            log.warning("preemption notice (signal %d): will checkpoint "
                        "and exit after the current step", sig)
            self._event.set()
            if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
                prev(sig, frame)

        self._prev_handler = prev
        signal.signal(signum, handler)
        return self

    def trigger(self) -> None:
        self._event.set()

    def __call__(self) -> bool:
        return self._event.is_set()
