"""Lease-based leader election for controllers.

The reference's controllers run under controller-runtime managers with
`--enable-leader-election` ("ensure there is only one active controller
manager", notebook-controller/main.go:51-62; profile-controller
main.go:52) backed by coordination.k8s.io Leases. Same mechanism here:
a `Lease` object holds {holderIdentity, leaseDurationSeconds,
renewTime, leaseTransitions}; candidates acquire it when absent or
expired, renew while holding it, and optimistic-concurrency (409 on
stale resourceVersion) arbitrates races — the loser simply stays on
standby. Controllers wrapped with `with_leader_election` keep watching
but skip reconciles until they hold the lease, so a standby replica
takes over within one lease duration of the leader dying.
"""

from __future__ import annotations

import datetime
import logging
import os
import socket
import threading
import time
import uuid
from typing import Callable

from kubeflow_tpu.control.k8s import objects as ob

log = logging.getLogger("kubeflow_tpu.leases")

API_VERSION = "coordination.k8s.io/v1"
KIND = "Lease"


def _to_micro_time(epoch: float) -> str:
    """LeaseSpec renewTime/acquireTime are MicroTime RFC3339 strings on a
    real apiserver — epoch floats would be rejected with 400/422."""
    return datetime.datetime.fromtimestamp(
        epoch, datetime.timezone.utc).isoformat(timespec="microseconds")


def _from_micro_time(value) -> float | None:
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value)
    return datetime.datetime.fromisoformat(
        str(value).replace("Z", "+00:00")).timestamp()


def default_identity() -> str:
    """pod-name/uuid identity (controller-runtime uses hostname_uuid)."""
    return f"{os.environ.get('POD_NAME', socket.gethostname())}_{uuid.uuid4().hex[:8]}"


class LeaderElector:
    """Acquire/renew a named Lease; thread-compatible with the
    controller's single-threaded run_until_idle loop (each poll is one
    try_acquire call)."""

    def __init__(self, client, name: str, namespace: str = "kubeflow",
                 identity: str | None = None,
                 lease_seconds: float = 15.0,
                 clock: Callable[[], float] = time.time):
        # clock MUST be wall-clock (default) in production: renewTime is
        # compared across processes, and monotonic epochs differ per
        # process. Injectable for deterministic tests.
        self.client = client
        self.name = name
        self.namespace = namespace
        self.identity = identity or default_identity()
        self.lease_seconds = lease_seconds
        self.clock = clock
        self._held = False
        self._last_renew = 0.0
        # one elector is shared by all worker threads of a controller:
        # serialize rounds so workers don't 409 against each other and
        # flap the held flag
        self._lock = threading.Lock()

    # -- helpers ------------------------------------------------------------

    def _spec(self, lease: dict) -> dict:
        return lease.setdefault("spec", {})

    def _expired(self, lease: dict) -> bool:
        spec = lease.get("spec") or {}
        renew = _from_micro_time(spec.get("renewTime"))
        dur = spec.get("leaseDurationSeconds", self.lease_seconds)
        if renew is None:
            return True
        return self.clock() - renew > float(dur)

    # -- protocol -----------------------------------------------------------

    def try_acquire(self) -> bool:
        """One election round: create the lease, renew it if held by us,
        or take it over if expired. Returns whether we are the leader.
        Held leadership is cached for lease_seconds/3 (controller-runtime
        retryPeriod shape), so the reconcile hot path is a local
        timestamp check, not an apiserver round-trip per item."""
        with self._lock:
            now = self.clock()
            if self._held and now - self._last_renew < self.lease_seconds / 3:
                return True
            return self._round(now)

    def _round(self, now: float) -> bool:
        try:
            lease = self.client.get_or_none(
                API_VERSION, KIND, self.name, self.namespace)
            if lease is None:
                lease = ob.new_object(API_VERSION, KIND, self.name,
                                      self.namespace)
                self._spec(lease).update(
                    holderIdentity=self.identity,
                    leaseDurationSeconds=int(self.lease_seconds),
                    acquireTime=_to_micro_time(now),
                    renewTime=_to_micro_time(now),
                    leaseTransitions=0)
                self.client.create(lease)
                return self._became(True, now)
            spec = self._spec(lease)
            if spec.get("holderIdentity") == self.identity:
                spec["renewTime"] = _to_micro_time(now)
                self.client.update(lease)
                return self._became(True, now)
            if self._expired(lease):
                spec.update(
                    holderIdentity=self.identity,
                    acquireTime=_to_micro_time(now),
                    renewTime=_to_micro_time(now),
                    leaseTransitions=spec.get("leaseTransitions", 0) + 1)
                self.client.update(lease)  # 409 if another standby won
                return self._became(True, now)
        except ob.Conflict:
            pass
        except ob.ApiError as e:
            log.warning("leader election for %s errored: %s", self.name, e)
            if self._held and now - self._last_renew < self.lease_seconds:
                # transient apiserver error on a RENEW: the Lease still
                # names us and has not expired, so dropping to standby
                # now would flap leadership on every blip. Stay leader —
                # WITHOUT advancing _last_renew (no real renewal
                # happened): if errors persist past the lease duration,
                # this guard stops holding exactly when a standby may
                # legitimately take over.
                return True
        return self._became(False, now)

    def release(self) -> None:
        """Voluntary hand-off on clean shutdown: zero the renewTime so a
        standby takes over immediately instead of after expiry. Runs
        regardless of the cached held flag — the lease may still name us
        even if the last round lost a 409 race."""
        with self._lock:
            try:
                lease = self.client.get_or_none(
                    API_VERSION, KIND, self.name, self.namespace)
                if lease and self._spec(lease).get("holderIdentity") == self.identity:
                    self._spec(lease)["renewTime"] = None
                    self.client.update(lease)
            except ob.ApiError:
                pass
            self._held = False

    def _became(self, leader: bool, now: float) -> bool:
        if leader and not self._held:
            log.info("%s: became leader for %s", self.identity, self.name)
        elif not leader and self._held:
            log.warning("%s: lost leadership for %s", self.identity, self.name)
        self._held = leader
        if leader:
            self._last_renew = now
        return leader

    @property
    def is_leader(self) -> bool:
        return self._held
