"""Hot-loop instrumentation the reference never had.

The reference's observability is Prometheus on the control plane only
(bootstrap/cmd/bootstrap/app/server.go:68-132, notebook-controller
pkg/metrics/metrics.go) — per-step training metrics don't exist. Here
every worker exports step time, throughput, and MFU in Prometheus text
exposition format, scrapeable at :9100/metrics, with zero third-party
dependencies (stdlib http.server on a daemon thread).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# Peak dense bf16 FLOP/s per chip, by jax device_kind. Source: public Cloud
# TPU docs tables (v4: 275T, v5e: 197T, v5p: 459T, v6e "Trillium": 918T).
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}
_DEFAULT_PEAK = 197e12

# Peak HBM bandwidth per chip (bytes/s), same doc tables (v4: 1.2TB/s,
# v5e: 819GB/s, v5p: 2.77TB/s, v6e: 1.64TB/s). Drives the roofline
# fields bench.py reports next to MFU.
PEAK_HBM_BW = {
    "TPU v4": 1.2e12,
    "TPU v5 lite": 819e9,
    "TPU v5": 2.77e12,
    "TPU v5p": 2.77e12,
    "TPU v6 lite": 1.64e12,
    "TPU v6e": 1.64e12,
}
_DEFAULT_BW = 819e9


def _lookup(table: dict, device_kind: str, default: float) -> float:
    for prefix, val in sorted(table.items(), key=lambda kv: -len(kv[0])):
        if device_kind.startswith(prefix):
            return val
    return default


def peak_flops(device_kind: str) -> float:
    return _lookup(PEAK_FLOPS, device_kind, _DEFAULT_PEAK)


def peak_hbm_bw(device_kind: str) -> float:
    return _lookup(PEAK_HBM_BW, device_kind, _DEFAULT_BW)


class StepMeter:
    """Tracks step wall time, examples/sec and MFU over a sliding window.

    With ``tracer`` set (an ``obs.trace.Tracer``), each start/stop pair
    additionally emits a ``train.step`` span under the ambient trace
    context — this is what links worker step timing back to the gang
    scheduler's admission span (one timeline, job submit → step)."""

    def __init__(self, flops_per_step: float, n_chips: int, device_kind: str = "", window: int = 20,
                 tracer=None, span_name: str = "train.step", step_base: int = 0):
        self.flops_per_step = float(flops_per_step)
        self.n_chips = max(1, n_chips)
        self.peak = peak_flops(device_kind) * self.n_chips if device_kind else None
        self._times: deque[float] = deque(maxlen=window)
        self._t0: float | None = None
        self.steps = 0
        self._tracer = tracer
        self._span_name = span_name
        # span step attr = step_base + metered count, so a trainer that
        # meters from global step N (compile step excluded) labels its
        # spans with the true global step indices
        self.step_base = step_base
        self._span = None

    def start(self) -> None:
        if self._tracer is not None:
            if self._span is not None:
                # the previous step never reached stop() (it raised):
                # close its span as ERROR so the failed step — the one
                # an operator most wants to see — still exports
                self._span.status = "ERROR"
                self._tracer.finish(self._span)
            self._span = self._tracer.begin(
                self._span_name, step=self.step_base + self.steps)
        self._t0 = time.perf_counter()

    def stop(self) -> float:
        assert self._t0 is not None, "stop() without start()"
        dt = time.perf_counter() - self._t0
        self._times.append(dt)
        self.steps += 1
        self._t0 = None
        if self._span is not None:
            self._span.attrs["step_time_s"] = round(dt, 6)
            self._tracer.finish(self._span)
            self._span = None
        return dt

    def close(self) -> None:
        """Finish a still-open step span as ERROR. Call when the loop
        unwinds between start() and stop() (a step raised): the failing
        step's span must still export — there is no later start() to
        self-heal it."""
        if self._span is not None:
            self._span.status = "ERROR"
            self._tracer.finish(self._span)
            self._span = None

    @property
    def step_time(self) -> float:
        return sum(self._times) / len(self._times) if self._times else float("nan")

    def throughput(self, examples_per_step: int) -> float:
        return examples_per_step / self.step_time

    @property
    def achieved_flops(self) -> float:
        return self.flops_per_step / self.step_time

    @property
    def mfu(self) -> float:
        if not self.peak:
            return float("nan")
        return self.achieved_flops / self.peak


# Default latency buckets (seconds) — controller-runtime's reconcile
# histogram range: sub-ms reconciles up to minute-scale stalls.
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class _Histogram:
    """Cumulative-bucket histogram state for one label set."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.counts = [0] * len(self.buckets)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        for i, le in enumerate(self.buckets):
            if value <= le:
                self.counts[i] += 1
                break
        self.sum += value
        self.count += 1


def _escape_label(value) -> str:
    """Prometheus text-format label-value escaping: backslash, quote and
    newline must be escaped or the exposition is unscrapeable."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _label_str(key: tuple, extra: tuple = ()) -> str:
    return ",".join(f'{k}="{_escape_label(v)}"' for k, v in (*key, *extra))


class MetricsRegistry:
    """Minimal Prometheus registry: gauges, counters and native
    histograms, text format 0.0.4."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, tuple[str, str, dict[tuple, object]]] = {}

    def _set(self, kind: str, name: str, help_: str, value: float, labels: dict | None):
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            _, _, series = self._metrics.setdefault(name, (kind, help_, {}))
            series[key] = value

    def gauge(self, name: str, value: float, help_: str = "", **labels) -> None:
        self._set("gauge", name, help_, value, labels)

    def counter_inc(self, name: str, help_: str = "", by: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            _, _, series = self._metrics.setdefault(name, ("counter", help_, {}))
            series[key] = series.get(key, 0.0) + by

    def histogram(self, name: str, value: float, help_: str = "",
                  buckets=DEFAULT_BUCKETS, **labels) -> None:
        """Observe ``value`` into a cumulative-bucket histogram. Renders
        as ``name_bucket{le=...}`` / ``name_sum`` / ``name_count`` —
        the native type the scheduler's hand-rolled ``_sum``/``_count``
        counter pair predated."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            _, _, series = self._metrics.setdefault(
                name, ("histogram", help_, {}))
            hist = series.get(key)
            if not isinstance(hist, _Histogram):
                hist = series[key] = _Histogram(buckets)
            hist.observe(float(value))

    @staticmethod
    def _render_histogram(out: list, name: str, key: tuple,
                          hist: _Histogram) -> None:
        cum = 0
        for le, n in zip(hist.buckets, hist.counts):
            cum += n
            out.append(f"{name}_bucket{{"
                       f"{_label_str(key, (('le', le),))}}} {cum}")
        out.append(f"{name}_bucket{{{_label_str(key, (('le', '+Inf'),))}}} "
                   f"{hist.count}")
        suffix = f"{{{_label_str(key)}}}" if key else ""
        out.append(f"{name}_sum{suffix} {hist.sum}")
        out.append(f"{name}_count{suffix} {hist.count}")

    def series(self, name: str) -> list[tuple[dict, float]]:
        """Structured read of one scalar metric's samples as
        ``(labels, value)`` pairs — the in-process fast path for
        consumers like the JAXService autoscaler's ``RegistrySignals``
        (parsing the full text exposition per signal read would cost
        O(total series) per reconcile). Histogram samples are skipped;
        read those through ``render()``."""
        out: list[tuple[dict, float]] = []
        with self._lock:
            entry = self._metrics.get(name)
            if entry is None:
                return out
            _, _, samples = entry
            for key, value in samples.items():
                if isinstance(value, _Histogram):
                    continue
                out.append((dict(key), float(value)))
        return out

    def render(self) -> str:
        out = []
        with self._lock:
            for name, (kind, help_, series) in sorted(self._metrics.items()):
                if help_:
                    out.append(f"# HELP {name} {_escape_help(help_)}")
                out.append(f"# TYPE {name} {kind}")
                for key in sorted(series):
                    value = series[key]
                    if isinstance(value, _Histogram):
                        self._render_histogram(out, name, key, value)
                    elif key:
                        out.append(f"{name}{{{_label_str(key)}}} {value}")
                    else:
                        out.append(f"{name} {value}")
        return "\n".join(out) + "\n"


REGISTRY = MetricsRegistry()

# -- prometheus_client interop ------------------------------------------------

_PROM_METRICS: dict[str, object] = {}


def prom_metric(name: str, kind, doc: str, **kw):
    """Process-global memoized prometheus_client metric: registering a
    name twice raises in prometheus_client, and several subsystems
    (serving server, control plane, router) share one process in tests
    and benches. The ONE spelling of that guard — the per-module copies
    in serving/server.py and control/jaxjob/controller.py delegate
    here."""
    if name not in _PROM_METRICS:
        _PROM_METRICS[name] = kind(name, doc, **kw)
    return _PROM_METRICS[name]


class _Handler(BaseHTTPRequestHandler):
    registry: MetricsRegistry = REGISTRY

    def do_GET(self):  # noqa: N802
        if self.path.rstrip("/") in ("", "/metrics"):
            body = self.registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/healthz":
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"ok")
        else:
            self.send_response(404)
            self.end_headers()

    def log_message(self, *a):  # silence per-request lines
        pass


def serve_metrics(port: int = 9100, registry: MetricsRegistry = REGISTRY) -> ThreadingHTTPServer:
    """Start the /metrics endpoint on a daemon thread; returns the server
    (caller may .shutdown()). Port 0 picks a free port (tests)."""
    handler = type("Handler", (_Handler,), {"registry": registry})
    srv = ThreadingHTTPServer(("0.0.0.0", port), handler)
    t = threading.Thread(target=srv.serve_forever, name="metrics", daemon=True)
    t.start()
    return srv
